"""Pallas TPU kernel for batch ed25519 verification — 24-limb radix.

The hot path of the framework (reference seam: crypto/ed25519/ed25519.go
BatchVerifier → types/validation.go verifyCommitBatch).  One fused Mosaic
kernel verifies a block of lanes end-to-end: ZIP-215 decompression,
4-bit-windowed Straus ladder for [8](s·B - R - k·A), and the identity
test — all in VMEM.

Second-generation field arithmetic (the r3 cost model's prescription,
KERNEL_NOTES.md): 24 balanced limbs in an (11, 11, 10)-bit cycle
(ops/field24.py has the schedule rationale and the int32 bounds
analysis).  The limb convolution drops from 1024 slab MACs (32x8-bit
kernel, kept as ed25519_pallas8.py behind COMETBFT_TPU_KERNEL=pallas8)
to 576, and the off-grid x2 corrections are separable by residue
class, so each of the 24 slab MACs just picks one of three pre-scaled
copies of the multiplier.

Round-4 carry discipline: conv inputs that are already resting values
(_norm outputs, pre-balanced constants) skip the input carry pass
entirely; sums/differences of resting values and raw byte digits get
exactly one pass, applied once per value even when it feeds several
products.  The exact per-position worst case (field24.conv_bound over
the resting fixed point, re-derived in tests/test_field24.py) is a
1.474e9 conv accumulator and 1.744e9 carry pre-scale — both < 2^31.
This removes ~60% of the input carry passes (~10% of kernel ops).

Inputs are identical to the byte kernel: [32, B] byte columns for
A and R, [64, B] nibble windows for s and k — the host prep and the
dispatch are unchanged; bytes convert to limbs in VMEM.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..crypto import _ed25519_ref as ref
from . import field24 as f24

LIMBS = f24.LIMBS               # 24
_FOLD = f24.FOLD                # 38 = 2^256 mod p
_SIZES = f24.SIZES
_OFFS = f24.OFFSETS
BLOCK = 128                     # lanes per grid step
_WINDOWS = 64

def _carry_row_consts():
    """Per-row carry constants ([24, 1], broadcast over lanes), built
    from iota because Mosaic kernels cannot capture ndarray constants:
    prescale m = 2^(11 - t_i) makes every row's rounding shift 11
    bits; weight 2^t_i undoes the scale when reconstructing the low
    part.  CSE collapses the repeats across carry passes."""
    m10 = (lax.broadcasted_iota(jnp.int32, (LIMBS, 1), 0) % 3) == 2
    prescale = jnp.where(m10, 2, 1)
    weight = jnp.where(m10, 1 << 10, 1 << 11)
    return prescale, weight


# --- balanced carry / field multiply ---------------------------------------

def _carry(x):
    """One balanced (round-to-nearest) carry pass, limb-major [24, B].
    The top carry folds into limb 0 at weight 38 and is immediately
    split again (fold-settle) so limb 0 keeps its resting bound.

    Vectorized across the limb dimension: per-row ops on [1, B] slices
    use one sublane of each (8, 128) int32 vreg — 1/8 of the VPU — so
    a 24-row loop here costs ~8x what a full [24, B] op does (measured
    on v5e: the row-sliced form put the whole kernel at ~126 ms for a
    16k batch, ~3x the full-utilization prediction).

    The per-row rounding shift uses the pre-scale trick instead of a
    two-way select on the (11, 11, 10) size cycle: z = x·m with
    m = 2^(11-t_i) ∈ {1, 2} makes every row an 11-bit shift, and
    lo = x - c·2^t_i is a per-row constant multiply.  Bound: under the
    relaxed carry discipline (resting operands enter the conv without
    an input pass) the exact per-position worst case of |x·m| is
    1.744e9 < 2^31 — 1.23x headroom (field24.conv_bound/resting_bound;
    re-derived in tests/test_field24.py)."""
    prescale, weight = _carry_row_consts()
    c = (x * prescale + 1024) >> 11
    lo = x - c * weight
    f = c[LIMBS - 1:] * _FOLD
    fc = (f + 1024) >> 11               # limb 0 is an 11-bit position
    y = lo + jnp.concatenate([f - (fc << 11), c[:LIMBS - 1]], axis=0)
    return jnp.concatenate([y[0:1], y[1:2] + fc, y[2:]], axis=0)


def _norm(x, passes):
    for _ in range(passes):
        x = _carry(x)
    return x


def _mul(a, b, pats, ca=1, cb=1):
    """Field multiply, limb-major.  ca/cb = input carry passes (0 or
    1) under the relaxed magnitude discipline (round 4):

      * ca=0 — operand is a RESTING value (a _norm(.., 2) output, a
        pre-balanced constant, or resting + O(1)).  With both operands
        resting, the exact worst-case conv accumulator is 1.474e9 and
        the carry pass's x*prescale peaks at 1.744e9 < 2^31 (1.23x
        headroom) — field24.conv_bound/resting_bound compute this and
        tests/test_field24.py re-derives it.
      * ca=1 — operand is a sum/difference of up to 4 resting values
        (or raw byte digits); one balanced pass brings it under ~1100
        per limb.  That is NOT elementwise below resting (resting
        limbs cycle down to ~543), so safety comes from the directly
        computed bounds conv(once, R) and conv(once, once) < 2^31 and
        from the closure property carry²(conv(once, once)) ≤ R —
        both asserted by tests/test_field24.py, not from domination
        by the resting case.

    Default (1,1) is the always-safe round-3 behavior."""
    return _mul_nn(_norm(a, ca), _norm(b, cb), pats)


def _mul_nn(a, b, pats):
    """Multiply of operands already inside the resting bound."""
    pat1, pat2 = pats
    v0 = b
    v1 = b * pat1
    v2 = b * pat2
    bt = []
    for v in (v0, v1, v2):
        w = v * _FOLD
        bt.append(jnp.concatenate([w[1:], v], axis=0))   # [47, B]
    acc = None
    for i in range(LIMBS):
        sl = bt[i % 3][LIMBS - 1 - i:2 * LIMBS - 1 - i]  # [24, B]
        term = sl * a[i:i + 1]
        acc = term if acc is None else acc + term
    return _norm(acc, 2)


def _make_sqr(pats):
    def _sqr(a, ca=0):
        """Square; ca=1 when the input is a sum or raw byte digits
        (same classes as _mul's ca)."""
        a = _norm(a, ca)
        return _mul_nn(a, a, pats)
    return _sqr


def _mul_const(x, c, passes=2):
    """x*c normalized.  passes=1 suffices when the result only feeds
    sums that are themselves carried before entering a conv (one
    balanced pass from 2R lands under ~1100 per limb)."""
    return _norm(x * c, passes)


# --- canonical / comparisons (limb-major) ----------------------------------

_P_DIGITS = [int(v) for v in f24.P_DIGITS]


def _seq_carry(x):
    """Exact sequential sweep: rows -> [0, 2^t_i), plus carry row."""
    outs = []
    c = jnp.zeros_like(x[0:1])
    for i in range(LIMBS):
        t = _SIZES[i]
        v = x[i:i + 1] + c
        outs.append(v & ((1 << t) - 1))
        c = v >> t
    return jnp.concatenate(outs, axis=0), c


def _canonical(x, four_p):
    x = _norm(x, 2)
    x = x + four_p                                        # + 4p > 0
    for _ in range(3):
        x, c = _seq_carry(x)
        x = jnp.concatenate([x[0:1] + _FOLD * c, x[1:]], axis=0)
    for _ in range(2):
        ge = jnp.ones_like(x[0:1], dtype=jnp.bool_)
        gt = jnp.zeros_like(x[0:1], dtype=jnp.bool_)
        for i in range(LIMBS - 1, -1, -1):
            pi = _P_DIGITS[i]
            gt = gt | (ge & (x[i:i + 1] > pi))
            ge = ge & (x[i:i + 1] == pi)
        take = gt | ge
        outs = []
        c = jnp.zeros_like(x[0:1])
        for i in range(LIMBS):
            t = _SIZES[i]
            v = x[i:i + 1] - _P_DIGITS[i] + c
            outs.append(v & ((1 << t) - 1))
            c = v >> t
        sub = jnp.concatenate(outs, axis=0)
        x = jnp.where(take, sub, x)
    return x


def _is_zero(x, four_p):
    c = _canonical(x, four_p)
    nz = c[0:1]
    for i in range(1, LIMBS):
        nz = nz | c[i:i + 1]
    return nz == 0


def _eq(a, b, four_p):
    return _is_zero(a - b, four_p)


def _parity(x, four_p):
    return _canonical(x, four_p)[0:1] & 1


# --- byte -> limb conversion (in VMEM) -------------------------------------

def _from_bytes(b):
    """[32, B] byte values -> [24, B] digits (limb i covers bits
    [OFFSETS[i], OFFSETS[i+1]) of the little-endian value)."""
    rows = []
    for i in range(LIMBS):
        s, t = _OFFS[i], _SIZES[i]
        b0, sh = s >> 3, s & 7
        acc = b[b0:b0 + 1] >> sh
        if sh + t > 8:
            acc = acc + (b[b0 + 1:b0 + 2] << (8 - sh))
        if sh + t > 16 and b0 + 2 < 32:
            acc = acc + (b[b0 + 2:b0 + 3] << (16 - sh))
        rows.append(acc & ((1 << t) - 1))
    return jnp.concatenate(rows, axis=0)


# --- exponentiation chain ---------------------------------------------------

def _pow_p58(x, pats):
    """x^(2^252 - 3) (same chain as field.pow_p58)."""
    _sqr = _make_sqr(pats)

    def pow2k(v, k):
        return lax.fori_loop(0, k, lambda _, u: _sqr(u), v)

    x2 = _sqr(x)
    t = _sqr(_sqr(x2))
    z9 = _mul(x, t, pats, 0, 0)
    z11 = _mul(x2, z9, pats, 0, 0)
    z_5_0 = _mul(z9, _sqr(z11), pats, 0, 0)
    z_10_0 = _mul(pow2k(z_5_0, 5), z_5_0, pats, 0, 0)
    z_20_0 = _mul(pow2k(z_10_0, 10), z_10_0, pats, 0, 0)
    z_40_0 = _mul(pow2k(z_20_0, 20), z_20_0, pats, 0, 0)
    z_50_0 = _mul(pow2k(z_40_0, 10), z_10_0, pats, 0, 0)
    z_100_0 = _mul(pow2k(z_50_0, 50), z_50_0, pats, 0, 0)
    z_200_0 = _mul(pow2k(z_100_0, 100), z_100_0, pats, 0, 0)
    z_250_0 = _mul(pow2k(z_200_0, 50), z_50_0, pats, 0, 0)
    return _mul(x, pow2k(z_250_0, 2), pats, 0, 0)


# --- point ops (extended twisted Edwards, limb-major) ----------------------

def _ext_add(p, q, two_d, pats, need_t=True):
    """Unified add (complete for a=-1).  Carry discipline: inputs are
    resting (point coords are _norm outputs; two_d is pre-balanced),
    sums get exactly one pass, each carried once even when used by two
    products — 8 input passes total vs 18 under the uniform rule."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    a = _mul(Y1 - X1, Y2 - X2, pats)            # 2R x 2R -> 1+1
    b = _mul(Y1 + X1, Y2 + X2, pats)
    c = _mul(_mul(T1, T2, pats, 0, 0), two_d, pats, 0, 0)
    d = _mul_const(_mul(Z1, Z2, pats, 0, 0), 2, passes=1)
    e = _carry(b - a)
    ff = _carry(d - c)
    g = _carry(d + c)
    h = _carry(b + a)
    return (_mul(e, ff, pats, 0, 0), _mul(g, h, pats, 0, 0),
            _mul(ff, g, pats, 0, 0),
            _mul(e, h, pats, 0, 0) if need_t else None)


def _ext_double(p, pats, need_t=True):
    """dbl-2008-hwcd, a=-1: 4 squarings + 4 products (3 when the
    caller doesn't need the extended T coordinate — the formula never
    reads T, so in a run of doublings only the last one, whose output
    feeds an addition, has to produce it)."""
    _sqr = _make_sqr(pats)
    X1, Y1, Z1, _ = p
    a = _sqr(X1)
    b = _sqr(Y1)
    c = _mul_const(_sqr(Z1), 2, passes=1)
    e = _carry(_sqr(X1 + Y1, ca=1) - a - b)     # 3R -> one pass
    g = b - a                                   # 2R
    ff = _carry(g - c)                          # ~2.5R -> one pass
    g = _carry(g)
    h = _carry(-(a + b))
    return (_mul(e, ff, pats, 0, 0), _mul(g, h, pats, 0, 0),
            _mul(ff, g, pats, 0, 0),
            _mul(e, h, pats, 0, 0) if need_t else None)


def _madd_affine(p, q3, pats):
    """Mixed add of a projective-extended point and an AFFINE
    precomputed entry (y-x, y+x, 2d·x·y) with Z2 = 1 — the constant
    B table ships in this form, saving the Z1·Z2 and 2d·T2 products
    of the unified add (madd-2008-hwcd shape): 7 field muls vs 9."""
    X1, Y1, Z1, T1 = p
    y2mx2, y2px2, dt2 = q3
    a = _mul(Y1 - X1, y2mx2, pats, 1, 0)        # table is pre-balanced
    b = _mul(Y1 + X1, y2px2, pats, 1, 0)
    c = _mul(T1, dt2, pats, 0, 0)
    d = Z1 + Z1                                 # 2R; sums below carry
    e = _carry(b - a)
    ff = _carry(d - c)
    g = _carry(d + c)
    h = _carry(b + a)
    return (_mul(e, ff, pats, 0, 0), _mul(g, h, pats, 0, 0),
            _mul(ff, g, pats, 0, 0), _mul(e, h, pats, 0, 0))


def _decompress(b, d_col, sqrt_m1, four_p, pats):
    """b: [32, B] int32 byte values -> (x, y, ok) limb-major [24, B]."""
    sign = b[31:32] >> 7
    yb = jnp.concatenate([b[:31], b[31:32] & 0x7F], axis=0)
    y = _from_bytes(yb)
    one = jnp.concatenate(
        [jnp.ones_like(y[0:1]), jnp.zeros_like(y[1:])], axis=0)
    _sqr = _make_sqr(pats)
    yy = _sqr(y, ca=1)              # y is raw byte digits -> one pass
    u = yy - one                    # resting + O(1)
    v = _mul(yy, d_col, pats, 0, 0) + one
    v3 = _mul(_sqr(v), v, pats, 0, 0)
    v7 = _mul(_sqr(v3), v, pats, 0, 0)
    x = _mul(_mul(u, v3, pats, 0, 0),
             _pow_p58(_mul(u, v7, pats, 0, 0), pats), pats, 0, 0)
    vxx = _mul(v, _sqr(x), pats, 0, 0)
    ok_direct = _eq(vxx, u, four_p)
    ok_flip = _eq(vxx, -u, four_p)
    x = jnp.where(ok_flip, _mul(x, sqrt_m1, pats, 0, 0), x)
    valid = ok_direct | ok_flip
    wrong_sign = _parity(x, four_p) != sign
    x = jnp.where(wrong_sign, -x, x)
    return x, y, valid


# --- constant tables --------------------------------------------------------

def _build_b_table_cols() -> np.ndarray:
    """Constant i·B table in affine-precomputed form, [16, 3, 24, 1]:
    (entry, (y-x | y+x | 2d·x·y), limb, bcast) — the shape
    _madd_affine consumes (entry 0 is the identity: (1, 1, 0))."""
    pts = [(0, 1)] + [ref.scalar_mult(i, ref.B) for i in range(1, 16)]
    out = np.zeros((16, 3, LIMBS, 1), np.int32)
    for i, (x, y) in enumerate(pts):
        out[i, 0, :, 0] = f24.balance(f24.to_limbs((y - x) % ref.P))
        out[i, 1, :, 0] = f24.balance(f24.to_limbs((y + x) % ref.P))
        out[i, 2, :, 0] = f24.balance(
            f24.to_limbs(2 * ref.D * x * y % ref.P))
    return out


_B_TABLE_NP = _build_b_table_cols()

# packed constants: D, 2D, sqrt(-1), 4p, pat1, pat2, then the B table.
# Field-element constants ship pre-balanced (one host-side carry) so
# they can enter the conv without a device-side input pass; 4p stays
# raw — _canonical's unsigned sweep depends on its exact digit rows.
_CONSTS_NP = np.concatenate([
    f24.balance(f24.to_limbs(ref.D)).reshape(LIMBS, 1),
    f24.balance(f24.to_limbs(2 * ref.D % ref.P)).reshape(LIMBS, 1),
    f24.balance(f24.to_limbs(ref.SQRT_M1)).reshape(LIMBS, 1),
    f24.FOUR_P_DIGITS.reshape(LIMBS, 1).astype(np.int32),
    f24.PAT_R1.reshape(LIMBS, 1).astype(np.int32),
    f24.PAT_R2.reshape(LIMBS, 1).astype(np.int32),
    _B_TABLE_NP.reshape(16 * 3 * LIMBS, 1),
], axis=0)


# --- the kernel -------------------------------------------------------------

def _kernel(a_ref, r_ref, swin_ref, kwin_ref, consts_ref, ok_ref,
            tab_ref):
    B = a_ref.shape[1]
    a_b = a_ref[:]
    r_b = r_ref[:]
    d_col = consts_ref[0:LIMBS]
    two_d = consts_ref[LIMBS:2 * LIMBS]
    sqrt_m1 = consts_ref[2 * LIMBS:3 * LIMBS]
    four_p = consts_ref[3 * LIMBS:4 * LIMBS]
    pats = (consts_ref[4 * LIMBS:5 * LIMBS],
            consts_ref[5 * LIMBS:6 * LIMBS])
    b_tab = consts_ref[6 * LIMBS:].reshape(16, 3, LIMBS, 1)

    ax, ay, a_ok = _decompress(a_b, d_col, sqrt_m1, four_p, pats)
    rx, ry, r_ok = _decompress(r_b, d_col, sqrt_m1, four_p, pats)
    zero = jnp.zeros((LIMBS, B), jnp.int32)
    one = jnp.concatenate(
        [jnp.ones((1, B), jnp.int32), zero[1:]], axis=0)

    # -A in extended coords
    nax, nay = -ax, ay
    nat = _mul(nax, nay, pats, 0, 0)

    # per-lane table of i·(-A), i=0..15, in VMEM scratch
    # tab layout: [16, 4*LIMBS, B]
    ident = jnp.concatenate([zero, one, one, zero], axis=0)
    tab_ref[0] = ident
    tab_ref[1] = jnp.concatenate([nax, nay, one, nat], axis=0)

    def build_body(i, _):
        prev = tab_ref[i]
        p = (prev[0:LIMBS], prev[LIMBS:2 * LIMBS],
             prev[2 * LIMBS:3 * LIMBS], prev[3 * LIMBS:])
        q = (nax, nay, one, nat)
        r = _ext_add(p, q, two_d, pats)
        tab_ref[i + 1] = jnp.concatenate(r, axis=0)
        return 0

    lax.fori_loop(1, 15, build_body, 0)

    def _where_tree(w, rows):
        """16-entry select as a binary where-tree over the window's 4
        index bits: 15 selects instead of 16 multiplies + 15 adds (the
        masked-sum form), ~2x fewer VPU ops.  Selected bounds are the
        max of the entries (no arithmetic on the values)."""
        bit = 1
        while len(rows) > 1:
            cond = (w & bit) != 0
            rows = [jnp.where(cond, rows[i + 1], rows[i])
                    for i in range(0, len(rows), 2)]
            bit <<= 1
        return rows[0]

    def select_lane_table(w):
        acc = _where_tree(w, [tab_ref[t] for t in range(16)])
        return (acc[0:LIMBS], acc[LIMBS:2 * LIMBS],
                acc[2 * LIMBS:3 * LIMBS], acc[3 * LIMBS:])

    def select_b_table(w):
        return tuple(_where_tree(w, [b_tab[t, cix] for t in range(16)])
                     for cix in range(3))

    def ladder_body(j, acc):
        # only the last doubling's output feeds an addition, so only
        # it needs the extended T coordinate (3 muls saved each on the
        # first three)
        for i in range(4):
            acc = _ext_double(acc, pats, need_t=(i == 3))
        w = (_WINDOWS - 1) - j
        sw = swin_ref[pl.ds(w, 1)]
        kw = kwin_ref[pl.ds(w, 1)]
        acc = _madd_affine(acc, select_b_table(sw), pats)
        acc = _ext_add(acc, select_lane_table(kw), two_d, pats)
        return acc

    acc = lax.fori_loop(0, _WINDOWS, ladder_body,
                        (zero, one, one, zero))

    # subtract R, clear cofactor, identity test — nothing after the
    # subtraction reads T again
    nrt = _mul(-rx, ry, pats, 0, 0)
    acc = _ext_add(acc, (-rx, ry, one, nrt), two_d, pats,
                   need_t=False)
    for _ in range(3):
        acc = _ext_double(acc, pats, need_t=False)
    X, Y, Z, _T = acc
    ok = _is_zero(X, four_p) & _eq(Y, Z, four_p) & a_ok & r_ok
    ok_ref[:] = jnp.broadcast_to(ok.astype(jnp.int32), (8, B))


@functools.partial(jax.jit, static_argnames=("interpret", "block"))
def _pallas_verify(a_cols, r_cols, s_win, k_win, interpret=False,
                   block=BLOCK):
    """a_cols, r_cols: [32, n] int32 byte values; s_win, k_win:
    [64, n] int32 nibble windows.  Returns ok [n] bool.  n must be a
    multiple of block."""
    n = a_cols.shape[1]
    if n % block != 0:
        raise ValueError(
            f"lane count {n} must be a multiple of block {block} — "
            "remainder lanes would never be written by the kernel")
    grid = n // block
    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((8, n), jnp.int32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((32, block), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((32, block), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_WINDOWS, block), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_WINDOWS, block), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_CONSTS_NP.shape[0], 1), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((8, block), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((16, 4 * LIMBS, block), jnp.int32),
        ],
        interpret=interpret,
    )(a_cols, r_cols, s_win, k_win, jnp.asarray(_CONSTS_NP))
    return out[0] != 0


def verify_cols(a_cols, r_cols, s_win, k_win, interpret=False,
                block=BLOCK):
    return _pallas_verify(a_cols, r_cols, s_win, k_win,
                          interpret=interpret, block=block)
