"""Data-parallel ed25519 verification on TPU (JAX/XLA).

This is the north-star offload (reference seam: crypto/ed25519/ed25519.go
BatchVerifier :189-222, consumed by types/validation.go verifyCommitBatch and
types/vote_set.go AddVote).  The design is TPU-first, not a port:

  * one fused XLA computation verifies N signatures in parallel: permissive
    (ZIP-215) point decompression, a 4-bit-windowed Straus ladder evaluating
    s·B - k·A per lane from precomputed tables, subtraction of R, cofactor
    clearing by three doublings, and a vectorized identity test;
  * field arithmetic is `ops.field` (32x8-bit limbs in int32);
  * verification is *cofactored* ([8](s·B - R - k·A) == 0) exactly like the
    reference's ZIP-215 semantics, so single and batch verdicts agree;
  * shapes are bucketed (powers of two) so each bucket compiles once;
  * the per-signature validity mask comes straight out of the kernel — no
    batch-equation fallback pass is needed to attribute failures.

Host-side work is limited to SHA-512 reductions mod L (cheap, OpenSSL via
hashlib) and nibble-window decomposition of the scalars.
"""
from __future__ import annotations

import functools
import hashlib
import os
from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..libs import tracing

_CACHE_CONFIGURED = False

# (kernel choice, bucket) shapes already dispatched in this process —
# the first dispatch of a shape pays tracing/compilation, so the
# flight-recorder span carries warm=False for it
_SEEN_SHAPES: set[tuple[str, int]] = set()

_DISPATCH_HIST = None


def _dispatch_histogram():
    """metrics v2: host_prep vs kernel_execute latency split per pad
    bucket, on the process-global registry (the chunk dispatcher has
    no node context; /metrics merges DEFAULT in).  ``warm`` separates
    first-dispatch compiles from steady-state execution so the
    execute distribution is not polluted by one-off trace+compile."""
    global _DISPATCH_HIST
    if _DISPATCH_HIST is None:
        from ..libs import metrics as libmetrics
        _DISPATCH_HIST = libmetrics.DEFAULT.histogram(
            "crypto", "kernel_dispatch_seconds",
            "ed25519 kernel dispatch phases (host_prep / "
            "kernel_execute) in seconds, by kernel, pad bucket and "
            "warm-shape flag.",
            labels=("phase", "kernel", "pad_bucket", "warm"),
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 5.0, 30.0, 120.0))
    return _DISPATCH_HIST


def enable_compilation_cache() -> None:
    """Point JAX's persistent compilation cache at a repo-local,
    host-feature-keyed directory so the kernel compiles once per
    bucket shape per machine, not once per process — and a cache
    carried to a different machine is simply not found rather than
    replayed with mismatched CPU features.  Called lazily on first
    kernel use; a cache dir already configured by the embedding
    application wins.  Override the location with
    COMETBFT_TPU_JAX_CACHE.

    Note: XLA:CPU may still log a feature-mismatch warning when
    replaying SAME-host entries — it bakes its own tuning pseudo-flags
    (+prefer-no-gather/-scatter) into the serialized executable's
    feature list, which the host detector never reports.  That
    residual warning is benign; the dangerous case (replaying a cache
    carried from a different CPU) is what the host keying removes."""
    global _CACHE_CONFIGURED
    if _CACHE_CONFIGURED:
        return
    _CACHE_CONFIGURED = True
    if jax.config.jax_compilation_cache_dir:
        return
    cache_dir = os.environ.get("COMETBFT_TPU_JAX_CACHE")
    if not cache_dir:
        # keyed by the CPU-feature fingerprint (shared with the
        # -march=native module loader): serialized XLA:CPU
        # executables are pinned to the compiling host's features,
        # and this tree persists across hosts between rounds
        from ..crypto._native_loader import _host_tag
        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
            ".jax_cache", _host_tag()[:12])
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from . import field
from ..crypto import _ed25519_ref as ref
from ..crypto.keys import BatchVerifier, PubKey

L = ref.L

# --- constants (host-computed once from the golden model) -------------------

_D = field.constant(ref.D)
_SQRT_M1 = field.constant(ref.SQRT_M1)
_ONE = field.constant(1)

# --- point arithmetic (extended twisted Edwards coordinates) ----------------

def _ext_add(p, q):
    """Unified add (add-2008-hwcd-3): complete for a=-1, handles doubling and
    the identity, so the Straus loop needs no special cases."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    a = field.mul(Y1 - X1, Y2 - X2)
    b = field.mul(Y1 + X1, Y2 + X2)
    c = field.mul(field.mul(T1, T2), _2D)
    d = field.mul_const(field.mul(Z1, Z2), 2)
    e = b - a
    f = d - c
    g = d + c
    h = b + a
    return (field.mul(e, f), field.mul(g, h),
            field.mul(f, g), field.mul(e, h))


_2D = field.constant(2 * ref.D % ref.P)


def _ext_double(p):
    """Dedicated doubling (dbl-2008-hwcd, a=-1): 4 squarings + 4 products —
    one multiply and several adds cheaper than the unified add, and the
    ladder is ~2/3 doublings."""
    X1, Y1, Z1, _ = p
    a = field.sqr(X1)
    b = field.sqr(Y1)
    c = field.mul_const(field.sqr(Z1), 2)
    e = field.sqr(X1 + Y1) - a - b
    g = b - a                      # D + B with D = -A
    f = g - c
    h = -(a + b)                   # D - B
    return (field.mul(e, f), field.mul(g, h),
            field.mul(f, g), field.mul(e, h))


def _identity(batch_shape):
    z = jnp.zeros(batch_shape + (field.LIMBS,), jnp.int32)
    one = jnp.zeros(batch_shape + (field.LIMBS,), jnp.int32).at[..., 0].set(1)
    return (z, one, one, z)


def _is_identity(p):
    X, Y, Z, _ = p
    return field.is_zero(X) & field.eq(Y, Z)


# --- decompression (ZIP-215 permissive) -------------------------------------

def _decompress(b: jnp.ndarray):
    """[..., 32] uint8 -> (x, y, valid). Non-canonical y (>= p) accepted;
    'negative zero' x accepted (reference semantics: ed25519.go:36-44;
    golden model crypto/_ed25519_ref.decompress)."""
    sign = (b[..., 31] >> 7).astype(jnp.int32)
    y_bytes = b.at[..., 31].set(b[..., 31] & 0x7F)
    y = field.bytes_to_limbs(y_bytes)
    yy = field.sqr(y)
    u = yy - _ONE
    v = field.mul(yy, _D) + _ONE
    v3 = field.mul(field.sqr(v), v)
    v7 = field.mul(field.sqr(v3), v)
    x = field.mul(field.mul(u, v3), field.pow_p58(field.mul(u, v7)))
    vxx = field.mul(v, field.sqr(x))
    ok_direct = field.eq(vxx, u)
    ok_flip = field.eq(vxx, -u)
    x = jnp.where(ok_flip[..., None], field.mul(x, _SQRT_M1), x)
    valid = ok_direct | ok_flip
    wrong_sign = field.parity(x) != sign
    x = jnp.where(wrong_sign[..., None], -x, x)
    return x, y, valid


def _to_ext(x, y):
    one = jnp.zeros(x.shape, jnp.int32).at[..., 0].set(1)
    return (x, y, one, field.mul(x, y))


def _neg_ext(p):
    X, Y, Z, T = p
    return (-X, Y, Z, -T)


# --- the verification kernel ------------------------------------------------

# Constant 4-bit window table for the base point: i·B for i in 0..15, in
# extended coordinates (X, Y, Z=1, T=XY), one [16, 32] limb array per
# coordinate.  Host-computed once from the golden model.
def _build_b_table() -> tuple[np.ndarray, ...]:
    pts = [(0, 1)] + [ref.scalar_mult(i, ref.B) for i in range(1, 16)]
    X = np.stack([field.to_limbs(x) for x, _ in pts])
    Y = np.stack([field.to_limbs(y) for _, y in pts])
    Z = np.stack([field.to_limbs(1)] * 16)
    T = np.stack([field.to_limbs(x * y % ref.P) for x, y in pts])
    return X, Y, Z, T


_B_TABLE = tuple(jnp.asarray(c) for c in _build_b_table())
_WINDOWS = 64          # 256 bits as 64 4-bit little-endian windows


def _gather_const_table(table, idx):
    """table: [16, 32] constant; idx: [n] int32 -> [n, 32]."""
    return tuple(jnp.take(c, idx, axis=0) for c in table)


def _gather_lane_table(table, idx):
    """table: [16, n, 32] per-lane; idx: [n] int32 -> [n, 32]."""
    ix = idx[None, :, None]
    return tuple(
        jnp.take_along_axis(c, ix, axis=0)[0] for c in table)


def _verify_kernel(a_bytes, r_bytes, s_win, k_win):
    """Verify N signatures in parallel (interleaved windowed Straus).

    a_bytes, r_bytes: [n, 32] uint8 compressed points (pubkey A, nonce R)
    s_win, k_win:     [64, n] int32 — 4-bit little-endian windows of S and
                      k = SHA512(R||A||msg) mod L
    Returns ok: [n] bool — per-signature ZIP-215 verdicts.

    Evaluates [8](s·B - R - k·A) == identity with a 4-bit windowed ladder:
    per window, 4 doublings + 2 unified adds from precomputed tables
    (constant i·B table; per-lane i·(-A) table built with 15 adds).  The
    unified addition handles identity entries, so window value 0 needs no
    special case — there are no per-bit selects at all.
    """
    ax, ay, a_ok = _decompress(a_bytes)
    rx, ry, r_ok = _decompress(r_bytes)
    neg_a = _neg_ext(_to_ext(ax, ay))
    neg_r = _neg_ext(_to_ext(rx, ry))
    n = a_bytes.shape[0]

    # per-lane table of i·(-A), i in 0..15: [16, n, 32] per coordinate
    entries = [_identity((n,)), neg_a]
    for _ in range(14):
        entries.append(_ext_add(entries[-1], neg_a))
    neg_a_tab = tuple(
        jnp.stack([e[c] for e in entries]) for c in range(4))

    def body(j, acc):
        for _ in range(4):
            acc = _ext_double(acc)
        w = (_WINDOWS - 1) - j
        sw = lax.dynamic_index_in_dim(s_win, w, axis=0, keepdims=False)
        kw = lax.dynamic_index_in_dim(k_win, w, axis=0, keepdims=False)
        acc = _ext_add(acc, _gather_const_table(_B_TABLE, sw))
        acc = _ext_add(acc, _gather_lane_table(neg_a_tab, kw))
        return acc

    # derive the identity init from a (possibly sharded) input so its sharding
    # "varying" type matches the loop body under shard_map
    lane_zero = (s_win[0] * 0)[:, None]
    zero = jnp.zeros((n, field.LIMBS), jnp.int32) + lane_zero
    one = zero.at[..., 0].set(1) + lane_zero
    acc = lax.fori_loop(0, _WINDOWS, body, (zero, one, one, zero))
    acc = _ext_add(acc, neg_r)
    for _ in range(3):                  # cofactor clearing: [8]·
        acc = _ext_double(acc)
    return _is_identity(acc) & a_ok & r_ok


_jit_verify = jax.jit(_verify_kernel)


# --- host orchestration -----------------------------------------------------

_BASE_BUCKETS = (64, 1024, 4096, 10240, 16384)
_BUCKETS = list(_BASE_BUCKETS)
_IDENTITY_BYTES = bytes([1] + [0] * 31)     # compressed identity (y=1)
_B_BYTES = ref.compress(ref.B)


def _bucket(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    return _BUCKETS[-1]


# --- measured pad-bucket refinement -----------------------------------------
# The base buckets have a 16x gap at the bottom (64 -> 1024): a 100-sig
# commit pads 10x.  On the CPU/XLA path kernel cost scales with padded
# lanes, so that gap is real wasted work — but each extra bucket costs
# a fresh compile, so refinement must be earned by measurement, not
# hardcoded.  The host_prep vs kernel_execute split (already observed
# per dispatch into the metrics-v2 histogram) is the steering signal:
# refine only when kernel_execute dominates host_prep for repeatedly
# low-occupancy warm dispatches of a bucket (on a TPU the kernel is so
# fast that padding costs ~nothing and host_prep dominates — no
# refinement there).

_REFINE_CANDIDATES = (128, 256, 512, 2048)
_TUNE_MIN_SAMPLES = 8
_TUNE_WINDOW = 64
_tune_samples: dict[int, list] = {}     # bucket -> [(n, prep_s, exec_s)]
_REFINED_COUNTER = None


def reset_bucket_tuning() -> None:
    """Test hook: drop refined buckets and samples."""
    global _BUCKETS
    _BUCKETS = list(_BASE_BUCKETS)
    _tune_samples.clear()


def _tune_record(n: int, m: int, prep_s: float, exec_s: float) -> None:
    if os.environ.get("COMETBFT_TPU_BUCKET_TUNE", "1") == "0":
        return
    samples = _tune_samples.setdefault(m, [])
    samples.append((n, prep_s, exec_s))
    if len(samples) > _TUNE_WINDOW:
        samples.pop(0)
    lows = [s for s in samples if s[0] <= m // 2]
    if len(lows) < _TUNE_MIN_SAMPLES:
        return
    lows_sorted = sorted(p for _, p, _ in lows)
    execs_sorted = sorted(e for _, _, e in lows)
    med_prep = lows_sorted[len(lows_sorted) // 2]
    med_exec = execs_sorted[len(execs_sorted) // 2]
    # host_prep-dominated (TPU shape): padding wastes almost nothing
    if med_exec < 2 * med_prep:
        return
    target = max(s[0] for s in lows)
    prev = 0
    for b in _BUCKETS:
        if b >= m:
            break
        prev = b
    for cand in _REFINE_CANDIDATES:
        if cand >= m or cand in _BUCKETS or cand < target or \
                cand <= prev:
            continue
        _BUCKETS.append(cand)
        _BUCKETS.sort()
        samples.clear()
        _refine_counter().add()
        return


def _refine_counter():
    global _REFINED_COUNTER
    if _REFINED_COUNTER is None:
        from ..libs import metrics as libmetrics
        _REFINED_COUNTER = libmetrics.DEFAULT.counter(
            "crypto", "pad_bucket_refinements",
            "Pad buckets inserted by the measured host_prep/"
            "kernel_execute steering (small batches were "
            "padding into oversized buckets).")
    return _REFINED_COUNTER


def _windows_u8(scalars: np.ndarray) -> np.ndarray:
    """[m, 32] uint8 little-endian scalars -> [m, 64] uint8 4-bit
    windows, lane-major (window 2i = low nibble of byte i, window
    2i+1 = high nibble) — the host-side wire layout; the device casts
    and transposes to the kernels' window-major int32."""
    m = scalars.shape[0]
    win = np.empty((m, 64), np.uint8)
    win[:, 0::2] = scalars & 0x0F
    win[:, 1::2] = scalars >> 4
    return win


def _win_cols(w8):
    """Device-side: [m, 64] uint8 lane-major windows -> [64, m] int32."""
    return jnp.transpose(w8).astype(jnp.int32)


def _byte_cols(b8):
    """Device-side: [m, 32] uint8 byte rows -> [32, m] int32 columns."""
    return jnp.transpose(b8).astype(jnp.int32)


def _verify_packed(a8, r8, s8, k8):
    """The xla kernel behind the packed uint8 wire layout: inputs are
    [m,32]/[m,64] uint8 host arrays (4x smaller transfers than the
    int32 device layouts — the e2e profile on the tunneled v5e was
    transfer-dominated); unpacking runs on device."""
    return _verify_kernel(a8, r8, _win_cols(s8), _win_cols(k8))


_jit_verify_packed = jax.jit(_verify_packed)
# the pipelined dispatch's TPU variant: per-tile input buffers are
# never reused, so donating them caps device memory at two in-flight
# tiles.  Separate executable cache key — TPU-only (see
# _dispatch_async).
_jit_verify_packed_donated = jax.jit(_verify_packed,
                                     donate_argnums=(0, 1, 2, 3))


@functools.partial(jax.jit,
                   static_argnames=("kernel", "interpret", "block"))
def _pallas_verify_packed(a8, r8, s8, k8, kernel="pallas",
                          interpret=False, block=0):
    """The pallas kernel behind the packed uint8 wire layout."""
    ep = _pallas_module(kernel)
    return ep.verify_cols(_byte_cols(a8), _byte_cols(r8),
                          _win_cols(s8), _win_cols(k8),
                          interpret=interpret, block=block or ep.BLOCK)


def verify_batch(
    items: Sequence[tuple[bytes, bytes, bytes]],
) -> tuple[bool, list[bool]]:
    """Verify [(pub, msg, sig), ...] on the default JAX device.

    Batches above one pipeline tile (crypto/pipeline.tile_size,
    default 4096 — a pad-bucket shape) run as an overlapped tile
    pipeline: while tile i executes under JAX's async dispatch, the
    host preps tile i+1 (decompress staging, sign-bytes packing,
    padding), so the measured ~3x host-work share of the e2e TPU path
    (KERNEL_NOTES: 452 ms e2e vs 116 ms device-only at 10k) stops
    serializing with the kernel.  Smaller batches keep the monolithic
    single-bucket dispatch.

    Returns (all_valid, per_sig_mask) — the reference BatchVerifier.Verify
    contract (crypto/crypto.go:47).
    """
    n = len(items)
    if n == 0:
        return True, []
    from ..crypto.pipeline import tile_size
    tile = _bucket(tile_size())
    if n <= tile:
        out = np.zeros(n, bool)
        out[:] = _verify_chunk(items)
        return bool(out.all()), out.tolist()
    return _verify_pipelined(items, tile)


def _verify_pipelined(items, tile: int) -> tuple[bool, list[bool]]:
    """Tiled, overlapped dispatch: host_prep of tile i+1 runs while
    tile i's kernel executes (JAX async dispatch — the jitted call
    returns a device future; np.asarray at settle time blocks).
    Multi-chip meshes pre-partition ONCE per pipeline
    (parallel/mesh.PipelinePartitioner) so per-tile dispatch pays no
    mesh/sharding re-resolution."""
    import time as _time

    from ..crypto.pipeline import overlap_histogram, tile_plan

    enable_compilation_cache()
    n = len(items)
    choice = _kernel_choice()
    hist = _dispatch_histogram()
    part = None
    ndev = _device_count()
    if ndev > 1 and tile >= _shard_min():
        from ..parallel import mesh as pmesh
        part = pmesh.pipeline_partitioner(ndev, kernel=choice)
    out = np.zeros(n, bool)
    plan = tile_plan(n, tile)
    t_run0 = _time.perf_counter()
    phase_s = 0.0
    inflight = None         # (lo, hi, m, warm, pre_bad, force, t_disp)

    def settle(inflight, prep_inside: float):
        lo, hi, m, warm, pre_bad, force, t_disp = inflight
        pad_bucket = str(m)
        with tracing.span(tracing.CRYPTO, "kernel_execute",
                          batch=hi - lo, bucket=m, kernel=choice,
                          warm=warm, pipelined=True):
            ok = force()
        t1 = _time.perf_counter()
        # dispatch -> settled: the window the device (or the XLA
        # runtime thread) owned the tile, i.e. what host_prep of the
        # NEXT tile overlapped with
        hist.with_labels("kernel_execute", choice, pad_bucket,
                         "1" if warm else "0").observe(t1 - t_disp)
        ok = np.asarray(ok)[:hi - lo].copy()
        ok[pre_bad[:hi - lo]] = False
        out[lo:hi] = ok
        # the overlap-ratio kernel phase subtracts the NEXT tile's
        # host_prep, which by construction sits inside this envelope
        # (stage(i+1) runs between dispatch(i) and settle(i)) — else
        # a pipeline whose device did nothing until the force would
        # still read ~2.0 "overlap"; what remains above the contained
        # prep is execution the async dispatch genuinely hid
        return max(0.0, (t1 - t_disp) - prep_inside)

    for lo, hi in plan:
        chunk = items[lo:hi]
        m = _bucket(hi - lo)
        if choice.startswith("pallas"):
            m = max(m, _pallas_module(choice).BLOCK)
        warm = (choice, m) in _SEEN_SHAPES
        pad_bucket = str(m)
        t0 = _time.perf_counter()
        with tracing.span(tracing.CRYPTO, "host_prep", batch=hi - lo,
                          bucket=m, pipelined=True):
            a_b, r_b, s_w8, k_w8, pre_bad = prep_arrays(chunk, m)
        t1 = _time.perf_counter()
        hist.with_labels("host_prep", choice, pad_bucket,
                         "1" if warm else "0").observe(t1 - t0)
        phase_s += t1 - t0
        force = _dispatch_async(a_b, r_b, s_w8, k_w8, choice=choice,
                                m=m, part=part)
        t_disp = _time.perf_counter()
        _SEEN_SHAPES.add((choice, m))
        if inflight is not None:
            phase_s += settle(inflight, prep_inside=t1 - t0)
        inflight = (lo, hi, m, warm, pre_bad, force, t_disp)
    phase_s += settle(inflight, prep_inside=0.0)
    wall = _time.perf_counter() - t_run0
    if wall > 0:
        overlap_histogram().observe(phase_s / wall)
    return bool(out.all()), out.tolist()


def _dispatch_async(a_b, r_b, s_w8, k_w8, *, choice: str, m: int,
                    part=None):
    """Dispatch the selected kernel WITHOUT forcing the result.

    Returns a zero-arg ``force()`` whose np.asarray blocks until the
    device (or XLA runtime thread) finishes — the pipeline settles
    tile i only after tile i+1 is already in flight.  Transfers use
    non-blocking ``jax.device_put``; on TPU platforms the xla kernel
    runs a donated-argument jit so each tile's input buffers free the
    moment the kernel consumes them (a pipeline keeps two tiles of
    buffers live instead of accumulating them)."""
    if part is not None:
        dev = part.dispatch(a_b, r_b, s_w8, k_w8)
        return lambda: np.asarray(dev)
    try:
        tpu = jax.default_backend() in TPU_PLATFORMS
    except RuntimeError:        # no backend could initialize
        tpu = False
    if tpu and choice in ("pallas", "xla") and \
            os.environ.get("COMETBFT_TPU_AOT", "1") != "0":
        from . import aot
        dev = aot.call(choice, jnp.asarray(a_b), jnp.asarray(r_b),
                       jnp.asarray(s_w8), jnp.asarray(k_w8))
        if dev is not None:
            return lambda: np.asarray(dev)
    da = jax.device_put(a_b)
    dr = jax.device_put(r_b)
    ds = jax.device_put(s_w8)
    dk = jax.device_put(k_w8)
    if choice.startswith("pallas"):
        dev = _pallas_verify_packed(da, dr, ds, dk, kernel=choice)
    elif tpu:
        # donation changes the executable cache key, so the donated
        # variant is TPU-only — on CPU it would force a second
        # multi-minute XLA compile of the same bucket for no benefit
        dev = _jit_verify_packed_donated(da, dr, ds, dk)
    else:
        dev = _jit_verify_packed(da, dr, ds, dk)
    return lambda: np.asarray(dev)


# Platforms whose devices run the Mosaic/Pallas TPU kernels.  The
# pooled chip may register under its plugin name ("axon") rather than
# "tpu" — but the set is an ALLOWLIST: a GPU or unknown accelerator
# would fail the TPU lowering on every batch (ADVICE r5 #1), so
# anything not listed here takes the portable XLA/CPU path.
TPU_PLATFORMS = frozenset({"tpu", "axon"})


def _kernel_choice() -> str:
    """'pallas' (fused Mosaic 24-limb kernel; TPU), 'pallas8' (the
    first-generation 32x8-bit kernel) or 'xla' (portable).

    COMETBFT_TPU_KERNEL=pallas|pallas8|xla overrides; auto picks
    pallas on known TPU platforms only — on CPU the pallas path would
    run interpreted, and on GPUs/unknown accelerators it would fail
    to lower."""
    choice = os.environ.get("COMETBFT_TPU_KERNEL", "auto").lower()
    if choice in ("pallas", "pallas8", "xla"):
        return choice
    try:
        platform = jax.devices()[0].platform
    except Exception:
        return "xla"
    return "pallas" if platform in TPU_PLATFORMS else "xla"


def _pallas_module(choice: str):
    """The Pallas kernel module for a 'pallas*' choice ('pallas' is
    the 24-limb kernel, 'pallas8' the first-generation byte kernel)."""
    if choice == "pallas8":
        from . import ed25519_pallas8 as ep8
        return ep8
    from . import ed25519_pallas as ep
    return ep


def _verify_chunk(items) -> np.ndarray:
    enable_compilation_cache()
    n = len(items)
    m = _bucket(n)
    choice = _kernel_choice()
    if choice.startswith("pallas"):
        m = max(m, _pallas_module(choice).BLOCK)
    import time as _time
    warm = (choice, m) in _SEEN_SHAPES
    hist = _dispatch_histogram()
    t0 = _time.perf_counter()
    with tracing.span(tracing.CRYPTO, "host_prep", batch=n,
                      bucket=m):
        a_b, r_b, s_win, k_win, pre_bad = prep_arrays(items, m)
    t1 = _time.perf_counter()
    # compile-vs-execute attribution: the first dispatch of a
    # (kernel, bucket) shape includes trace+compile (unless the AOT
    # artifact or persistent cache serves it); warm dispatches are
    # pure execution
    with tracing.span(tracing.CRYPTO, "kernel_execute", batch=n,
                      bucket=m, kernel=choice, warm=warm):
        out = _dispatch(n, a_b, r_b, s_win, k_win, pre_bad)
    t2 = _time.perf_counter()
    w = "1" if warm else "0"
    hist.with_labels("host_prep", choice, str(m), w).observe(t1 - t0)
    hist.with_labels("kernel_execute", choice, str(m),
                     w).observe(t2 - t1)
    if warm:
        # only warm dispatches steer bucket refinement — a cold one
        # includes trace+compile, which is exactly the cost refinement
        # must NOT mistake for per-lane kernel work
        _tune_record(n, m, t1 - t0, t2 - t1)
    _SEEN_SHAPES.add((choice, m))
    return out


def prep_arrays(items, m: int):
    """The full host-side prep for a batch of (pub, msg, sig) items,
    padded to m lanes: length/canonical-S checks, k = SHA-512(R||A||msg)
    mod L, 4-bit window split.  Returns (a_b [m,32]u8, r_b [m,32]u8,
    s_w8 [m,64]u8, k_w8 [m,64]u8, pre_bad [m]bool) — the packed uint8
    wire layout; the device transposes/casts to the kernels' int32
    layouts (the tunneled-TPU e2e profile is transfer-bound, so the
    wire stays at 1 byte per element).  Uses the one-pass C prep when
    the native module is built, else the vectorized numpy path."""
    from ..crypto._native_loader import load as _load_native
    native = _load_native(allow_build=False)
    if native is not None and hasattr(native, "ed25519_prep"):
        # the ENTIRE host prep in one C pass (length checks,
        # canonical-S, k = SHA-512(R||A||msg) mod L, window split),
        # threaded across cores with the GIL released
        a_buf, r_buf, sw_buf, kw_buf, bad_buf = native.ed25519_prep(
            items, m, _B_BYTES, _IDENTITY_BYTES)
        a_b = np.frombuffer(a_buf, np.uint8).reshape(m, 32)
        r_b = np.frombuffer(r_buf, np.uint8).reshape(m, 32)
        s_w8 = np.frombuffer(sw_buf, np.uint8).reshape(m, 64)
        k_w8 = np.frombuffer(kw_buf, np.uint8).reshape(m, 64)
        pre_bad = np.frombuffer(bad_buf, np.uint8).astype(bool)
        return a_b, r_b, s_w8, k_w8, pre_bad

    a_b = np.zeros((m, 32), np.uint8)
    r_b = np.zeros((m, 32), np.uint8)
    s_raw = np.zeros((m, 32), np.uint8)
    k_raw = np.zeros((m, 32), np.uint8)
    # padding lanes verify trivially: 0·B - identity - 0·A == identity
    a_b[:] = np.frombuffer(_B_BYTES, np.uint8)
    r_b[:] = np.frombuffer(_IDENTITY_BYTES, np.uint8)
    pre_bad = np.zeros(m, bool)

    # ---- host prep, vectorized (it sits inside the <5 ms e2e budget:
    # a python per-item loop alone costs ~40 ms at 10k sigs) ----------
    good_idx = []
    pubs = []
    rs = []
    ss = []
    hashed = []            # R || A || msg per good item
    for i, (pub, msg, sig) in enumerate(items):
        if len(pub) != 32 or len(sig) != 64:
            pre_bad[i] = True
            continue
        good_idx.append(i)
        pubs.append(pub)
        rs.append(sig[:32])
        ss.append(sig[32:])
        hashed.append(sig[:32] + pub + msg)
    if good_idx:
        gi = np.asarray(good_idx)
        a_g = np.frombuffer(b"".join(pubs), np.uint8).reshape(-1, 32)
        r_g = np.frombuffer(b"".join(rs), np.uint8).reshape(-1, 32)
        s_g = np.frombuffer(b"".join(ss), np.uint8).reshape(-1, 32)
        # non-canonical S (>= L) rejection, vectorized as a
        # lexicographic big-endian compare (ZIP-215 requires S < L)
        s_be = s_g[:, ::-1]
        L_be = np.frombuffer(L.to_bytes(32, "big"), np.uint8)
        neq = s_be != L_be
        first = np.argmax(neq, axis=1)
        differs = neq.any(axis=1)
        s_ok = differs & (s_be[np.arange(len(gi)), first] <
                          L_be[first])
        pre_bad[gi[~s_ok]] = True
        # k = SHA-512(R || A || msg) mod L via the python reference —
        # this branch runs when the native module is absent or lacks
        # ed25519_prep (both native entry points ship together, so a
        # partial module cannot occur through our own loader)
        k_g = np.zeros((len(gi), 32), np.uint8)
        for j, buf in enumerate(hashed):
            k = ref.sha512_mod_l(buf[:32], buf[32:64], buf[64:])
            k_g[j] = np.frombuffer(k.to_bytes(32, "little"),
                                   np.uint8)
        keep = np.asarray(s_ok)
        a_b[gi[keep]] = a_g[keep]
        r_b[gi[keep]] = r_g[keep]
        s_raw[gi[keep]] = s_g[keep]
        k_raw[gi[keep]] = k_g[keep]
    return a_b, r_b, _windows_u8(s_raw), _windows_u8(k_raw), pre_bad


def _try_aot(choice: str, interpret: bool, a_b, r_b, s_w8, k_w8):
    """On a live TPU, prefer the committed AOT-exported artifact for
    this kernel+bucket (zero tracing; stable cache key).  Returns the
    ok array or None to fall through to plain jit.  Opt out with
    COMETBFT_TPU_AOT=0."""
    if interpret or os.environ.get("COMETBFT_TPU_AOT", "1") == "0":
        return None
    try:
        # artifacts are TPU-lowered: only attempt them on a known TPU
        # platform name (allowlist, not "anything non-cpu" — a GPU
        # would fail the deserialized program on every batch)
        if jax.default_backend() not in TPU_PLATFORMS:
            return None
    except Exception:
        return None
    if choice not in ("pallas", "xla"):
        return None     # no committed artifacts for fallback kernels
    from . import aot
    out = aot.call(choice, jnp.asarray(a_b), jnp.asarray(r_b),
                   jnp.asarray(s_w8), jnp.asarray(k_w8))
    return None if out is None else np.asarray(out)


def _device_count() -> int:
    try:
        return len(jax.devices())
    except Exception:
        return 1


def _shard_min() -> int:
    """Smallest padded batch that auto-shards over a multi-device
    mesh.  Small batches stay single-device — the collective + copy
    overhead dwarfs the kernel there."""
    return int(os.environ.get("COMETBFT_TPU_SHARD_MIN", "1024"))


def _dispatch(n: int, a_b, r_b, s_w8, k_w8, pre_bad, *,
              kernel: str = "", interpret: bool = False,
              block: int = 0) -> np.ndarray:
    """Run the selected kernel on prepped arrays.  kernel/interpret/
    block override the environment-driven choice (used by the
    interpret-mode Pallas parity tests, which exercise this exact
    path with a small block).

    Multi-chip: when more than one JAX device is visible and the
    padded batch is at least COMETBFT_TPU_SHARD_MIN lanes, the batch
    shards data-parallel over the full device mesh
    (parallel/mesh.py; SURVEY §2.11)."""
    choice = kernel or _kernel_choice()
    ndev = _device_count()
    if ndev > 1 and n >= _shard_min():
        from ..parallel import mesh as pmesh
        ok = pmesh.verify_sharded(
            a_b, r_b, s_w8, k_w8, ndev=ndev, kernel=choice,
            interpret=interpret, block=block)
    elif (aot_ok := _try_aot(choice, interpret, a_b, r_b, s_w8,
                             k_w8)) is not None:
        ok = aot_ok
    elif choice.startswith("pallas"):
        ok = np.asarray(_pallas_verify_packed(
            jnp.asarray(a_b), jnp.asarray(r_b), jnp.asarray(s_w8),
            jnp.asarray(k_w8), kernel=choice, interpret=interpret,
            block=block))
    else:
        ok = np.asarray(_jit_verify_packed(
            jnp.asarray(a_b), jnp.asarray(r_b),
            jnp.asarray(s_w8), jnp.asarray(k_w8)))
    ok = ok[:n].copy()
    ok[pre_bad[:n]] = False
    return ok


def warmup(n: int) -> None:
    """Pre-compile the kernel for the bucket covering n lanes."""
    _warmup_bucket(_bucket(n))


@functools.lru_cache(maxsize=None)
def _warmup_bucket(m: int) -> None:
    enable_compilation_cache()
    choice = _kernel_choice()
    if choice.startswith("pallas"):
        m = max(m, _pallas_module(choice).BLOCK)
    a = np.tile(np.frombuffer(_B_BYTES, np.uint8), (m, 1))
    r = np.tile(np.frombuffer(_IDENTITY_BYTES, np.uint8), (m, 1))
    z = np.zeros((m, _WINDOWS), np.uint8)
    with tracing.span(tracing.CRYPTO, "kernel_compile", bucket=m,
                      kernel=choice) as sp:
        if _try_aot(choice, False, a, r, z, z) is not None:
            sp.note(aot=True)   # artifact served it: no compile paid
        elif choice.startswith("pallas"):
            np.asarray(_pallas_verify_packed(
                jnp.asarray(a), jnp.asarray(r), jnp.asarray(z),
                jnp.asarray(z), kernel=choice))
        else:
            _jit_verify_packed(jnp.asarray(a), jnp.asarray(r),
                               jnp.asarray(z),
                               jnp.asarray(z)).block_until_ready()
    _SEEN_SHAPES.add((choice, m))


class TpuBatchVerifier(BatchVerifier):
    """BatchVerifier backed by the XLA kernel (reference contract:
    crypto/crypto.go:47-55; created via crypto/batch.py dispatch)."""

    def __init__(self):
        self._items: list[tuple[bytes, bytes, bytes]] = []

    def add(self, pub_key: PubKey, msg: bytes, sig: bytes) -> None:
        if pub_key.type() != "ed25519":
            raise TypeError("TpuBatchVerifier requires ed25519 keys")
        if len(sig) != 64:
            raise ValueError("malformed signature")
        self._items.append((pub_key.bytes(), bytes(msg), bytes(sig)))

    def __len__(self) -> int:
        return len(self._items)

    def verify(self) -> tuple[bool, Sequence[bool]]:
        return verify_batch(self._items)


# keep crypto/batch.pad_bucket in lockstep with the live (possibly
# measurement-refined) bucket ladder — both label the same histograms
from ..crypto import batch as _crypto_batch  # noqa: E402

_crypto_batch.register_pad_bucket_fn(_bucket)
