"""Node assembly: wiring every subsystem into one process."""
from .node import Node, init_files

__all__ = ["Node", "init_files"]
