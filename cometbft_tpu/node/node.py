"""Node: assembles DBs, state, ABCI, mempool, consensus, p2p, RPC.

Reference: node/node.go:53 (Node struct), node/setup.go:102-750 (the
constructors), boot order in OnStart (:~370): RPC listeners → transport
listen → switch start (dials peers) → consensus.
"""
from __future__ import annotations

import asyncio
import os
from typing import Optional

from ..abci.client import AppConns, ClientCreator
from ..abci.kvstore import KVStoreApplication
from ..config import Config
from ..consensus.reactor import ConsensusReactor
from ..consensus.replay import Handshaker, ReplayError, catchup_replay
from ..consensus.state import ConsensusState
from ..consensus.wal import WAL, CorruptWALError
from ..db import new_db
from ..libs.log import Logger, new_logger, set_level
from ..mempool import CListMempool
from ..mempool.reactor import MempoolReactor
from ..p2p.key import NodeKey
from ..p2p.switch import Switch
from ..privval import FilePV
from ..state import make_genesis_state
from ..state.execution import BlockExecutor
from ..state.store import Store
from ..store import BlockStore
from ..types.events import EventBus
from ..types.genesis import GenesisDoc, pub_key_to_json
from ..abci import types as abci


class NodeError(Exception):
    pass


def init_files(config: Config, chain_id: str = "") -> GenesisDoc:
    """`cometbft init`: write node key, priv validator, genesis
    (reference: cmd/cometbft/commands/init.go)."""
    import secrets as _secrets
    from ..types.genesis import GenesisValidator
    from ..types.timestamp import Timestamp

    home = config.base.home
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)

    pv = FilePV.load_or_generate(
        config.base.path(config.base.priv_validator_key_file),
        config.base.path(config.base.priv_validator_state_file))
    NodeKey.load_or_gen(config.base.path(config.base.node_key_file))

    genesis_path = config.base.path(config.base.genesis_file)
    if os.path.exists(genesis_path):
        return GenesisDoc.from_file(genesis_path)
    doc = GenesisDoc(
        chain_id=chain_id or f"test-chain-{_secrets.token_hex(3)}",
        genesis_time=Timestamp.now(),
        validators=[GenesisValidator(
            address=b"", pub_key=pv.get_pub_key(), power=10)],
    )
    doc.validate_and_complete()
    doc.save_as(genesis_path)
    return doc


class Node:
    def __init__(self, config: Config,
                 app=None,
                 genesis_doc: Optional[GenesisDoc] = None,
                 logger: Optional[Logger] = None):
        self.config = config
        from ..config import validate_basic
        validate_basic(config)
        self.logger = logger if logger is not None else \
            new_logger("node")
        set_level(config.base.log_level)
        home = config.base.home
        db_dir = config.base.path(config.base.db_dir)

        # flight recorder (libs/tracing.py): the always-on span rings
        # every subsystem appends to; crash dumps land in the data dir
        # unless instrumentation.dump_dir points elsewhere
        from ..libs import tracing
        dump_dir = config.instrumentation.dump_dir
        tracing.configure(
            enabled=config.instrumentation.trace_enabled,
            buffer_size=config.instrumentation.trace_buffer_size,
            categories=config.instrumentation.trace_categories or None,
            dump_dir=config.base.path(dump_dir) if dump_dir
            else db_dir,
            anchor_interval_s=config.instrumentation
            .trace_anchor_interval_s)
        from ..types import signature_cache
        signature_cache.set_default_capacity(
            config.base.signature_cache_size)

        # --- genesis & identity -----------------------------------------
        self.genesis_doc = genesis_doc if genesis_doc is not None else \
            GenesisDoc.from_file(config.base.path(
                config.base.genesis_file))
        self.node_key = NodeKey.load_or_gen(
            config.base.path(config.base.node_key_file))
        # stamp the recorder with our identity (the key loads after
        # configure) so every dump/scrape names the node it came from
        tracing.recorder().node_id = self.node_key.id[:12]
        if config.base.priv_validator_laddr:
            # remote signer: key lives in an external process
            # (reference: createAndStartPrivValidatorSocketClient,
            # setup.go:715); connection established in start()
            self.priv_validator = None
        else:
            self.priv_validator = FilePV.load_or_generate(
                config.base.path(config.base.priv_validator_key_file),
                config.base.path(config.base.priv_validator_state_file))

        # --- storage ----------------------------------------------------
        backend = config.base.db_backend
        self.block_store = BlockStore(new_db("blockstore", backend,
                                             db_dir))
        self.state_store = Store(new_db("state", backend, db_dir))

        # --- application ------------------------------------------------
        if app is None and config.base.abci in ("builtin",
                                                "builtin_unsync"):
            if config.base.proxy_app in ("kvstore", "persistent_kvstore"):
                # snapshots on by default so any builtin-kvstore node
                # can serve statesync joiners (reference: e2e kvstore
                # manifests set SnapshotInterval; snapshots are cheap)
                app = KVStoreApplication(
                    db=new_db("app", backend, db_dir),
                    snapshot_interval=10)
            else:
                raise NodeError(
                    f"unknown proxy_app {config.base.proxy_app!r} "
                    f"(pass an Application instance for custom apps)")
        self.app = app
        self.app_conns = ClientCreator(
            app=app, addr=config.base.proxy_app,
            transport=config.base.abci).new_app_conns()

        # --- state ------------------------------------------------------
        state = self.state_store.load()
        if state is None:
            state = make_genesis_state(self.genesis_doc)
            self.state_store.save(state)
        self.initial_state = state

        # --- event bus --------------------------------------------------
        self.event_bus = EventBus()

        # --- metrics: one shared registry, per-subsystem families fed
        # at the point of action (reference: per-package metrics.go,
        # served at /metrics) -------------------------------------------
        from ..abci.metrics import Metrics as ProxyMetrics
        from ..blocksync.metrics import Metrics as BlocksyncMetrics
        from ..consensus.metrics import Metrics as ConsensusMetrics
        from ..libs.metrics import Registry
        from ..libs.supervisor import Metrics as SupervisorMetrics
        from ..libs.supervisor import Supervisor
        from ..mempool.metrics import Metrics as MempoolMetrics
        from ..p2p.metrics import Metrics as P2PMetrics
        from ..state.metrics import Metrics as StateMetrics
        from ..statesync.metrics import Metrics as StatesyncMetrics
        self.metrics_registry = Registry()
        self.consensus_metrics = ConsensusMetrics(self.metrics_registry)
        self.mempool_metrics = MempoolMetrics(self.metrics_registry)
        self.p2p_metrics = P2PMetrics(self.metrics_registry)
        self.blocksync_metrics = BlocksyncMetrics(self.metrics_registry)
        self.statesync_metrics = StatesyncMetrics(self.metrics_registry)
        self.state_metrics = StateMetrics(self.metrics_registry)
        self.proxy_metrics = ProxyMetrics(self.metrics_registry)
        # failure-domain supervision: node-level loops (consensus
        # receive) run under this supervisor; the switch owns a
        # sibling sharing the same metric family, so every restart is
        # visible at /metrics
        self.supervisor_metrics = SupervisorMetrics(
            self.metrics_registry)
        self.supervisor = Supervisor("node", logger=self.logger,
                                     metrics=self.supervisor_metrics)
        # liveness plane (libs/health.py): event-loop lag histogram
        # sampled by a supervised task started in start(), served by
        # /health and /metrics
        from ..libs.health import Metrics as HealthMetrics
        self.health_metrics = HealthMetrics(self.metrics_registry)

        # --- lightserve: height-keyed RPC response cache ----------------
        # immutable responses (blocks/commits/light blocks/multiproofs
        # below the tip) served from RAM so light-client read traffic
        # never reaches the stores (docs/light_proofs.md)
        from ..lightserve.cache import Metrics as LightserveMetrics
        from ..lightserve.cache import ResponseCache
        self.lightserve_cache = None
        if config.rpc.cache_max_bytes > 0:
            self.lightserve_cache = ResponseCache(
                config.rpc.cache_max_bytes,
                metrics=LightserveMetrics(self.metrics_registry))
            # statetree pruning must not drop a version the cache
            # still serves responses for — a client that just read a
            # cached height could no longer get it proven
            if hasattr(self.app, "version_pin"):
                cache = self.lightserve_cache
                self.app.version_pin = cache.heights

        # --- mempool ----------------------------------------------------
        self.mempool: Optional[CListMempool] = None
        self.mempool_reactor: Optional[MempoolReactor] = None

        # --- consensus (created in start after handshake) ---------------
        self.consensus_state: Optional[ConsensusState] = None
        self.consensus_reactor: Optional[ConsensusReactor] = None

        # --- p2p --------------------------------------------------------
        self.switch = Switch(
            self.node_key, self.genesis_doc.chain_id,
            listen_addr=config.p2p.laddr.replace("tcp://", ""),
            moniker=config.base.moniker,
            send_rate=config.p2p.send_rate,
            recv_rate=config.p2p.recv_rate,
            metrics=self.p2p_metrics,
            supervisor_metrics=self.supervisor_metrics)
        self.switch.private_ids = {
            s.strip() for s in
            config.p2p.private_peer_ids.split(",") if s.strip()}

        self._rpc_server = None
        self._started = False

    # ------------------------------------------------------------------
    async def start(self) -> None:
        """Boot order mirrors node.OnStart."""
        cfg = self.config

        # compile the C++ fast paths off-thread so the first big
        # merkle hash in the consensus loop never waits on g++
        from ..crypto._native_loader import prebuild_async
        prebuild_async()

        if cfg.base.priv_validator_laddr:
            from ..privval.signer import (
                RetrySignerClient, SignerClient, SignerListenerEndpoint,
            )
            self._signer_endpoint = SignerListenerEndpoint(
                cfg.base.priv_validator_laddr)
            await self._signer_endpoint.start()
            await self._signer_endpoint.wait_for_signer()
            client = RetrySignerClient(SignerClient(
                self._signer_endpoint, self.genesis_doc.chain_id))
            await client.fetch_pub_key()
            self.priv_validator = client

        # out-of-process app: open the four socket AppConns first
        # (reference: createAndStartProxyAppConns, setup.go:179)
        await self.app_conns.start()

        # deadline propagation on the remote ABCI boundary: a wedged
        # app process cannot hang consensus forever (builtin apps
        # share our event loop, so no deadline there)
        if cfg.base.abci not in ("local", "builtin",
                                 "builtin_unsync") and \
                cfg.base.abci_call_timeout_ns > 0:
            from ..abci.client import apply_deadlines
            apply_deadlines(
                self.app_conns,
                default_timeout_s=cfg.base.abci_call_timeout_ns / 1e9,
                retries=cfg.base.abci_call_retries)

        # flight-recorder span per ABCI call: the execute slice of the
        # per-height timeline (/trace, tools/trace_report.py)
        from ..abci.client import apply_tracing
        apply_tracing(self.app_conns)

        # per-method ABCI timing (reference: proxy metrics)
        from ..abci.metrics import instrument_app_conns
        instrument_app_conns(self.app_conns, self.proxy_metrics)

        # optional ABCI call-trace recording for the grammar checker
        # (reference: the e2e app records requests for
        # test/e2e/pkg/grammar/checker.go)
        if cfg.base.abci_grammar_trace:
            from ..abci.grammar import RecordingClient
            self.abci_trace: list = []
            for _conn in ("consensus", "mempool", "query", "snapshot"):
                setattr(self.app_conns, _conn,
                        RecordingClient(getattr(self.app_conns, _conn),
                                        self.abci_trace))

        # ABCI handshake reconciles app and store
        handshaker = Handshaker(self.state_store, self.initial_state,
                                self.block_store, self.genesis_doc,
                                logger=new_logger("handshaker"))
        await handshaker.handshake(self.app_conns)
        state = self.state_store.load() or self.initial_state

        # mempool (lanes from the app's Info)
        info = await self.app_conns.query.info(abci.InfoRequest())
        self.mempool = CListMempool(
            cfg.mempool, self.app_conns.mempool,
            lanes=info.lane_priorities or None,
            default_lane=info.default_lane,
            height=state.last_block_height,
            metrics=self.mempool_metrics)

        # pruner service (reference: state/pruner.go via setup.go)
        from ..state.pruner import Pruner
        self.pruner = Pruner(
            self.state_store, self.block_store,
            new_db("pruner", cfg.base.db_backend,
                   cfg.base.path(cfg.base.db_dir)),
            # must be known BEFORE the first prune pass: with a
            # companion configured, blocks it hasn't released must
            # survive restarts
            companion_enabled=bool(cfg.grpc.privileged_laddr and
                                   cfg.grpc.pruning_service_enabled),
            metrics=self.state_metrics)
        # started below, once the indexers are attached — a pass that
        # ran before attachment would skip indexer pruning

        # evidence pool
        from ..evidence import EvidencePool
        from ..evidence.reactor import EvidenceReactor
        self.evidence_pool = EvidencePool(
            new_db("evidence", cfg.base.db_backend,
                   cfg.base.path(cfg.base.db_dir)),
            self.state_store, self.block_store)

        # indexers + service (reference: setup.go createAndStartIndexerService)
        from ..indexer import BlockIndexer, IndexerService, TxIndexer
        if cfg.tx_index.indexer == "kv":
            idx_db = new_db("tx_index", cfg.base.db_backend,
                            cfg.base.path(cfg.base.db_dir))
            self.tx_indexer = TxIndexer(idx_db)
            self.block_indexer = BlockIndexer(idx_db)
            self.indexer_service = IndexerService(
                self.tx_indexer, self.block_indexer, self.event_bus)
            await self.indexer_service.start()
        elif cfg.tx_index.indexer == "psql":
            # relational event sink (reference: state/indexer/sink/psql
            # wired via setup.go; embedded SQL engine in this build)
            import os as _os
            from ..indexer import SQLEventSink
            conn = cfg.tx_index.psql_conn or cfg.base.path(
                _os.path.join(cfg.base.db_dir, "events.sqlite"))
            self._event_sink = SQLEventSink(
                conn, self.genesis_doc.chain_id)
            self.tx_indexer = self._event_sink.tx_indexer
            self.block_indexer = self._event_sink.block_indexer
            self.indexer_service = IndexerService(
                self.tx_indexer, self.block_indexer, self.event_bus)
            await self.indexer_service.start()
        else:
            self.tx_indexer = None
            self.block_indexer = None
            self.indexer_service = None
        # companion pruning covers the indexers too (pruner.go)
        self.pruner.tx_indexer = self.tx_indexer
        self.pruner.block_indexer = self.block_indexer
        await self.pruner.start()

        block_exec = BlockExecutor(
            self.state_store, self.app_conns.consensus,
            mempool=self.mempool, evpool=self.evidence_pool,
            event_bus=self.event_bus,
            block_store=self.block_store,
            metrics=self.state_metrics)
        block_exec.pruner = self.pruner

        wal_path = cfg.base.path(cfg.consensus.wal_file)
        self.consensus_state = ConsensusState(
            cfg.consensus, state, block_exec, self.block_store,
            priv_validator=self.priv_validator,
            event_bus=self.event_bus, wal=WAL(wal_path),
            metrics=self.consensus_metrics,
            supervisor=self.supervisor)
        try:
            try:
                await catchup_replay(self.consensus_state, wal_path)
            except CorruptWALError as e:
                # reference state.go OnStart: one repair attempt — keep
                # the valid prefix, stash the corrupt tail, replay again
                from ..consensus.wal import repair_wal_file
                # justified synchronous durability point: one-shot WAL
                # repair during startup replay — consensus is not
                # running yet and the truncate must complete before
                # anything else touches the WAL
                # bftlint: disable=blocking-in-async
                dropped = repair_wal_file(wal_path)
                # repair may have renamed the head file out from under
                # the already-open append handle
                self.consensus_state.wal.reopen()
                self.logger.error(
                    "WAL corrupted; repaired by truncating",
                    err=str(e), dropped_bytes=dropped)
                await catchup_replay(self.consensus_state, wal_path)
        except (ReplayError, CorruptWALError) as e:
            # reference state.go OnStart: a non-corruption catchup error
            # (e.g. the end-height barrier was never written because we
            # crashed between block save and WAL fsync — the handshake
            # already replayed the block) is logged and the node starts
            # anyway; only height-in-flight votes are lost
            self.logger.error(
                "Error on catchup replay; proceeding to start node "
                "anyway", err=str(e))
        # WAL catchup can itself finalize a block — use the freshest
        # state for the blocksync decision and reactor
        state = self.state_store.load() or state

        # statesync runs only on a fresh node; it always hands off to
        # blocksync, so blocksync is forced on behind it (reference:
        # setup.go:569 startStateSync -> blocksync reactor)
        run_statesync = (cfg.statesync.enable and
                         state.last_block_height == 0)

        # blocksync decision (reference: setup.go — sync unless we are
        # the only validator)
        run_blocksync = run_statesync or (
            cfg.blocksync.enable and
            not _only_validator_is_us(
                state, self.priv_validator.get_pub_key()))

        self.consensus_reactor = ConsensusReactor(
            self.consensus_state, wait_sync=run_blocksync)
        self.switch.add_reactor(self.consensus_reactor)
        self.mempool_reactor = MempoolReactor(self.mempool, cfg.mempool)
        self.switch.add_reactor(self.mempool_reactor)
        self.switch.add_reactor(EvidenceReactor(self.evidence_pool))

        from ..blocksync import BlocksyncReactor

        async def _switch_to_consensus(new_state, height):
            """Reference: consensus.Reactor.SwitchToConsensus —
            reconstruct LastCommit from the stored seen commit before
            updating to the synced state."""
            if new_state.last_block_height > 0:
                self.consensus_state.rs.last_commit = None
                # off the event loop: the seen commit's batch verify
                # is O(validators) kernel work and the p2p loop is
                # live during the switch (crypto/pipeline.py seam)
                await self.consensus_state \
                    .reconstruct_last_commit_off_loop(new_state)
            self.consensus_state.update_to_state(new_state)
            # flip wait_sync only once RoundState reflects the synced
            # height: the off-loop reconstruction above yields the
            # loop, and a peer connecting mid-window must not be sent
            # a NewRoundStep built from the stale pre-sync state
            self.consensus_reactor.wait_sync = False
            await self.consensus_state.start()
            self.logger.info("Switched from blocksync to consensus",
                             height=height)

        self.blocksync_reactor = BlocksyncReactor(
            state, block_exec, self.block_store,
            active=run_blocksync,
            on_caught_up=_switch_to_consensus,
            metrics=self.blocksync_metrics)
        self.switch.add_reactor(self.blocksync_reactor)
        self._run_blocksync = run_blocksync

        # statesync (reference: setup.go:569 startStateSync): a fresh
        # node with statesync enabled bootstraps from a peer snapshot,
        # with trusted state/commit fetched via the light client over
        # the configured RPC servers; every node serves snapshots
        from ..statesync.reactor import StatesyncReactor
        from ..statesync.syncer import Syncer
        self._statesync_syncer = None
        if run_statesync:
            sp = await self._make_state_provider(state)
            syncer = Syncer(
                self.app_conns, sp,
                request_chunk=lambda snap, i:
                    self.statesync_reactor.request_chunk(snap, i),
                chunk_timeout_s=(cfg.statesync
                                 .chunk_request_timeout_ns / 1e9),
                chunk_dir=cfg.statesync.temp_dir or None)
            self._statesync_syncer = syncer
            self.statesync_reactor = StatesyncReactor(
                self.app_conns, syncer,
                metrics=self.statesync_metrics)
        else:
            self.statesync_reactor = StatesyncReactor(
                self.app_conns, metrics=self.statesync_metrics)
        self.switch.add_reactor(self.statesync_reactor)

        # event-loop lag sampler: always-on liveness signal behind
        # /health and cometbft_node_event_loop_lag_seconds; dies with
        # the supervisor in stop()
        if cfg.instrumentation.loop_lag_interval_s > 0:
            from ..libs.health import LoopLagSampler
            sampler = LoopLagSampler(
                self.health_metrics,
                interval_s=cfg.instrumentation.loop_lag_interval_s)
            self.supervisor.spawn(sampler.run, name="loop_lag",
                                  kind="loop_lag")

        # RPC before p2p (reference: OnStart order)
        if cfg.rpc.laddr:
            from ..rpc.server import RPCServer
            self._rpc_server = RPCServer(self, cfg.rpc)
            await self._rpc_server.start()

        # live profiling endpoint (reference: node.go pprofSrv, gated
        # by instrumentation.pprof_laddr)
        if cfg.instrumentation.pprof_listen_addr:
            from ..libs.pprof import PprofServer
            self._pprof_server = PprofServer(
                cfg.instrumentation.pprof_listen_addr)
            await self._pprof_server.start()

        # gRPC data-companion services (reference: node.go grpcSrv +
        # grpcPrivSrv, config.go GRPCConfig)
        if cfg.grpc.laddr:
            from ..rpc.grpc import GRPCServer
            self._grpc_server = GRPCServer(
                block_store=self.block_store,
                state_store=self.state_store,
                event_bus=self.event_bus,
                version_service=cfg.grpc.version_service_enabled,
                block_service=cfg.grpc.block_service_enabled,
                block_results_service=(
                    cfg.grpc.block_results_service_enabled))
            await self._grpc_server.start(cfg.grpc.laddr)
        if cfg.grpc.privileged_laddr and \
                cfg.grpc.pruning_service_enabled:
            from ..rpc.grpc import GRPCServer
            self._grpc_priv_server = GRPCServer(
                pruner=self.pruner, pruning_service=True)
            await self._grpc_priv_server.start(
                cfg.grpc.privileged_laddr)

        await self.switch.start()
        if cfg.p2p.persistent_peers:
            addrs = [a.strip() for a in
                     cfg.p2p.persistent_peers.split(",") if a.strip()]
            self.switch.dial_peers_async(
                [a.split("@")[-1] for a in addrs])

        if self._statesync_syncer is not None:
            try:
                new_state, commit = \
                    await self._statesync_syncer.sync_any(
                        cfg.statesync.discovery_time_ns / 1e9)
            except Exception:
                # boot failed mid-way: tear down what already started
                # (switch, RPC, pruner, indexer) instead of leaking it
                await self.stop()
                raise
            # bootstrap stores at the snapshot height (reference:
            # statesync.Reactor -> state.Store.Bootstrap + the seen
            # commit the blocksync verify path needs); consensus state
            # is updated (with LastCommit reconstruction) by the
            # blocksync->consensus handoff
            self.state_store.bootstrap(new_state)
            self.block_store.save_seen_commit_standalone(commit)
            self.blocksync_reactor.state = new_state
            self.statesync_reactor.metrics.syncing.set(0)
            self.logger.info("State sync complete",
                             height=new_state.last_block_height)
            await self.blocksync_reactor.start_sync()
        elif self._run_blocksync:
            await self.blocksync_reactor.start_sync()
        else:
            await self.consensus_state.start()
        self._started = True
        self.logger.info("Node started",
                         node_id=self.node_key.id[:12],
                         chain=self.genesis_doc.chain_id)

    async def stop(self) -> None:
        if getattr(self, "pruner", None) is not None:
            await self.pruner.stop()
        if getattr(self, "indexer_service", None) is not None:
            await self.indexer_service.stop()
        if getattr(self, "_event_sink", None) is not None:
            self._event_sink.close()
        if self.consensus_state is not None:
            await self.consensus_state.stop()
        await self.supervisor.stop()
        await self.switch.stop()
        if self._rpc_server is not None:
            await self._rpc_server.stop()
        if getattr(self, "_pprof_server", None) is not None:
            await self._pprof_server.stop()
        if getattr(self, "_grpc_server", None) is not None:
            await self._grpc_server.stop()
        if getattr(self, "_grpc_priv_server", None) is not None:
            await self._grpc_priv_server.stop()
        await self.app_conns.stop()
        if getattr(self, "_signer_endpoint", None) is not None:
            await self._signer_endpoint.stop()
        self._started = False
        self.logger.info("Node stopped")

    async def _make_state_provider(self, state):
        """Light-client state provider over the configured RPC servers
        (reference: stateprovider.go:29)."""
        from ..statesync.syncer import new_rpc_state_provider
        cfg = self.config.statesync
        if not cfg.rpc_servers or not cfg.trust_hash or \
                not cfg.trust_height:
            raise NodeError(
                "statesync.enable requires rpc_servers and "
                "trust_height/trust_hash (reference config)")
        return await new_rpc_state_provider(
            self.genesis_doc.chain_id, self.genesis_doc,
            list(cfg.rpc_servers), cfg.trust_height,
            bytes.fromhex(cfg.trust_hash), cfg.trust_period_ns)

    # ------------------------------------------------------------------
    @property
    def height(self) -> int:
        return self.block_store.height

    def status(self) -> dict:
        state = self.state_store.load()
        latest_meta = self.block_store.load_block_meta(
            self.block_store.height)
        pub = self.priv_validator.get_pub_key()
        return {
            "node_info": {
                "id": self.node_key.id,
                "listen_addr": self.switch.listen_addr,
                "network": self.genesis_doc.chain_id,
                "moniker": self.config.base.moniker,
            },
            "sync_info": {
                "latest_block_hash":
                    latest_meta.block_id.hash.hex().upper()
                    if latest_meta else "",
                "latest_app_hash":
                    (state.app_hash.hex().upper() if state else ""),
                "latest_block_height": str(self.block_store.height),
                "latest_block_time":
                    latest_meta.header.time.rfc3339()
                    if latest_meta else "",
                "earliest_block_height": str(self.block_store.base),
                "catching_up": False,
            },
            "validator_info": {
                "address": pub.address().hex().upper(),
                "pub_key": pub_key_to_json(pub),
                "voting_power": str(_voting_power(state, pub)),
            },
        }


def _voting_power(state, pub) -> int:
    if state is None or state.validators is None:
        return 0
    _, val = state.validators.get_by_address(pub.address())
    return val.voting_power if val else 0


def _only_validator_is_us(state, pub) -> bool:
    """Reference: node/setup.go onlyValidatorIsUs."""
    if state.validators is None or state.validators.size() != 1:
        return False
    return state.validators.validators[0].address == pub.address()
