"""BlockID: a block's hash plus its part-set header.

Reference: types/block.go BlockID (IsNil/IsComplete/ValidateBasic, Key).
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import tmhash
from .part_set import PartSetHeader, PartSetError


@dataclass(frozen=True)
class BlockID:
    hash: bytes = b""
    part_set_header: PartSetHeader = field(default_factory=PartSetHeader)

    def is_nil(self) -> bool:
        return len(self.hash) == 0 and self.part_set_header.is_zero()

    def is_complete(self) -> bool:
        return (len(self.hash) == tmhash.SIZE and
                self.part_set_header.total > 0 and
                len(self.part_set_header.hash) == tmhash.SIZE)

    def validate_basic(self) -> None:
        if self.hash and len(self.hash) != tmhash.SIZE:
            raise PartSetError(f"wrong BlockID hash size {len(self.hash)}")
        self.part_set_header.validate_basic()

    def key(self) -> bytes:
        """Map key uniquely identifying this BlockID."""
        return (self.hash + self.part_set_header.total.to_bytes(4, "big") +
                self.part_set_header.hash)

    def to_proto(self) -> dict:
        d: dict = {"part_set_header": self.part_set_header.to_proto()}
        if self.hash:
            d["hash"] = self.hash
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "BlockID":
        return cls(
            hash=d.get("hash", b""),
            part_set_header=PartSetHeader.from_proto(
                d.get("part_set_header") or {}),
        )

    def __str__(self) -> str:
        if self.is_nil():
            return "nil-BlockID"
        return f"{self.hash.hex().upper()[:12]}:{self.part_set_header}"


NIL_BLOCK_ID = BlockID()
