"""Block part sets: blocks split into 64 kB parts for gossip.

Reference: types/part_set.go — BlockPartSizeBytes, Part with merkle proof,
PartSetHeader, PartSet accumulation with a bit array.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import merkle, tmhash
from ..wire import pb, encode, decode

BLOCK_PART_SIZE = 65536  # reference: types/part_set.go BlockPartSizeBytes


class PartSetError(Exception):
    pass


@dataclass(frozen=True)
class PartSetHeader:
    total: int = 0
    hash: bytes = b""

    def is_zero(self) -> bool:
        return self.total == 0 and len(self.hash) == 0

    def validate_basic(self) -> None:
        if self.hash and len(self.hash) != tmhash.SIZE:
            raise PartSetError(
                f"wrong PartSetHeader hash size {len(self.hash)}")

    def to_proto(self) -> dict:
        d: dict = {}
        if self.total:
            d["total"] = self.total
        if self.hash:
            d["hash"] = self.hash
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "PartSetHeader":
        return cls(total=d.get("total", 0), hash=d.get("hash", b""))

    def __str__(self) -> str:
        return f"{self.total}:{self.hash.hex().upper()[:12]}"


@dataclass(frozen=True)
class Part:
    index: int
    bytes_: bytes
    proof: merkle.Proof

    def validate_basic(self) -> None:
        if len(self.bytes_) > BLOCK_PART_SIZE:
            raise PartSetError(f"part oversized: {len(self.bytes_)}")
        if self.proof.index != self.index:
            raise PartSetError("part proof index mismatch")

    def to_proto(self) -> dict:
        return {
            "index": self.index,
            "bytes": self.bytes_,
            "proof": {
                "total": self.proof.total,
                "index": self.proof.index,
                "leaf_hash": self.proof.leaf_hash,
                "aunts": list(self.proof.aunts),
            },
        }

    @classmethod
    def from_proto(cls, d: dict) -> "Part":
        p = d.get("proof") or {}
        return cls(
            index=d.get("index", 0),
            bytes_=d.get("bytes", b""),
            proof=merkle.Proof(
                total=p.get("total", 0), index=p.get("index", 0),
                leaf_hash=p.get("leaf_hash", b""),
                aunts=list(p.get("aunts", []))),
        )


class PartSet:
    """Accumulates parts of one block; complete when all present."""

    def __init__(self, header: PartSetHeader):
        self._header = header
        self._parts: list[Part | None] = [None] * header.total
        self._count = 0
        self._byte_size = 0

    @classmethod
    def from_data(cls, data: bytes,
                  part_size: int = BLOCK_PART_SIZE) -> "PartSet":
        chunks = [data[i:i + part_size]
                  for i in range(0, len(data), part_size)] or [b""]
        root, proofs = merkle.proofs_from_byte_slices(chunks)
        ps = cls(PartSetHeader(total=len(chunks), hash=root))
        for i, (chunk, proof) in enumerate(zip(chunks, proofs)):
            part = Part(index=i, bytes_=chunk, proof=proof)
            ps._parts[i] = part
            ps._count += 1
            ps._byte_size += len(chunk)
        return ps

    def header(self) -> PartSetHeader:
        return self._header

    def has_header(self, h: PartSetHeader) -> bool:
        return self._header == h

    @property
    def total(self) -> int:
        return self._header.total

    @property
    def count(self) -> int:
        return self._count

    @property
    def byte_size(self) -> int:
        return self._byte_size

    def is_complete(self) -> bool:
        return self._count == self._header.total and self._header.total > 0

    def has_part(self, index: int) -> bool:
        return 0 <= index < len(self._parts) and \
            self._parts[index] is not None

    def bit_array(self) -> list[bool]:
        return [p is not None for p in self._parts]

    def add_part(self, part: Part) -> bool:
        """Add a verified part; returns False if duplicate.

        Raises PartSetError on invalid index or merkle proof mismatch
        (reference: part_set.go AddPart).
        """
        if part.index >= self._header.total:
            raise PartSetError(
                f"part index {part.index} >= total {self._header.total}")
        if self._parts[part.index] is not None:
            return False
        part.validate_basic()
        leaf = merkle.leaf_hash(part.bytes_)
        if part.proof.leaf_hash != leaf:
            raise PartSetError("part leaf hash mismatch")
        part.proof.verify(self._header.hash, part.bytes_)
        self._parts[part.index] = part
        self._count += 1
        self._byte_size += len(part.bytes_)
        return True

    def get_part(self, index: int) -> Part | None:
        if 0 <= index < len(self._parts):
            return self._parts[index]
        return None

    def assemble(self) -> bytes:
        if not self.is_complete():
            raise PartSetError("part set incomplete")
        return b"".join(p.bytes_ for p in self._parts)  # type: ignore
