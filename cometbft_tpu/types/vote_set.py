"""VoteSet: per-(height, round, type) vote tally with conflict tracking.

Reference: types/vote_set.go — two storage areas (.votes canonical,
.votesByBlock per-block with peer-maj23 tracking), 2/3 majority detection,
MakeExtendedCommit.  Memory is bounded: conflicting votes are only tracked
for blocks a peer claims have 2/3 (each peer gets one claim).
"""
from __future__ import annotations

from typing import Optional

from ..libs.bits import BitArray
from . import canonical
from .block_id import BlockID
from .commit import ExtendedCommit, ExtendedCommitSig
from .validator_set import ValidatorSet
from .vote import (
    BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT, BLOCK_ID_FLAG_NIL,
    InvalidSignatureError, Vote, VoteError,
)
from .timestamp import Timestamp

MAX_VOTES_COUNT = 10000  # DoS bound; reference: vote_set.go:14


class VoteSetError(Exception):
    pass


class ConflictingVoteError(VoteSetError):
    """Equivocation detected: same validator, same step, different blocks."""

    def __init__(self, vote_a: Vote, vote_b: Vote):
        super().__init__(f"conflicting votes from validator "
                         f"{vote_a.validator_address.hex().upper()}")
        self.vote_a = vote_a
        self.vote_b = vote_b


class _BlockVotes:
    """Votes for one particular block (reference: blockVotes)."""

    __slots__ = ("peer_maj23", "bit_array", "votes", "sum")

    def __init__(self, peer_maj23: bool, num_validators: int):
        self.peer_maj23 = peer_maj23
        self.bit_array = BitArray(num_validators)
        self.votes: list[Optional[Vote]] = [None] * num_validators
        self.sum = 0

    def add_verified_vote(self, vote: Vote, voting_power: int) -> None:
        idx = vote.validator_index
        if self.votes[idx] is None:
            self.bit_array.set_index(idx, True)
            self.votes[idx] = vote
            self.sum += voting_power

    def get_by_index(self, idx: int) -> Optional[Vote]:
        return self.votes[idx]


class VoteSet:
    def __init__(self, chain_id: str, height: int, round_: int,
                 signed_msg_type: int, val_set: ValidatorSet,
                 extensions_enabled: bool = False):
        if height == 0:
            raise VoteSetError("cannot make VoteSet for height == 0")
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.signed_msg_type = signed_msg_type
        self.val_set = val_set
        self.extensions_enabled = extensions_enabled
        self.votes_bit_array = BitArray(val_set.size())
        self.votes: list[Optional[Vote]] = [None] * val_set.size()
        self.sum = 0
        self.maj23: Optional[BlockID] = None
        self.votes_by_block: dict[bytes, _BlockVotes] = {}
        self.peer_maj23s: dict[str, BlockID] = {}
        # set only by from_aggregate_commit (restart without per-vote
        # signatures); the proposal path prefers it when present
        self.stored_aggregate_commit = None

    @classmethod
    def extended(cls, chain_id: str, height: int, round_: int,
                 signed_msg_type: int, val_set: ValidatorSet) -> "VoteSet":
        """NewExtendedVoteSet: verifies extension data on every vote."""
        return cls(chain_id, height, round_, signed_msg_type, val_set,
                   extensions_enabled=True)

    @classmethod
    def from_aggregate_commit(cls, chain_id: str, agg_commit,
                              val_set: ValidatorSet) -> "VoteSet":
        """LastCommit restored from an AggregateCommit (blocksync /
        statesync restart — no per-vote signatures exist on disk, so
        individual votes cannot be reconstructed).

        The set reports the 2/3 majority (maj23) the verified
        aggregate proves, holds the aggregate for re-proposal
        (make_extended_commit yields all-absent signatures; the
        proposer embeds stored_aggregate_commit instead —
        consensus/state.py _create_proposal_block), and still accepts
        late precommits via add_vote — sum starts at zero so live
        votes tally normally."""
        vs = cls(chain_id, agg_commit.height, agg_commit.round,
                 canonical.PRECOMMIT_TYPE, val_set)
        vs.maj23 = agg_commit.block_id
        vs.stored_aggregate_commit = agg_commit
        return vs

    # ------------------------------------------------------------------
    def size(self) -> int:
        return self.val_set.size()

    def get_height(self) -> int:
        return self.height

    def get_round(self) -> int:
        return self.round

    def type(self) -> int:
        return self.signed_msg_type

    # ------------------------------------------------------------------
    def add_vote(self, vote: Optional[Vote]) -> bool:
        """Add a vote; returns True if added (False for exact duplicates).

        Raises VoteSetError/ConflictingVoteError (reference: addVote)."""
        if vote is None:
            raise VoteSetError("nil vote")
        val_index = vote.validator_index
        val_addr = vote.validator_address
        block_key = vote.block_id.key()

        if val_index < 0:
            raise VoteSetError("validator index < 0")
        if not val_addr:
            raise VoteSetError("empty validator address")
        if (vote.height != self.height or vote.round != self.round or
                vote.type != self.signed_msg_type):
            raise VoteSetError(
                f"expected {self.height}/{self.round}/"
                f"{self.signed_msg_type}, got {vote.height}/"
                f"{vote.round}/{vote.type}")

        lookup_addr, val = self.val_set.get_by_index(val_index)
        if val is None:
            raise VoteSetError(
                f"cannot find validator {val_index} in valSet of size "
                f"{self.val_set.size()}")
        if val_addr != lookup_addr:
            raise VoteSetError(
                "vote validator address does not match index; ensure the "
                "genesis file is correct across all validators")

        existing = self._get_vote(val_index, block_key, vote.block_id)
        if existing is not None:
            if existing.signature == vote.signature:
                return False  # exact duplicate
            raise VoteSetError("non-deterministic signature")

        # verify signature (and extensions when enabled)
        try:
            if self.extensions_enabled:
                vote.verify_vote_and_extension(self.chain_id, val.pub_key)
            else:
                vote.verify(self.chain_id, val.pub_key)
                if (vote.extension or vote.extension_signature or
                        vote.non_rp_extension or
                        vote.non_rp_extension_signature):
                    raise VoteSetError(
                        "unexpected vote extension data present in vote")
        except InvalidSignatureError as e:
            raise VoteSetError(f"failed to verify vote: {e}") from e

        added, conflicting = self._add_verified_vote(
            vote, block_key, val.voting_power)
        if conflicting is not None:
            raise ConflictingVoteError(conflicting, vote)
        if not added:
            raise VoteSetError("expected to add non-conflicting vote")
        return True

    def _get_vote(self, val_index: int, block_key: bytes,
                  block_id: BlockID) -> Optional[Vote]:
        existing = self.votes[val_index]
        if existing is not None and existing.block_id == block_id:
            return existing
        bv = self.votes_by_block.get(block_key)
        if bv is not None:
            return bv.get_by_index(val_index)
        return None

    def _add_verified_vote(self, vote: Vote, block_key: bytes,
                           voting_power: int):
        """Reference: addVerifiedVote — returns (added, conflicting)."""
        val_index = vote.validator_index
        conflicting: Optional[Vote] = None

        existing = self.votes[val_index]
        if existing is not None:
            if existing.block_id == vote.block_id:
                raise VoteSetError(
                    "add_verified_vote does not expect duplicate votes")
            conflicting = existing
            # replace canonical vote only if it matches a known maj23
            if self.maj23 is not None and self.maj23 == vote.block_id:
                self.votes[val_index] = vote
                self.votes_bit_array.set_index(val_index, True)
        else:
            self.votes[val_index] = vote
            self.votes_bit_array.set_index(val_index, True)
            self.sum += voting_power

        bv = self.votes_by_block.get(block_key)
        if bv is not None:
            if conflicting is not None and not bv.peer_maj23:
                # conflict and no peer claims this block is special
                return False, conflicting
        else:
            if conflicting is not None:
                # not tracking this block — forget it
                return False, conflicting
            bv = _BlockVotes(False, self.val_set.size())
            self.votes_by_block[block_key] = bv

        orig_sum = bv.sum
        quorum = self.val_set.total_voting_power() * 2 // 3 + 1
        bv.add_verified_vote(vote, voting_power)

        if orig_sum < quorum <= bv.sum and self.maj23 is None:
            self.maj23 = vote.block_id
            # copy this block's votes over to the canonical list
            for i, v in enumerate(bv.votes):
                if v is not None:
                    self.votes[i] = v
        return True, conflicting

    # ------------------------------------------------------------------
    def set_peer_maj23(self, peer_id: str, block_id: BlockID) -> None:
        """A peer claims 2/3 majority for block_id (reference:
        SetPeerMaj23)."""
        block_key = block_id.key()
        existing = self.peer_maj23s.get(peer_id)
        if existing is not None:
            if existing == block_id:
                return
            raise VoteSetError(
                f"conflicting blockID from peer {peer_id}")
        self.peer_maj23s[peer_id] = block_id
        bv = self.votes_by_block.get(block_key)
        if bv is not None:
            bv.peer_maj23 = True
        else:
            self.votes_by_block[block_key] = _BlockVotes(
                True, self.val_set.size())

    # ------------------------------------------------------------------
    def bit_array(self) -> BitArray:
        return self.votes_bit_array.copy()

    def bit_array_by_block_id(self, block_id: BlockID) -> Optional[BitArray]:
        bv = self.votes_by_block.get(block_id.key())
        return bv.bit_array.copy() if bv is not None else None

    def get_by_index(self, val_index: int) -> Optional[Vote]:
        return self.votes[val_index]

    def get_by_address(self, address: bytes) -> Optional[Vote]:
        idx, val = self.val_set.get_by_address(address)
        if val is None:
            raise VoteSetError("address not in validator set")
        return self.votes[idx]

    def list(self) -> list[Vote]:
        return [v for v in self.votes if v is not None]

    def has_two_thirds_majority(self) -> bool:
        return self.maj23 is not None

    def has_two_thirds_votes_for_maj23(self) -> bool:
        """True when the INDIVIDUAL votes held for maj23 reach quorum
        — distinguishes a live vote set from one whose majority is
        proven only by an injected/restored aggregate commit (the
        latter has maj23 set but few or no votes)."""
        if self.maj23 is None:
            return False
        bv = self.votes_by_block.get(self.maj23.key())
        quorum = self.val_set.total_voting_power() * 2 // 3 + 1
        return bv is not None and bv.sum >= quorum

    def inject_aggregate_majority(self, agg_commit) -> bool:
        """Record a VERIFIED aggregate commit as this round's +2/3
        precommit evidence (catchup on aggregate-commit chains — the
        caller MUST have verified it against the height's validator
        set first).  Keeps any live majority already found; refuses a
        conflicting one (two verified majorities for different blocks
        at one height/round is a safety violation upstream, not
        something to paper over here)."""
        if self.signed_msg_type != canonical.PRECOMMIT_TYPE or \
                agg_commit.height != self.height or \
                agg_commit.round != self.round:
            return False
        if self.maj23 is not None and self.maj23 != agg_commit.block_id:
            return False
        self.maj23 = agg_commit.block_id
        self.stored_aggregate_commit = agg_commit
        return True

    def is_commit(self) -> bool:
        return (self.signed_msg_type == canonical.PRECOMMIT_TYPE and
                self.maj23 is not None)

    def has_two_thirds_any(self) -> bool:
        return self.sum > self.val_set.total_voting_power() * 2 // 3

    def has_all(self) -> bool:
        return self.sum == self.val_set.total_voting_power()

    def two_thirds_majority(self) -> tuple[BlockID, bool]:
        if self.maj23 is not None:
            return self.maj23, True
        return BlockID(), False

    # ------------------------------------------------------------------
    def make_extended_commit(self, extensions_enabled_height: int = 0
                             ) -> ExtendedCommit:
        """Build the ExtendedCommit once 2/3 precommitted a block.

        Reference: vote_set.go MakeExtendedCommit (:638)."""
        if self.signed_msg_type != canonical.PRECOMMIT_TYPE:
            raise VoteSetError(
                "cannot make_extended_commit unless type is Precommit")
        if self.maj23 is None:
            raise VoteSetError(
                "cannot make_extended_commit unless a block has +2/3")
        sigs = []
        for v in self.votes:
            sig = _extended_commit_sig(v)
            # if block ID exists but doesn't match maj23, exclude sig
            if sig.block_id_flag == BLOCK_ID_FLAG_COMMIT and \
                    v.block_id != self.maj23:
                sig = _absent_extended_commit_sig()
            sigs.append(sig)
        ec = ExtendedCommit(
            height=self.height, round=self.round, block_id=self.maj23,
            extended_signatures=sigs)
        ext_enabled = (extensions_enabled_height > 0 and
                       ec.height >= extensions_enabled_height)
        ec.ensure_extensions(ext_enabled)
        return ec

    def log_string(self) -> str:
        total = self.val_set.total_voting_power()
        frac = self.sum / total if total else 0.0
        return f"Votes:{self.sum}/{total}({frac:.3f})"

    def __str__(self) -> str:
        return (f"VoteSet{{H:{self.height} R:{self.round} "
                f"T:{self.signed_msg_type} +2/3:{self.maj23} "
                f"{self.votes_bit_array}}}")


def _absent_extended_commit_sig() -> ExtendedCommitSig:
    return ExtendedCommitSig(block_id_flag=BLOCK_ID_FLAG_ABSENT,
                             timestamp=Timestamp.zero())


def _extended_commit_sig(v: Optional[Vote]) -> ExtendedCommitSig:
    """Reference: vote.go ExtendedCommitSig — absent for nil vote."""
    if v is None:
        return _absent_extended_commit_sig()
    flag = BLOCK_ID_FLAG_NIL if v.block_id.is_nil() else \
        BLOCK_ID_FLAG_COMMIT
    return ExtendedCommitSig(
        block_id_flag=flag,
        validator_address=v.validator_address,
        timestamp=v.timestamp,
        signature=v.signature,
        extension=v.extension,
        extension_signature=v.extension_signature,
        non_rp_extension=v.non_rp_extension,
        non_rp_extension_signature=v.non_rp_extension_signature,
    )
