"""Typed consensus events and the EventBus.

Reference: types/events.go (event strings + query constants) and
types/event_bus.go:34 (EventBus wrapping libs/pubsub, feeding RPC
websocket subscribers and the indexer).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ..libs import pubsub

# event types (reference: types/events.go)
EVENT_NEW_BLOCK = "NewBlock"
EVENT_NEW_BLOCK_HEADER = "NewBlockHeader"
EVENT_NEW_BLOCK_EVENTS = "NewBlockEvents"
EVENT_NEW_EVIDENCE = "NewEvidence"
EVENT_TX = "Tx"
EVENT_VALIDATOR_SET_UPDATES = "ValidatorSetUpdates"
EVENT_COMPLETE_PROPOSAL = "CompleteProposal"
EVENT_LOCK = "Lock"
EVENT_NEW_ROUND = "NewRound"
EVENT_NEW_ROUND_STEP = "NewRoundStep"
EVENT_POLKA = "Polka"
EVENT_RELOCK = "Relock"
EVENT_TIMEOUT_PROPOSE = "TimeoutPropose"
EVENT_TIMEOUT_WAIT = "TimeoutWait"
EVENT_VALID_BLOCK = "ValidBlock"
EVENT_VOTE = "Vote"
EVENT_PROPOSAL_BLOCK_PART = "ProposalBlockPart"

# reserved event attribute keys
EVENT_TYPE_KEY = "tm.event"
TX_HASH_KEY = "tx.hash"
TX_HEIGHT_KEY = "tx.height"
BLOCK_HEIGHT_KEY = "block.height"


def query_for_event(event_type: str) -> pubsub.Query:
    return pubsub.Query(f"{EVENT_TYPE_KEY} = '{event_type}'")


EVENT_QUERY_NEW_BLOCK = query_for_event(EVENT_NEW_BLOCK)
EVENT_QUERY_NEW_BLOCK_HEADER = query_for_event(EVENT_NEW_BLOCK_HEADER)
EVENT_QUERY_NEW_BLOCK_EVENTS = query_for_event(EVENT_NEW_BLOCK_EVENTS)
EVENT_QUERY_TX = query_for_event(EVENT_TX)
EVENT_QUERY_VOTE = query_for_event(EVENT_VOTE)
EVENT_QUERY_NEW_EVIDENCE = query_for_event(EVENT_NEW_EVIDENCE)
EVENT_QUERY_VALIDATOR_SET_UPDATES = query_for_event(
    EVENT_VALIDATOR_SET_UPDATES)


@dataclass
class EventData:
    """A published event: payload + ABCI-style event attributes."""
    kind: str
    payload: Any = None
    attrs: dict[str, list[str]] = field(default_factory=dict)


class EventBus:
    """Typed pub/sub over libs/pubsub (reference: event_bus.go:34)."""

    def __init__(self):
        self._server = pubsub.Server()

    def subscribe(self, subscriber: str, query: pubsub.Query | str,
                  out_capacity: int = 100) -> pubsub.Subscription:
        return self._server.subscribe(subscriber, query, out_capacity)

    def unsubscribe(self, subscriber: str,
                    query: pubsub.Query | str) -> None:
        self._server.unsubscribe(subscriber, query)

    def unsubscribe_all(self, subscriber: str) -> None:
        self._server.unsubscribe_all(subscriber)

    def num_clients(self) -> int:
        return self._server.num_clients()

    def num_client_subscriptions(self, subscriber: str) -> int:
        return self._server.num_client_subscriptions(subscriber)

    # ------------------------------------------------------------------
    def _publish(self, event_type: str, payload: Any,
                 extra: Optional[dict[str, list[str]]] = None) -> None:
        events = dict(extra or {})
        events.setdefault(EVENT_TYPE_KEY, []).append(event_type)
        self._server.publish(
            EventData(kind=event_type, payload=payload, attrs=events),
            events)

    def publish_new_block(self, block, block_id, result_finalize) -> None:
        self._publish(EVENT_NEW_BLOCK,
                      {"block": block, "block_id": block_id,
                       "result_finalize_block": result_finalize},
                      {BLOCK_HEIGHT_KEY: [str(block.header.height)]})

    def publish_new_block_header(self, header) -> None:
        self._publish(EVENT_NEW_BLOCK_HEADER, {"header": header},
                      {BLOCK_HEIGHT_KEY: [str(header.height)]})

    def publish_new_block_events(self, height: int, events: list,
                                 num_txs: int) -> None:
        extra = _abci_events_to_map(events)
        extra[BLOCK_HEIGHT_KEY] = [str(height)]
        self._publish(EVENT_NEW_BLOCK_EVENTS,
                      {"height": height, "events": events,
                       "num_txs": num_txs}, extra)

    def publish_tx(self, height: int, index: int, tx: bytes, result,
                   events: list) -> None:
        from .tx import tx_hash
        extra = _abci_events_to_map(events)
        extra[TX_HASH_KEY] = [tx_hash(tx).hex().upper()]
        extra[TX_HEIGHT_KEY] = [str(height)]
        self._publish(EVENT_TX, {"height": height, "index": index,
                                 "tx": tx, "result": result}, extra)

    def publish_vote(self, vote) -> None:
        self._publish(EVENT_VOTE, {"vote": vote})

    def publish_new_evidence(self, evidence, height: int) -> None:
        self._publish(EVENT_NEW_EVIDENCE,
                      {"evidence": evidence, "height": height})

    def publish_validator_set_updates(self, updates: list) -> None:
        self._publish(EVENT_VALIDATOR_SET_UPDATES,
                      {"validator_updates": updates})

    def publish_new_round_step(self, round_state) -> None:
        self._publish(EVENT_NEW_ROUND_STEP, round_state)

    def publish_new_round(self, round_state) -> None:
        self._publish(EVENT_NEW_ROUND, round_state)

    def publish_complete_proposal(self, round_state) -> None:
        self._publish(EVENT_COMPLETE_PROPOSAL, round_state)

    def publish_polka(self, round_state) -> None:
        self._publish(EVENT_POLKA, round_state)

    def publish_lock(self, round_state) -> None:
        self._publish(EVENT_LOCK, round_state)

    def publish_relock(self, round_state) -> None:
        self._publish(EVENT_RELOCK, round_state)

    def publish_valid_block(self, round_state) -> None:
        self._publish(EVENT_VALID_BLOCK, round_state)

    def publish_timeout_propose(self, round_state) -> None:
        self._publish(EVENT_TIMEOUT_PROPOSE, round_state)

    def publish_timeout_wait(self, round_state) -> None:
        self._publish(EVENT_TIMEOUT_WAIT, round_state)


def _field(obj, name: str, default):
    if isinstance(obj, dict):
        return obj.get(name, default)
    return getattr(obj, name, default)


def _abci_events_to_map(events: list) -> dict[str, list[str]]:
    """Flatten ABCI events [{type, attributes: [{key, value, index}]}]
    into composite-key tag map (reference: pubsub 'events' map)."""
    out: dict[str, list[str]] = {}
    for ev in events or []:
        etype = _field(ev, "type", "")
        for attr in _field(ev, "attributes", []):
            k = _field(attr, "key", "")
            v = _field(attr, "value", "")
            if etype and k:
                out.setdefault(f"{etype}.{k}", []).append(v)
    return out


class NopEventBus:
    """Event bus that drops everything (reference: event_bus.go
    NopEventBus — subscribe/unsubscribe are no-ops too)."""

    def subscribe(self, subscriber, query, out_capacity: int = 100):
        return pubsub.Subscription(out_capacity)

    def unsubscribe(self, subscriber, query) -> None:
        pass

    def unsubscribe_all(self, subscriber) -> None:
        pass

    def num_clients(self) -> int:
        return 0

    def num_client_subscriptions(self, subscriber) -> int:
        return 0

    def __getattr__(self, name):
        if name.startswith("publish"):
            return lambda *a, **k: None
        raise AttributeError(name)
