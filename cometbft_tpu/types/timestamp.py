"""Canonical time representation.

The reference signs google.protobuf.Timestamp values derived from Go
time.Time (UTC, no monotonic component — types/canonical.go CanonicalTime,
types/time/time.go Canonical).  Go's zero time is year 1, which encodes as
seconds = -62135596800 — a consensus-visible constant pinned by the
reference's sign-bytes test vectors.
"""
from __future__ import annotations

import time as _time
from datetime import datetime, timezone
from typing import NamedTuple

# Go time.Time{} (0001-01-01T00:00:00Z) as Unix seconds.
_GO_ZERO_SECONDS = -62135596800


class Timestamp(NamedTuple):
    seconds: int
    nanos: int

    @classmethod
    def zero(cls) -> "Timestamp":
        return cls(_GO_ZERO_SECONDS, 0)

    def is_zero(self) -> bool:
        return self.seconds == _GO_ZERO_SECONDS and self.nanos == 0

    @classmethod
    def now(cls) -> "Timestamp":
        ns = _time.time_ns()
        return cls(ns // 1_000_000_000, ns % 1_000_000_000)

    @classmethod
    def from_unix_ns(cls, ns: int) -> "Timestamp":
        return cls(ns // 1_000_000_000, ns % 1_000_000_000)

    def unix_ns(self) -> int:
        return self.seconds * 1_000_000_000 + self.nanos

    def to_proto(self) -> dict:
        d: dict = {}
        if self.seconds:
            d["seconds"] = self.seconds
        if self.nanos:
            d["nanos"] = self.nanos
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "Timestamp":
        return cls(d.get("seconds", 0), d.get("nanos", 0))

    def add_ns(self, ns: int) -> "Timestamp":
        return Timestamp.from_unix_ns(self.unix_ns() + ns)

    def sub(self, other: "Timestamp") -> int:
        """Difference in nanoseconds."""
        return self.unix_ns() - other.unix_ns()

    def rfc3339(self) -> str:
        dt = datetime.fromtimestamp(self.seconds, tz=timezone.utc)
        # NOT strftime("%Y..."): glibc renders year 1 as "1", which no
        # RFC-3339 parser (including ours) accepts — the zero time
        # 0001-01-01T00:00:00Z appears in every absent commit sig
        base = (f"{dt.year:04d}-{dt.month:02d}-{dt.day:02d}"
                f"T{dt.hour:02d}:{dt.minute:02d}:{dt.second:02d}")
        if self.nanos:
            frac = f"{self.nanos:09d}".rstrip("0")
            return f"{base}.{frac}Z"
        return base + "Z"

    @classmethod
    def from_rfc3339(cls, s: str) -> "Timestamp":
        s = s.strip()
        if s.endswith("Z"):
            s = s[:-1] + "+00:00"
        frac_ns = 0
        if "." in s:
            head, rest = s.split(".", 1)
            # split fractional digits from the timezone suffix
            i = 0
            while i < len(rest) and rest[i].isdigit():
                i += 1
            frac = rest[:i]
            frac_ns = int(frac.ljust(9, "0")[:9]) if frac else 0
            s = head + rest[i:]
        dt = datetime.fromisoformat(s)
        return cls(int(dt.timestamp()), frac_ns)


ZERO = Timestamp.zero()
