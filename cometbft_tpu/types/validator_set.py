"""ValidatorSet: sorted validator list with proposer-priority round-robin.

Reference: types/validator_set.go — deterministic proposer selection
(:122-250), change-set updates with priority rescaling (:430-717), hash over
SimpleValidator bytes.  Byte-for-byte reproducibility of the priority
arithmetic (int64 clipping, floor-average centering) is consensus-critical.
"""
from __future__ import annotations

from typing import Iterable, Optional, Sequence

from ..crypto import merkle
from .validator import (
    INT64_MAX, INT64_MIN, MAX_TOTAL_VOTING_POWER,
    PRIORITY_WINDOW_SIZE_FACTOR, Validator, ValidatorError,
    safe_add_clip, safe_sub_clip,
)


class ValidatorSetError(Exception):
    pass


class TotalVotingPowerOverflowError(ValidatorSetError):
    pass


def _by_voting_power_key(v: Validator):
    # descending voting power, then ascending address
    return (-v.voting_power, v.address)


class ValidatorSet:
    def __init__(self, validators: Optional[Iterable[Validator]] = None):
        """NewValidatorSet: apply initial change-set then rotate proposer
        once.  Raises on invalid input (reference panics)."""
        self.validators: list[Validator] = []
        self.proposer: Optional[Validator] = None
        self._total_voting_power = 0
        self._all_keys_same_type = True
        self._hash_memo: Optional[bytes] = None
        self._addr_index_memo: Optional[dict] = None
        vals = [v.copy() for v in (validators or [])]
        if vals:
            self._update_with_change_set(vals, allow_deletes=False)
            self.increment_proposer_priority(1)

    # ------------------------------------------------------------------
    def is_nil_or_empty(self) -> bool:
        return len(self.validators) == 0

    def __len__(self) -> int:
        return len(self.validators)

    def size(self) -> int:
        return len(self.validators)

    def copy(self) -> "ValidatorSet":
        cp = ValidatorSet()
        cp.validators = [v.copy() for v in self.validators]
        cp.proposer = self.proposer.copy() if self.proposer else None
        cp._total_voting_power = self._total_voting_power
        cp._all_keys_same_type = self._all_keys_same_type
        cp._hash_memo = self._hash_memo
        # _addr_index_memo stays None: rebuilt lazily on first use
        return cp

    def has_address(self, address: bytes) -> bool:
        return any(v.address == address for v in self.validators)

    def get_by_address(self, address: bytes) -> tuple[int, Optional[Validator]]:
        for i, v in enumerate(self.validators):
            if v.address == address:
                return i, v.copy()
        return -1, None

    def index_by_address(self, address: bytes) -> int:
        """Index of the validator with ``address``, or -1.  O(1) after
        the first call: the address->index map is built once per
        mutation generation (invalidated with the hash memo in
        _update_with_change_set) — the aggregate-commit trusting path
        resolves every signer by address, which with the linear
        get_by_address scan was O(n^2) at 10k validators."""
        memo = self._addr_index_memo
        if memo is None:
            memo = {v.address: i for i, v in enumerate(self.validators)}
            self._addr_index_memo = memo
        return memo.get(address, -1)

    def get_by_index(self, index: int) -> tuple[bytes, Optional[Validator]]:
        if index < 0 or index >= len(self.validators):
            return b"", None
        v = self.validators[index]
        return v.address, v.copy()

    def all_keys_have_same_type(self) -> bool:
        return self._all_keys_same_type

    def _check_all_keys_same_type(self) -> None:
        types = {v.pub_key.type() for v in self.validators
                 if v.pub_key is not None}
        self._all_keys_same_type = len(types) <= 1

    # ------------------------------------------------------------------
    def total_voting_power(self) -> int:
        if self._total_voting_power == 0 and self.validators:
            self._update_total_voting_power()
        return self._total_voting_power

    def _update_total_voting_power(self) -> None:
        total = 0
        for v in self.validators:
            total = safe_add_clip(total, v.voting_power)
            if total > MAX_TOTAL_VOTING_POWER:
                raise TotalVotingPowerOverflowError(
                    f"total voting power exceeds {MAX_TOTAL_VOTING_POWER}")
        self._total_voting_power = total

    # ------------------------------------------------------------------
    # Proposer selection (reference: validator_set.go:122-250)

    def get_proposer(self) -> Validator:
        if not self.validators:
            raise ValidatorSetError("empty validator set")
        if self.proposer is None:
            self.proposer = self._find_proposer()
        return self.proposer.copy()

    def _find_proposer(self) -> Validator:
        proposer = None
        for v in self.validators:
            proposer = v if proposer is None else \
                proposer.compare_proposer_priority(v)
        return proposer

    def increment_proposer_priority(self, times: int) -> None:
        if self.is_nil_or_empty():
            raise ValidatorSetError("empty validator set")
        if times <= 0:
            raise ValidatorSetError(
                "cannot call increment_proposer_priority with "
                "non-positive times")
        diff_max = PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power()
        self.rescale_priorities(diff_max)
        self._shift_by_avg_proposer_priority()
        proposer = None
        for _ in range(times):
            proposer = self._increment_proposer_priority()
        self.proposer = proposer

    def copy_increment_proposer_priority(self, times: int) -> "ValidatorSet":
        cp = self.copy()
        cp.increment_proposer_priority(times)
        return cp

    def advance_proposer_priority_step(self) -> None:
        """One raw increment step WITHOUT the rescale+shift prologue —
        the k-th loop iteration of increment_proposer_priority(k).
        Chaining increment(1) calls instead would re-run the prologue
        each step and diverge from a one-shot increment(k) whenever
        the priority spread exceeds the rescale window; the state
        store's roll-forward cache uses this to stay bit-identical to
        the cold LoadValidators path."""
        self.proposer = self._increment_proposer_priority()

    def _increment_proposer_priority(self) -> Validator:
        for v in self.validators:
            v.proposer_priority = safe_add_clip(
                v.proposer_priority, v.voting_power)
        mostest = self._find_proposer()
        mostest.proposer_priority = safe_sub_clip(
            mostest.proposer_priority, self.total_voting_power())
        return mostest

    def rescale_priorities(self, diff_max: int) -> None:
        if self.is_nil_or_empty():
            raise ValidatorSetError("empty validator set")
        if diff_max <= 0:
            return
        diff = self._max_min_priority_diff()
        ratio = (diff + diff_max - 1) // diff_max
        if diff > diff_max:
            for v in self.validators:
                # Go int64 division truncates toward zero
                p = v.proposer_priority
                v.proposer_priority = -(-p // ratio) if p < 0 else p // ratio

    def _max_min_priority_diff(self) -> int:
        mx = max(v.proposer_priority for v in self.validators)
        mn = min(v.proposer_priority for v in self.validators)
        return abs(mx - mn)

    def _compute_avg_proposer_priority(self) -> int:
        # big-int sum then floor division (Go big.Int.Div is Euclidean,
        # equal to floor for positive divisor)
        n = len(self.validators)
        total = sum(v.proposer_priority for v in self.validators)
        return total // n

    def _shift_by_avg_proposer_priority(self) -> None:
        avg = self._compute_avg_proposer_priority()
        for v in self.validators:
            v.proposer_priority = safe_sub_clip(v.proposer_priority, avg)

    # ------------------------------------------------------------------
    # Change-set updates (reference: validator_set.go:430-717)

    def update_with_change_set(self, changes: Sequence[Validator]) -> None:
        self._update_with_change_set(
            [v.copy() for v in changes], allow_deletes=True)

    def _update_with_change_set(self, changes: list[Validator],
                                allow_deletes: bool) -> None:
        if not changes:
            return
        self._hash_memo = None
        self._addr_index_memo = None
        updates, deletes = self._process_changes(changes)
        if not allow_deletes and deletes:
            raise ValidatorSetError(
                "cannot process validators with voting power 0")
        new_count = sum(1 for u in updates
                        if not self.has_address(u.address))
        if new_count == 0 and len(self.validators) == len(deletes):
            raise ValidatorSetError(
                "applying the validator changes would result in empty set")
        removed_power = self._verify_removals(deletes)
        tvp_after_updates = self._verify_updates(updates, removed_power)
        self._compute_new_priorities(updates, tvp_after_updates)
        self._apply_updates(updates)
        self._apply_removals(deletes)
        self._check_all_keys_same_type()
        self._update_total_voting_power()
        self.rescale_priorities(
            PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power())
        self._shift_by_avg_proposer_priority()
        self.validators.sort(key=_by_voting_power_key)

    @staticmethod
    def _process_changes(changes: list[Validator]):
        changes = sorted(changes, key=lambda v: v.address)
        updates: list[Validator] = []
        deletes: list[Validator] = []
        prev_addr = None
        for v in changes:
            if v.address == prev_addr:
                raise ValidatorSetError(f"duplicate entry {v}")
            if v.voting_power < 0:
                raise ValidatorSetError("voting power can't be negative")
            if v.voting_power > MAX_TOTAL_VOTING_POWER:
                raise ValidatorSetError(
                    f"voting power can't exceed {MAX_TOTAL_VOTING_POWER}")
            if v.voting_power == 0:
                deletes.append(v)
            else:
                updates.append(v)
            prev_addr = v.address
        return updates, deletes

    def _verify_updates(self, updates: list[Validator],
                        removed_power: int) -> int:
        def delta(u: Validator) -> int:
            _, val = self.get_by_address(u.address)
            return u.voting_power - val.voting_power if val else \
                u.voting_power

        tvp_after_removals = self.total_voting_power() - removed_power
        for u in sorted(updates, key=delta):
            tvp_after_removals += delta(u)
            if tvp_after_removals > MAX_TOTAL_VOTING_POWER:
                raise TotalVotingPowerOverflowError(
                    "total voting power overflow")
        return tvp_after_removals + removed_power

    def _verify_removals(self, deletes: list[Validator]) -> int:
        removed = 0
        for d in deletes:
            _, val = self.get_by_address(d.address)
            if val is None:
                raise ValidatorSetError(
                    f"failed to find validator {d.address.hex()} to remove")
            removed += val.voting_power
        if len(deletes) > len(self.validators):
            raise ValidatorSetError("more deletes than validators")
        return removed

    def _compute_new_priorities(self, updates: list[Validator],
                                updated_tvp: int) -> None:
        for u in updates:
            _, val = self.get_by_address(u.address)
            if val is None:
                # new validator starts at -1.125*totalVotingPower so
                # unbond/re-bond can't reset a negative priority
                u.proposer_priority = -(updated_tvp + (updated_tvp >> 3))
            else:
                u.proposer_priority = val.proposer_priority

    def _apply_updates(self, updates: list[Validator]) -> None:
        existing = sorted(self.validators, key=lambda v: v.address)
        merged: list[Validator] = []
        i = j = 0
        while i < len(existing) and j < len(updates):
            if existing[i].address < updates[j].address:
                merged.append(existing[i])
                i += 1
            else:
                merged.append(updates[j])
                if existing[i].address == updates[j].address:
                    i += 1
                j += 1
        merged.extend(existing[i:])
        merged.extend(updates[j:])
        self.validators = merged

    def _apply_removals(self, deletes: list[Validator]) -> None:
        if not deletes:
            return
        gone = {d.address for d in deletes}
        self.validators = [v for v in self.validators
                           if v.address not in gone]

    # ------------------------------------------------------------------
    def hash(self) -> bytes:
        """Merkle root over SimpleValidator bytes (reference:
        validator_set.go Hash).

        Memoized: the hash covers (pubkey, power) only, which change
        solely through update_with_change_set (the invalidation
        point) — proposer-priority rotation does not touch it.  At
        10k validators the recompute is ~40 ms and sat directly on
        the aggregate-commit verify path."""
        if self._hash_memo is None:
            self._hash_memo = merkle.hash_from_byte_slices(
                [v.bytes() for v in self.validators])
        return self._hash_memo

    def validate_basic(self) -> None:
        if self.is_nil_or_empty():
            raise ValidatorSetError("validator set is nil or empty")
        for v in self.validators:
            v.validate_basic()
        if self.proposer is None:
            raise ValidatorSetError("proposer failed validate basic")
        self.proposer.validate_basic()
        if not any(v.address == self.proposer.address
                   for v in self.validators):
            raise ValidatorSetError("proposer not in validator set")

    def to_proto(self) -> dict:
        d: dict = {
            "validators": [v.to_proto() for v in self.validators],
            "total_voting_power": self.total_voting_power(),
        }
        if self.proposer is not None:
            d["proposer"] = self.proposer.to_proto()
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "ValidatorSet":
        vs = cls()
        vs.validators = [Validator.from_proto(v)
                         for v in d.get("validators", [])]
        if d.get("proposer") is not None:
            vs.proposer = Validator.from_proto(d["proposer"])
        vs._check_all_keys_same_type()
        if vs.validators:
            vs._update_total_voting_power()
        return vs

    def __iter__(self):
        return iter(self.validators)

    def __str__(self) -> str:
        prop = self.proposer.address.hex().upper()[:12] \
            if self.proposer else "nil"
        return (f"ValidatorSet{{P:{prop} N:{len(self.validators)} "
                f"TVP:{self.total_voting_power()}}}")
