"""On-chain consensus parameters.

Reference: types/params.go — ConsensusParams tree, defaults, Hash over
HashedParams, ValidateBasic, feature-height gates (vote extensions, PBTS).
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional

from ..crypto import tmhash
from ..wire import pb, encode

MAX_BLOCK_SIZE_BYTES = 100 * 1024 * 1024
BLOCK_PART_SIZE_BYTES = 65536
MAX_BLOCK_PARTS_COUNT = MAX_BLOCK_SIZE_BYTES // BLOCK_PART_SIZE_BYTES + 1
ABCI_PUB_KEY_TYPE_ED25519 = "ed25519"

_NS_PER_MS = 1_000_000
_NS_PER_S = 1_000_000_000
MAX_MESSAGE_DELAY_NS = 24 * 3600 * _NS_PER_S
MAX_PRECISION_NS = 30 * _NS_PER_S


class ParamsError(Exception):
    pass


def _dur_proto(ns: int) -> dict:
    d: dict = {}
    s, rem = divmod(ns, _NS_PER_S)
    if s:
        d["seconds"] = s
    if rem:
        d["nanos"] = rem
    return d


def _dur_from_proto(d: dict) -> int:
    return d.get("seconds", 0) * _NS_PER_S + d.get("nanos", 0)


@dataclass
class BlockParams:
    max_bytes: int = 4194304   # 4 MB
    max_gas: int = 10_000_000

    def validate(self) -> None:
        if self.max_bytes == 0:
            raise ParamsError("block.MaxBytes cannot be 0")
        if self.max_bytes < -1:
            raise ParamsError("block.MaxBytes must be -1 or greater")
        if self.max_bytes > MAX_BLOCK_SIZE_BYTES:
            raise ParamsError(
                f"block.MaxBytes is too big, max {MAX_BLOCK_SIZE_BYTES}")
        if self.max_gas < -1:
            raise ParamsError("block.MaxGas must be -1 or greater")


@dataclass
class EvidenceParams:
    max_age_num_blocks: int = 100_000
    max_age_duration_ns: int = 48 * 3600 * _NS_PER_S
    max_bytes: int = 1_048_576

    def validate(self, block_max_bytes: int) -> None:
        if self.max_age_num_blocks <= 0:
            raise ParamsError("evidence.MaxAgeNumBlocks must be positive")
        if self.max_age_duration_ns <= 0:
            raise ParamsError("evidence.MaxAgeDuration must be positive")
        cap_ = block_max_bytes if block_max_bytes >= 0 \
            else MAX_BLOCK_SIZE_BYTES
        if self.max_bytes > cap_:
            raise ParamsError("evidence.MaxBytes exceeds block.MaxBytes")
        if self.max_bytes < 0:
            raise ParamsError("evidence.MaxBytes must be non-negative")


@dataclass
class ValidatorParams:
    pub_key_types: list[str] = field(
        default_factory=lambda: [ABCI_PUB_KEY_TYPE_ED25519])

    def validate(self) -> None:
        if not self.pub_key_types:
            raise ParamsError("validator.PubKeyTypes must not be empty")
        for t in self.pub_key_types:
            if t not in ("ed25519", "secp256k1", "bls12_381",
                         "secp256k1eth"):
                raise ParamsError(f"unknown pubkey type {t!r}")

    def is_valid_pub_key_type(self, key_type: str) -> bool:
        return key_type in self.pub_key_types


@dataclass
class VersionParams:
    app: int = 0


@dataclass
class SynchronyParams:
    precision_ns: int = 505 * _NS_PER_MS
    message_delay_ns: int = 15 * _NS_PER_S

    def validate(self) -> None:
        if self.precision_ns <= 0:
            raise ParamsError("synchrony.Precision must be positive")
        if self.message_delay_ns <= 0:
            raise ParamsError("synchrony.MessageDelay must be positive")
        if self.precision_ns > MAX_PRECISION_NS:
            raise ParamsError("synchrony.Precision too large")
        if self.message_delay_ns > MAX_MESSAGE_DELAY_NS:
            raise ParamsError("synchrony.MessageDelay too large")

    def in_round(self, round_: int) -> "SynchronyParams":
        """Adaptive per-round relaxation of PBTS bounds (reference:
        params.go SynchronyParams.InRound)."""
        delay = self.message_delay_ns
        for _ in range(round_):
            delay = delay * 110 // 100  # +10% per round
            if delay > MAX_MESSAGE_DELAY_NS:
                delay = MAX_MESSAGE_DELAY_NS
                break
        return SynchronyParams(self.precision_ns, delay)


@dataclass
class FeatureParams:
    vote_extensions_enable_height: int = 0
    pbts_enable_height: int = 0
    # TPU-native extension (docs/aggregate_commits.md): commits for
    # heights >= this are one BLS aggregate signature + signer bitmap
    # — O(1) pairing verification in validator count.  Requires PBTS
    # (aggregate commits carry no per-vote timestamps, so BFT time's
    # weighted median is unavailable) and is incompatible with vote
    # extensions (per-validator extension signatures cannot be
    # aggregated into one shared-message signature).
    aggregate_commit_enable_height: int = 0

    def vote_extensions_enabled(self, height: int) -> bool:
        h = self.vote_extensions_enable_height
        return h > 0 and height >= h

    def pbts_enabled(self, height: int) -> bool:
        h = self.pbts_enable_height
        return h > 0 and height >= h

    def aggregate_commits_enabled(self, height: int) -> bool:
        """True when the commit FOR height must be the aggregate form."""
        h = self.aggregate_commit_enable_height
        return h > 0 and height >= h

    def validate(self) -> None:
        if self.vote_extensions_enable_height < 0:
            raise ParamsError(
                "feature.VoteExtensionsEnableHeight must be non-negative")
        if self.pbts_enable_height < 0:
            raise ParamsError(
                "feature.PbtsEnableHeight must be non-negative")
        agg = self.aggregate_commit_enable_height
        if agg < 0:
            raise ParamsError(
                "feature.AggregateCommitEnableHeight must be "
                "non-negative")
        if agg > 0:
            if not (0 < self.pbts_enable_height <= agg):
                raise ParamsError(
                    "feature.AggregateCommitEnableHeight requires PBTS "
                    "enabled at or before it (aggregate commits have "
                    "no per-vote timestamps for BFT time)")
            if self.vote_extensions_enable_height > 0:
                raise ParamsError(
                    "feature.AggregateCommitEnableHeight is "
                    "incompatible with vote extensions")


@dataclass
class ConsensusParams:
    block: BlockParams = field(default_factory=BlockParams)
    evidence: EvidenceParams = field(default_factory=EvidenceParams)
    validator: ValidatorParams = field(default_factory=ValidatorParams)
    version: VersionParams = field(default_factory=VersionParams)
    synchrony: SynchronyParams = field(default_factory=SynchronyParams)
    feature: FeatureParams = field(default_factory=FeatureParams)

    def validate_basic(self) -> None:
        self.block.validate()
        self.evidence.validate(self.block.max_bytes)
        self.validator.validate()
        self.synchrony.validate()
        self.feature.validate()
        if self.feature.aggregate_commit_enable_height > 0 and \
                self.validator.pub_key_types != ["bls12_381"]:
            # cross-struct check (FeatureParams.validate cannot see
            # validator params): a non-BLS signer would make every
            # post-enable proposal fail AggregateCommit.from_commit —
            # the chain halts with the root cause buried in logs.
            # Reject the misconfiguration at genesis/param-update
            # instead.
            raise ParamsError(
                "feature.AggregateCommitEnableHeight requires "
                "validator.PubKeyTypes == ['bls12_381']")

    def hash(self) -> bytes:
        """sha256 of HashedParams proto (reference: params.go:425)."""
        d: dict = {}
        if self.block.max_bytes:
            d["block_max_bytes"] = self.block.max_bytes
        if self.block.max_gas:
            d["block_max_gas"] = self.block.max_gas
        return tmhash.sum(encode(pb.HASHED_PARAMS, d))

    def update(self, updates: Optional["ConsensusParams"]) -> \
            "ConsensusParams":
        """Nil-aware merge: sub-structs the update leaves as None keep the
        current values (reference: params.go Update — only non-nil proto
        sub-messages are applied)."""
        if updates is None:
            return replace(self)

        def pick(new, cur):
            return replace(new) if new is not None else replace(cur)

        return ConsensusParams(
            block=pick(updates.block, self.block),
            evidence=pick(updates.evidence, self.evidence),
            validator=pick(updates.validator, self.validator),
            version=pick(updates.version, self.version),
            synchrony=pick(updates.synchrony, self.synchrony),
            feature=pick(updates.feature, self.feature),
        )

    def to_proto(self) -> dict:
        return {
            "block": {
                **({"max_bytes": self.block.max_bytes}
                   if self.block.max_bytes else {}),
                **({"max_gas": self.block.max_gas}
                   if self.block.max_gas else {}),
            },
            "evidence": {
                **({"max_age_num_blocks": self.evidence.max_age_num_blocks}
                   if self.evidence.max_age_num_blocks else {}),
                "max_age_duration": _dur_proto(
                    self.evidence.max_age_duration_ns),
                **({"max_bytes": self.evidence.max_bytes}
                   if self.evidence.max_bytes else {}),
            },
            "validator": {"pub_key_types": list(
                self.validator.pub_key_types)},
            "version": {**({"app": self.version.app}
                           if self.version.app else {})},
            "synchrony": {
                "precision": _dur_proto(self.synchrony.precision_ns),
                "message_delay": _dur_proto(
                    self.synchrony.message_delay_ns),
            },
            "feature": {
                **({"vote_extensions_enable_height":
                    {"value": self.feature.vote_extensions_enable_height}}
                   if self.feature.vote_extensions_enable_height else {}),
                **({"pbts_enable_height":
                    {"value": self.feature.pbts_enable_height}}
                   if self.feature.pbts_enable_height else {}),
                **({"aggregate_commit_enable_height":
                    {"value":
                     self.feature.aggregate_commit_enable_height}}
                   if self.feature.aggregate_commit_enable_height
                   else {}),
            },
        }

    @classmethod
    def from_proto(cls, d: dict) -> "ConsensusParams":
        blk = d.get("block") or {}
        ev = d.get("evidence") or {}
        val = d.get("validator") or {}
        ver = d.get("version") or {}
        syn = d.get("synchrony") or {}
        feat = d.get("feature") or {}
        return cls(
            block=BlockParams(max_bytes=blk.get("max_bytes", 0),
                              max_gas=blk.get("max_gas", 0)),
            evidence=EvidenceParams(
                max_age_num_blocks=ev.get("max_age_num_blocks", 0),
                max_age_duration_ns=_dur_from_proto(
                    ev.get("max_age_duration") or {}),
                max_bytes=ev.get("max_bytes", 0)),
            validator=ValidatorParams(
                pub_key_types=list(val.get("pub_key_types", []))),
            version=VersionParams(app=ver.get("app", 0)),
            synchrony=SynchronyParams(
                precision_ns=_dur_from_proto(syn.get("precision") or {}),
                message_delay_ns=_dur_from_proto(
                    syn.get("message_delay") or {})),
            feature=FeatureParams(
                vote_extensions_enable_height=(
                    feat.get("vote_extensions_enable_height") or {}
                ).get("value", 0),
                pbts_enable_height=(
                    feat.get("pbts_enable_height") or {}).get("value", 0),
                aggregate_commit_enable_height=(
                    feat.get("aggregate_commit_enable_height") or {}
                ).get("value", 0)),
        )


def default_consensus_params() -> ConsensusParams:
    return ConsensusParams()
