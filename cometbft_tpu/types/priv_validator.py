"""PrivValidator: the validator signing interface.

Reference: types/priv_validator.go — SignVote / SignProposal /
SignBytes(raw) over a PrivKey; MockPV for tests.  The production
file-backed signer with double-sign protection lives in privval/.
"""
from __future__ import annotations

import abc

from ..crypto.keys import PrivKey, PubKey
from .proposal import Proposal
from .vote import Vote
from . import canonical


class PrivValidatorError(Exception):
    pass


class PrivValidator(abc.ABC):
    @abc.abstractmethod
    def get_pub_key(self) -> PubKey: ...

    @abc.abstractmethod
    def sign_vote(self, chain_id: str, vote: Vote,
                  sign_extension: bool) -> None:
        """Sign the vote in place (vote.signature, and extension
        signatures when sign_extension and vote is a precommit)."""

    @abc.abstractmethod
    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        """Sign the proposal in place."""

    def sign_bytes(self, msg: bytes) -> bytes:
        raise PrivValidatorError("raw sign_bytes not supported")


class MockPV(PrivValidator):
    """In-memory signer without double-sign protection (reference:
    types/priv_validator.go MockPV — test use only)."""

    def __init__(self, priv_key: PrivKey,
                 break_proposal_sigs: bool = False,
                 break_vote_sigs: bool = False):
        self.priv_key = priv_key
        self.break_proposal_sigs = break_proposal_sigs
        self.break_vote_sigs = break_vote_sigs

    def get_pub_key(self) -> PubKey:
        return self.priv_key.pub_key()

    def sign_vote(self, chain_id: str, vote: Vote,
                  sign_extension: bool) -> None:
        use_chain_id = "incorrect-chain-id" if self.break_vote_sigs \
            else chain_id
        vote.signature = self.priv_key.sign(vote.sign_bytes(use_chain_id))
        if sign_extension and vote.type == canonical.PRECOMMIT_TYPE and \
                not vote.block_id.is_nil():
            vote.extension_signature = self.priv_key.sign(
                vote.extension_sign_bytes(use_chain_id))
            vote.non_rp_extension_signature = self.priv_key.sign(
                vote.non_rp_extension_sign_bytes())

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        use_chain_id = "incorrect-chain-id" if self.break_proposal_sigs \
            else chain_id
        proposal.signature = self.priv_key.sign(
            proposal.sign_bytes(use_chain_id))

    def sign_bytes(self, msg: bytes) -> bytes:
        return self.priv_key.sign(msg)


def new_mock_pv() -> MockPV:
    from ..crypto import ed25519
    return MockPV(ed25519.gen_priv_key())
