"""Genesis document: the chain-level configuration.

Reference: types/genesis.go — GenesisDoc with validators, consensus params,
app state; JSON on disk with amino-compatible pubkey encoding
({"type": "tendermint/PubKeyEd25519", "value": <b64>}).
"""
from __future__ import annotations

import base64
import json
from dataclasses import dataclass, field
from typing import Any, Optional

from ..crypto import encoding as crypto_encoding
from ..crypto.keys import PubKey
from .params import ConsensusParams, default_consensus_params
from .timestamp import Timestamp
from .validator import MAX_TOTAL_VOTING_POWER

MAX_CHAIN_ID_LEN = 50

# amino-compatible JSON type tags (single registry: crypto/encoding.py)
_PUBKEY_JSON_TYPES = crypto_encoding.AMINO_PUBKEY_NAMES
_PUBKEY_JSON_TYPES_REV = {v: k for k, v in _PUBKEY_JSON_TYPES.items()}


class GenesisError(Exception):
    pass


def pub_key_to_json(pk: PubKey) -> dict:
    tag = _PUBKEY_JSON_TYPES.get(pk.type())
    if tag is None:
        raise GenesisError(f"unsupported pubkey type {pk.type()}")
    return {"type": tag,
            "value": base64.b64encode(pk.bytes()).decode()}


def pub_key_from_json(d: dict) -> PubKey:
    key_type = _PUBKEY_JSON_TYPES_REV.get(d.get("type", ""))
    if key_type is None:
        raise GenesisError(f"unsupported pubkey json type {d.get('type')}")
    return crypto_encoding.pub_key_from_type_and_bytes(
        key_type, base64.b64decode(d["value"]))


@dataclass
class GenesisValidator:
    address: bytes
    pub_key: PubKey
    power: int
    name: str = ""


@dataclass
class GenesisDoc:
    chain_id: str
    genesis_time: Timestamp = field(default_factory=Timestamp.now)
    initial_height: int = 1
    consensus_params: Optional[ConsensusParams] = field(
        default_factory=default_consensus_params)
    validators: list[GenesisValidator] = field(default_factory=list)
    app_hash: bytes = b""
    app_state: Any = None

    def validate_and_complete(self) -> None:
        """Reference: genesis.go ValidateAndComplete."""
        if not self.chain_id:
            raise GenesisError("genesis doc must include non-empty chain_id")
        if len(self.chain_id) > MAX_CHAIN_ID_LEN:
            raise GenesisError(
                f"chain_id in genesis doc is too long (max: "
                f"{MAX_CHAIN_ID_LEN})")
        if self.initial_height < 0:
            raise GenesisError("initial_height cannot be negative")
        if self.initial_height == 0:
            self.initial_height = 1
        if self.consensus_params is None:
            self.consensus_params = default_consensus_params()
        else:
            self.consensus_params.validate_basic()
        for i, v in enumerate(self.validators):
            if v.power == 0:
                raise GenesisError(
                    f"genesis file cannot contain validators with no "
                    f"voting power: {v.name or i}")
            if v.power < 0:
                raise GenesisError("negative voting power")
            if v.power > MAX_TOTAL_VOTING_POWER:
                raise GenesisError("voting power too large")
            if v.address and v.address != v.pub_key.address():
                raise GenesisError(
                    f"incorrect address for validator {v.name or i}")
            if not v.address:
                v.address = v.pub_key.address()
        if self.genesis_time.is_zero():
            self.genesis_time = Timestamp.now()

    def validator_hash(self) -> bytes:
        from .validator import Validator
        from .validator_set import ValidatorSet
        vset = ValidatorSet([Validator.new(v.pub_key, v.power)
                             for v in self.validators])
        return vset.hash()

    # ------------------------------------------------------------------
    def to_json(self) -> str:
        doc = {
            "genesis_time": self.genesis_time.rfc3339(),
            "chain_id": self.chain_id,
            "initial_height": str(self.initial_height),
            "consensus_params": _params_to_json(self.consensus_params),
            "validators": [
                {
                    "address": v.address.hex().upper(),
                    "pub_key": pub_key_to_json(v.pub_key),
                    "power": str(v.power),
                    "name": v.name,
                }
                for v in self.validators
            ],
            "app_hash": self.app_hash.hex().upper(),
        }
        if self.app_state is not None:
            doc["app_state"] = self.app_state
        return json.dumps(doc, indent=2)

    @classmethod
    def from_json(cls, raw: str) -> "GenesisDoc":
        d = json.loads(raw)
        if "chain_id" not in d:
            raise GenesisError("genesis doc missing chain_id")
        vals = []
        for v in d.get("validators") or []:
            pk = pub_key_from_json(v["pub_key"])
            vals.append(GenesisValidator(
                address=bytes.fromhex(v.get("address", "")) or
                pk.address(),
                pub_key=pk,
                power=int(v["power"]),
                name=v.get("name", ""),
            ))
        gt = d.get("genesis_time")
        doc = cls(
            chain_id=d["chain_id"],
            genesis_time=Timestamp.from_rfc3339(gt) if gt
            else Timestamp.zero(),
            initial_height=int(d.get("initial_height", 1) or 1),
            consensus_params=_params_from_json(d.get("consensus_params")),
            validators=vals,
            app_hash=bytes.fromhex(d.get("app_hash", "")),
            app_state=d.get("app_state"),
        )
        doc.validate_and_complete()
        return doc

    def save_as(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @classmethod
    def from_file(cls, path: str) -> "GenesisDoc":
        with open(path) as f:
            return cls.from_json(f.read())


def _params_to_json(p: Optional[ConsensusParams]) -> Optional[dict]:
    if p is None:
        return None
    return {
        "block": {"max_bytes": str(p.block.max_bytes),
                  "max_gas": str(p.block.max_gas)},
        "evidence": {
            "max_age_num_blocks": str(p.evidence.max_age_num_blocks),
            "max_age_duration": str(p.evidence.max_age_duration_ns),
            "max_bytes": str(p.evidence.max_bytes),
        },
        "validator": {"pub_key_types": list(p.validator.pub_key_types)},
        "version": {"app": str(p.version.app)},
        "synchrony": {
            "precision": str(p.synchrony.precision_ns),
            "message_delay": str(p.synchrony.message_delay_ns),
        },
        "feature": {
            "vote_extensions_enable_height": str(
                p.feature.vote_extensions_enable_height),
            "pbts_enable_height": str(p.feature.pbts_enable_height),
            "aggregate_commit_enable_height": str(
                p.feature.aggregate_commit_enable_height),
        },
    }


def _params_from_json(d: Optional[dict]) -> Optional[ConsensusParams]:
    if d is None:
        return None
    from .params import (
        BlockParams, EvidenceParams, FeatureParams, SynchronyParams,
        ValidatorParams, VersionParams,
    )
    blk = d.get("block") or {}
    ev = d.get("evidence") or {}
    val = d.get("validator") or {}
    ver = d.get("version") or {}
    syn = d.get("synchrony") or {}
    feat = d.get("feature") or {}
    dflt = ConsensusParams()
    return ConsensusParams(
        block=BlockParams(
            max_bytes=int(blk.get("max_bytes", dflt.block.max_bytes)),
            max_gas=int(blk.get("max_gas", dflt.block.max_gas))),
        evidence=EvidenceParams(
            max_age_num_blocks=int(ev.get(
                "max_age_num_blocks", dflt.evidence.max_age_num_blocks)),
            max_age_duration_ns=int(ev.get(
                "max_age_duration", dflt.evidence.max_age_duration_ns)),
            max_bytes=int(ev.get("max_bytes", dflt.evidence.max_bytes))),
        validator=ValidatorParams(pub_key_types=list(
            val.get("pub_key_types", dflt.validator.pub_key_types))),
        version=VersionParams(app=int(ver.get("app", 0))),
        synchrony=SynchronyParams(
            precision_ns=int(syn.get(
                "precision", dflt.synchrony.precision_ns)),
            message_delay_ns=int(syn.get(
                "message_delay", dflt.synchrony.message_delay_ns))),
        feature=FeatureParams(
            vote_extensions_enable_height=int(feat.get(
                "vote_extensions_enable_height", 0)),
            pbts_enable_height=int(feat.get("pbts_enable_height", 0)),
            aggregate_commit_enable_height=int(feat.get(
                "aggregate_commit_enable_height", 0))),
    )
