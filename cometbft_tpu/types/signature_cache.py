"""Signature cache: skip re-verification of identical (sig, addr, msg).

Reference: types/signature_cache.go — map sig → (valAddr, signBytes),
shared across light-client adjacent/non-adjacent checks.

Beyond the reference: the map is LRU-bounded (``base.
signature_cache_size``, default 10k — the reference cache lives only
for one verification pair, ours is reused across heights, so sustained
traffic would otherwise grow it without limit), and hit/miss/evict
counters are exported on the shared metrics registry
(``cometbft_light_signature_cache_*``).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import NamedTuple, Optional

# process default; the node overrides it from base.signature_cache_size
DEFAULT_CAPACITY = 10_000

_METRICS = None


def _metrics():
    """Lazily-registered counters on the process-global registry (the
    same pattern as the crypto breaker state): sig caches are built in
    light-client and validation paths that have no node registry."""
    global _METRICS
    if _METRICS is None:
        from ..libs import metrics as libmetrics
        m = libmetrics.DEFAULT
        _METRICS = (
            m.counter("light", "signature_cache_hits",
                      "Signature-cache hits across commit "
                      "verifications."),
            m.counter("light", "signature_cache_misses",
                      "Signature-cache misses."),
            m.counter("light", "signature_cache_evictions",
                      "Entries evicted by the signature-cache LRU "
                      "cap."),
        )
    return _METRICS


def set_default_capacity(n: int) -> None:
    global DEFAULT_CAPACITY
    if n > 0:
        DEFAULT_CAPACITY = n


class SignatureCacheValue(NamedTuple):
    validator_address: bytes
    vote_sign_bytes: bytes


class SignatureCache:
    def __init__(self, capacity: int = 0):
        self.capacity = capacity if capacity > 0 else DEFAULT_CAPACITY
        self._m: OrderedDict[bytes, SignatureCacheValue] = \
            OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, sig: bytes) -> Optional[SignatureCacheValue]:
        v = self._m.get(sig)
        hits, misses, _ = _metrics()
        if v is not None:
            self._m.move_to_end(sig)
            self.hits += 1
            hits.add()
        else:
            self.misses += 1
            misses.add()
        return v

    def add(self, sig: bytes, value: SignatureCacheValue) -> None:
        if sig in self._m:
            self._m.move_to_end(sig)
        self._m[sig] = value
        if len(self._m) > self.capacity:
            self._m.popitem(last=False)
            self.evictions += 1
            _metrics()[2].add()

    def __len__(self) -> int:
        return len(self._m)
