"""Signature cache: skip re-verification of identical (sig, addr, msg).

Reference: types/signature_cache.go — map sig → (valAddr, signBytes),
shared across light-client adjacent/non-adjacent checks.
"""
from __future__ import annotations

from typing import NamedTuple, Optional


class SignatureCacheValue(NamedTuple):
    validator_address: bytes
    vote_sign_bytes: bytes


class SignatureCache:
    def __init__(self):
        self._m: dict[bytes, SignatureCacheValue] = {}

    def get(self, sig: bytes) -> Optional[SignatureCacheValue]:
        return self._m.get(sig)

    def add(self, sig: bytes, value: SignatureCacheValue) -> None:
        self._m[sig] = value

    def __len__(self) -> int:
        return len(self._m)
