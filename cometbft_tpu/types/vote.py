"""Vote: a prevote or precommit, optionally carrying vote extensions.

Reference: types/vote.go — Vote struct (:66-81), Verify/VerifyWithExtension/
VerifyExtension (:247,256,281), ValidateBasic, MaxVoteBytes/extension caps.
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace

from ..crypto.keys import PubKey
from . import canonical
from .block_id import BlockID
from .part_set import PartSetError
from .timestamp import Timestamp

# max(ed25519=64, bls12_381=96); reference: types/signable.go:13
MAX_SIGNATURE_SIZE = 96

# reference: types/vote.go:20 — 1 MiB cap on any single extension
MAX_VOTE_EXTENSION_SIZE = 1024 * 1024

# BlockIDFlag (proto/cometbft/types/v2/validator.proto)
BLOCK_ID_FLAG_UNKNOWN = 0
BLOCK_ID_FLAG_ABSENT = 1
BLOCK_ID_FLAG_COMMIT = 2
BLOCK_ID_FLAG_NIL = 3


class VoteError(Exception):
    pass


class InvalidSignatureError(VoteError):
    pass


# ---------------------------------------------------------------------------
# Verified-signature memo + burst pre-verification — the tally-path
# batching the reference leaves on the table (SURVEY: vote_set.go:219-236
# verifies per vote inside AddVote).  The consensus receive loop drains
# whatever vote messages are queued, batch-verifies their signatures
# through the grouped batch machinery (TPU kernel / native MSM / RLC
# pairings product by key type), and memoizes the VALID triples; the
# serial state-machine processing then hits the memo instead of paying
# a per-signature verify.  Only positives are cached (a valid
# (pubkey, message, signature) triple is valid forever), the memo is
# bounded, and processing order is unchanged — determinism and verdicts
# are identical to the unbatched path.

import hashlib as _hashlib
from collections import OrderedDict as _OrderedDict

_VERIFIED: "_OrderedDict[tuple[bytes, bytes, bytes], None]" = \
    _OrderedDict()
_VERIFIED_MAX = 8192

# Negative memo: verification is deterministic, so a failed
# (pubkey, msg-hash, sig) triple is invalid forever.  Without it, a
# byzantine peer re-sending vote storms with one bad signature per
# burst gets amplified work per message: the batch rejects, the mask
# pass pays the slow per-signature fallback on the bad entry, and the
# serial path then re-verifies the SAME bad entry again (only
# positives used to be memoized).  Bounded like the positive memo.
_REJECTED: "_OrderedDict[tuple[bytes, bytes, bytes], None]" = \
    _OrderedDict()
_REJECTED_MAX = 4096


def _memo_key(pub_key: PubKey, msg: bytes,
              sig: bytes) -> tuple[bytes, bytes, bytes]:
    # the message is HASHED into the key: extension sign bytes can be
    # ~1 MiB, and 8192 entries of embedded messages would be a
    # byzantine-controllable multi-GB memo; a digest bounds every
    # entry to ~130 bytes
    return (pub_key.bytes(), _hashlib.sha256(msg).digest(), bytes(sig))


def _memo_add(key: tuple[bytes, bytes, bytes]) -> None:
    _VERIFIED[key] = None
    if len(_VERIFIED) > _VERIFIED_MAX:
        _VERIFIED.popitem(last=False)


def _memo_reject(key: tuple[bytes, bytes, bytes]) -> None:
    _REJECTED[key] = None
    if len(_REJECTED) > _REJECTED_MAX:
        _REJECTED.popitem(last=False)


def checked_verify(pub_key: PubKey, msg: bytes, sig: bytes) -> bool:
    """pub_key.verify_signature with the verified/rejected memos."""
    key = _memo_key(pub_key, msg, sig)
    if key in _VERIFIED:
        _VERIFIED.move_to_end(key)
        return True
    if key in _REJECTED:
        _REJECTED.move_to_end(key)
        return False
    ok = pub_key.verify_signature(msg, sig)
    if ok:
        _memo_add(key)
    else:
        _memo_reject(key)
    return ok


def preverify_signatures(entries) -> None:
    """Batch-verify (pub_key, msg, sig) triples and memoize both
    verdicts.  Never raises and proves nothing on its own: entries the
    batch could not judge (None mask — unsupported key type, malformed
    input, singleton group, verifier error) are left for the caller's
    serial path to verify and reject with its own errors.

    A False mask entry is confirmed by ONE serial verify before it
    enters the negative memo: the CPU/BLS batch verifiers' reject
    masks are already exact (per-signature fallback), but the TPU
    kernel's are the kernel's own verdicts — the serial verifier must
    keep final say, or a kernel false-negative would be cached as
    invalid-forever and this node would reject votes its peers accept
    (consensus divergence).  The confirmation costs what the caller's
    serial path would have paid anyway; re-sent storms then hit the
    memo."""
    from ..crypto import batch as crypto_batch

    fresh = []
    keys = []
    for pub_key, msg, sig in entries:
        key = _memo_key(pub_key, msg, sig)
        if key in _VERIFIED or key in _REJECTED:
            continue
        fresh.append((pub_key, msg, sig))
        keys.append(key)
    if len(fresh) < 2:
        return
    mask = crypto_batch.batch_verify_by_type(fresh)
    for (pub_key, msg, sig), key, good in zip(fresh, keys, mask):
        if good:
            _memo_add(key)
        elif good is not None:
            if pub_key.verify_signature(msg, sig):
                _memo_add(key)           # batch false-negative fixed
            else:
                _memo_reject(key)


def preverify_signatures_async(entries):
    """``preverify_signatures`` on the verification staging worker:
    returns a concurrent Future that resolves (to None) once the
    burst's verdicts are memoized — the consensus receive routine
    awaits it as a verdict barrier while the event loop keeps
    draining gossip (consensus/state.py).  Memo reads/writes are
    single-op dict mutations, atomic under the GIL, so the worker
    and the loop-side ``checked_verify`` interleave safely; the memo
    is advisory either way (a miss just re-verifies serially)."""
    from ..crypto import pipeline
    return pipeline.submit(preverify_signatures, entries)


@dataclass
class Vote:
    type: int = canonical.UNKNOWN_TYPE
    height: int = 0
    round: int = 0
    block_id: BlockID = field(default_factory=BlockID)
    timestamp: Timestamp = field(default_factory=Timestamp.zero)
    validator_address: bytes = b""
    validator_index: int = 0
    signature: bytes = b""
    extension: bytes = b""
    extension_signature: bytes = b""
    non_rp_extension: bytes = b""
    non_rp_extension_signature: bytes = b""

    # ------------------------------------------------------------------
    def sign_bytes(self, chain_id: str) -> bytes:
        # memoized on the FULL signed-field tuple: the burst
        # pre-verification and the serial verify both marshal the same
        # canonical bytes on the consensus hot loop.  Keying every
        # signed field (not just chain id + timestamp) means staleness
        # safety is enforced rather than resting on a never-mutate
        # invariant — privval's double-sign protection really does
        # rebind vote.timestamp on the same-HRS re-sign path
        # (privval/file.py) after sign bytes may have been computed,
        # and any future mutation of another signed field now misses
        # the memo instead of silently signing stale bytes.
        # (signature/extensions are set later but are not signed over.)
        key = (chain_id, self.type, self.height, self.round,
               self.block_id, self.timestamp)
        cache = self.__dict__.get("_sb_memo")
        if cache is not None and cache[0] == key:
            return cache[1]
        sb = canonical.vote_sign_bytes(
            chain_id, self.type, self.height, self.round, self.block_id,
            self.timestamp)
        self.__dict__["_sb_memo"] = (key, sb)
        return sb

    def extension_sign_bytes(self, chain_id: str) -> bytes:
        return canonical.vote_extension_sign_bytes(
            chain_id, self.height, self.round, self.extension)

    def non_rp_extension_sign_bytes(self) -> bytes:
        """Reference: vote.go VoteExtensionSignBytes (:173-183) — the
        non-replay-protected extension signs its raw bytes (no chain-id /
        height canonicalization, by design)."""
        return self.non_rp_extension

    def is_nil(self) -> bool:
        return self.block_id.is_nil()

    # ------------------------------------------------------------------
    def _verify_vote_sig(self, chain_id: str, pub_key: PubKey) -> None:
        if pub_key.address() != self.validator_address:
            raise InvalidSignatureError(
                "vote validator address does not match pubkey")
        if not checked_verify(pub_key, self.sign_bytes(chain_id),
                              self.signature):
            raise InvalidSignatureError("invalid vote signature")

    def verify(self, chain_id: str, pub_key: PubKey) -> None:
        """Reference: vote.go Verify — vote signature only."""
        self._verify_vote_sig(chain_id, pub_key)

    def verify_vote_and_extension(self, chain_id: str,
                                  pub_key: PubKey) -> None:
        """Reference: vote.go VerifyVoteAndExtension — for precommits on a
        block, additionally checks the extension signature."""
        self._verify_vote_sig(chain_id, pub_key)
        if (self.type == canonical.PRECOMMIT_TYPE and
                not self.block_id.is_nil()):
            self.verify_extension(chain_id, pub_key)

    def verify_extension(self, chain_id: str, pub_key: PubKey) -> None:
        """Reference: vote.go VerifyExtension (:280-299) — both the
        replay-protected and the non-RP extension signatures are required
        and checked for non-nil precommits."""
        if self.type != canonical.PRECOMMIT_TYPE or self.block_id.is_nil():
            return
        if not self.extension_signature or \
                not self.non_rp_extension_signature:
            raise InvalidSignatureError("vote extension signature missing")
        if not checked_verify(pub_key,
                              self.extension_sign_bytes(chain_id),
                              self.extension_signature):
            raise InvalidSignatureError("invalid vote extension signature")
        if not checked_verify(pub_key,
                              self.non_rp_extension_sign_bytes(),
                              self.non_rp_extension_signature):
            raise InvalidSignatureError(
                "invalid non-RP vote extension signature")

    # ------------------------------------------------------------------
    def validate_basic(self) -> None:
        """Reference: vote.go ValidateBasic."""
        if not canonical.is_vote_type_valid(self.type):
            raise VoteError(f"invalid vote type {self.type}")
        if self.height <= 0:
            raise VoteError("vote height must be positive")
        if self.round < 0:
            raise VoteError("vote round must be non-negative")
        try:
            self.block_id.validate_basic()
        except PartSetError as e:
            raise VoteError(f"wrong BlockID: {e}") from e
        if not self.block_id.is_nil() and not self.block_id.is_complete():
            raise VoteError("BlockID must be either empty or complete")
        if len(self.validator_address) != 20:
            raise VoteError("wrong validator address size")
        if self.validator_index < 0:
            raise VoteError("negative validator index")
        if len(self.signature) == 0:
            raise VoteError("signature is missing")
        if len(self.signature) > MAX_SIGNATURE_SIZE:
            raise VoteError("signature is too big")
        if self.type == canonical.PRECOMMIT_TYPE and \
                not self.block_id.is_nil():
            if len(self.extension) > MAX_VOTE_EXTENSION_SIZE:
                raise VoteError("vote extension too big")
            if self.extension and not self.extension_signature:
                raise VoteError("vote extension signature is missing")
            if len(self.non_rp_extension) > MAX_VOTE_EXTENSION_SIZE:
                raise VoteError("non-RP vote extension too big")
            if len(self.non_rp_extension_signature) > MAX_SIGNATURE_SIZE:
                raise VoteError("non-RP extension signature is too big")
            if self.non_rp_extension and \
                    not self.non_rp_extension_signature:
                raise VoteError("non-RP extension signature is missing")
            # reference vote.go:385 — the two extension signatures come
            # as a pair: both present (extensions enabled) or neither
            if bool(self.extension_signature) != \
                    bool(self.non_rp_extension_signature):
                raise VoteError(
                    "extension signatures must both be present or absent")
        else:
            # reference: extensions only allowed on non-nil precommits
            if self.extension or self.extension_signature or \
                    self.non_rp_extension or self.non_rp_extension_signature:
                raise VoteError(
                    "unexpected vote extension on non-precommit vote")

    # ------------------------------------------------------------------
    def commit_sig(self) -> dict:
        """CommitSig view of this vote (reference: vote.go CommitSig)."""
        if self.block_id.is_nil():
            flag = BLOCK_ID_FLAG_NIL
        else:
            flag = BLOCK_ID_FLAG_COMMIT
        return {
            "block_id_flag": flag,
            "validator_address": self.validator_address,
            "timestamp": self.timestamp,
            "signature": self.signature,
        }

    def to_proto(self) -> dict:
        d: dict = {
            "block_id": self.block_id.to_proto(),
            "timestamp": self.timestamp.to_proto(),
        }
        if self.type:
            d["type"] = self.type
        if self.height:
            d["height"] = self.height
        if self.round:
            d["round"] = self.round
        if self.validator_address:
            d["validator_address"] = self.validator_address
        if self.validator_index:
            d["validator_index"] = self.validator_index
        if self.signature:
            d["signature"] = self.signature
        if self.extension:
            d["extension"] = self.extension
        if self.extension_signature:
            d["extension_signature"] = self.extension_signature
        if self.non_rp_extension:
            d["non_rp_extension"] = self.non_rp_extension
        if self.non_rp_extension_signature:
            d["non_rp_extension_signature"] = self.non_rp_extension_signature
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "Vote":
        return cls(
            type=d.get("type", 0),
            height=d.get("height", 0),
            round=d.get("round", 0),
            block_id=BlockID.from_proto(d.get("block_id") or {}),
            timestamp=Timestamp.from_proto(d.get("timestamp") or {}),
            validator_address=d.get("validator_address", b""),
            validator_index=d.get("validator_index", 0),
            signature=d.get("signature", b""),
            extension=d.get("extension", b""),
            extension_signature=d.get("extension_signature", b""),
            non_rp_extension=d.get("non_rp_extension", b""),
            non_rp_extension_signature=d.get(
                "non_rp_extension_signature", b""),
        )

    def copy(self) -> "Vote":
        return replace(self)

    def __str__(self) -> str:
        tname = {1: "Prevote", 2: "Precommit"}.get(self.type, "?")
        return (f"Vote{{{self.validator_index}:"
                f"{self.validator_address.hex().upper()[:12]} "
                f"{self.height}/{self.round:02d} {tname} "
                f"{self.block_id}}}")
