"""Transactions and their merkle hashing.

Reference: types/tx.go — Tx.Hash = sha256(tx); Txs.Hash = merkle root over
the per-tx hashes (leaves are TxIDs); Proof via merkle proofs.
"""
from __future__ import annotations

from typing import Sequence

from ..crypto import merkle, tmhash


def tx_hash(tx: bytes) -> bytes:
    return tmhash.sum(tx)


def tx_key(tx: bytes) -> bytes:
    """Map key for mempool dedup (reference: types/tx.go TxKey —
    the sha256 of the tx)."""
    return tmhash.sum(tx)


def txs_hash(txs: Sequence[bytes]) -> bytes:
    return merkle.hash_from_byte_slices(hash_each(txs))


def hash_each(txs: Sequence[bytes]) -> list[bytes]:
    """Per-tx sha256 digests, batched through the C++ fast path for
    larger blocks (reference: Txs.Hash's per-tx TxID loop)."""
    from ..crypto._native_loader import batched_hashes
    hashes = batched_hashes("sha256_many", txs)
    return hashes if hashes is not None else \
        [tx_hash(tx) for tx in txs]


def txs_proof(txs: Sequence[bytes], index: int):
    """(root, proof) of tx at index (reference: Txs.Proof)."""
    root, proofs = merkle.proofs_from_byte_slices(
        [tx_hash(tx) for tx in txs])
    return root, proofs[index]


def compute_proto_size_overhead(n: int) -> int:
    """Upper-bound proto overhead for a bytes field of length n
    (reference: types/tx.go ComputeProtoSizeForTxs usage)."""
    # field tag (1 byte for field 1) + uvarint length
    ln = n
    bytes_needed = 1
    while ln >= 0x80:
        ln >>= 7
        bytes_needed += 1
    return 1 + bytes_needed
