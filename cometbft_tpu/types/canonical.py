"""Canonical sign-bytes: the exact bytes validators sign.

Reference: types/canonical.go + proto/cometbft/types/v2/canonical.proto.
Height/round are sfixed64 (fixed-size for canonicalization); the BlockID is
dropped entirely for nil votes; sign-bytes are uvarint-length-delimited
(libs/protoio MarshalDelimited).  Byte-identical output is pinned by the
reference's own test vectors (types/vote_test.go TestVoteSignBytesTestVectors)
in tests/test_wire.py.
"""
from __future__ import annotations

from ..wire import pb, marshal_delimited
from .block_id import BlockID
from .timestamp import Timestamp

# SignedMsgType (proto/cometbft/types/v2/types.proto)
UNKNOWN_TYPE = 0
PREVOTE_TYPE = 1
PRECOMMIT_TYPE = 2
PROPOSAL_TYPE = 32


def is_vote_type_valid(t: int) -> bool:
    return t in (PREVOTE_TYPE, PRECOMMIT_TYPE)


def canonicalize_block_id(bid: BlockID) -> dict | None:
    """nil → None (field omitted from sign-bytes); else CanonicalBlockID."""
    if bid.is_nil():
        return None
    d: dict = {"part_set_header": bid.part_set_header.to_proto()}
    if bid.hash:
        d["hash"] = bid.hash
    return d


def _canonical_vote(chain_id: str, type_: int, height: int, round_: int,
                    bid: BlockID, ts: Timestamp) -> dict:
    d: dict = {"timestamp": ts.to_proto()}
    if type_:
        d["type"] = type_
    if height:
        d["height"] = height
    if round_:
        d["round"] = round_
    cbid = canonicalize_block_id(bid)
    if cbid is not None:
        d["block_id"] = cbid
    if chain_id:
        d["chain_id"] = chain_id
    return d


def vote_sign_bytes(chain_id: str, type_: int, height: int, round_: int,
                    bid: BlockID, ts: Timestamp) -> bytes:
    """Reference: types/vote.go VoteSignBytes."""
    return marshal_delimited(
        pb.CANONICAL_VOTE,
        _canonical_vote(chain_id, type_, height, round_, bid, ts))


def vote_extension_sign_bytes(chain_id: str, height: int, round_: int,
                              extension: bytes) -> bytes:
    """Reference: types/vote.go VoteExtensionSignBytes."""
    d: dict = {}
    if extension:
        d["extension"] = extension
    if height:
        d["height"] = height
    if round_:
        d["round"] = round_
    if chain_id:
        d["chain_id"] = chain_id
    return marshal_delimited(pb.CANONICAL_VOTE_EXTENSION, d)


def proposal_sign_bytes(chain_id: str, height: int, round_: int,
                        pol_round: int, bid: BlockID,
                        ts: Timestamp) -> bytes:
    """Reference: types/proposal.go ProposalSignBytes."""
    d: dict = {"type": PROPOSAL_TYPE, "timestamp": ts.to_proto()}
    if height:
        d["height"] = height
    if round_:
        d["round"] = round_
    if pol_round:
        d["pol_round"] = pol_round
    cbid = canonicalize_block_id(bid)
    if cbid is not None:
        d["block_id"] = cbid
    if chain_id:
        d["chain_id"] = chain_id
    return marshal_delimited(pb.CANONICAL_PROPOSAL, d)
