"""Canonical sign-bytes: the exact bytes validators sign.

Reference: types/canonical.go + proto/cometbft/types/v2/canonical.proto.
Height/round are sfixed64 (fixed-size for canonicalization); the BlockID is
dropped entirely for nil votes; sign-bytes are uvarint-length-delimited
(libs/protoio MarshalDelimited).  Byte-identical output is pinned by the
reference's own test vectors (types/vote_test.go TestVoteSignBytesTestVectors)
in tests/test_wire.py.
"""
from __future__ import annotations

from ..wire import pb, marshal_delimited
from .block_id import BlockID
from .timestamp import Timestamp

# SignedMsgType (proto/cometbft/types/v2/types.proto)
UNKNOWN_TYPE = 0
PREVOTE_TYPE = 1
PRECOMMIT_TYPE = 2
PROPOSAL_TYPE = 32


def is_vote_type_valid(t: int) -> bool:
    return t in (PREVOTE_TYPE, PRECOMMIT_TYPE)


def canonicalize_block_id(bid: BlockID) -> dict | None:
    """nil → None (field omitted from sign-bytes); else CanonicalBlockID."""
    if bid.is_nil():
        return None
    d: dict = {"part_set_header": bid.part_set_header.to_proto()}
    if bid.hash:
        d["hash"] = bid.hash
    return d


def _canonical_vote(chain_id: str, type_: int, height: int, round_: int,
                    bid: BlockID, ts: Timestamp) -> dict:
    d: dict = {"timestamp": ts.to_proto()}
    if type_:
        d["type"] = type_
    if height:
        d["height"] = height
    if round_:
        d["round"] = round_
    cbid = canonicalize_block_id(bid)
    if cbid is not None:
        d["block_id"] = cbid
    if chain_id:
        d["chain_id"] = chain_id
    return d


def vote_sign_bytes(chain_id: str, type_: int, height: int, round_: int,
                    bid: BlockID, ts: Timestamp) -> bytes:
    """Reference: types/vote.go VoteSignBytes."""
    return marshal_delimited(
        pb.CANONICAL_VOTE,
        _canonical_vote(chain_id, type_, height, round_, bid, ts))


def _split_canonical_vote_desc():
    """CANONICAL_VOTE split at the timestamp field.  Split descriptors
    (not dict filtering) because timestamp is always=True — encoding
    the full descriptor with the field unset would still emit an empty
    timestamp submessage into the wrong half."""
    from ..wire.proto import Msg
    fields = pb.CANONICAL_VOTE.fields
    if [f.name for f in fields] != \
            ["type", "height", "round", "block_id", "timestamp",
             "chain_id"]:
        # explicit (not assert): must fail fast even under python -O —
        # a drifted descriptor would otherwise emit wrong sign bytes
        raise ValueError("CANONICAL_VOTE field layout drifted; "
                         "fix the template split")
    pre = Msg(pb.CANONICAL_VOTE.name + ".pre", *fields[:4])
    ts = Msg(pb.CANONICAL_VOTE.name + ".ts", fields[4])
    suf = Msg(pb.CANONICAL_VOTE.name + ".suf", fields[5])
    return pre, ts, suf


_CV_SPLIT = None


def vote_sign_bytes_template(chain_id: str, type_: int, height: int,
                             round_: int, bid: BlockID):
    """Returns make(ts) -> the same bytes as vote_sign_bytes for that
    timestamp.  Canonical proto fields marshal in field-number order
    (type=1, height=2, round=3, block_id=4, timestamp=5, chain_id=6),
    so everything except the timestamp field marshals ONCE and each
    vote splices its own timestamp between the two halves — a commit's
    votes share every signed field but the timestamp (~20 us -> ~2 us
    per signature; parity with vote_sign_bytes pinned by tests)."""
    global _CV_SPLIT
    if _CV_SPLIT is None:
        _CV_SPLIT = _split_canonical_vote_desc()
    pre_desc, ts_desc, suf_desc = _CV_SPLIT
    from ..wire.proto import encode, encode_uvarint
    d = _canonical_vote(chain_id, type_, height, round_, bid,
                        Timestamp(0, 0))
    d.pop("timestamp")
    pre = encode(pre_desc, d)
    suf = encode(suf_desc, d)

    def make(ts: Timestamp) -> bytes:
        mid = encode(ts_desc, {"timestamp": ts.to_proto()})
        body_len = len(pre) + len(mid) + len(suf)
        return encode_uvarint(body_len) + pre + mid + suf

    return make


def vote_extension_sign_bytes(chain_id: str, height: int, round_: int,
                              extension: bytes) -> bytes:
    """Reference: types/vote.go VoteExtensionSignBytes."""
    d: dict = {}
    if extension:
        d["extension"] = extension
    if height:
        d["height"] = height
    if round_:
        d["round"] = round_
    if chain_id:
        d["chain_id"] = chain_id
    return marshal_delimited(pb.CANONICAL_VOTE_EXTENSION, d)


def proposal_sign_bytes(chain_id: str, height: int, round_: int,
                        pol_round: int, bid: BlockID,
                        ts: Timestamp) -> bytes:
    """Reference: types/proposal.go ProposalSignBytes."""
    d: dict = {"type": PROPOSAL_TYPE, "timestamp": ts.to_proto()}
    if height:
        d["height"] = height
    if round_:
        d["round"] = round_
    if pol_round:
        d["pol_round"] = pol_round
    cbid = canonicalize_block_id(bid)
    if cbid is not None:
        d["block_id"] = cbid
    if chain_id:
        d["chain_id"] = chain_id
    return marshal_delimited(pb.CANONICAL_PROPOSAL, d)
