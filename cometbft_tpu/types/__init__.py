"""Core consensus types: blocks, votes, validators, commits, evidence.

Mirrors the capability surface of the reference's types/ package (~8.7k LoC)
with byte-identical consensus-critical encodings (sign-bytes, hashes).
"""
