"""Block proposal.

Reference: types/proposal.go — Proposal with POLRound (-1 when no
proof-of-lock), canonical sign-bytes, timely check for PBTS.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from . import canonical
from .block_id import BlockID
from .vote import MAX_SIGNATURE_SIZE
from .part_set import PartSetError
from .timestamp import Timestamp


class ProposalError(Exception):
    pass


@dataclass
class Proposal:
    type: int = canonical.PROPOSAL_TYPE
    height: int = 0
    round: int = 0
    pol_round: int = -1
    block_id: BlockID = field(default_factory=BlockID)
    timestamp: Timestamp = field(default_factory=Timestamp.zero)
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical.proposal_sign_bytes(
            chain_id, self.height, self.round, self.pol_round,
            self.block_id, self.timestamp)

    def validate_basic(self) -> None:
        """Reference: proposal.go ValidateBasic."""
        if self.type != canonical.PROPOSAL_TYPE:
            raise ProposalError("invalid type")
        if self.height <= 0:
            raise ProposalError("height must be positive")
        if self.round < 0:
            raise ProposalError("negative round")
        if self.pol_round < -1 or (self.pol_round >= self.round and
                                   self.pol_round != -1):
            raise ProposalError(
                "POLRound must be -1 or in [0, round)")
        try:
            self.block_id.validate_basic()
        except PartSetError as e:
            raise ProposalError(f"wrong BlockID: {e}") from e
        if not self.block_id.is_complete():
            raise ProposalError("expected a complete, non-empty BlockID")
        if not self.signature:
            raise ProposalError("signature is missing")
        if len(self.signature) > MAX_SIGNATURE_SIZE:
            raise ProposalError("signature is too big")

    def is_timely(self, recv_time: Timestamp, sp) -> bool:
        """PBTS timely check (reference: proposal.go IsTimely):
        proposal time within [recv - precision, recv + delay + precision].
        sp is SynchronyParams (already adapted to the round)."""
        lhs = self.timestamp.unix_ns() - sp.precision_ns
        rhs = self.timestamp.unix_ns() + sp.message_delay_ns + \
            sp.precision_ns
        return lhs <= recv_time.unix_ns() <= rhs

    def to_proto(self) -> dict:
        d: dict = {
            "type": self.type,
            "block_id": self.block_id.to_proto(),
            "timestamp": self.timestamp.to_proto(),
        }
        if self.height:
            d["height"] = self.height
        if self.round:
            d["round"] = self.round
        if self.pol_round:
            d["pol_round"] = self.pol_round
        if self.signature:
            d["signature"] = self.signature
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "Proposal":
        return cls(
            type=d.get("type", 0),
            height=d.get("height", 0),
            round=d.get("round", 0),
            pol_round=d.get("pol_round", 0),
            block_id=BlockID.from_proto(d.get("block_id") or {}),
            timestamp=Timestamp.from_proto(d.get("timestamp") or {}),
            signature=d.get("signature", b""),
        )

    def __str__(self) -> str:
        return (f"Proposal{{{self.height}/{self.round} "
                f"({self.block_id}, -1:{self.pol_round}) "
                f"{self.timestamp.rfc3339()}}}")
