"""Block, Header, Data, SignedHeader, LightBlock, BlockMeta.

Reference: types/block.go — Header.Hash is a merkle root over the 14
field encodings (:446), Block.Hash = Header.Hash, part-set splitting for
gossip, MaxDataBytes accounting.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .. import version as _version
from ..crypto import merkle, tmhash
from ..wire import pb, encode
from .block_id import BlockID
from .commit import AggregateCommit, Commit, CommitError
from .part_set import PartSet, PartSetHeader
from .timestamp import Timestamp

MAX_CHAIN_ID_LEN = 50
# MaxHeaderBytes/MaxOverheadForBlock — reference: types/block.go
MAX_HEADER_BYTES = 626
MAX_OVERHEAD_FOR_BLOCK = 11


class BlockError(Exception):
    pass


def validate_hash(h: bytes) -> None:
    """Reference: types/validation.go ValidateHash — empty or tmhash-sized."""
    if h and len(h) != tmhash.SIZE:
        raise BlockError(
            f"expected size to be {tmhash.SIZE} bytes, got {len(h)} bytes")


def _cdc_bytes(b: bytes) -> bytes:
    """gogotypes.BytesValue wrapping (reference: encoding_helper.go
    cdcEncode); empty input → empty encoding."""
    if not b:
        return b""
    return encode(pb.BYTES_VALUE, {"value": b})


def _cdc_string(s: str) -> bytes:
    if not s:
        return b""
    return encode(pb.STRING_VALUE, {"value": s})


def _cdc_int64(i: int) -> bytes:
    if not i:
        return b""
    return encode(pb.INT64_VALUE, {"value": i})


@dataclass(frozen=True)
class ConsensusVersion:
    block: int = _version.BLOCK_PROTOCOL
    app: int = 0

    def to_proto(self) -> dict:
        d: dict = {}
        if self.block:
            d["block"] = self.block
        if self.app:
            d["app"] = self.app
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "ConsensusVersion":
        return cls(block=d.get("block", 0), app=d.get("app", 0))


@dataclass
class Header:
    version: ConsensusVersion = field(default_factory=ConsensusVersion)
    chain_id: str = ""
    height: int = 0
    time: Timestamp = field(default_factory=Timestamp.zero)
    last_block_id: BlockID = field(default_factory=BlockID)
    last_commit_hash: bytes = b""
    data_hash: bytes = b""
    validators_hash: bytes = b""
    next_validators_hash: bytes = b""
    consensus_hash: bytes = b""
    app_hash: bytes = b""
    last_results_hash: bytes = b""
    evidence_hash: bytes = b""
    proposer_address: bytes = b""

    def hash(self) -> bytes:
        """Merkle root over the 14 encoded fields (reference: block.go:446).

        Returns b"" when the header is incomplete (no ValidatorsHash)."""
        if not self.validators_hash:
            return b""
        leaves = [
            encode(pb.CONSENSUS_VERSION, self.version.to_proto()),
            _cdc_string(self.chain_id),
            _cdc_int64(self.height),
            encode(pb.TIMESTAMP, self.time.to_proto()),
            encode(pb.BLOCK_ID, self.last_block_id.to_proto()),
            _cdc_bytes(self.last_commit_hash),
            _cdc_bytes(self.data_hash),
            _cdc_bytes(self.validators_hash),
            _cdc_bytes(self.next_validators_hash),
            _cdc_bytes(self.consensus_hash),
            _cdc_bytes(self.app_hash),
            _cdc_bytes(self.last_results_hash),
            _cdc_bytes(self.evidence_hash),
            _cdc_bytes(self.proposer_address),
        ]
        return merkle.hash_from_byte_slices(leaves)

    def validate_basic(self) -> None:
        if self.version.block != _version.BLOCK_PROTOCOL:
            raise BlockError(
                f"block protocol is incorrect: got {self.version.block}, "
                f"want {_version.BLOCK_PROTOCOL}")
        if len(self.chain_id) > MAX_CHAIN_ID_LEN:
            raise BlockError("chainID is too long")
        if self.height < 0:
            raise BlockError("negative Height")
        if self.height == 0:
            raise BlockError("zero Height")
        self.last_block_id.validate_basic()
        validate_hash(self.last_commit_hash)
        validate_hash(self.data_hash)
        validate_hash(self.evidence_hash)
        if len(self.proposer_address) != 20:
            raise BlockError("invalid ProposerAddress length")
        validate_hash(self.validators_hash)
        validate_hash(self.next_validators_hash)
        validate_hash(self.consensus_hash)
        validate_hash(self.last_results_hash)

    def to_proto(self) -> dict:
        d: dict = {
            "version": self.version.to_proto(),
            "time": self.time.to_proto(),
            "last_block_id": self.last_block_id.to_proto(),
        }
        if self.chain_id:
            d["chain_id"] = self.chain_id
        if self.height:
            d["height"] = self.height
        for name in ("last_commit_hash", "data_hash", "validators_hash",
                     "next_validators_hash", "consensus_hash", "app_hash",
                     "last_results_hash", "evidence_hash",
                     "proposer_address"):
            v = getattr(self, name)
            if v:
                d[name] = v
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "Header":
        return cls(
            version=ConsensusVersion.from_proto(d.get("version") or {}),
            chain_id=d.get("chain_id", ""),
            height=d.get("height", 0),
            time=Timestamp.from_proto(d.get("time") or {}),
            last_block_id=BlockID.from_proto(d.get("last_block_id") or {}),
            last_commit_hash=d.get("last_commit_hash", b""),
            data_hash=d.get("data_hash", b""),
            validators_hash=d.get("validators_hash", b""),
            next_validators_hash=d.get("next_validators_hash", b""),
            consensus_hash=d.get("consensus_hash", b""),
            app_hash=d.get("app_hash", b""),
            last_results_hash=d.get("last_results_hash", b""),
            evidence_hash=d.get("evidence_hash", b""),
            proposer_address=d.get("proposer_address", b""),
        )


@dataclass
class Data:
    txs: list[bytes] = field(default_factory=list)
    _hash: Optional[bytes] = field(default=None, repr=False, compare=False)

    def hash(self) -> bytes:
        if self._hash is None:
            from .tx import txs_hash
            self._hash = txs_hash(self.txs)
        return self._hash

    def to_proto(self) -> dict:
        return {"txs": list(self.txs)} if self.txs else {}

    @classmethod
    def from_proto(cls, d: dict) -> "Data":
        return cls(txs=list(d.get("txs", [])))


@dataclass
class Block:
    header: Header = field(default_factory=Header)
    data: Data = field(default_factory=Data)
    evidence: list = field(default_factory=list)  # list[Evidence]
    # per-signature Commit, or AggregateCommit on chains past the
    # aggregate-commit enable height (docs/aggregate_commits.md); both
    # expose size/hash/validate_basic/height/round/block_id
    last_commit: Commit | AggregateCommit | None = None

    def hash(self) -> bytes:
        return self.header.hash()

    def block_id(self, part_set_header: PartSetHeader) -> BlockID:
        return BlockID(hash=self.hash(), part_set_header=part_set_header)

    def make_part_set(self, part_size: int | None = None) -> PartSet:
        from .part_set import BLOCK_PART_SIZE
        raw = encode(pb.BLOCK, self.to_proto())
        return PartSet.from_data(raw, part_size or BLOCK_PART_SIZE)

    def evidence_hash(self) -> bytes:
        return merkle.hash_from_byte_slices(
            [ev.bytes() for ev in self.evidence])

    def fill_header(self) -> None:
        """Derive LastCommitHash/DataHash/EvidenceHash (reference:
        block.go fillHeader)."""
        if not self.header.last_commit_hash and self.last_commit:
            self.header.last_commit_hash = self.last_commit.hash()
        if not self.header.data_hash:
            self.header.data_hash = self.data.hash()
        if not self.header.evidence_hash:
            self.header.evidence_hash = self.evidence_hash()

    def validate_basic(self) -> None:
        """Reference: block.go Block.ValidateBasic."""
        self.header.validate_basic()
        if self.last_commit is None:
            if self.header.height != 1:
                raise BlockError("nil LastCommit")
        else:
            try:
                self.last_commit.validate_basic()
            except CommitError as e:
                raise BlockError(f"wrong LastCommit: {e}") from e
            if self.header.last_commit_hash != self.last_commit.hash():
                raise BlockError("wrong LastCommitHash")
        if self.header.data_hash != self.data.hash():
            raise BlockError("wrong DataHash")
        if self.header.evidence_hash != self.evidence_hash():
            raise BlockError("wrong EvidenceHash")

    def to_proto(self) -> dict:
        d: dict = {
            "header": self.header.to_proto(),
            "data": self.data.to_proto(),
            "evidence": {"evidence": [ev.to_proto_wrapped()
                                      for ev in self.evidence]}
            if self.evidence else {},
        }
        if isinstance(self.last_commit, AggregateCommit):
            d["last_aggregate_commit"] = self.last_commit.to_proto()
        elif self.last_commit is not None:
            d["last_commit"] = self.last_commit.to_proto()
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "Block":
        from .evidence import evidence_from_proto_wrapped
        lc = d.get("last_commit")
        lac = d.get("last_aggregate_commit")
        if lc is not None and lac is not None:
            raise BlockError(
                "block carries both per-signature and aggregate "
                "LastCommit")
        last_commit: Commit | AggregateCommit | None = None
        if lc is not None:
            last_commit = Commit.from_proto(lc)
        elif lac is not None:
            last_commit = AggregateCommit.from_proto(lac)
        return cls(
            header=Header.from_proto(d.get("header") or {}),
            data=Data.from_proto(d.get("data") or {}),
            evidence=[evidence_from_proto_wrapped(e)
                      for e in (d.get("evidence") or {}).get("evidence",
                                                             [])],
            last_commit=last_commit,
        )

    @classmethod
    def from_parts(cls, ps: PartSet) -> "Block":
        from ..wire import decode
        return cls.from_proto(decode(pb.BLOCK, ps.assemble()))

    def __str__(self) -> str:
        return (f"Block{{H:{self.header.height} "
                f"#{self.hash().hex().upper()[:12]} "
                f"txs:{len(self.data.txs)}}}")


@dataclass
class SignedHeader:
    header: Optional[Header] = None
    # Commit or AggregateCommit (see Block.last_commit)
    commit: Commit | AggregateCommit | None = None

    def validate_basic(self, chain_id: str) -> None:
        """Reference: block.go SignedHeader.ValidateBasic."""
        if self.header is None:
            raise BlockError("missing header")
        if self.commit is None:
            raise BlockError("missing commit")
        self.header.validate_basic()
        if self.header.chain_id != chain_id:
            raise BlockError(
                f"header belongs to another chain {self.header.chain_id!r}")
        self.commit.validate_basic()
        if self.header.height != self.commit.height:
            raise BlockError("header and commit height mismatch")
        hhash, chash = self.header.hash(), self.commit.block_id.hash
        if hhash != chash:
            raise BlockError("commit signs block which differs from header")

    @property
    def height(self) -> int:
        return self.header.height if self.header else 0

    def to_proto(self) -> dict:
        d: dict = {}
        if self.header is not None:
            d["header"] = self.header.to_proto()
        if isinstance(self.commit, AggregateCommit):
            d["aggregate_commit"] = self.commit.to_proto()
        elif self.commit is not None:
            d["commit"] = self.commit.to_proto()
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "SignedHeader":
        h, c = d.get("header"), d.get("commit")
        ac = d.get("aggregate_commit")
        if c is not None and ac is not None:
            raise BlockError(
                "signed header carries both per-signature and "
                "aggregate commit")
        commit: Commit | AggregateCommit | None = None
        if c is not None:
            commit = Commit.from_proto(c)
        elif ac is not None:
            commit = AggregateCommit.from_proto(ac)
        return cls(
            header=Header.from_proto(h) if h is not None else None,
            commit=commit,
        )


@dataclass
class LightBlock:
    signed_header: Optional[SignedHeader] = None
    validator_set: Optional[object] = None  # ValidatorSet

    def validate_basic(self, chain_id: str) -> None:
        from .validator_set import ValidatorSet
        if self.signed_header is None:
            raise BlockError("missing signed header")
        if self.validator_set is None:
            raise BlockError("missing validator set")
        self.signed_header.validate_basic(chain_id)
        self.validator_set.validate_basic()
        vh = self.validator_set.hash()
        if self.signed_header.header.validators_hash != vh:
            raise BlockError("validator set hash mismatch with header")

    @property
    def height(self) -> int:
        return self.signed_header.height if self.signed_header else 0

    def hash(self) -> bytes:
        return self.signed_header.header.hash() if (
            self.signed_header and self.signed_header.header) else b""

    def to_proto(self) -> dict:
        d: dict = {}
        if self.signed_header is not None:
            d["signed_header"] = self.signed_header.to_proto()
        if self.validator_set is not None:
            d["validator_set"] = self.validator_set.to_proto()
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "LightBlock":
        from .validator_set import ValidatorSet
        sh, vs = d.get("signed_header"), d.get("validator_set")
        return cls(
            signed_header=SignedHeader.from_proto(sh)
            if sh is not None else None,
            validator_set=ValidatorSet.from_proto(vs)
            if vs is not None else None,
        )


@dataclass
class BlockMeta:
    block_id: BlockID = field(default_factory=BlockID)
    block_size: int = 0
    header: Header = field(default_factory=Header)
    num_txs: int = 0

    def to_proto(self) -> dict:
        d: dict = {"block_id": self.block_id.to_proto(),
                   "header": self.header.to_proto()}
        if self.block_size:
            d["block_size"] = self.block_size
        if self.num_txs:
            d["num_txs"] = self.num_txs
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "BlockMeta":
        return cls(
            block_id=BlockID.from_proto(d.get("block_id") or {}),
            block_size=d.get("block_size", 0),
            header=Header.from_proto(d.get("header") or {}),
            num_txs=d.get("num_txs", 0),
        )


def make_block(height: int, txs: list[bytes], last_commit: Commit,
               evidence: list) -> Block:
    """Reference: block.go MakeBlock."""
    b = Block(
        header=Header(height=height),
        data=Data(txs=txs),
        evidence=list(evidence),
        last_commit=last_commit,
    )
    b.fill_header()
    return b
