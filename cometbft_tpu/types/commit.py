"""Commit and ExtendedCommit: the evidence a block was committed.

Reference: types/block.go:634-1300 — CommitSig (one slot per validator,
flag Absent/Commit/Nil), Commit.Hash (merkle over CommitSig proto bytes),
GetVote/VoteSignBytes reconstruction, BFT-time MedianTime.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import merkle
from ..libs.bits import BitArray
from ..wire import pb, encode
from .block_id import BlockID
from .timestamp import Timestamp
from .vote import (
    BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT, BLOCK_ID_FLAG_NIL,
    MAX_SIGNATURE_SIZE, Vote,
)
from . import canonical


_VALID_FLAGS = (BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT,
                BLOCK_ID_FLAG_NIL)


class CommitError(Exception):
    pass


@dataclass
class CommitSig:
    block_id_flag: int = BLOCK_ID_FLAG_ABSENT
    validator_address: bytes = b""
    timestamp: Timestamp = field(default_factory=Timestamp.zero)
    signature: bytes = b""

    @classmethod
    def absent(cls) -> "CommitSig":
        """Reference: NewCommitSigAbsent — validator did not sign.

        Timestamp is the Go zero time so CommitSig proto bytes (and hence
        Commit.Hash) match the reference byte-for-byte."""
        return cls(block_id_flag=BLOCK_ID_FLAG_ABSENT,
                   timestamp=Timestamp.zero())

    def for_block(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_COMMIT

    def absent_flag(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_ABSENT

    def block_id(self, commit_block_id: BlockID) -> BlockID:
        """The BlockID this sig signed over (reference: CommitSig.BlockID)."""
        if self.block_id_flag == BLOCK_ID_FLAG_COMMIT:
            return commit_block_id
        if self.block_id_flag in (BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_NIL):
            return BlockID()
        raise CommitError(f"unknown BlockIDFlag {self.block_id_flag}")

    def validate_basic(self) -> None:
        if self.block_id_flag not in _VALID_FLAGS:
            raise CommitError(f"unknown BlockIDFlag {self.block_id_flag}")
        if self.block_id_flag == BLOCK_ID_FLAG_ABSENT:
            if self.validator_address:
                raise CommitError("validator address is present")
            if not (self.timestamp == Timestamp(0, 0) or
                    self.timestamp.is_zero()):
                raise CommitError("time is present")
            if self.signature:
                raise CommitError("signature is present")
        else:
            if len(self.validator_address) != 20:
                raise CommitError("wrong validator address size")
            if not self.signature:
                raise CommitError("signature is missing")
            if len(self.signature) > MAX_SIGNATURE_SIZE:
                raise CommitError("signature is too big")

    def to_proto(self) -> dict:
        d: dict = {"timestamp": self.timestamp.to_proto()}
        if self.block_id_flag:
            d["block_id_flag"] = self.block_id_flag
        if self.validator_address:
            d["validator_address"] = self.validator_address
        if self.signature:
            d["signature"] = self.signature
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "CommitSig":
        return cls(
            block_id_flag=d.get("block_id_flag", 0),
            validator_address=d.get("validator_address", b""),
            timestamp=Timestamp.from_proto(d.get("timestamp") or {}),
            signature=d.get("signature", b""),
        )


@dataclass
class Commit:
    height: int = 0
    round: int = 0
    block_id: BlockID = field(default_factory=BlockID)
    signatures: list[CommitSig] = field(default_factory=list)
    _hash: bytes | None = field(default=None, repr=False, compare=False)

    def size(self) -> int:
        return len(self.signatures)

    def get_vote(self, val_idx: int) -> Vote:
        """Reconstruct the precommit Vote of validator val_idx.

        Reference: block.go GetVote (:898)."""
        cs = self.signatures[val_idx]
        return Vote(
            type=canonical.PRECOMMIT_TYPE,
            height=self.height,
            round=self.round,
            block_id=cs.block_id(self.block_id),
            timestamp=cs.timestamp,
            validator_address=cs.validator_address,
            validator_index=val_idx,
            signature=cs.signature,
        )

    def vote_sign_bytes(self, chain_id: str, val_idx: int) -> bytes:
        """Canonical signed bytes of validator val_idx's vote.

        A commit's votes share every signed field except the
        timestamp (and the block-id variant selected by the flag), so
        the canonical marshal runs once per (chain id, flag) and each
        vote splices its timestamp — ~10x cheaper on the verification
        hot loops (byte-for-byte parity with the Vote.sign_bytes path
        is pinned in tests/test_types.py).  The memo assumes commits
        are not mutated in place after first use (nothing does; tests
        that rebuild signatures replace whole CommitSig objects, and
        the timestamp/flag are part of the lookup).

        Reference: block.go VoteSignBytes (:921)."""
        cs = self.signatures[val_idx]
        tmpls = self.__dict__.setdefault("_vsb_tmpls", {})
        key = (chain_id, cs.block_id_flag)
        make = tmpls.get(key)
        if make is None:
            make = canonical.vote_sign_bytes_template(
                chain_id, canonical.PRECOMMIT_TYPE, self.height,
                self.round, cs.block_id(self.block_id))
            tmpls[key] = make
        return make(cs.timestamp)

    def validate_basic(self) -> None:
        if self.height < 0:
            raise CommitError("negative Height")
        if self.round < 0:
            raise CommitError("negative Round")
        if self.height >= 1:
            if self.block_id.is_nil():
                raise CommitError("commit cannot be for nil block")
            if not self.signatures:
                raise CommitError("no signatures in commit")
            for i, cs in enumerate(self.signatures):
                try:
                    cs.validate_basic()
                except CommitError as e:
                    raise CommitError(f"wrong CommitSig #{i}: {e}") from e

    def hash(self) -> bytes:
        """Merkle root over CommitSig proto bytes (reference: :988)."""
        if self._hash is None:
            self._hash = merkle.hash_from_byte_slices(
                [encode(pb.COMMIT_SIG, cs.to_proto())
                 for cs in self.signatures])
        return self._hash

    def median_time(self, validators) -> Timestamp:
        """Voting-power-weighted median of commit vote timestamps (BFT time).

        Reference: block.go MedianTime (:968), types/time WeightedMedian."""
        weighted: list[tuple[Timestamp, int]] = []
        total_power = 0
        for cs in self.signatures:
            if cs.absent_flag():
                continue
            _, val = validators.get_by_address(cs.validator_address)
            if val is not None:
                total_power += val.voting_power
                weighted.append((cs.timestamp, val.voting_power))
        median = total_power // 2
        weighted.sort(key=lambda wt: wt[0].unix_ns())
        for ts, w in weighted:
            if median < w:
                return ts
            median -= w
        return Timestamp(0, 0)

    def to_proto(self) -> dict:
        d: dict = {"block_id": self.block_id.to_proto(),
                   "signatures": [cs.to_proto() for cs in self.signatures]}
        if self.height:
            d["height"] = self.height
        if self.round:
            d["round"] = self.round
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "Commit":
        return cls(
            height=d.get("height", 0),
            round=d.get("round", 0),
            block_id=BlockID.from_proto(d.get("block_id") or {}),
            signatures=[CommitSig.from_proto(s)
                        for s in d.get("signatures", [])],
        )

    def wrapped_extended_commit(self) -> "ExtendedCommit":
        """Wrap as an ExtendedCommit with empty extensions (reference:
        :1013)."""
        return ExtendedCommit(
            height=self.height, round=self.round, block_id=self.block_id,
            extended_signatures=[
                ExtendedCommitSig(
                    block_id_flag=cs.block_id_flag,
                    validator_address=cs.validator_address,
                    timestamp=cs.timestamp, signature=cs.signature)
                for cs in self.signatures])


@dataclass
class AggregateCommit:
    """One BLS signature + a signer bitmap for a whole commit
    (TPU-native extension; docs/aggregate_commits.md).

    In aggregate-commit mode every precommit FOR a block signs the
    same canonical message — the zero-timestamp canonical precommit
    over (chain_id, height, round, block_id) — so the signatures sum
    in G2 and verification is one 2-Miller-loop pairing check
    regardless of validator count.  Bit i of ``signers`` means
    validator index i (in the height's validator set) precommitted
    the block; nil and absent precommits are simply unset (their
    signatures cover different messages and cannot be aggregated in).

    There is no per-vote timestamp, so BFT time's weighted median is
    unavailable: consensus params require PBTS at or before the
    aggregate enable height (types/params.py FeatureParams.validate).
    """
    height: int = 0
    round: int = 0
    block_id: BlockID = field(default_factory=BlockID)
    signers: BitArray = field(default_factory=lambda: BitArray(0))
    signature: bytes = b""
    _hash: bytes | None = field(default=None, repr=False, compare=False)

    BLS_SIGNATURE_SIZE = 96

    def size(self) -> int:
        """Validator slots covered (= validator-set size), matching
        Commit.size() so shared size checks work on either kind."""
        return self.signers.size()

    def signed_indices(self) -> list[int]:
        return self.signers.true_indices()

    def signers_bytes(self) -> bytes:
        """Canonical wire form of the bitmap: little-endian packed,
        (size+7)//8 bytes, padding bits zero."""
        return self.signers.to_le_bytes()

    def vote_sign_bytes(self, chain_id: str) -> bytes:
        """THE message every aggregated precommit signed: canonical
        precommit with the zero timestamp (consensus/state.py signs
        precommits with a zero timestamp once aggregate mode is
        enabled, so all signers share one sign-bytes message)."""
        return canonical.vote_sign_bytes(
            chain_id, canonical.PRECOMMIT_TYPE, self.height, self.round,
            self.block_id, Timestamp.zero())

    def validate_basic(self) -> None:
        if self.height < 0:
            raise CommitError("negative Height")
        if self.round < 0:
            raise CommitError("negative Round")
        if self.height >= 1:
            if self.block_id.is_nil():
                raise CommitError(
                    "aggregate commit cannot be for nil block")
            if self.signers.size() == 0:
                raise CommitError("no validator slots in "
                                  "aggregate commit")
            if self.signers.is_empty():
                raise CommitError("no signers in aggregate commit")
            if len(self.signature) != self.BLS_SIGNATURE_SIZE:
                raise CommitError(
                    f"aggregate signature must be "
                    f"{self.BLS_SIGNATURE_SIZE} bytes, "
                    f"got {len(self.signature)}")

    def hash(self) -> bytes:
        """Merkle leaf hash over the proto bytes (the aggregate
        analogue of Commit.hash's merkle over CommitSig protos)."""
        if self._hash is None:
            self._hash = merkle.hash_from_byte_slices(
                [encode(pb.AGGREGATE_COMMIT, self.to_proto())])
        return self._hash

    def median_time(self, validators) -> Timestamp:
        """Aggregate commits carry no per-vote timestamps; BFT time is
        never computed for them (PBTS is required by params
        validation).  Reaching this is a wiring bug, not a data
        error."""
        raise CommitError(
            "aggregate commit has no per-vote timestamps (BFT time "
            "requires per-signature commits; enable PBTS)")

    def to_proto(self) -> dict:
        d: dict = {"block_id": self.block_id.to_proto()}
        if self.height:
            d["height"] = self.height
        if self.round:
            d["round"] = self.round
        if self.signers.size():
            d["signer_count"] = self.signers.size()
        sb = self.signers_bytes()
        if sb:
            d["signers"] = sb
        if self.signature:
            d["signature"] = self.signature
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "AggregateCommit":
        count = d.get("signer_count", 0)
        try:
            ba = BitArray.from_le_bytes(d.get("signers", b""), count)
        except ValueError as e:
            raise CommitError(f"signer bitmap: {e}") from None
        return cls(
            height=d.get("height", 0),
            round=d.get("round", 0),
            block_id=BlockID.from_proto(d.get("block_id") or {}),
            signers=ba,
            signature=d.get("signature", b""),
        )

    @classmethod
    def from_commit(cls, commit: Commit) -> "AggregateCommit":
        """Aggregate a per-signature commit's FOR-block signatures
        (the proposer path: the precommit vote set is materialized as
        a Commit first, then aggregated — O(n) G2 adds through the
        native batched-inversion tree).  All COMMIT-flag signatures
        must be BLS; nil/absent slots stay unset."""
        from ..crypto import bls12381
        ba = BitArray(len(commit.signatures))
        sigs = []
        for i, cs in enumerate(commit.signatures):
            if cs.block_id_flag != BLOCK_ID_FLAG_COMMIT:
                continue
            if len(cs.signature) != cls.BLS_SIGNATURE_SIZE:
                raise CommitError(
                    f"commit sig #{i} is not a BLS signature "
                    f"({len(cs.signature)} bytes)")
            ba.set_index(i, True)
            sigs.append(cs.signature)
        if not sigs:
            raise CommitError("no FOR-block signatures to aggregate")
        try:
            agg = bls12381.aggregate(sigs)
        except ValueError as e:
            raise CommitError(f"cannot aggregate commit: {e}") from e
        return cls(height=commit.height, round=commit.round,
                   block_id=commit.block_id, signers=ba, signature=agg)


@dataclass
class ExtendedCommitSig(CommitSig):
    extension: bytes = b""
    extension_signature: bytes = b""
    non_rp_extension: bytes = b""
    non_rp_extension_signature: bytes = b""

    def ensure_extension(self, ext_enabled: bool) -> None:
        """Reference: block.go EnsureExtension (:791) — BOTH signatures
        (replay-protected and non-RP) required on COMMIT entries."""
        if ext_enabled:
            if self.block_id_flag == BLOCK_ID_FLAG_COMMIT and \
                    (not self.extension_signature or
                     not self.non_rp_extension_signature):
                raise CommitError(
                    "vote extension signature missing with extensions "
                    "enabled")
            if self.block_id_flag != BLOCK_ID_FLAG_COMMIT and \
                    (self.extension or self.non_rp_extension or
                     self.extension_signature or
                     self.non_rp_extension_signature):
                raise CommitError(
                    "non-commit vote extension (signature) present")
        else:
            if self.extension or self.extension_signature or \
                    self.non_rp_extension or self.non_rp_extension_signature:
                raise CommitError(
                    "vote extension present with extensions disabled")

    def to_proto(self) -> dict:
        d = super().to_proto()
        if self.extension:
            d["extension"] = self.extension
        if self.extension_signature:
            d["extension_signature"] = self.extension_signature
        if self.non_rp_extension:
            d["non_rp_extension"] = self.non_rp_extension
        if self.non_rp_extension_signature:
            d["non_rp_extension_signature"] = self.non_rp_extension_signature
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "ExtendedCommitSig":
        return cls(
            block_id_flag=d.get("block_id_flag", 0),
            validator_address=d.get("validator_address", b""),
            timestamp=Timestamp.from_proto(d.get("timestamp") or {}),
            signature=d.get("signature", b""),
            extension=d.get("extension", b""),
            extension_signature=d.get("extension_signature", b""),
            non_rp_extension=d.get("non_rp_extension", b""),
            non_rp_extension_signature=d.get(
                "non_rp_extension_signature", b""),
        )


@dataclass
class ExtendedCommit:
    height: int = 0
    round: int = 0
    block_id: BlockID = field(default_factory=BlockID)
    extended_signatures: list[ExtendedCommitSig] = field(
        default_factory=list)

    def size(self) -> int:
        return len(self.extended_signatures)

    def is_commit(self) -> bool:
        return len(self.extended_signatures) != 0

    def to_commit(self) -> Commit:
        """Strip extensions (reference: block.go ToCommit :1184)."""
        return Commit(
            height=self.height, round=self.round, block_id=self.block_id,
            signatures=[
                CommitSig(block_id_flag=ecs.block_id_flag,
                          validator_address=ecs.validator_address,
                          timestamp=ecs.timestamp,
                          signature=ecs.signature)
                for ecs in self.extended_signatures])

    def get_extended_vote(self, val_idx: int) -> Vote:
        """Reference: block.go GetExtendedVote (:1200)."""
        ecs = self.extended_signatures[val_idx]
        return Vote(
            type=canonical.PRECOMMIT_TYPE,
            height=self.height, round=self.round,
            block_id=ecs.block_id(self.block_id),
            timestamp=ecs.timestamp,
            validator_address=ecs.validator_address,
            validator_index=val_idx,
            signature=ecs.signature,
            extension=ecs.extension,
            extension_signature=ecs.extension_signature,
            non_rp_extension=ecs.non_rp_extension,
            non_rp_extension_signature=ecs.non_rp_extension_signature,
        )

    def ensure_extensions(self, ext_enabled: bool) -> None:
        for ecs in self.extended_signatures:
            ecs.ensure_extension(ext_enabled)

    def validate_basic(self) -> None:
        if self.height < 0:
            raise CommitError("negative Height")
        if self.round < 0:
            raise CommitError("negative Round")
        if self.height >= 1:
            if self.block_id.is_nil():
                raise CommitError("extended commit cannot be for nil block")
            if not self.extended_signatures:
                raise CommitError("no signatures in commit")
            for i, ecs in enumerate(self.extended_signatures):
                try:
                    ecs.validate_basic()
                except CommitError as e:
                    raise CommitError(
                        f"wrong ExtendedCommitSig #{i}: {e}") from e

    def to_proto(self) -> dict:
        d: dict = {
            "block_id": self.block_id.to_proto(),
            "extended_signatures": [ecs.to_proto()
                                    for ecs in self.extended_signatures],
        }
        if self.height:
            d["height"] = self.height
        if self.round:
            d["round"] = self.round
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "ExtendedCommit":
        return cls(
            height=d.get("height", 0),
            round=d.get("round", 0),
            block_id=BlockID.from_proto(d.get("block_id") or {}),
            extended_signatures=[
                ExtendedCommitSig.from_proto(s)
                for s in d.get("extended_signatures", [])],
        )
