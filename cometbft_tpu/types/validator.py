"""Validator: address, pubkey, voting power, proposer priority.

Reference: types/validator.go.
"""
from __future__ import annotations

from dataclasses import dataclass, replace

from ..crypto import encoding
from ..crypto.keys import PubKey
from ..wire import pb, encode

INT64_MAX = (1 << 63) - 1
INT64_MIN = -(1 << 63)

# MaxTotalVotingPower — reference: types/validator_set.go (MaxInt64 / 8)
MAX_TOTAL_VOTING_POWER = INT64_MAX // 8
# PriorityWindowSizeFactor — reference: types/validator_set.go
PRIORITY_WINDOW_SIZE_FACTOR = 2


def safe_add_clip(a: int, b: int) -> int:
    c = a + b
    return min(max(c, INT64_MIN), INT64_MAX)


def safe_sub_clip(a: int, b: int) -> int:
    c = a - b
    return min(max(c, INT64_MIN), INT64_MAX)


class ValidatorError(Exception):
    pass


@dataclass
class Validator:
    address: bytes
    pub_key: PubKey
    voting_power: int
    proposer_priority: int = 0

    @classmethod
    def new(cls, pub_key: PubKey, voting_power: int) -> "Validator":
        return cls(address=pub_key.address(), pub_key=pub_key,
                   voting_power=voting_power, proposer_priority=0)

    def validate_basic(self) -> None:
        if self.pub_key is None:
            raise ValidatorError("validator does not have a public key")
        if self.voting_power < 0:
            raise ValidatorError("validator has negative voting power")
        if len(self.address) != 20:
            raise ValidatorError("wrong validator address size")

    def copy(self) -> "Validator":
        return replace(self)

    def compare_proposer_priority(self, other: "Validator") -> "Validator":
        """Higher priority wins; ties break toward the lower address.

        Reference: validator.go CompareProposerPriority."""
        if self.proposer_priority > other.proposer_priority:
            return self
        if self.proposer_priority < other.proposer_priority:
            return other
        if self.address < other.address:
            return self
        if self.address > other.address:
            return other
        raise ValidatorError("cannot compare identical validators")

    def bytes(self) -> bytes:
        """SimpleValidator proto bytes — merkle leaf for ValidatorSet.Hash.

        Reference: validator.go Bytes (:142-158)."""
        return encode(pb.SIMPLE_VALIDATOR, {
            "pub_key": encoding.pub_key_to_proto(self.pub_key),
            "voting_power": self.voting_power,
        })

    def to_proto(self) -> dict:
        d: dict = {}
        if self.address:
            d["address"] = self.address
        if self.voting_power:
            d["voting_power"] = self.voting_power
        if self.proposer_priority:
            d["proposer_priority"] = self.proposer_priority
        d["pub_key_bytes"] = self.pub_key.bytes()
        d["pub_key_type"] = self.pub_key.type()
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "Validator":
        if d.get("pub_key_bytes"):
            pk = encoding.pub_key_from_type_and_bytes(
                d.get("pub_key_type", "ed25519"), d["pub_key_bytes"])
        else:
            pk = encoding.pub_key_from_proto(d.get("pub_key") or {})
        return cls(
            address=d.get("address", b"") or pk.address(),
            pub_key=pk,
            voting_power=d.get("voting_power", 0),
            proposer_priority=d.get("proposer_priority", 0),
        )

    def __str__(self) -> str:
        return (f"Validator{{{self.address.hex().upper()[:12]} "
                f"VP:{self.voting_power} A:{self.proposer_priority}}}")
