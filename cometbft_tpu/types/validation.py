"""Commit verification — the primary TPU offload seam.

Reference: types/validation.go.  Semantics preserved exactly:
  * batching requires >= 2 signatures, a batch-capable key type, and all
    validators sharing one key type (:15-21);
  * VerifyCommit checks ALL signatures (incentivization contract),
    VerifyCommitLight* stop at 2/3 unless the AllSignatures variant;
  * on batch failure, the first invalid signature is identified (:384-397);
  * signature-cache hits skip verification and successes populate the cache.

The batch path dispatches through crypto.batch.create_batch_verifier, which
routes ed25519 batches to the TPU kernel (ops/ed25519_jax.py): one padded
device batch verifies every signature and the voting-power tally is a masked
segment-sum in the same XLA program.

Beyond the reference: MIXED-key commits — where the reference falls back to
per-signature verification outright — run through _verify_commit_grouped,
which batches each key-type group separately (ed25519 → TPU kernel,
bls12381 → one RLC pairings product) and verifies the rest inline, with
verdicts identical to the per-signature path.
"""
from __future__ import annotations

from typing import Callable, NamedTuple, Optional

from ..crypto import batch as crypto_batch
from .commit import Commit, CommitSig, CommitError
from .block_id import BlockID
from .signature_cache import SignatureCache, SignatureCacheValue
from .validator_set import ValidatorSet
from .vote import BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT

BATCH_VERIFY_THRESHOLD = 2


class Fraction(NamedTuple):
    numerator: int
    denominator: int


class VerificationError(Exception):
    pass


class NotEnoughVotingPowerError(VerificationError):
    def __init__(self, got: int, needed: int):
        super().__init__(
            f"invalid commit -- insufficient voting power: got {got}, "
            f"needed more than {needed}")
        self.got = got
        self.needed = needed


def _should_batch_verify(vals: ValidatorSet, commit: Commit) -> bool:
    return (len(commit.signatures) >= BATCH_VERIFY_THRESHOLD and
            crypto_batch.supports_batch_verifier(
                vals.get_proposer().pub_key) and
            vals.all_keys_have_same_type())


def _should_group_verify(vals: ValidatorSet, commit: Commit) -> bool:
    """Mixed-key commits: batch per key-type group when any batchable
    type appears at least twice.  The reference disables batching
    entirely for mixed sets (types/validation.go:15-21 +
    AllKeysHaveSameType); grouping recovers the batch win for the
    dominant key types while unsupported ones verify inline."""
    if len(commit.signatures) < BATCH_VERIFY_THRESHOLD:
        return False
    counts: dict[str, int] = {}
    for val in vals.validators:
        if val.pub_key is None:
            continue
        if crypto_batch.supports_batch_verifier(val.pub_key):
            kt = val.pub_key.type()
            counts[kt] = counts.get(kt, 0) + 1
            if counts[kt] >= 2:
                return True
    return False


def _verify_basic_vals_and_commit(vals: ValidatorSet, commit: Commit,
                                  height: int, block_id: BlockID) -> None:
    if vals is None:
        raise VerificationError("nil validator set")
    if commit is None:
        raise VerificationError("nil commit")
    if vals.size() != len(commit.signatures):
        raise VerificationError(
            f"invalid commit -- wrong set size: {vals.size()} vs "
            f"{len(commit.signatures)}")
    if height != commit.height:
        raise VerificationError(
            f"invalid commit -- wrong height: {height} vs {commit.height}")
    if block_id != commit.block_id:
        raise VerificationError(
            f"invalid commit -- wrong block ID: want {block_id}, "
            f"got {commit.block_id}")


def verify_commit(chain_id: str, vals: ValidatorSet, block_id: BlockID,
                  height: int, commit: Commit,
                  cache: Optional[SignatureCache] = None) -> None:
    """+2/3 signed; checks ALL signatures (reference: VerifyCommit :30)."""
    _verify_basic_vals_and_commit(vals, commit, height, block_id)
    voting_power_needed = vals.total_voting_power() * 2 // 3
    ignore = lambda c: c.block_id_flag == BLOCK_ID_FLAG_ABSENT  # noqa: E731
    count = lambda c: c.block_id_flag == BLOCK_ID_FLAG_COMMIT  # noqa: E731
    if _should_batch_verify(vals, commit):
        _verify_commit_batch(
            chain_id, vals, commit, voting_power_needed, ignore, count,
            count_all_signatures=True, look_up_by_index=True, cache=cache)
    elif _should_group_verify(vals, commit):
        _verify_commit_grouped(
            chain_id, vals, commit, voting_power_needed, ignore, count,
            count_all_signatures=True, look_up_by_index=True, cache=cache)
    else:
        _verify_commit_single(
            chain_id, vals, commit, voting_power_needed, ignore, count,
            count_all_signatures=True, look_up_by_index=True, cache=cache)


def verify_commit_light(chain_id: str, vals: ValidatorSet,
                        block_id: BlockID, height: int, commit: Commit,
                        count_all_signatures: bool = False,
                        cache: Optional[SignatureCache] = None) -> None:
    """Light-client variant: stops at 2/3 unless count_all_signatures.

    Reference: VerifyCommitLight / ...AllSignatures / ...WithCache (:65)."""
    _verify_basic_vals_and_commit(vals, commit, height, block_id)
    voting_power_needed = vals.total_voting_power() * 2 // 3
    ignore = lambda c: c.block_id_flag != BLOCK_ID_FLAG_COMMIT  # noqa: E731
    count = lambda c: True  # noqa: E731
    if _should_batch_verify(vals, commit):
        _verify_commit_batch(
            chain_id, vals, commit, voting_power_needed, ignore, count,
            count_all_signatures=count_all_signatures,
            look_up_by_index=True, cache=cache)
    elif _should_group_verify(vals, commit):
        _verify_commit_grouped(
            chain_id, vals, commit, voting_power_needed, ignore, count,
            count_all_signatures=count_all_signatures,
            look_up_by_index=True, cache=cache)
    else:
        _verify_commit_single(
            chain_id, vals, commit, voting_power_needed, ignore, count,
            count_all_signatures=count_all_signatures,
            look_up_by_index=True, cache=cache)


def verify_commit_light_trusting(
        chain_id: str, vals: ValidatorSet, commit: Commit,
        trust_level: Fraction, count_all_signatures: bool = False,
        cache: Optional[SignatureCache] = None) -> None:
    """trustLevel (e.g. 1/3) of a TRUSTED validator set signed; used for
    skipping verification.  Looks validators up by address since the sets
    need not correspond (reference: VerifyCommitLightTrusting :150)."""
    if vals is None:
        raise VerificationError("nil validator set")
    if trust_level.denominator == 0:
        raise VerificationError("trustLevel has zero Denominator")
    if commit is None:
        raise VerificationError("nil commit")
    product = vals.total_voting_power() * trust_level.numerator
    if product >= (1 << 63):
        raise VerificationError(
            "int64 overflow while calculating voting power needed")
    voting_power_needed = product // trust_level.denominator
    ignore = lambda c: c.block_id_flag != BLOCK_ID_FLAG_COMMIT  # noqa: E731
    count = lambda c: True  # noqa: E731
    if _should_batch_verify(vals, commit):
        _verify_commit_batch(
            chain_id, vals, commit, voting_power_needed, ignore, count,
            count_all_signatures=count_all_signatures,
            look_up_by_index=False, cache=cache)
    elif _should_group_verify(vals, commit):
        _verify_commit_grouped(
            chain_id, vals, commit, voting_power_needed, ignore, count,
            count_all_signatures=count_all_signatures,
            look_up_by_index=False, cache=cache)
    else:
        _verify_commit_single(
            chain_id, vals, commit, voting_power_needed, ignore, count,
            count_all_signatures=count_all_signatures,
            look_up_by_index=False, cache=cache)

# ---------------------------------------------------------------------------


def _walk_commit(
        chain_id: str, vals: ValidatorSet, commit: Commit,
        voting_power_needed: int,
        ignore_sig: Callable[[CommitSig], bool],
        count_sig: Callable[[CommitSig], bool],
        count_all_signatures: bool, look_up_by_index: bool,
        cache: Optional[SignatureCache], strict: bool,
        handle: Callable) -> int:
    """The signature walk shared by the three verification paths
    (single / batch / grouped): ignore filter, optional structural
    validation, by-index or by-address validator lookup with
    double-vote detection, cache short-circuit, voting-power tally
    with the early exit.  Returns the tallied power.

    handle(idx, val, sign_bytes, commit_sig) is called for every
    signature the cache does not satisfy — it verifies inline
    (raising VerificationError) or defers into a batch verifier;
    returning False stops the walk (the grouped path uses this to
    reconcile an inline failure against its deferred groups before
    reporting, so the LOWEST failing index is named either way).

    strict adds commit_sig.validate_basic() (the per-signature path's
    behavior); the same-type batch path omits it, mirroring the
    reference's verifyCommitBatch.  The nil-pubkey check is
    UNCONDITIONAL on every path — see the comment at the raise.
    """
    seen_vals: dict[int, int] = {}
    tallied = 0
    for idx, commit_sig in enumerate(commit.signatures):
        if ignore_sig(commit_sig):
            continue
        if strict:
            try:
                commit_sig.validate_basic()
            except CommitError as e:
                raise VerificationError(
                    f"invalid signature at index {idx}: {e}") from e
        if look_up_by_index:
            val = vals.validators[idx]
        else:
            val_idx, val = vals.get_by_address(
                commit_sig.validator_address)
            if val is None:
                continue
            if val_idx in seen_vals:
                raise VerificationError(
                    f"double vote from {val} "
                    f"({seen_vals[val_idx]} and {idx})")
            seen_vals[val_idx] = idx
        if val.pub_key is None:
            # unconditional (not strict-gated): the same-type gate
            # skips nil-pubkey validators, so a nil key CAN reach the
            # batch path, where BatchVerifier.add would raise
            # TypeError and the cache probe below would crash — the
            # reference's batch path rejects via Add's error return
            raise VerificationError(
                f"validator {val} has a nil PubKey at index {idx}")

        vote_sign_bytes = commit.vote_sign_bytes(chain_id, idx)

        cache_hit = False
        if cache is not None:
            cv = cache.get(commit_sig.signature)
            cache_hit = (cv is not None and
                         cv.validator_address == val.pub_key.address() and
                         cv.vote_sign_bytes == vote_sign_bytes)
        if not cache_hit:
            if handle(idx, val, vote_sign_bytes, commit_sig) is False:
                break

        if count_sig(commit_sig):
            tallied += val.voting_power
        if not count_all_signatures and tallied > voting_power_needed:
            break
    return tallied


def _verify_commit_batch(
        chain_id: str, vals: ValidatorSet, commit: Commit,
        voting_power_needed: int,
        ignore_sig: Callable[[CommitSig], bool],
        count_sig: Callable[[CommitSig], bool],
        count_all_signatures: bool, look_up_by_index: bool,
        cache: Optional[SignatureCache]) -> None:
    """Reference: verifyCommitBatch (:265) — including its ordering:
    the voting-power threshold is judged before the deferred batch
    runs.  Cache entries record the VERIFIED key's address, never
    commit_sig.validator_address: in by-index mode that field is
    attacker-controlled, and caching it would let one validator's
    signature poison the cache under another validator's address
    (canonical vote sign bytes exclude address/index, so a later
    by-index lookup in the other validator's slot would hit)."""
    bv = crypto_batch.create_batch_verifier(vals.get_proposer().pub_key)
    entries: list[tuple[int, bytes, bytes]] = []

    def handle(idx, val, sign_bytes, commit_sig):
        try:
            bv.add(val.pub_key, sign_bytes, commit_sig.signature)
        except (ValueError, TypeError) as e:
            # malformed (e.g. wrong-length) signature the structural
            # checks let through — the reference returns Add's error
            # here; surface it as the usual wrong-signature verdict
            raise VerificationError(
                f"wrong signature (#{idx}): "
                f"{commit_sig.signature.hex().upper()}") from e
        entries.append((idx, val.pub_key.address(), sign_bytes))

    tallied = _walk_commit(
        chain_id, vals, commit, voting_power_needed, ignore_sig,
        count_sig, count_all_signatures, look_up_by_index, cache,
        strict=False, handle=handle)

    if tallied <= voting_power_needed:
        raise NotEnoughVotingPowerError(tallied, voting_power_needed)

    if not entries:
        return  # everything was cached

    ok, valid_sigs = bv.verify()
    if ok:
        if cache is not None:
            for idx, addr, sign_bytes in entries:
                cache.add(commit.signatures[idx].signature,
                          SignatureCacheValue(addr, sign_bytes))
        return

    # find and report the first invalid signature
    for sig_ok, (idx, addr, sign_bytes) in zip(valid_sigs, entries):
        sig = commit.signatures[idx]
        if not sig_ok:
            raise VerificationError(
                f"wrong signature (#{idx}): {sig.signature.hex().upper()}")
        if cache is not None:
            cache.add(sig.signature,
                      SignatureCacheValue(addr, sign_bytes))
    raise VerificationError(
        "BUG: batch verification failed with no invalid signatures")


def _verify_commit_grouped(
        chain_id: str, vals: ValidatorSet, commit: Commit,
        voting_power_needed: int,
        ignore_sig: Callable[[CommitSig], bool],
        count_sig: Callable[[CommitSig], bool],
        count_all_signatures: bool, look_up_by_index: bool,
        cache: Optional[SignatureCache]) -> None:
    """Mixed-key commit verification with per-key-type batch groups
    (TPU-native extension; see _should_group_verify).  Walk semantics
    match _verify_commit_single (strict structural checks, cache,
    early threshold exit); batchable signatures defer into one
    verifier per key type, unsupported ones verify inline.  Verdict
    parity with the single path: any invalid signature raises
    VerificationError naming the LOWEST failing commit index — an
    inline failure stops the walk and is reconciled against the
    deferred groups before reporting — and does so before the
    voting-power threshold is judged, as inline verification would.
    """
    # key type -> (verifier, [(idx, key address, sign bytes)])
    groups: dict[str, tuple] = {}
    inline_bad: Optional[int] = None

    def handle(idx, val, sign_bytes, commit_sig):
        nonlocal inline_bad
        if crypto_batch.supports_batch_verifier(val.pub_key):
            kt = val.pub_key.type()
            entry = groups.get(kt)
            if entry is None:
                entry = (crypto_batch.create_batch_verifier(val.pub_key),
                         [])
                groups[kt] = entry
            try:
                entry[0].add(val.pub_key, sign_bytes,
                             commit_sig.signature)
            except (ValueError, TypeError):
                # malformed signature the structural checks let
                # through (e.g. wrong length): same verdict as a
                # failed inline verify, reconciled for lowest index
                inline_bad = idx
                return False
            entry[1].append((idx, val.pub_key.address(), sign_bytes))
            return None
        if not val.pub_key.verify_signature(sign_bytes,
                                            commit_sig.signature):
            inline_bad = idx
            return False        # stop: reconcile vs deferred groups
        if cache is not None:
            cache.add(commit_sig.signature, SignatureCacheValue(
                val.pub_key.address(), sign_bytes))
        return None

    tallied = _walk_commit(
        chain_id, vals, commit, voting_power_needed, ignore_sig,
        count_sig, count_all_signatures, look_up_by_index, cache,
        strict=True, handle=handle)

    first_bad: Optional[int] = inline_bad
    for bv, entries in groups.values():
        if not entries:
            continue
        ok, valid_sigs = bv.verify()
        if ok:
            if cache is not None:
                for idx, addr, sign_bytes in entries:
                    cache.add(commit.signatures[idx].signature,
                              SignatureCacheValue(addr, sign_bytes))
            continue
        group_bad = [entries[i][0] for i, sig_ok in enumerate(valid_sigs)
                     if not sig_ok]
        if not group_bad:
            raise VerificationError(
                "BUG: batch verification failed with no invalid "
                "signatures")
        if cache is not None:
            bad_set = set(group_bad)
            for idx, addr, sign_bytes in entries:
                if idx not in bad_set:
                    cache.add(commit.signatures[idx].signature,
                              SignatureCacheValue(addr, sign_bytes))
        if first_bad is None or group_bad[0] < first_bad:
            first_bad = group_bad[0]
    if first_bad is not None:
        sig = commit.signatures[first_bad]
        raise VerificationError(
            f"wrong signature (#{first_bad}): "
            f"{sig.signature.hex().upper()}")

    if tallied <= voting_power_needed:
        raise NotEnoughVotingPowerError(tallied, voting_power_needed)


def _verify_commit_single(
        chain_id: str, vals: ValidatorSet, commit: Commit,
        voting_power_needed: int,
        ignore_sig: Callable[[CommitSig], bool],
        count_sig: Callable[[CommitSig], bool],
        count_all_signatures: bool, look_up_by_index: bool,
        cache: Optional[SignatureCache]) -> None:
    """Reference: verifyCommitSingle (:413)."""

    def handle(idx, val, sign_bytes, commit_sig):
        if not val.pub_key.verify_signature(sign_bytes,
                                            commit_sig.signature):
            raise VerificationError(
                f"wrong signature (#{idx}): "
                f"{commit_sig.signature.hex().upper()}")
        if cache is not None:
            cache.add(commit_sig.signature, SignatureCacheValue(
                val.pub_key.address(), sign_bytes))

    tallied = _walk_commit(
        chain_id, vals, commit, voting_power_needed, ignore_sig,
        count_sig, count_all_signatures, look_up_by_index, cache,
        strict=True, handle=handle)

    if tallied <= voting_power_needed:
        raise NotEnoughVotingPowerError(tallied, voting_power_needed)
