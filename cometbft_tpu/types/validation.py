"""Commit verification — the primary TPU offload seam.

Reference: types/validation.go.  Semantics preserved exactly:
  * batching requires >= 2 signatures, a batch-capable key type, and all
    validators sharing one key type (:15-21);
  * VerifyCommit checks ALL signatures (incentivization contract),
    VerifyCommitLight* stop at 2/3 unless the AllSignatures variant;
  * on batch failure, the first invalid signature is identified (:384-397);
  * signature-cache hits skip verification and successes populate the cache.

The batch path dispatches through crypto.batch.create_batch_verifier, which
routes ed25519 batches to the TPU kernel (ops/ed25519_jax.py): one padded
device batch verifies every signature and the voting-power tally is a masked
segment-sum in the same XLA program.
"""
from __future__ import annotations

from typing import Callable, NamedTuple, Optional

from ..crypto import batch as crypto_batch
from .commit import Commit, CommitSig, CommitError
from .block_id import BlockID
from .signature_cache import SignatureCache, SignatureCacheValue
from .validator_set import ValidatorSet
from .vote import BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT

BATCH_VERIFY_THRESHOLD = 2


class Fraction(NamedTuple):
    numerator: int
    denominator: int


class VerificationError(Exception):
    pass


class NotEnoughVotingPowerError(VerificationError):
    def __init__(self, got: int, needed: int):
        super().__init__(
            f"invalid commit -- insufficient voting power: got {got}, "
            f"needed more than {needed}")
        self.got = got
        self.needed = needed


def _should_batch_verify(vals: ValidatorSet, commit: Commit) -> bool:
    return (len(commit.signatures) >= BATCH_VERIFY_THRESHOLD and
            crypto_batch.supports_batch_verifier(
                vals.get_proposer().pub_key) and
            vals.all_keys_have_same_type())


def _verify_basic_vals_and_commit(vals: ValidatorSet, commit: Commit,
                                  height: int, block_id: BlockID) -> None:
    if vals is None:
        raise VerificationError("nil validator set")
    if commit is None:
        raise VerificationError("nil commit")
    if vals.size() != len(commit.signatures):
        raise VerificationError(
            f"invalid commit -- wrong set size: {vals.size()} vs "
            f"{len(commit.signatures)}")
    if height != commit.height:
        raise VerificationError(
            f"invalid commit -- wrong height: {height} vs {commit.height}")
    if block_id != commit.block_id:
        raise VerificationError(
            f"invalid commit -- wrong block ID: want {block_id}, "
            f"got {commit.block_id}")


def verify_commit(chain_id: str, vals: ValidatorSet, block_id: BlockID,
                  height: int, commit: Commit,
                  cache: Optional[SignatureCache] = None) -> None:
    """+2/3 signed; checks ALL signatures (reference: VerifyCommit :30)."""
    _verify_basic_vals_and_commit(vals, commit, height, block_id)
    voting_power_needed = vals.total_voting_power() * 2 // 3
    ignore = lambda c: c.block_id_flag == BLOCK_ID_FLAG_ABSENT  # noqa: E731
    count = lambda c: c.block_id_flag == BLOCK_ID_FLAG_COMMIT  # noqa: E731
    if _should_batch_verify(vals, commit):
        _verify_commit_batch(
            chain_id, vals, commit, voting_power_needed, ignore, count,
            count_all_signatures=True, look_up_by_index=True, cache=cache)
    else:
        _verify_commit_single(
            chain_id, vals, commit, voting_power_needed, ignore, count,
            count_all_signatures=True, look_up_by_index=True, cache=cache)


def verify_commit_light(chain_id: str, vals: ValidatorSet,
                        block_id: BlockID, height: int, commit: Commit,
                        count_all_signatures: bool = False,
                        cache: Optional[SignatureCache] = None) -> None:
    """Light-client variant: stops at 2/3 unless count_all_signatures.

    Reference: VerifyCommitLight / ...AllSignatures / ...WithCache (:65)."""
    _verify_basic_vals_and_commit(vals, commit, height, block_id)
    voting_power_needed = vals.total_voting_power() * 2 // 3
    ignore = lambda c: c.block_id_flag != BLOCK_ID_FLAG_COMMIT  # noqa: E731
    count = lambda c: True  # noqa: E731
    if _should_batch_verify(vals, commit):
        _verify_commit_batch(
            chain_id, vals, commit, voting_power_needed, ignore, count,
            count_all_signatures=count_all_signatures,
            look_up_by_index=True, cache=cache)
    else:
        _verify_commit_single(
            chain_id, vals, commit, voting_power_needed, ignore, count,
            count_all_signatures=count_all_signatures,
            look_up_by_index=True, cache=cache)


def verify_commit_light_trusting(
        chain_id: str, vals: ValidatorSet, commit: Commit,
        trust_level: Fraction, count_all_signatures: bool = False,
        cache: Optional[SignatureCache] = None) -> None:
    """trustLevel (e.g. 1/3) of a TRUSTED validator set signed; used for
    skipping verification.  Looks validators up by address since the sets
    need not correspond (reference: VerifyCommitLightTrusting :150)."""
    if vals is None:
        raise VerificationError("nil validator set")
    if trust_level.denominator == 0:
        raise VerificationError("trustLevel has zero Denominator")
    if commit is None:
        raise VerificationError("nil commit")
    product = vals.total_voting_power() * trust_level.numerator
    if product >= (1 << 63):
        raise VerificationError(
            "int64 overflow while calculating voting power needed")
    voting_power_needed = product // trust_level.denominator
    ignore = lambda c: c.block_id_flag != BLOCK_ID_FLAG_COMMIT  # noqa: E731
    count = lambda c: True  # noqa: E731
    if _should_batch_verify(vals, commit):
        _verify_commit_batch(
            chain_id, vals, commit, voting_power_needed, ignore, count,
            count_all_signatures=count_all_signatures,
            look_up_by_index=False, cache=cache)
    else:
        _verify_commit_single(
            chain_id, vals, commit, voting_power_needed, ignore, count,
            count_all_signatures=count_all_signatures,
            look_up_by_index=False, cache=cache)


# ---------------------------------------------------------------------------


def _verify_commit_batch(
        chain_id: str, vals: ValidatorSet, commit: Commit,
        voting_power_needed: int,
        ignore_sig: Callable[[CommitSig], bool],
        count_sig: Callable[[CommitSig], bool],
        count_all_signatures: bool, look_up_by_index: bool,
        cache: Optional[SignatureCache]) -> None:
    """Reference: verifyCommitBatch (:265)."""
    bv = crypto_batch.create_batch_verifier(vals.get_proposer().pub_key)
    seen_vals: dict[int, int] = {}
    batch_sig_idxs: list[int] = []
    tallied = 0

    for idx, commit_sig in enumerate(commit.signatures):
        if ignore_sig(commit_sig):
            continue
        if look_up_by_index:
            val = vals.validators[idx]
        else:
            val_idx, val = vals.get_by_address(
                commit_sig.validator_address)
            if val is None:
                continue
            if val_idx in seen_vals:
                raise VerificationError(
                    f"double vote from {val} "
                    f"({seen_vals[val_idx]} and {idx})")
            seen_vals[val_idx] = idx

        vote_sign_bytes = commit.vote_sign_bytes(chain_id, idx)

        cache_hit = False
        if cache is not None:
            cv = cache.get(commit_sig.signature)
            cache_hit = (cv is not None and
                         cv.validator_address == val.pub_key.address() and
                         cv.vote_sign_bytes == vote_sign_bytes)
        if not cache_hit:
            bv.add(val.pub_key, vote_sign_bytes, commit_sig.signature)
            batch_sig_idxs.append(idx)

        if count_sig(commit_sig):
            tallied += val.voting_power
        if not count_all_signatures and tallied > voting_power_needed:
            break

    if tallied <= voting_power_needed:
        raise NotEnoughVotingPowerError(tallied, voting_power_needed)

    if not batch_sig_idxs:
        return  # everything was cached

    ok, valid_sigs = bv.verify()
    if ok:
        if cache is not None:
            for i in range(len(valid_sigs)):
                idx = batch_sig_idxs[i]
                sig = commit.signatures[idx]
                cache.add(sig.signature, SignatureCacheValue(
                    sig.validator_address,
                    commit.vote_sign_bytes(chain_id, idx)))
        return

    # find and report the first invalid signature
    for i, sig_ok in enumerate(valid_sigs):
        idx = batch_sig_idxs[i]
        sig = commit.signatures[idx]
        if not sig_ok:
            raise VerificationError(
                f"wrong signature (#{idx}): {sig.signature.hex().upper()}")
        if cache is not None:
            cache.add(sig.signature, SignatureCacheValue(
                sig.validator_address,
                commit.vote_sign_bytes(chain_id, idx)))
    raise VerificationError(
        "BUG: batch verification failed with no invalid signatures")


def _verify_commit_single(
        chain_id: str, vals: ValidatorSet, commit: Commit,
        voting_power_needed: int,
        ignore_sig: Callable[[CommitSig], bool],
        count_sig: Callable[[CommitSig], bool],
        count_all_signatures: bool, look_up_by_index: bool,
        cache: Optional[SignatureCache]) -> None:
    """Reference: verifyCommitSingle (:413)."""
    seen_vals: dict[int, int] = {}
    tallied = 0
    for idx, commit_sig in enumerate(commit.signatures):
        if ignore_sig(commit_sig):
            continue
        try:
            commit_sig.validate_basic()
        except CommitError as e:
            raise VerificationError(
                f"invalid signature at index {idx}: {e}") from e
        if look_up_by_index:
            val = vals.validators[idx]
        else:
            val_idx, val = vals.get_by_address(
                commit_sig.validator_address)
            if val is None:
                continue
            if val_idx in seen_vals:
                raise VerificationError(
                    f"double vote from {val} "
                    f"({seen_vals[val_idx]} and {idx})")
            seen_vals[val_idx] = idx
        if val.pub_key is None:
            raise VerificationError(
                f"validator {val} has a nil PubKey at index {idx}")

        vote_sign_bytes = commit.vote_sign_bytes(chain_id, idx)

        cache_hit = False
        if cache is not None:
            cv = cache.get(commit_sig.signature)
            cache_hit = (cv is not None and
                         cv.validator_address == val.pub_key.address() and
                         cv.vote_sign_bytes == vote_sign_bytes)
        if not cache_hit:
            if not val.pub_key.verify_signature(vote_sign_bytes,
                                                commit_sig.signature):
                raise VerificationError(
                    f"wrong signature (#{idx}): "
                    f"{commit_sig.signature.hex().upper()}")
            if cache is not None:
                cache.add(commit_sig.signature, SignatureCacheValue(
                    val.pub_key.address(), vote_sign_bytes))

        if count_sig(commit_sig):
            tallied += val.voting_power
        if not count_all_signatures and tallied > voting_power_needed:
            return

    if tallied <= voting_power_needed:
        raise NotEnoughVotingPowerError(tallied, voting_power_needed)
