"""Commit verification — the primary TPU offload seam.

Reference: types/validation.go.  Semantics preserved exactly:
  * batching requires >= 2 signatures, a batch-capable key type, and all
    validators sharing one key type (:15-21);
  * VerifyCommit checks ALL signatures (incentivization contract),
    VerifyCommitLight* stop at 2/3 unless the AllSignatures variant;
  * on batch failure, the first invalid signature is identified (:384-397);
  * signature-cache hits skip verification and successes populate the cache.

The batch path dispatches through crypto.batch.create_batch_verifier, which
routes ed25519 batches to the TPU kernel (ops/ed25519_jax.py): one padded
device batch verifies every signature and the voting-power tally is a masked
segment-sum in the same XLA program.

Beyond the reference: MIXED-key commits — where the reference falls back to
per-signature verification outright — run through _verify_commit_grouped,
which batches each key-type group separately (ed25519 → TPU kernel,
bls12381 → one RLC pairings product) and verifies the rest inline, with
verdicts identical to the per-signature path.
"""
from __future__ import annotations

import hashlib
import time
from typing import Callable, NamedTuple, Optional

from ..crypto import batch as crypto_batch
from ..libs.bits import BitArray
from .commit import AggregateCommit, Commit, CommitSig, CommitError
from .block_id import BlockID
from .signature_cache import SignatureCache, SignatureCacheValue
from .validator_set import ValidatorSet
from .vote import BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT

BATCH_VERIFY_THRESHOLD = 2

# metrics v2: commit-verification latency split by commit kind
# ("aggregate" = the O(1) BLS pairing path; "batch"/"grouped"/
# "single" = the per-signature paths).  Process-global registry —
# this module has no node context; /metrics merges DEFAULT in.
_COMMIT_VERIFY_HIST = None


def commit_verify_histogram():
    global _COMMIT_VERIFY_HIST
    if _COMMIT_VERIFY_HIST is None:
        from ..libs import metrics as libmetrics
        _COMMIT_VERIFY_HIST = libmetrics.DEFAULT.histogram(
            "consensus", "commit_verify_seconds",
            "Commit verification latency in seconds, by verification "
            "kind (aggregate = O(1) BLS pairing path; "
            "batch/grouped/single = per-signature paths).",
            labels=("kind",),
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5))
    return _COMMIT_VERIFY_HIST


class _observe_kind:
    """Context manager timing one commit verification into the
    kind-labeled histogram (failures observe too — a rejected commit
    still paid the verification cost)."""

    __slots__ = ("kind", "t0")

    def __init__(self, kind: str):
        self.kind = kind

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        # bounded: every instantiation site passes one of the four
        # literal kinds {aggregate, batch, grouped, single}
        kind = self.kind
        commit_verify_histogram().with_labels(kind).observe(
            time.perf_counter() - self.t0)
        return False


class Fraction(NamedTuple):
    numerator: int
    denominator: int


class VerificationError(Exception):
    pass


class NotEnoughVotingPowerError(VerificationError):
    def __init__(self, got: int, needed: int):
        super().__init__(
            f"invalid commit -- insufficient voting power: got {got}, "
            f"needed more than {needed}")
        self.got = got
        self.needed = needed


def _should_batch_verify(vals: ValidatorSet, commit: Commit) -> bool:
    return (len(commit.signatures) >= BATCH_VERIFY_THRESHOLD and
            crypto_batch.supports_batch_verifier(
                vals.get_proposer().pub_key) and
            vals.all_keys_have_same_type())


def _should_group_verify(vals: ValidatorSet, commit: Commit) -> bool:
    """Mixed-key commits: batch per key-type group when any batchable
    type appears at least twice.  The reference disables batching
    entirely for mixed sets (types/validation.go:15-21 +
    AllKeysHaveSameType); grouping recovers the batch win for the
    dominant key types while unsupported ones verify inline."""
    if len(commit.signatures) < BATCH_VERIFY_THRESHOLD:
        return False
    counts: dict[str, int] = {}
    for val in vals.validators:
        if val.pub_key is None:
            continue
        if crypto_batch.supports_batch_verifier(val.pub_key):
            kt = val.pub_key.type()
            counts[kt] = counts.get(kt, 0) + 1
            if counts[kt] >= 2:
                return True
    return False


def _verify_basic_vals_and_commit(vals: ValidatorSet, commit,
                                  height: int, block_id: BlockID) -> None:
    if vals is None:
        raise VerificationError("nil validator set")
    if commit is None:
        raise VerificationError("nil commit")
    if vals.size() != commit.size():
        raise VerificationError(
            f"invalid commit -- wrong set size: {vals.size()} vs "
            f"{commit.size()}")
    if height != commit.height:
        raise VerificationError(
            f"invalid commit -- wrong height: {height} vs {commit.height}")
    if block_id != commit.block_id:
        raise VerificationError(
            f"invalid commit -- wrong block ID: want {block_id}, "
            f"got {commit.block_id}")


def _dispatch_aggregate(chain_id: str, vals: ValidatorSet,
                        block_id: BlockID, height: int,
                        commit: AggregateCommit,
                        cache: Optional[SignatureCache]) -> None:
    """The O(1) arm shared by verify_commit and verify_commit_light:
    one aggregate signature covers every signer, so "all signatures"
    and "stop at 2/3" coincide."""
    _verify_basic_vals_and_commit(vals, commit, height, block_id)
    with _observe_kind("aggregate"):
        _verify_aggregate_commit(
            chain_id, vals, commit,
            vals.total_voting_power() * 2 // 3, cache=cache)


def verify_commit(chain_id: str, vals: ValidatorSet, block_id: BlockID,
                  height: int, commit: Commit | AggregateCommit,
                  cache: Optional[SignatureCache] = None) -> None:
    """+2/3 signed; checks ALL signatures (reference: VerifyCommit :30).

    AggregateCommit commits take the O(1) pairing path
    (_dispatch_aggregate)."""
    if isinstance(commit, AggregateCommit):
        _dispatch_aggregate(chain_id, vals, block_id, height, commit,
                            cache)
        return
    _verify_basic_vals_and_commit(vals, commit, height, block_id)
    voting_power_needed = vals.total_voting_power() * 2 // 3
    ignore = lambda c: c.block_id_flag == BLOCK_ID_FLAG_ABSENT  # noqa: E731
    count = lambda c: c.block_id_flag == BLOCK_ID_FLAG_COMMIT  # noqa: E731
    if _should_batch_verify(vals, commit):
        with _observe_kind("batch"):
            _verify_commit_batch(
                chain_id, vals, commit, voting_power_needed, ignore,
                count, count_all_signatures=True, look_up_by_index=True,
                cache=cache)
    elif _should_group_verify(vals, commit):
        with _observe_kind("grouped"):
            _verify_commit_grouped(
                chain_id, vals, commit, voting_power_needed, ignore,
                count, count_all_signatures=True, look_up_by_index=True,
                cache=cache)
    else:
        with _observe_kind("single"):
            _verify_commit_single(
                chain_id, vals, commit, voting_power_needed, ignore,
                count, count_all_signatures=True, look_up_by_index=True,
                cache=cache)


def verify_commit_light(chain_id: str, vals: ValidatorSet,
                        block_id: BlockID, height: int,
                        commit: Commit | AggregateCommit,
                        count_all_signatures: bool = False,
                        cache: Optional[SignatureCache] = None) -> None:
    """Light-client variant: stops at 2/3 unless count_all_signatures.

    Reference: VerifyCommitLight / ...AllSignatures / ...WithCache (:65)."""
    if isinstance(commit, AggregateCommit):
        _dispatch_aggregate(chain_id, vals, block_id, height, commit,
                            cache)
        return
    _verify_basic_vals_and_commit(vals, commit, height, block_id)
    voting_power_needed = vals.total_voting_power() * 2 // 3
    ignore = lambda c: c.block_id_flag != BLOCK_ID_FLAG_COMMIT  # noqa: E731
    count = lambda c: True  # noqa: E731
    if _should_batch_verify(vals, commit):
        with _observe_kind("batch"):
            _verify_commit_batch(
                chain_id, vals, commit, voting_power_needed, ignore,
                count, count_all_signatures=count_all_signatures,
                look_up_by_index=True, cache=cache)
    elif _should_group_verify(vals, commit):
        with _observe_kind("grouped"):
            _verify_commit_grouped(
                chain_id, vals, commit, voting_power_needed, ignore,
                count, count_all_signatures=count_all_signatures,
                look_up_by_index=True, cache=cache)
    else:
        with _observe_kind("single"):
            _verify_commit_single(
                chain_id, vals, commit, voting_power_needed, ignore,
                count, count_all_signatures=count_all_signatures,
                look_up_by_index=True, cache=cache)


def verify_commit_light_trusting(
        chain_id: str, vals: ValidatorSet,
        commit: Commit | AggregateCommit,
        trust_level: Fraction, count_all_signatures: bool = False,
        cache: Optional[SignatureCache] = None,
        signer_vals: Optional[ValidatorSet] = None) -> None:
    """trustLevel (e.g. 1/3) of a TRUSTED validator set signed; used for
    skipping verification.  Looks validators up by address since the sets
    need not correspond (reference: VerifyCommitLightTrusting :150).

    For an AggregateCommit the signer bitmap indexes the set that
    SIGNED the commit's height, so the caller must supply that set as
    ``signer_vals`` (the light client has it — the untrusted header's
    validator set, already checked against validators_hash).
    signer_vals is used ONLY to map bitmap indices to addresses: the
    pairing runs against the TRUSTED set's keys for those addresses
    (signer_vals may be self-certified by the header under
    verification, so its claimed keys prove nothing — see
    _verify_aggregate_commit), and a signer outside the trusted set
    reports as not-enough-provable-power so skipping callers bisect."""
    if vals is None:
        raise VerificationError("nil validator set")
    if trust_level.denominator == 0:
        raise VerificationError("trustLevel has zero Denominator")
    if commit is None:
        raise VerificationError("nil commit")
    product = vals.total_voting_power() * trust_level.numerator
    if product >= (1 << 63):
        raise VerificationError(
            "int64 overflow while calculating voting power needed")
    voting_power_needed = product // trust_level.denominator
    if isinstance(commit, AggregateCommit):
        if signer_vals is None:
            raise VerificationError(
                "aggregate commit trusting verification needs the "
                "signing validator set")
        if signer_vals.size() != commit.size():
            raise VerificationError(
                f"invalid commit -- wrong set size: "
                f"{signer_vals.size()} vs {commit.size()}")
        with _observe_kind("aggregate"):
            _verify_aggregate_commit(
                chain_id, signer_vals, commit, voting_power_needed,
                cache=cache, tally_vals=vals)
        return
    ignore = lambda c: c.block_id_flag != BLOCK_ID_FLAG_COMMIT  # noqa: E731
    count = lambda c: True  # noqa: E731
    if _should_batch_verify(vals, commit):
        with _observe_kind("batch"):
            _verify_commit_batch(
                chain_id, vals, commit, voting_power_needed, ignore,
                count, count_all_signatures=count_all_signatures,
                look_up_by_index=False, cache=cache)
    elif _should_group_verify(vals, commit):
        with _observe_kind("grouped"):
            _verify_commit_grouped(
                chain_id, vals, commit, voting_power_needed, ignore,
                count, count_all_signatures=count_all_signatures,
                look_up_by_index=False, cache=cache)
    else:
        with _observe_kind("single"):
            _verify_commit_single(
                chain_id, vals, commit, voting_power_needed, ignore,
                count, count_all_signatures=count_all_signatures,
                look_up_by_index=False, cache=cache)


# ---------------------------------------------------------------------------
# aggregate-commit verification: O(1) pairing work in validator count
# (docs/aggregate_commits.md)

def _agg_memo_key(commit: AggregateCommit, valset_hash: bytes,
                  bitmap: bytes) -> bytes:
    """Verdict-memo key binding (block_id, valset, bitmap, signature);
    hashed so the shared SignatureCache stores 32-byte keys, prefixed
    so it can never collide with a raw signature key.  ``valset_hash``
    and ``bitmap`` describe the set the pubkeys were RESOLVED from —
    on the trusting path that is the trusted set and the bitmap
    re-indexed into it, so a verdict cached against one trusted set
    can never answer for another."""
    h = hashlib.sha256()
    h.update(b"aggcommit/1\x00")
    h.update(valset_hash)
    h.update(commit.block_id.key())
    h.update(bitmap)
    h.update(commit.signature)
    return b"agg:" + h.digest()


# per-valset raw-pubkey table: the G1 point-sum consumes the keys'
# raw 96-byte serializations; re-extracting them (10k method calls +
# key-type checks) on every new signer bitmap costs more than the
# join itself.  Keyed by valset hash, tiny LRU — a handful of live
# valsets exist at once.
_PK_RAWS: "OrderedDict[bytes, Optional[tuple]]" = None  # type: ignore


def _pubkey_raws(vals: ValidatorSet, valset_hash: bytes):
    """Tuple of 96-byte raw BLS pubkey serializations (valset order),
    or None when any validator key is not bls12_381."""
    global _PK_RAWS
    if _PK_RAWS is None:
        from collections import OrderedDict
        _PK_RAWS = OrderedDict()
    _MISS = object()
    entry = _PK_RAWS.get(valset_hash, _MISS)
    if entry is not _MISS:
        _PK_RAWS.move_to_end(valset_hash)
        return entry
    from ..crypto import bls12381
    raws = []
    for v in vals.validators:
        pk = v.pub_key
        if not isinstance(pk, bls12381.Bls12381PubKey):
            raws = None
            break
        raws.append(pk.bytes())
    entry = tuple(raws) if raws is not None else None
    _PK_RAWS[valset_hash] = entry
    if len(_PK_RAWS) > 8:
        _PK_RAWS.popitem(last=False)
    return entry


def _verify_aggregate_commit(
        chain_id: str, vals: ValidatorSet, commit: AggregateCommit,
        voting_power_needed: int,
        cache: Optional[SignatureCache] = None,
        tally_vals: Optional[ValidatorSet] = None) -> None:
    """One pairing check for the whole commit.

    ``vals`` is the set the signer bitmap indexes (the commit
    height's validator set).  When ``tally_vals`` is given (the light
    client's TRUSTED set — the trusting path) every signer is
    resolved through it BY ADDRESS: the power tally and the pubkey
    sum both use the trusted set's entries, never the claimed keys in
    ``vals``.  ``vals`` may be self-certified by the very header under
    verification (a skipping hop checks it only against that header's
    validators_hash), so verifying the pairing against its keys would
    let a rogue aggregate key (pk_r = [x]g1 - sum of trusted keys,
    placed at a fabricated index) cancel the trusted keys and forge
    the 1/3-trust check with zero honest signatures.  A signer whose
    address is NOT in the trusted set cannot be authenticated at all,
    so the hop reports zero provable power (NotEnoughVotingPowerError
    — the light client bisects toward the trusted header until the
    sets overlap, converging on adjacent hops whose valset is
    chain-certified).

    The G1 pubkey sum — the only O(n) step, and it is point adds, not
    pairings — is memoized per (valset_hash, bitmap) in the
    process-global AggregatePubKeyCache; the full verdict is memoized
    in the SignatureCache keyed (block_id, valset_hash, bitmap,
    signature) — both keyed on the set the keys were RESOLVED from
    (the trusted set on the trusting path)."""
    from ..crypto import bls12381

    try:
        commit.validate_basic()
    except CommitError as e:
        raise VerificationError(f"invalid aggregate commit: {e}") from e

    top = commit.signers.highest_true_index()
    if top >= vals.size():
        raise VerificationError(
            f"signer bit {top} out of range for validator set "
            f"of {vals.size()}")

    # voting-power tally (cheap, judged before the pairing as the
    # batch path judges threshold before its deferred verify) —
    # key_vals/key_bits name the set + bitmap the PAIRING runs over
    if tally_vals is None:
        # complement walk: healthy chains have near-full bitmaps, so
        # summing the MISSING validators' power is O(absent), not
        # O(n) — at 10k validators this is what keeps the warm path
        # inside the pairing budget
        key_vals, key_bits = vals, commit.signers
        tallied = vals.total_voting_power()
        for i in commit.signers.not_().true_indices():
            tallied -= vals.validators[i].voting_power
    else:
        # trusting: every signer resolved through the TRUSTED set by
        # address (see docstring — ``vals`` may be self-certified and
        # its claimed keys are never used here); an unknown signer
        # means zero soundly-attributable power, a repeated address
        # means ``vals`` is malformed
        key_vals = tally_vals
        key_bits = BitArray(tally_vals.size())
        tallied = 0
        for i in commit.signed_indices():
            addr = vals.validators[i].address
            tidx = tally_vals.index_by_address(addr)
            if tidx < 0:
                raise NotEnoughVotingPowerError(0, voting_power_needed)
            if key_bits.get_index(tidx):
                raise VerificationError(
                    f"duplicate signer address {addr.hex().upper()} "
                    f"in aggregate commit signer set")
            key_bits.set_index(tidx, True)
            tallied += tally_vals.validators[tidx].voting_power
    if tallied <= voting_power_needed:
        raise NotEnoughVotingPowerError(tallied, voting_power_needed)

    sign_bytes = commit.vote_sign_bytes(chain_id)
    valset_hash = key_vals.hash()
    bitmap = key_bits.to_le_bytes()

    memo_key = _agg_memo_key(commit, valset_hash, bitmap)
    if cache is not None:
        cv = cache.get(memo_key)
        if cv is not None and cv.vote_sign_bytes == sign_bytes:
            return

    def build():
        raws = _pubkey_raws(key_vals, valset_hash)
        if raws is None:
            raise VerificationError(
                "aggregate commits need a bls12_381 validator set")
        if key_bits.popcount() == len(raws):
            blob = b"".join(raws)
        else:
            blob = b"".join(raws[i] for i in key_bits.true_indices())
        return bls12381.aggregate_pub_keys_raw(blob)

    pk_cache = bls12381.aggregate_pubkey_cache()
    agg_pk = pk_cache.get(valset_hash, bitmap)
    fresh = agg_pk is None
    if fresh:
        agg_pk = build()

    if not bls12381.verify_aggregate(agg_pk, sign_bytes,
                                     commit.signature):
        raise VerificationError(
            f"wrong aggregate signature: "
            f"{commit.signature.hex().upper()[:24]}...")

    if fresh:
        # insert only after success: a forged-signature stream with
        # varying bitmaps must not evict the honest sums
        pk_cache.put(valset_hash, bitmap, agg_pk)
    if cache is not None:
        cache.add(memo_key, SignatureCacheValue(b"aggregate",
                                                sign_bytes))

# ---------------------------------------------------------------------------


def _walk_commit(
        chain_id: str, vals: ValidatorSet, commit: Commit,
        voting_power_needed: int,
        ignore_sig: Callable[[CommitSig], bool],
        count_sig: Callable[[CommitSig], bool],
        count_all_signatures: bool, look_up_by_index: bool,
        cache: Optional[SignatureCache], strict: bool,
        handle: Callable) -> int:
    """The signature walk shared by the three verification paths
    (single / batch / grouped): ignore filter, optional structural
    validation, by-index or by-address validator lookup with
    double-vote detection, cache short-circuit, voting-power tally
    with the early exit.  Returns the tallied power.

    handle(idx, val, sign_bytes, commit_sig) is called for every
    signature the cache does not satisfy — it verifies inline
    (raising VerificationError) or defers into a batch verifier;
    returning False stops the walk (the grouped path uses this to
    reconcile an inline failure against its deferred groups before
    reporting, so the LOWEST failing index is named either way).

    strict adds commit_sig.validate_basic() (the per-signature path's
    behavior); the same-type batch path omits it, mirroring the
    reference's verifyCommitBatch.  The nil-pubkey check is
    UNCONDITIONAL on every path — see the comment at the raise.
    """
    seen_vals: dict[int, int] = {}
    tallied = 0
    for idx, commit_sig in enumerate(commit.signatures):
        if ignore_sig(commit_sig):
            continue
        if strict:
            try:
                commit_sig.validate_basic()
            except CommitError as e:
                raise VerificationError(
                    f"invalid signature at index {idx}: {e}") from e
        if look_up_by_index:
            val = vals.validators[idx]
        else:
            val_idx, val = vals.get_by_address(
                commit_sig.validator_address)
            if val is None:
                continue
            if val_idx in seen_vals:
                raise VerificationError(
                    f"double vote from {val} "
                    f"({seen_vals[val_idx]} and {idx})")
            seen_vals[val_idx] = idx
        if val.pub_key is None:
            # unconditional (not strict-gated): the same-type gate
            # skips nil-pubkey validators, so a nil key CAN reach the
            # batch path, where BatchVerifier.add would raise
            # TypeError and the cache probe below would crash — the
            # reference's batch path rejects via Add's error return
            raise VerificationError(
                f"validator {val} has a nil PubKey at index {idx}")

        vote_sign_bytes = commit.vote_sign_bytes(chain_id, idx)

        cache_hit = False
        if cache is not None:
            cv = cache.get(commit_sig.signature)
            cache_hit = (cv is not None and
                         cv.validator_address == val.pub_key.address() and
                         cv.vote_sign_bytes == vote_sign_bytes)
        if not cache_hit:
            if handle(idx, val, vote_sign_bytes, commit_sig) is False:
                break

        if count_sig(commit_sig):
            tallied += val.voting_power
        if not count_all_signatures and tallied > voting_power_needed:
            break
    return tallied


def _verify_commit_batch(
        chain_id: str, vals: ValidatorSet, commit: Commit,
        voting_power_needed: int,
        ignore_sig: Callable[[CommitSig], bool],
        count_sig: Callable[[CommitSig], bool],
        count_all_signatures: bool, look_up_by_index: bool,
        cache: Optional[SignatureCache]) -> None:
    """Reference: verifyCommitBatch (:265) — including its ordering:
    the voting-power threshold is judged before the deferred batch
    runs.  Cache entries record the VERIFIED key's address, never
    commit_sig.validator_address: in by-index mode that field is
    attacker-controlled, and caching it would let one validator's
    signature poison the cache under another validator's address
    (canonical vote sign bytes exclude address/index, so a later
    by-index lookup in the other validator's slot would hit)."""
    bv = crypto_batch.create_batch_verifier(vals.get_proposer().pub_key)
    entries: list[tuple[int, bytes, bytes]] = []

    def handle(idx, val, sign_bytes, commit_sig):
        try:
            bv.add(val.pub_key, sign_bytes, commit_sig.signature)
        except (ValueError, TypeError) as e:
            # malformed (e.g. wrong-length) signature the structural
            # checks let through — the reference returns Add's error
            # here; surface it as the usual wrong-signature verdict
            raise VerificationError(
                f"wrong signature (#{idx}): "
                f"{commit_sig.signature.hex().upper()}") from e
        entries.append((idx, val.pub_key.address(), sign_bytes))

    tallied = _walk_commit(
        chain_id, vals, commit, voting_power_needed, ignore_sig,
        count_sig, count_all_signatures, look_up_by_index, cache,
        strict=False, handle=handle)

    if tallied <= voting_power_needed:
        raise NotEnoughVotingPowerError(tallied, voting_power_needed)

    if not entries:
        return  # everything was cached

    ok, valid_sigs = bv.verify()
    if ok:
        if cache is not None:
            for idx, addr, sign_bytes in entries:
                cache.add(commit.signatures[idx].signature,
                          SignatureCacheValue(addr, sign_bytes))
        return

    # find and report the first invalid signature
    for sig_ok, (idx, addr, sign_bytes) in zip(valid_sigs, entries):
        sig = commit.signatures[idx]
        if not sig_ok:
            raise VerificationError(
                f"wrong signature (#{idx}): {sig.signature.hex().upper()}")
        if cache is not None:
            cache.add(sig.signature,
                      SignatureCacheValue(addr, sign_bytes))
    raise VerificationError(
        "BUG: batch verification failed with no invalid signatures")


def _verify_commit_grouped(
        chain_id: str, vals: ValidatorSet, commit: Commit,
        voting_power_needed: int,
        ignore_sig: Callable[[CommitSig], bool],
        count_sig: Callable[[CommitSig], bool],
        count_all_signatures: bool, look_up_by_index: bool,
        cache: Optional[SignatureCache]) -> None:
    """Mixed-key commit verification with per-key-type batch groups
    (TPU-native extension; see _should_group_verify).  Walk semantics
    match _verify_commit_single (strict structural checks, cache,
    early threshold exit); batchable signatures defer into one
    verifier per key type, unsupported ones verify inline.  Verdict
    parity with the single path: any invalid signature raises
    VerificationError naming the LOWEST failing commit index — an
    inline failure stops the walk and is reconciled against the
    deferred groups before reporting — and does so before the
    voting-power threshold is judged, as inline verification would.
    """
    # key type -> (verifier, [(idx, key address, sign bytes)])
    groups: dict[str, tuple] = {}
    inline_bad: Optional[int] = None

    def handle(idx, val, sign_bytes, commit_sig):
        nonlocal inline_bad
        if crypto_batch.supports_batch_verifier(val.pub_key):
            kt = val.pub_key.type()
            entry = groups.get(kt)
            if entry is None:
                entry = (crypto_batch.create_batch_verifier(val.pub_key),
                         [])
                groups[kt] = entry
            try:
                entry[0].add(val.pub_key, sign_bytes,
                             commit_sig.signature)
            except (ValueError, TypeError):
                # malformed signature the structural checks let
                # through (e.g. wrong length): same verdict as a
                # failed inline verify, reconciled for lowest index
                inline_bad = idx
                return False
            entry[1].append((idx, val.pub_key.address(), sign_bytes))
            return None
        if not val.pub_key.verify_signature(sign_bytes,
                                            commit_sig.signature):
            inline_bad = idx
            return False        # stop: reconcile vs deferred groups
        if cache is not None:
            cache.add(commit_sig.signature, SignatureCacheValue(
                val.pub_key.address(), sign_bytes))
        return None

    tallied = _walk_commit(
        chain_id, vals, commit, voting_power_needed, ignore_sig,
        count_sig, count_all_signatures, look_up_by_index, cache,
        strict=True, handle=handle)

    first_bad: Optional[int] = inline_bad
    for bv, entries in groups.values():
        if not entries:
            continue
        ok, valid_sigs = bv.verify()
        if ok:
            if cache is not None:
                for idx, addr, sign_bytes in entries:
                    cache.add(commit.signatures[idx].signature,
                              SignatureCacheValue(addr, sign_bytes))
            continue
        group_bad = [entries[i][0] for i, sig_ok in enumerate(valid_sigs)
                     if not sig_ok]
        if not group_bad:
            raise VerificationError(
                "BUG: batch verification failed with no invalid "
                "signatures")
        if cache is not None:
            bad_set = set(group_bad)
            for idx, addr, sign_bytes in entries:
                if idx not in bad_set:
                    cache.add(commit.signatures[idx].signature,
                              SignatureCacheValue(addr, sign_bytes))
        if first_bad is None or group_bad[0] < first_bad:
            first_bad = group_bad[0]
    if first_bad is not None:
        sig = commit.signatures[first_bad]
        raise VerificationError(
            f"wrong signature (#{first_bad}): "
            f"{sig.signature.hex().upper()}")

    if tallied <= voting_power_needed:
        raise NotEnoughVotingPowerError(tallied, voting_power_needed)


def _verify_commit_single(
        chain_id: str, vals: ValidatorSet, commit: Commit,
        voting_power_needed: int,
        ignore_sig: Callable[[CommitSig], bool],
        count_sig: Callable[[CommitSig], bool],
        count_all_signatures: bool, look_up_by_index: bool,
        cache: Optional[SignatureCache]) -> None:
    """Reference: verifyCommitSingle (:413)."""

    def handle(idx, val, sign_bytes, commit_sig):
        if not val.pub_key.verify_signature(sign_bytes,
                                            commit_sig.signature):
            raise VerificationError(
                f"wrong signature (#{idx}): "
                f"{commit_sig.signature.hex().upper()}")
        if cache is not None:
            cache.add(commit_sig.signature, SignatureCacheValue(
                val.pub_key.address(), sign_bytes))

    tallied = _walk_commit(
        chain_id, vals, commit, voting_power_needed, ignore_sig,
        count_sig, count_all_signatures, look_up_by_index, cache,
        strict=True, handle=handle)

    if tallied <= voting_power_needed:
        raise NotEnoughVotingPowerError(tallied, voting_power_needed)
