"""Evidence of validator misbehavior.

Reference: types/evidence.go — DuplicateVoteEvidence (equivocation) and
LightClientAttackEvidence (conflicting light block), hashing and validation.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..crypto import merkle, tmhash
from ..wire import pb, encode
from .block import LightBlock
from .timestamp import Timestamp
from .validator import Validator
from .vote import Vote


class EvidenceError(Exception):
    pass


def _varint_bytes(n: int) -> bytes:
    """Go binary.PutVarint — zigzag varint."""
    zz = (n << 1) ^ (n >> 63) if n < 0 else n << 1
    out = bytearray()
    while True:
        b = zz & 0x7F
        zz >>= 7
        if zz:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


@dataclass
class DuplicateVoteEvidence:
    vote_a: Vote
    vote_b: Vote
    total_voting_power: int = 0
    validator_power: int = 0
    timestamp: Timestamp = field(default_factory=Timestamp.zero)

    TYPE = "duplicate_vote"

    @classmethod
    def new(cls, vote1: Vote, vote2: Vote, block_time: Timestamp,
            val_set) -> "DuplicateVoteEvidence":
        """Orders votes by BlockID key (reference: evidence.go
        NewDuplicateVoteEvidence)."""
        if vote1 is None or vote2 is None:
            raise EvidenceError("missing vote")
        _, val = val_set.get_by_address(vote1.validator_address)
        if val is None:
            raise EvidenceError("validator not in validator set")
        if vote1.block_id.key() < vote2.block_id.key():
            vote_a, vote_b = vote1, vote2
        else:
            vote_a, vote_b = vote2, vote1
        return cls(
            vote_a=vote_a, vote_b=vote_b,
            total_voting_power=val_set.total_voting_power(),
            validator_power=val.voting_power,
            timestamp=block_time,
        )

    def bytes(self) -> bytes:
        return encode(pb.DUPLICATE_VOTE_EVIDENCE, self.to_proto())

    def hash(self) -> bytes:
        return tmhash.sum(self.bytes())

    @property
    def height(self) -> int:
        return self.vote_a.height

    @property
    def time(self) -> Timestamp:
        return self.timestamp

    def validate_basic(self) -> None:
        if self.vote_a is None or self.vote_b is None:
            raise EvidenceError("empty duplicate vote evidence")
        self.vote_a.validate_basic()
        self.vote_b.validate_basic()
        if self.vote_a.block_id.key() >= self.vote_b.block_id.key():
            raise EvidenceError(
                "duplicate votes in invalid order (or the same block id)")

    def validate_abci(self) -> None:
        """Cross-field consistency (reference: evidence.go ValidateABCI)."""
        va, vb = self.vote_a, self.vote_b
        if va.height != vb.height or va.round != vb.round or \
                va.type != vb.type:
            raise EvidenceError("duplicate votes from different H/R/S")
        if va.validator_address != vb.validator_address:
            raise EvidenceError("duplicate votes from different validators")
        if va.block_id == vb.block_id:
            raise EvidenceError("duplicate votes for the same block")

    def to_proto(self) -> dict:
        d: dict = {
            "vote_a": self.vote_a.to_proto(),
            "vote_b": self.vote_b.to_proto(),
            "timestamp": self.timestamp.to_proto(),
        }
        if self.total_voting_power:
            d["total_voting_power"] = self.total_voting_power
        if self.validator_power:
            d["validator_power"] = self.validator_power
        return d

    def to_proto_wrapped(self) -> dict:
        return {"duplicate_vote_evidence": self.to_proto()}

    @classmethod
    def from_proto(cls, d: dict) -> "DuplicateVoteEvidence":
        return cls(
            vote_a=Vote.from_proto(d.get("vote_a") or {}),
            vote_b=Vote.from_proto(d.get("vote_b") or {}),
            total_voting_power=d.get("total_voting_power", 0),
            validator_power=d.get("validator_power", 0),
            timestamp=Timestamp.from_proto(d.get("timestamp") or {}),
        )


@dataclass
class LightClientAttackEvidence:
    conflicting_block: LightBlock
    common_height: int
    byzantine_validators: list[Validator] = field(default_factory=list)
    total_voting_power: int = 0
    timestamp: Timestamp = field(default_factory=Timestamp.zero)

    TYPE = "light_client_attack"

    def conflicting_header_is_invalid(self, trusted_header) -> bool:
        """Lunatic-attack detection: any state-derived header field
        differs (reference: evidence.go ConflictingHeaderIsInvalid
        :313)."""
        ch = self.conflicting_block.signed_header.header
        return (trusted_header.validators_hash != ch.validators_hash or
                trusted_header.next_validators_hash !=
                ch.next_validators_hash or
                trusted_header.consensus_hash != ch.consensus_hash or
                trusted_header.app_hash != ch.app_hash or
                trusted_header.last_results_hash != ch.last_results_hash)

    def get_byzantine_validators(self, common_vals,
                                 trusted_signed_header
                                 ) -> list[Validator]:
        """Attribute the equivocators (reference: evidence.go
        GetByzantineValidators :260): lunatic -> common-set validators
        who signed the lunatic header; equivocation (same round) ->
        validators who signed both; amnesia (different rounds) ->
        unattributable, empty."""
        from .commit import AggregateCommit
        from .vote import BLOCK_ID_FLAG_COMMIT
        out: list[Validator] = []
        conflicting = self.conflicting_block

        def signer_addrs(commit, vals):
            """Addresses that signed FOR the commit's block — signer
            bitmap resolved through the commit's own valset for the
            aggregate form, COMMIT-flag CommitSigs otherwise."""
            if isinstance(commit, AggregateCommit):
                return [vals.validators[i].address
                        for i in commit.signed_indices()
                        if i < vals.size()]
            return [cs.validator_address for cs in commit.signatures
                    if cs.block_id_flag == BLOCK_ID_FLAG_COMMIT]

        if self.conflicting_header_is_invalid(
                trusted_signed_header.header):
            for addr in signer_addrs(conflicting.signed_header.commit,
                                     conflicting.validator_set):
                _, val = common_vals.get_by_address(addr)
                if val is not None:
                    out.append(val)
        elif trusted_signed_header.commit.round == \
                conflicting.signed_header.commit.round:
            conf_commit = conflicting.signed_header.commit
            trusted_commit = trusted_signed_header.commit
            if isinstance(conf_commit, AggregateCommit) or \
                    isinstance(trusted_commit, AggregateCommit):
                # equivocation attribution needs BOTH signer sets;
                # resolve each through its own structure and
                # intersect by address (index alignment only holds
                # for identical valsets, which equivocation implies
                # at common height — address intersection is the
                # conservative general form)
                conf_addrs = set(signer_addrs(
                    conf_commit, conflicting.validator_set))
                trusted_addrs = set()
                if isinstance(trusted_commit, AggregateCommit):
                    # the trusted header's signers index OUR valset
                    # at that height, which common_vals approximates;
                    # out-of-range bits simply don't attribute
                    trusted_addrs = set(signer_addrs(
                        trusted_commit, common_vals))
                else:
                    trusted_addrs = set(
                        cs.validator_address
                        for cs in trusted_commit.signatures
                        if cs.block_id_flag == BLOCK_ID_FLAG_COMMIT)
                for addr in conf_addrs & trusted_addrs:
                    _, val = conflicting.validator_set \
                        .get_by_address(addr)
                    if val is not None:
                        out.append(val)
            else:
                trusted_sigs = trusted_commit.signatures
                for i, sig_a in enumerate(conf_commit.signatures):
                    if sig_a.block_id_flag != BLOCK_ID_FLAG_COMMIT:
                        continue
                    if i >= len(trusted_sigs) or \
                            trusted_sigs[i].block_id_flag != \
                            BLOCK_ID_FLAG_COMMIT:
                        continue
                    _, val = conflicting.validator_set.get_by_address(
                        sig_a.validator_address)
                    if val is not None:
                        out.append(val)
        out.sort(key=lambda v: (-v.voting_power, v.address))
        return out

    def bytes(self) -> bytes:
        return encode(pb.LIGHT_CLIENT_ATTACK_EVIDENCE, self.to_proto())

    def hash(self) -> bytes:
        """Hash = sha256(conflicting block hash[:31] || varint common
        height) — reference: evidence.go:329-336 (including its
        off-by-one truncation of the block hash)."""
        buf = _varint_bytes(self.common_height)
        bz = bytearray(tmhash.SIZE + len(buf))
        bh = self.conflicting_block.hash()
        bz[:tmhash.SIZE - 1] = bh[:tmhash.SIZE - 1]
        bz[tmhash.SIZE:] = buf
        return tmhash.sum(bytes(bz))

    @property
    def height(self) -> int:
        return self.common_height

    @property
    def time(self) -> Timestamp:
        return self.timestamp

    def validate_basic(self) -> None:
        if self.conflicting_block is None or \
                self.conflicting_block.signed_header is None:
            raise EvidenceError("conflicting block missing header")
        if self.common_height <= 0:
            raise EvidenceError("negative or zero common height")
        if self.conflicting_block.validator_set is None:
            raise EvidenceError("conflicting block missing validator set")
        self.conflicting_block.validate_basic(
            self.conflicting_block.signed_header.header.chain_id)

    def to_proto(self) -> dict:
        d: dict = {
            "conflicting_block": self.conflicting_block.to_proto(),
            "timestamp": self.timestamp.to_proto(),
        }
        if self.common_height:
            d["common_height"] = self.common_height
        if self.byzantine_validators:
            d["byzantine_validators"] = [
                v.to_proto() for v in self.byzantine_validators]
        if self.total_voting_power:
            d["total_voting_power"] = self.total_voting_power
        return d

    def to_proto_wrapped(self) -> dict:
        return {"light_client_attack_evidence": self.to_proto()}

    @classmethod
    def from_proto(cls, d: dict) -> "LightClientAttackEvidence":
        return cls(
            conflicting_block=LightBlock.from_proto(
                d.get("conflicting_block") or {}),
            common_height=d.get("common_height", 0),
            byzantine_validators=[
                Validator.from_proto(v)
                for v in d.get("byzantine_validators", [])],
            total_voting_power=d.get("total_voting_power", 0),
            timestamp=Timestamp.from_proto(d.get("timestamp") or {}),
        )


Evidence = DuplicateVoteEvidence | LightClientAttackEvidence


def evidence_from_proto_wrapped(d: dict) -> Evidence:
    if "duplicate_vote_evidence" in d:
        return DuplicateVoteEvidence.from_proto(d["duplicate_vote_evidence"])
    if "light_client_attack_evidence" in d:
        return LightClientAttackEvidence.from_proto(
            d["light_client_attack_evidence"])
    raise EvidenceError(f"unknown evidence oneof {sorted(d)}")


def evidence_list_hash(evidence: list[Evidence]) -> bytes:
    """Reference: evidence.go EvidenceList.Hash — merkle over proto bytes."""
    return merkle.hash_from_byte_slices([ev.bytes() for ev in evidence])
