"""Consensus metrics, fed at the point of action inside the state
machine and reactor.

Reference: internal/consensus/metrics.go:190 (+ metrics.gen.go) — the
metric names, labels and semantics match the reference so existing
dashboards port unchanged; recording mirrors recordMetrics in
internal/consensus/state.go.
"""
from __future__ import annotations

import time
from typing import Optional

from ..libs import metrics as libmetrics


class Metrics:
    def __init__(self, registry: Optional[libmetrics.Registry] = None):
        m = registry if registry is not None else libmetrics.Registry()
        self.height = m.gauge(
            "consensus", "height", "Height of the chain.")
        self.validator_last_signed_height = m.gauge(
            "consensus", "validator_last_signed_height",
            "Last height signed by this validator if the node is a "
            "validator.")
        self.rounds = m.gauge(
            "consensus", "rounds", "Number of rounds.")
        self.round_duration_seconds = m.histogram(
            "consensus", "round_duration_seconds",
            "Histogram of round duration.")
        self.validators = m.gauge(
            "consensus", "validators", "Number of validators.")
        self.validators_power = m.gauge(
            "consensus", "validators_power",
            "Total power of all validators.")
        self.missing_validators = m.gauge(
            "consensus", "missing_validators",
            "Number of validators who did not sign.")
        self.missing_validators_power = m.gauge(
            "consensus", "missing_validators_power",
            "Total power of the missing validators.")
        self.byzantine_validators = m.gauge(
            "consensus", "byzantine_validators",
            "Number of validators who tried to double sign.")
        self.byzantine_validators_power = m.gauge(
            "consensus", "byzantine_validators_power",
            "Total power of the byzantine validators.")
        self.block_interval_seconds = m.histogram(
            "consensus", "block_interval_seconds",
            "Time between this and the last block.")
        self.num_txs = m.gauge(
            "consensus", "num_txs", "Number of transactions.")
        self.block_size_bytes = m.gauge(
            "consensus", "block_size_bytes", "Size of the block.")
        self.chain_size_bytes = m.counter(
            "consensus", "chain_size_bytes",
            "Size of the chain in bytes.")
        self.total_txs = m.counter(
            "consensus", "total_txs",
            "Total number of transactions.")
        self.latest_block_height = m.gauge(
            "consensus", "latest_block_height",
            "The latest block height.")
        self.step_duration_seconds = m.histogram(
            "consensus", "step_duration_seconds",
            "Histogram of durations for each step in the consensus "
            "protocol.", labels=("step",))
        self.block_parts = m.counter(
            "consensus", "block_parts",
            "Number of block parts transmitted by each peer.",
            labels=("peer_id",))
        self.duplicate_block_part = m.counter(
            "consensus", "duplicate_block_part",
            "Number of times we received a duplicate block part")
        self.duplicate_vote = m.counter(
            "consensus", "duplicate_vote",
            "Number of times we received a duplicate vote")
        self.block_gossip_parts_received = m.counter(
            "consensus", "block_gossip_parts_received",
            "Number of block parts received by the node, separated "
            "by whether the part was relevant to the block the node "
            "is trying to gather or not.",
            labels=("matches_current",))
        # compact-block proposal relay (docs/gossip.md)
        self.compact_blocks_sent = m.counter(
            "consensus", "compact_blocks_sent",
            "Compact proposals (skeleton + tx hashes) sent to "
            "negotiated peers instead of full parts.")
        self.compact_blocks_reconstructed = m.counter(
            "consensus", "compact_blocks_reconstructed",
            "Compact proposals fully rebuilt from the local mempool "
            "— no full block parts needed.")
        self.compact_block_misses = m.counter(
            "consensus", "compact_block_misses",
            "Compact proposals with at least one tx hash the local "
            "mempool could not resolve (fell back to full parts).")
        self.compact_block_mismatches = m.counter(
            "consensus", "compact_block_mismatches",
            "Compact proposals whose reconstructed part set did not "
            "match the advertised part-set header.")
        self.vote_batches_sent = m.counter(
            "consensus", "vote_batches_sent",
            "Coalesced vote messages sent on the vote channel "
            "(votebatch/1 links).")
        self.quorum_prevote_delay = m.gauge(
            "consensus", "quorum_prevote_delay",
            "Interval in seconds between the proposal timestamp and "
            "the timestamp of the earliest prevote that achieved a "
            "quorum.", labels=("proposer_address",))
        self.full_prevote_delay = m.gauge(
            "consensus", "full_prevote_delay",
            "Interval in seconds between the proposal timestamp and "
            "the timestamp of the latest prevote in a round where "
            "all validators voted.", labels=("proposer_address",))
        # metrics v2: distribution views of the quorum/full delays.
        # The reference gauges above only hold the LAST delay per
        # proposer; the unlabeled histograms answer "what is the p99
        # quorum delay" over time without a per-proposer bucket
        # explosion.
        _delay_buckets = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                          1.0, 2.5, 5.0, 10.0)
        self.quorum_prevote_delay_seconds = m.histogram(
            "consensus", "quorum_prevote_delay_seconds",
            "Histogram of the interval in seconds between the "
            "proposal timestamp and the earliest quorum-achieving "
            "prevote.", buckets=_delay_buckets)
        self.full_prevote_delay_seconds = m.histogram(
            "consensus", "full_prevote_delay_seconds",
            "Histogram of the interval in seconds between the "
            "proposal timestamp and the latest prevote in rounds "
            "where all validators voted.", buckets=_delay_buckets)
        self.rounds_per_height = m.histogram(
            "consensus", "rounds_per_height",
            "Histogram of the round number blocks commit in "
            "(0 = first round).",
            buckets=(0, 1, 2, 3, 5, 10, 20))
        self.vote_extension_receive_count = m.counter(
            "consensus", "vote_extension_receive_count",
            "Number of vote extensions received, annotated by "
            "application verdict.", labels=("status",))
        self.proposal_receive_count = m.counter(
            "consensus", "proposal_receive_count",
            "Total number of proposals received since process "
            "start, annotated by app verdict.", labels=("status",))
        self.proposal_create_count = m.counter(
            "consensus", "proposal_create_count",
            "Total number of proposals created since process start.")
        self.round_voting_power_percent = m.gauge(
            "consensus", "round_voting_power_percent",
            "Percentage of the total voting power received with a "
            "round, by vote type.", labels=("vote_type",))
        self.late_votes = m.counter(
            "consensus", "late_votes",
            "Number of votes received corresponding to earlier "
            "heights/rounds than the node is in.",
            labels=("vote_type",))
        # commit pipeline (docs/pipeline.md): how long the background
        # execute/commit of height H ran, and how long the receive
        # routine actually stalled on the barrier when it needed the
        # applied state — overlap won = apply minus barrier wait
        _pipe_buckets = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                         0.5, 1.0, 2.5, 5.0)
        self.pipeline_apply_seconds = m.histogram(
            "consensus", "pipeline_apply_seconds",
            "Duration of the pipelined background execute/commit "
            "(FinalizeBlock through mempool update) per height.",
            buckets=_pipe_buckets)
        self.pipeline_barrier_wait_seconds = m.histogram(
            "consensus", "pipeline_barrier_wait_seconds",
            "Time the consensus routine waited on the pipeline "
            "barrier before a step that needs the applied state.",
            buckets=_pipe_buckets)
        self.proposal_timestamp_difference = m.histogram(
            "consensus", "proposal_timestamp_difference",
            "Difference in seconds between local receive time and "
            "the proposal message timestamp.",
            labels=("is_timely",),
            buckets=(-1.0, -0.5, -0.1, 0.0, 0.1, 0.5, 1.0, 2.0, 5.0))

        self._step_name = ""
        self._step_t = time.monotonic()
        self._round_t = time.monotonic()
        self._block_t = 0.0

    # ---- recording hooks (mirrors recordMetrics) ---------------------
    def mark_step(self, rs) -> None:
        now = time.monotonic()
        if self._step_name:
            self.step_duration_seconds.with_labels(
                self._step_name).observe(now - self._step_t)
        self._step_name = rs.step_name()
        self._step_t = now
        self.rounds.set(rs.round)

    def mark_round(self, round_: int) -> None:
        now = time.monotonic()
        self.round_duration_seconds.observe(now - self._round_t)
        self._round_t = now
        self.rounds.set(round_)

    def record_commit(self, block, last_validators,
                      current_validators,
                      block_size: int = 0,
                      commit_round: int = -1) -> None:
        """Per-commit stats (reference: recordMetrics, state.go).
        last_validators signed block.last_commit; block_size is the
        full wire size (part-set byte size)."""
        now = time.monotonic()
        self.height.set(block.header.height)
        if commit_round >= 0:
            self.rounds_per_height.observe(commit_round)
        self.latest_block_height.set(block.header.height)
        self.num_txs.set(len(block.data.txs))
        self.total_txs.add(len(block.data.txs))
        size = block_size or sum(len(tx) for tx in block.data.txs)
        self.block_size_bytes.set(size)
        self.chain_size_bytes.add(size)
        if self._block_t:
            self.block_interval_seconds.observe(now - self._block_t)
        self._block_t = now
        if current_validators is not None:
            self.validators.set(current_validators.size())
            self.validators_power.set(
                current_validators.total_voting_power())
        lc = block.last_commit
        if last_validators is not None and lc is not None and lc.size():
            from ..types.commit import AggregateCommit
            missing = 0
            missing_power = 0
            if isinstance(lc, AggregateCommit):
                # aggregate form: unset signer bits are "missing"
                # (nil votes are indistinguishable from absence —
                # both are excluded from the bitmap); complement walk
                # keeps this O(absent), not O(n) bignum shifts
                nvals = last_validators.size()
                for i in lc.signers.not_().true_indices():
                    if i < nvals:
                        missing += 1
                        missing_power += \
                            last_validators.validators[i].voting_power
            else:
                from ..types.commit import BLOCK_ID_FLAG_ABSENT
                for i, sig in enumerate(lc.signatures):
                    if sig.block_id_flag == BLOCK_ID_FLAG_ABSENT and \
                            i < last_validators.size():
                        missing += 1
                        missing_power += \
                            last_validators.validators[i].voting_power
            self.missing_validators.set(missing)
            self.missing_validators_power.set(missing_power)
        byz = 0
        byz_power = 0
        for ev in block.evidence:   # gauges reset below when no evidence
            byz_vals = getattr(ev, "byzantine_validators", None)
            if byz_vals is not None:       # light-client attack
                addrs = [v.address for v in byz_vals]
            else:
                va = getattr(ev, "vote_a", None)
                addrs = [va.validator_address] if va is not None \
                    else []
            for addr in addrs:
                byz += 1
                if last_validators is not None:
                    _, v = last_validators.get_by_address(addr)
                    if v is not None:
                        byz_power += v.voting_power
        self.byzantine_validators.set(byz)
        self.byzantine_validators_power.set(byz_power)
