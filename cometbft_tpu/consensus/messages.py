"""Consensus messages (gossip + WAL payloads).

Reference: internal/consensus/msgs.go — ProposalMessage, BlockPartMessage,
VoteMessage, NewRoundStepMessage, NewValidBlockMessage, HasVoteMessage,
VoteSetMaj23Message, VoteSetBitsMessage, ProposalPOLMessage.

WAL/JSON codec: proto-shaped dicts with bytes hex-tagged, so records are
self-describing and durable across code changes.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ..libs.bits import BitArray
from ..types.block_id import BlockID
from ..types.part_set import Part
from ..types.proposal import Proposal
from ..types.vote import Vote


def jsonify(obj: Any) -> Any:
    """Nested proto-dict → JSON-safe (bytes → {"__b": hex})."""
    if isinstance(obj, (bytes, bytearray)):
        return {"__b": bytes(obj).hex()}
    if isinstance(obj, dict):
        return {k: jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [jsonify(v) for v in obj]
    return obj


def dejsonify(obj: Any) -> Any:
    if isinstance(obj, dict):
        if set(obj.keys()) == {"__b"}:
            return bytes.fromhex(obj["__b"])
        return {k: dejsonify(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [dejsonify(v) for v in obj]
    return obj


@dataclass
class ProposalMessage:
    proposal: Proposal

    TYPE = "proposal"

    def to_wal(self) -> dict:
        return {"type": self.TYPE,
                "proposal": jsonify(self.proposal.to_proto())}


@dataclass
class BlockPartMessage:
    height: int
    round: int
    part: Part

    TYPE = "block_part"

    def to_wal(self) -> dict:
        return {"type": self.TYPE, "height": self.height,
                "round": self.round,
                "part": jsonify(self.part.to_proto())}


@dataclass
class VoteMessage:
    vote: Vote

    TYPE = "vote"

    def to_wal(self) -> dict:
        return {"type": self.TYPE, "vote": jsonify(self.vote.to_proto())}


@dataclass
class NewRoundStepMessage:
    height: int
    round: int
    step: int
    seconds_since_start_time: int = 0
    last_commit_round: int = -1

    TYPE = "new_round_step"


@dataclass
class NewValidBlockMessage:
    height: int
    round: int
    block_part_set_header: object = None   # PartSetHeader
    block_parts: Optional[BitArray] = None
    is_commit: bool = False

    TYPE = "new_valid_block"


@dataclass
class HasVoteMessage:
    height: int
    round: int
    type: int
    index: int

    TYPE = "has_vote"


@dataclass
class VoteSetMaj23Message:
    height: int
    round: int
    type: int
    block_id: BlockID = field(default_factory=BlockID)

    TYPE = "vote_set_maj23"


@dataclass
class VoteSetBitsMessage:
    height: int
    round: int
    type: int
    block_id: BlockID = field(default_factory=BlockID)
    votes: Optional[BitArray] = None

    TYPE = "vote_set_bits"


@dataclass
class ProposalPOLMessage:
    height: int
    proposal_pol_round: int
    proposal_pol: Optional[BitArray] = None

    TYPE = "proposal_pol"


@dataclass
class HasProposalBlockPartMessage:
    height: int
    round: int
    index: int

    TYPE = "has_proposal_block_part"


FEATURE_COMPACT_BLOCKS = "compactblocks/1"
FEATURE_VOTE_BATCH = "votebatch/1"
# can parse AggregateCommit wire arms (blocks/signed headers of
# chains past feature.aggregate_commit_enable_height).  Advertised
# whenever the software supports it; on an aggregate-commit chain the
# consensus reactor refuses peers that do not advertise it — they
# cannot decode the chain's blocks (docs/aggregate_commits.md).
# Ed25519 chains ignore it entirely; compatible_with is unchanged.
FEATURE_AGG_COMMIT = "aggcommit/1"

# below this many txs the compact form saves almost nothing over the
# single part it replaces, and the reconstruct round trip only adds
# latency risk — small proposals always go out as full parts
COMPACT_MIN_TXS = 8


@dataclass
class CompactBlockPartMessage:
    """The whole proposal as skeleton + ordered tx hashes
    (docs/gossip.md): ``skeleton`` is the block's canonical proto
    encoding with ``data.txs`` emptied, ``tx_hashes`` the
    concatenated 32-byte tx keys in block order.  A receiver that
    holds every tx rebuilds the byte-identical part set
    (``reconstruct_block_bytes``) and never needs the full
    BlockPartMessages; one that doesn't falls back to the existing
    part gossip.  Never written to the WAL — the reconstructed parts
    are fed through the normal BlockPartMessage path, so replay sees
    exactly what a full-part peer would have logged."""
    height: int
    round: int
    part_set_header: object        # PartSetHeader
    skeleton: bytes
    tx_hashes: list                # list[bytes], 32 bytes each

    TYPE = "compact_block"


@dataclass
class CompactBlockNackMessage:
    """Receiver-driven fallback: reconstruction failed (missing txs,
    header mismatch), cancel the grace window and push full parts
    immediately."""
    height: int
    round: int

    TYPE = "compact_block_nack"


@dataclass
class VoteBatchMessage:
    votes: list                    # list[Vote]

    TYPE = "vote_batch"


@dataclass
class AggregateCommitMessage:
    """Catchup on an aggregate-commit chain: the stored commit for a
    lagging peer's height is ONE aggregate signature + signer bitmap,
    so individual precommit votes cannot be reconstructed and gossiped
    — the aggregate itself is shipped instead and injected as the
    height's +2/3 precommit evidence after verification
    (docs/aggregate_commits.md).  WAL'd like a vote: replay re-verifies
    and re-injects it."""
    commit: object                 # types.commit.AggregateCommit

    TYPE = "aggregate_commit"

    def to_wal(self) -> dict:
        return {"type": self.TYPE,
                "commit": jsonify(self.commit.to_proto())}


def make_compact_block(height: int, round_: int, block,
                       part_set_header) -> CompactBlockPartMessage:
    """Build the compact form from a complete proposal block."""
    from ..types.tx import tx_key
    d = block.to_proto()
    data = dict(d.get("data") or {})
    data.pop("txs", None)
    d["data"] = data
    from ..wire import pb, encode
    return CompactBlockPartMessage(
        height=height, round=round_,
        part_set_header=part_set_header,
        skeleton=encode(pb.BLOCK, d),
        tx_hashes=[tx_key(tx) for tx in block.data.txs])


def reconstruct_block_bytes(skeleton: bytes, txs: list) -> bytes:
    """Splice resolved txs back into the skeleton and re-encode.
    The wire codec is canonical (ascending field order, proto3 zero
    omission), so the result is byte-identical to the proposer's
    ``Block.make_part_set`` input whenever the txs match."""
    from ..wire import pb, decode, encode
    d = decode(pb.BLOCK, skeleton)
    data = dict(d.get("data") or {})
    data["txs"] = list(txs)
    d["data"] = data
    return encode(pb.BLOCK, d)


def message_from_wal(d: dict):
    """Decode a WAL msg record back into a message object."""
    t = d.get("type")
    if t == ProposalMessage.TYPE:
        return ProposalMessage(
            Proposal.from_proto(dejsonify(d["proposal"])))
    if t == BlockPartMessage.TYPE:
        return BlockPartMessage(
            height=d["height"], round=d["round"],
            part=Part.from_proto(dejsonify(d["part"])))
    if t == VoteMessage.TYPE:
        return VoteMessage(Vote.from_proto(dejsonify(d["vote"])))
    if t == AggregateCommitMessage.TYPE:
        from ..types.commit import AggregateCommit
        return AggregateCommitMessage(
            AggregateCommit.from_proto(dejsonify(d["commit"])))
    raise ValueError(f"unknown WAL message type {t!r}")


# ---------------------------------------------------------------------------
# p2p wire codec (reference: internal/consensus/msgs.go MsgToProto /
# MsgFromProto over cometbft.consensus.v2.Message)

def encode_p2p(msg) -> bytes:
    from ..wire import consensus_pb, encode
    from ..types.part_set import PartSetHeader

    if isinstance(msg, ProposalMessage):
        d = {"proposal": {"proposal": msg.proposal.to_proto()}}
    elif isinstance(msg, BlockPartMessage):
        d = {"block_part": {
            **({"height": msg.height} if msg.height else {}),
            **({"round": msg.round} if msg.round else {}),
            "part": msg.part.to_proto()}}
    elif isinstance(msg, VoteMessage):
        d = {"vote": {"vote": msg.vote.to_proto()}}
    elif isinstance(msg, NewRoundStepMessage):
        d = {"new_round_step": {
            **({"height": msg.height} if msg.height else {}),
            **({"round": msg.round} if msg.round else {}),
            **({"step": msg.step} if msg.step else {}),
            **({"seconds_since_start_time":
                msg.seconds_since_start_time}
               if msg.seconds_since_start_time else {}),
            **({"last_commit_round": msg.last_commit_round}
               if msg.last_commit_round else {})}}
    elif isinstance(msg, NewValidBlockMessage):
        d = {"new_valid_block": {
            **({"height": msg.height} if msg.height else {}),
            **({"round": msg.round} if msg.round else {}),
            "block_part_set_header":
                msg.block_part_set_header.to_proto(),
            **({"block_parts": msg.block_parts.to_proto()}
               if msg.block_parts is not None else {}),
            **({"is_commit": True} if msg.is_commit else {})}}
    elif isinstance(msg, HasVoteMessage):
        d = {"has_vote": {
            **({"height": msg.height} if msg.height else {}),
            **({"round": msg.round} if msg.round else {}),
            **({"type": msg.type} if msg.type else {}),
            **({"index": msg.index} if msg.index else {})}}
    elif isinstance(msg, VoteSetMaj23Message):
        d = {"vote_set_maj23": {
            **({"height": msg.height} if msg.height else {}),
            **({"round": msg.round} if msg.round else {}),
            **({"type": msg.type} if msg.type else {}),
            "block_id": msg.block_id.to_proto()}}
    elif isinstance(msg, VoteSetBitsMessage):
        d = {"vote_set_bits": {
            **({"height": msg.height} if msg.height else {}),
            **({"round": msg.round} if msg.round else {}),
            **({"type": msg.type} if msg.type else {}),
            "block_id": msg.block_id.to_proto(),
            "votes": msg.votes.to_proto() if msg.votes is not None
            else {}}}
    elif isinstance(msg, ProposalPOLMessage):
        d = {"proposal_pol": {
            **({"height": msg.height} if msg.height else {}),
            **({"proposal_pol_round": msg.proposal_pol_round}
               if msg.proposal_pol_round else {}),
            "proposal_pol": msg.proposal_pol.to_proto()
            if msg.proposal_pol is not None else {}}}
    elif isinstance(msg, HasProposalBlockPartMessage):
        d = {"has_proposal_block_part": {
            **({"height": msg.height} if msg.height else {}),
            **({"round": msg.round} if msg.round else {}),
            **({"index": msg.index} if msg.index else {})}}
    elif isinstance(msg, CompactBlockPartMessage):
        d = {"compact_block": {
            **({"height": msg.height} if msg.height else {}),
            **({"round": msg.round} if msg.round else {}),
            "part_set_header": msg.part_set_header.to_proto(),
            "skeleton": msg.skeleton,
            "tx_hashes": b"".join(msg.tx_hashes)}}
    elif isinstance(msg, CompactBlockNackMessage):
        d = {"compact_block_nack": {
            **({"height": msg.height} if msg.height else {}),
            **({"round": msg.round} if msg.round else {})}}
    elif isinstance(msg, VoteBatchMessage):
        d = {"vote_batch": {
            "votes": [v.to_proto() for v in msg.votes]}}
    elif isinstance(msg, AggregateCommitMessage):
        d = {"aggregate_commit": {"commit": msg.commit.to_proto()}}
    else:
        raise ValueError(f"cannot encode message {type(msg)}")
    return encode(consensus_pb.MESSAGE, d)


def decode_p2p(raw: bytes):
    from ..wire import consensus_pb, decode
    from ..libs.bits import BitArray
    from ..types.block_id import BlockID
    from ..types.part_set import Part, PartSetHeader

    d = decode(consensus_pb.MESSAGE, raw)
    if "proposal" in d:
        return ProposalMessage(Proposal.from_proto(
            d["proposal"].get("proposal") or {}))
    if "block_part" in d:
        bp = d["block_part"]
        return BlockPartMessage(
            height=bp.get("height", 0), round=bp.get("round", 0),
            part=Part.from_proto(bp.get("part") or {}))
    if "vote" in d:
        return VoteMessage(Vote.from_proto(
            d["vote"].get("vote") or {}))
    if "new_round_step" in d:
        n = d["new_round_step"]
        return NewRoundStepMessage(
            height=n.get("height", 0), round=n.get("round", 0),
            step=n.get("step", 0),
            seconds_since_start_time=n.get(
                "seconds_since_start_time", 0),
            last_commit_round=n.get("last_commit_round", 0))
    if "new_valid_block" in d:
        n = d["new_valid_block"]
        return NewValidBlockMessage(
            height=n.get("height", 0), round=n.get("round", 0),
            block_part_set_header=PartSetHeader.from_proto(
                n.get("block_part_set_header") or {}),
            block_parts=BitArray.from_proto(n["block_parts"])
            if n.get("block_parts") is not None else None,
            is_commit=n.get("is_commit", False))
    if "has_vote" in d:
        n = d["has_vote"]
        return HasVoteMessage(height=n.get("height", 0),
                              round=n.get("round", 0),
                              type=n.get("type", 0),
                              index=n.get("index", 0))
    if "vote_set_maj23" in d:
        n = d["vote_set_maj23"]
        return VoteSetMaj23Message(
            height=n.get("height", 0), round=n.get("round", 0),
            type=n.get("type", 0),
            block_id=BlockID.from_proto(n.get("block_id") or {}))
    if "vote_set_bits" in d:
        n = d["vote_set_bits"]
        return VoteSetBitsMessage(
            height=n.get("height", 0), round=n.get("round", 0),
            type=n.get("type", 0),
            block_id=BlockID.from_proto(n.get("block_id") or {}),
            votes=BitArray.from_proto(n.get("votes") or {}))
    if "proposal_pol" in d:
        n = d["proposal_pol"]
        return ProposalPOLMessage(
            height=n.get("height", 0),
            proposal_pol_round=n.get("proposal_pol_round", 0),
            proposal_pol=BitArray.from_proto(
                n.get("proposal_pol") or {}))
    if "has_proposal_block_part" in d:
        n = d["has_proposal_block_part"]
        return HasProposalBlockPartMessage(
            height=n.get("height", 0), round=n.get("round", 0),
            index=n.get("index", 0))
    if "compact_block" in d:
        n = d["compact_block"]
        blob = n.get("tx_hashes", b"")
        return CompactBlockPartMessage(
            height=n.get("height", 0), round=n.get("round", 0),
            part_set_header=PartSetHeader.from_proto(
                n.get("part_set_header") or {}),
            skeleton=n.get("skeleton", b""),
            tx_hashes=[blob[i:i + 32]
                       for i in range(0, len(blob) - 31, 32)])
    if "compact_block_nack" in d:
        n = d["compact_block_nack"]
        return CompactBlockNackMessage(height=n.get("height", 0),
                                       round=n.get("round", 0))
    if "vote_batch" in d:
        return VoteBatchMessage(
            votes=[Vote.from_proto(v)
                   for v in d["vote_batch"].get("votes", [])])
    if "aggregate_commit" in d:
        from ..types.commit import AggregateCommit
        return AggregateCommitMessage(AggregateCommit.from_proto(
            d["aggregate_commit"].get("commit") or {}))
    raise ValueError(f"unknown consensus message {sorted(d)}")
