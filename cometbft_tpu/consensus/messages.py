"""Consensus messages (gossip + WAL payloads).

Reference: internal/consensus/msgs.go — ProposalMessage, BlockPartMessage,
VoteMessage, NewRoundStepMessage, NewValidBlockMessage, HasVoteMessage,
VoteSetMaj23Message, VoteSetBitsMessage, ProposalPOLMessage.

WAL/JSON codec: proto-shaped dicts with bytes hex-tagged, so records are
self-describing and durable across code changes.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ..libs.bits import BitArray
from ..types.block_id import BlockID
from ..types.part_set import Part
from ..types.proposal import Proposal
from ..types.vote import Vote


def jsonify(obj: Any) -> Any:
    """Nested proto-dict → JSON-safe (bytes → {"__b": hex})."""
    if isinstance(obj, (bytes, bytearray)):
        return {"__b": bytes(obj).hex()}
    if isinstance(obj, dict):
        return {k: jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [jsonify(v) for v in obj]
    return obj


def dejsonify(obj: Any) -> Any:
    if isinstance(obj, dict):
        if set(obj.keys()) == {"__b"}:
            return bytes.fromhex(obj["__b"])
        return {k: dejsonify(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [dejsonify(v) for v in obj]
    return obj


@dataclass
class ProposalMessage:
    proposal: Proposal

    TYPE = "proposal"

    def to_wal(self) -> dict:
        return {"type": self.TYPE,
                "proposal": jsonify(self.proposal.to_proto())}


@dataclass
class BlockPartMessage:
    height: int
    round: int
    part: Part

    TYPE = "block_part"

    def to_wal(self) -> dict:
        return {"type": self.TYPE, "height": self.height,
                "round": self.round,
                "part": jsonify(self.part.to_proto())}


@dataclass
class VoteMessage:
    vote: Vote

    TYPE = "vote"

    def to_wal(self) -> dict:
        return {"type": self.TYPE, "vote": jsonify(self.vote.to_proto())}


@dataclass
class NewRoundStepMessage:
    height: int
    round: int
    step: int
    seconds_since_start_time: int = 0
    last_commit_round: int = -1

    TYPE = "new_round_step"


@dataclass
class NewValidBlockMessage:
    height: int
    round: int
    block_part_set_header: object = None   # PartSetHeader
    block_parts: Optional[BitArray] = None
    is_commit: bool = False

    TYPE = "new_valid_block"


@dataclass
class HasVoteMessage:
    height: int
    round: int
    type: int
    index: int

    TYPE = "has_vote"


@dataclass
class VoteSetMaj23Message:
    height: int
    round: int
    type: int
    block_id: BlockID = field(default_factory=BlockID)

    TYPE = "vote_set_maj23"


@dataclass
class VoteSetBitsMessage:
    height: int
    round: int
    type: int
    block_id: BlockID = field(default_factory=BlockID)
    votes: Optional[BitArray] = None

    TYPE = "vote_set_bits"


@dataclass
class ProposalPOLMessage:
    height: int
    proposal_pol_round: int
    proposal_pol: Optional[BitArray] = None

    TYPE = "proposal_pol"


@dataclass
class HasProposalBlockPartMessage:
    height: int
    round: int
    index: int

    TYPE = "has_proposal_block_part"


def message_from_wal(d: dict):
    """Decode a WAL msg record back into a message object."""
    t = d.get("type")
    if t == ProposalMessage.TYPE:
        return ProposalMessage(
            Proposal.from_proto(dejsonify(d["proposal"])))
    if t == BlockPartMessage.TYPE:
        return BlockPartMessage(
            height=d["height"], round=d["round"],
            part=Part.from_proto(dejsonify(d["part"])))
    if t == VoteMessage.TYPE:
        return VoteMessage(Vote.from_proto(dejsonify(d["vote"])))
    raise ValueError(f"unknown WAL message type {t!r}")
