"""HeightVoteSet: all VoteSets for one height, keyed by round.

Reference: internal/consensus/types/height_vote_set.go — prevotes and
precommits per round, plus peer catch-up rounds (each peer may make us
track one extra round via SetPeerMaj23).
"""
from __future__ import annotations

from typing import Optional

from ..types import canonical
from ..types.validator_set import ValidatorSet
from ..types.vote import Vote
from ..types.vote_set import VoteSet


class HeightVoteSetError(Exception):
    pass


class HeightVoteSet:
    def __init__(self, chain_id: str, height: int,
                 val_set: ValidatorSet,
                 extensions_enabled: bool = False):
        self.chain_id = chain_id
        self.height = height
        self.val_set = val_set
        self.extensions_enabled = extensions_enabled
        self.round = 0
        self._round_vote_sets: dict[int, tuple[VoteSet, VoteSet]] = {}
        self._peer_catchup_rounds: dict[str, list[int]] = {}
        self._add_round(0)
        self.set_round(0)

    def reset(self, height: int, val_set: ValidatorSet) -> None:
        self.height = height
        self.val_set = val_set
        self.round = 0
        self._round_vote_sets = {}
        self._peer_catchup_rounds = {}
        self._add_round(0)
        self.set_round(0)

    def _add_round(self, round_: int) -> None:
        if round_ in self._round_vote_sets:
            raise HeightVoteSetError(f"add_round for existing {round_}")
        mk = VoteSet.extended if self.extensions_enabled else VoteSet
        prevotes = VoteSet(self.chain_id, self.height, round_,
                           canonical.PREVOTE_TYPE, self.val_set)
        precommits = mk(self.chain_id, self.height, round_,
                        canonical.PRECOMMIT_TYPE, self.val_set)
        self._round_vote_sets[round_] = (prevotes, precommits)

    def set_round(self, round_: int) -> None:
        """Track rounds 0..round+1 (reference: SetRound — round+1 allows
        round-skipping)."""
        new_round = self.round - 1 if self.round > 0 else 0
        if round_ < new_round and self.round != 0:
            raise HeightVoteSetError("set_round must increment round")
        for r in range(new_round, round_ + 2):
            if r not in self._round_vote_sets:
                self._add_round(r)
        self.round = round_

    def ensure_round_tracked(self, round_: int) -> None:
        """Track one specific round without advancing the round
        cursor.  Aggregate-commit catchup injects VERIFIED +2/3
        evidence for a commit round this node may never have reached
        locally (the chain decided at round 3 while we churned at 0)
        — allocation is bounded because callers verify the aggregate
        signature first."""
        if round_ >= 0 and round_ not in self._round_vote_sets:
            self._add_round(round_)

    # ------------------------------------------------------------------
    def add_vote(self, vote: Vote, peer_id: str = "") -> bool:
        """Returns True if added.  Unwanted rounds (beyond round+1) are
        only tracked as peer catch-up (one per peer)."""
        if not canonical.is_vote_type_valid(vote.type):
            raise HeightVoteSetError(f"invalid vote type {vote.type}")
        vote_set = self._get_vote_set(vote.round, vote.type)
        if vote_set is None:
            rounds = self._peer_catchup_rounds.setdefault(peer_id, [])
            if len(rounds) < 2:
                self._add_round(vote.round)
                vote_set = self._get_vote_set(vote.round, vote.type)
                rounds.append(vote.round)
            else:
                raise HeightVoteSetError(
                    "peer has sent a vote that does not match our round "
                    "for more than one round")
        return vote_set.add_vote(vote)

    def prevotes(self, round_: int) -> Optional[VoteSet]:
        return self._get_vote_set(round_, canonical.PREVOTE_TYPE)

    def precommits(self, round_: int) -> Optional[VoteSet]:
        return self._get_vote_set(round_, canonical.PRECOMMIT_TYPE)

    def _get_vote_set(self, round_: int,
                      type_: int) -> Optional[VoteSet]:
        rvs = self._round_vote_sets.get(round_)
        if rvs is None:
            return None
        return rvs[0] if type_ == canonical.PREVOTE_TYPE else rvs[1]

    # ------------------------------------------------------------------
    def pol_info(self) -> tuple[int, Optional[object]]:
        """Highest round with a 2/3 prevote majority (POL), or -1.

        Reference: POLInfo."""
        for r in range(self.round, -1, -1):
            pv = self.prevotes(r)
            if pv is not None:
                bid, ok = pv.two_thirds_majority()
                if ok:
                    return r, bid
        return -1, None

    def set_peer_maj23(self, round_: int, type_: int, peer_id: str,
                       block_id) -> None:
        if not canonical.is_vote_type_valid(type_):
            raise HeightVoteSetError(f"invalid vote type {type_}")
        vote_set = self._get_vote_set(round_, type_)
        if vote_set is None:
            return
        vote_set.set_peer_maj23(peer_id, block_id)
