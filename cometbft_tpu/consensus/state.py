"""The Tendermint consensus state machine.

Reference: internal/consensus/state.go (2792 LoC) — a single receive
routine serializes ALL inputs (peer messages, internal messages,
timeouts); step functions enterNewRound → enterPropose → enterPrevote →
enterPrecommit → enterCommit → finalizeCommit; WAL-before-process;
lock/valid-block rules; PBTS timely checks; vote extensions.

Here the receive routine is one asyncio task; the same serialization
invariant holds (only that task mutates RoundState).
"""
from __future__ import annotations

import asyncio
import time
from typing import Callable, Optional

from ..config import ConsensusConfig
from ..libs import fail
from ..libs import tracing
from ..libs.log import Logger, new_logger
from ..state.execution import BlockExecutor, provisional_next_state
from ..state.state import State as SMState
from ..state.validation import BlockValidationError
from ..types import canonical
from ..types.block import Block
from ..types.block_id import BlockID
from ..types.commit import AggregateCommit, Commit, ExtendedCommit
from ..types.events import EventBus, NopEventBus
from ..types.params import MAX_BLOCK_SIZE_BYTES, BLOCK_PART_SIZE_BYTES
from ..types.part_set import PartSet, PartSetError, PartSetHeader
from ..types.priv_validator import PrivValidator
from ..types.proposal import Proposal
from ..types.timestamp import Timestamp
from ..types import vote as vote_mod
from ..types.vote import Vote, VoteError
from ..types.vote_set import ConflictingVoteError, VoteSet, VoteSetError
from ..wire import pb, decode
from .height_vote_set import HeightVoteSet, HeightVoteSetError
from .messages import (
    COMPACT_MIN_TXS, AggregateCommitMessage, BlockPartMessage,
    CompactBlockPartMessage, ProposalMessage, VoteBatchMessage,
    VoteMessage, reconstruct_block_bytes,
)
from .adaptive import AdaptiveTimeouts
from .round_state import (
    STEP_COMMIT, STEP_NAMES, STEP_NEW_HEIGHT, STEP_NEW_ROUND,
    STEP_PRECOMMIT, STEP_PRECOMMIT_WAIT, STEP_PREVOTE,
    STEP_PREVOTE_WAIT, STEP_PROPOSE, RoundState, TimeoutInfo,
)
from .ticker import TimeoutTicker
from .wal import WAL, NilWAL

_TIME_IOTA_NS = 1_000_000  # minimum time increment between blocks (1ms)


class ConsensusError(Exception):
    pass


class _PipelinedCommit:
    """One in-flight background execute/commit (docs/pipeline.md).

    ``future`` resolves to the post-apply SMState (or the apply
    failure).  Only the receive routine awaits it — the completion
    hand-off back into consensus state happens on the single-writer
    task, never from the background task itself."""

    __slots__ = ("height", "future", "task", "t0")

    def __init__(self, height: int, future: "asyncio.Future",
                 t0: float):
        self.height = height
        self.future = future
        self.task = None
        self.t0 = t0


class ConsensusState:
    """The consensus machine for one node.

    External inputs arrive via set_proposal / add_proposal_block_part /
    try_add_vote (thread-unsafe; call from the event loop) or the async
    queues used by the reactor.
    """

    def __init__(self, config: ConsensusConfig, state: SMState,
                 block_exec: BlockExecutor, block_store,
                 priv_validator: Optional[PrivValidator] = None,
                 event_bus: Optional[EventBus] = None,
                 wal: Optional[WAL] = None,
                 logger: Optional[Logger] = None,
                 metrics: Optional["Metrics"] = None,
                 supervisor=None):
        from .metrics import Metrics
        self.metrics = metrics if metrics is not None else Metrics()
        # when set (node wiring), the receive routine is
        # supervisor-owned: a crash restarts it (bounded) with metrics
        # instead of silently halting consensus
        self.supervisor = supervisor
        self.config = config
        self.block_exec = block_exec
        self.block_store = block_store
        self.priv_validator = priv_validator
        self.priv_validator_pub_key = \
            priv_validator.get_pub_key() if priv_validator else None
        self.event_bus = event_bus if event_bus is not None \
            else NopEventBus()
        self.wal = wal if wal is not None else NilWAL()
        self.logger = logger if logger is not None else \
            new_logger("consensus")

        self.rs = RoundState()
        self.sm_state: Optional[SMState] = None
        # pipelined commit: the one background execute/commit allowed
        # in flight (pipeline depth 1); None when the machine is fully
        # applied.  Steps that need the applied state call
        # _sync_pipeline() — the explicit barrier.
        self._pipeline: Optional[_PipelinedCommit] = None
        # measured adaptive timeouts (consensus.adaptive_timeouts):
        # fed from the same quorum-prevote-delay latch the histogram
        # records; None = static config only
        self._adaptive: Optional[AdaptiveTimeouts] = None
        if getattr(config, "adaptive_timeouts", False):
            self._adaptive = AdaptiveTimeouts(
                config.adaptive_timeout_floor_ns,
                config.adaptive_timeout_ceiling_ns)
        # highest (height, round) whose quorum-prevote delay was
        # observed: two_thirds_majority() stays true for every prevote
        # trailing the quorum — including stragglers from EARLIER
        # rounds arriving after a later round already observed — and
        # the histogram must record only the earliest quorum-achieving
        # prevote of each round, once, so the latch is monotonic
        self._quorum_delay_observed: tuple = (-1, -1)

        # one merged input queue (Go's select over the three channels is
        # unbiased, so FIFO merging preserves the semantics)
        self._input_queue: asyncio.Queue = asyncio.Queue(2000)
        self.ticker = TimeoutTicker(self._on_timeout_fired)
        self._task: Optional[asyncio.Task] = None
        self._stopped = asyncio.Event()
        self.n_steps = 0
        self.replay_mode = False
        # peers that sent a provably-invalid aggregate catchup commit
        # (each costs an O(n) pubkey sum + pairing to reject — see
        # _try_add_aggregate_commit).  Peer ids are attacker-minted
        # (fresh node key per reconnect), so this is a bounded
        # insertion-ordered dict with oldest-evicted, not a grow-only
        # set — an id churner gets one wasted verification per
        # identity either way, without growing memory
        self._agg_commit_forgers: dict = {}
        self._agg_commit_forgers_max = 1024
        # flight recorder: (height, round, step, t0_ns) of the step in
        # progress — closed into a span when the next step begins
        self._trace_step: Optional[tuple] = None
        # monotonic anchor for rs.start_time (wall): interval math on
        # it (reactor's seconds_since_start_time) must survive
        # wall-clock steps
        self._start_time_mono = time.monotonic()

        # hooks for the reactor / tests: called after state transitions
        self.on_new_step: list[Callable[[RoundState], None]] = []
        # broadcast hooks: the reactor wires these to peer gossip
        self.broadcast_hooks: list[Callable[[object], None]] = []
        # decide-proposal override (byzantine tests)
        self.decide_proposal_override: Optional[Callable] = None

        # reconstruct LastCommit from the stored seen commit BEFORE
        # updateToState (reference: NewState — reconstructLastCommit runs
        # first when LastBlockHeight > 0)
        self._reconstruct_last_commit_if_needed(state)
        self.update_to_state(state)

    # ==================================================================
    # lifecycle

    async def start(self) -> None:
        self._stopped.clear()
        if self.supervisor is None:
            # standalone (tests / light wiring): the receive routine
            # still runs supervisor-owned — a bare create_task would
            # die silently on the first uncaught exception, and the
            # tier-1 bftlint supervised-spawn rule locks that
            # invariant for all reactor/node loops
            from ..libs.supervisor import Supervisor
            self.supervisor = Supervisor("consensus",
                                         logger=self.logger)
        from ..libs.supervisor import RestartPolicy
        self._task = self.supervisor.spawn(
            lambda: self._receive_routine(),
            name="consensus_receive", kind="consensus_receive",
            policy=RestartPolicy(max_restarts=3, window_s=60.0,
                                 backoff_base_s=0.05,
                                 backoff_max_s=1.0))
        self._schedule_round0()

    async def stop(self, drain_pipeline: bool = True) -> None:
        """``drain_pipeline=False`` models a hard crash: an in-flight
        pipelined apply is aborted instead of awaited, leaving the
        stores wherever the crash-consistency barriers put them — the
        WAL end-height record is already fsync'd, so restart recovery
        (handshake + catchup replay) re-applies the block."""
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        # drain any in-flight pipelined apply: the block is decided
        # and WAL-barriered, so letting the execute/commit finish
        # keeps the stores one-height-consistent when it can complete;
        # a failure here is already logged by the task itself
        p, self._pipeline = self._pipeline, None
        if p is not None and drain_pipeline:
            # join the TASK, not the barrier future: cancelling the
            # receive routine mid-barrier also cancelled the future
            # it was awaiting, but the background apply keeps running
            # and must be waited out (or aborted) before the stores
            # are handed to a restart
            try:
                if p.task is not None:
                    await asyncio.wait_for(p.task.wait(), 10.0)
                else:
                    await asyncio.wait_for(asyncio.shield(p.future),
                                           10.0)
            except Exception:
                self.logger.info(
                    "in-flight pipelined apply did not complete on "
                    "stop; replay/handshake re-applies the block",
                    height=p.height, exc_info=True)
                if p.task is not None:
                    p.task.cancel()
        elif p is not None:
            if p.task is not None:
                p.task.cancel()
            if not p.future.done():
                p.future.cancel()
            else:
                try:
                    p.future.exception()   # consume, never re-raised
                except asyncio.CancelledError:
                    pass
        self.ticker.stop()
        self.wal.close()
        self._stopped.set()

    # ==================================================================
    # external input API (reference: state.go AddVote/SetProposal/
    # AddProposalBlockPart — enqueue into peer/internal queues)

    def send_internal(self, msg, peer_id: str = "") -> None:
        item = ("internal", msg, peer_id)
        try:
            self._input_queue.put_nowait(item)
        except asyncio.QueueFull:
            # overload (e.g. a 900-height catchup storm filling the
            # queue with peer messages): our OWN vote/proposal must
            # never crash the receive routine — and since that
            # routine IS the consumer, blocking here would deadlock.
            # Defer the put to a supervised task; the state machine
            # re-validates on delivery, so the slight reordering is
            # benign (the nemesis catchup scenario caught the old
            # put_nowait crash wedging a node for good).
            self.logger.info(
                "consensus input queue full; deferring internal "
                "message", msg_type=type(msg).__name__)
            if self.supervisor is not None:
                self.supervisor.spawn(
                    lambda: self._input_queue.put(item),
                    name="internal_requeue",
                    kind="consensus_internal_requeue")

    def send_peer(self, msg, peer_id: str) -> None:
        self._input_queue.put_nowait(("peer", msg, peer_id))

    def _on_timeout_fired(self, ti: TimeoutInfo) -> None:
        self._input_queue.put_nowait(("timeout", ti, ""))

    # ==================================================================
    # the receive routine — the ONLY mutator of RoundState

    async def _receive_routine(self) -> None:
        while True:
            try:
                # fairness: Queue.get returns without suspending while
                # items are ready, which would starve every other task
                # (peers, RPC, watchers) on a busy chain
                await asyncio.sleep(0)
                first = await self._input_queue.get()
                # burst drain: batch-pre-verify the signatures of every
                # queued vote in one shot (TPU kernel / native MSM by
                # key type), then process the burst serially in the
                # exact arrival order — the state machine sees the same
                # sequence as unbatched processing, but vote storms pay
                # one batched verification instead of per-vote ones
                burst = [first]
                while len(burst) < 256:
                    try:
                        burst.append(self._input_queue.get_nowait())
                    except asyncio.QueueEmpty:
                        break
                if len(burst) > 1:
                    await self._preverify_burst(burst)
                for i, (kind, msg, peer_id) in enumerate(burst):
                    if i:
                        # keep the old per-message fairness yield: the
                        # handlers have no guaranteed suspension point,
                        # and a 256-message stretch would starve peers
                        await asyncio.sleep(0)
                    if kind == "timeout":
                        await self._handle_timeout(msg)
                    else:
                        await self._handle_msg(
                            msg, peer_id, internal=(kind == "internal"))
            except asyncio.CancelledError:
                raise
            except Exception:
                # reference: receiveRoutine recovers by flushing WAL then
                # re-panicking; we log and crash the task
                self.logger.error("consensus failure",
                                  exc_info=True)
                self.wal.flush_and_sync()
                raise

    async def _preverify_burst(self, burst) -> None:
        """Collect the signatures of queued VoteMessages for the
        CURRENT height's validator set and batch-verify them into the
        verified-triple memo (types/vote.py) — the tally-path batching
        the reference leaves per-vote (SURVEY: vote_set.go:219-236).

        The batch itself runs OFF the event loop, on the verification
        staging worker (crypto/pipeline.py): this await is a verdict
        barrier, not a stall — while the native kernels verify the
        storm GIL-free, the loop keeps draining p2p recv, gossip and
        RPC, which is exactly the stall QA_r08 profiled stacking
        behind a synchronous burst verify.  Burst messages are
        processed only after the barrier, so the state machine sees
        the same serial order as before.  Purely advisory: lookup
        failures or invalid signatures are left for the serial path,
        whose verdicts do not change."""
        entries = []
        for kind, msg, _peer in burst:
            if kind == "timeout" or not isinstance(msg, VoteMessage):
                continue
            vote = msg.vote
            if vote is None or vote.height != self.rs.height:
                continue
            vals = self.rs.validators
            if (vals is None or vote.validator_index < 0 or
                    vote.validator_index >= vals.size()):
                continue
            val = vals.validators[vote.validator_index]
            if (val.pub_key is None or
                    val.pub_key.address() != vote.validator_address):
                continue
            self._append_vote_entries(
                entries, vote, val.pub_key, self.sm_state.chain_id)
        if len(entries) >= 2:
            try:
                await asyncio.wrap_future(
                    vote_mod.preverify_signatures_async(entries))
            except Exception:
                # advisory: a worker failure just means the serial
                # tally verifies each signature itself
                self.logger.debug(
                    "burst pre-verification failed (serial tally "
                    "decides)", exc_info=True)

    def _append_vote_entries(self, entries, vote, pub_key,
                             chain_id: str) -> None:
        """Append a vote's signature triples (main + both extension
        signatures for non-nil precommits) for advisory batch
        pre-verification.  Never raises: malformed fields are left for
        the serial path's own errors."""
        try:
            entries.append((pub_key, vote.sign_bytes(chain_id),
                            vote.signature))
            if (vote.type == canonical.PRECOMMIT_TYPE and
                    not vote.block_id.is_nil() and
                    vote.extension_signature and
                    vote.non_rp_extension_signature):
                entries.append((pub_key,
                                vote.extension_sign_bytes(chain_id),
                                vote.extension_signature))
                entries.append((pub_key,
                                vote.non_rp_extension_sign_bytes(),
                                vote.non_rp_extension_signature))
        except Exception:
            self.logger.debug(
                "vote preverify: skipping malformed vote "
                "(serial tally will report it)", exc_info=True)

    async def _handle_msg(self, msg, peer_id: str, internal: bool) -> None:
        # a vote batch unpacks into individual VoteMessages (each
        # WAL'd exactly as an unbatched peer would have logged it);
        # the batch rides the input queue as ONE entry so wire-level
        # backpressure is preserved
        if isinstance(msg, VoteBatchMessage):
            for v in msg.votes:
                await self._handle_msg(VoteMessage(v), peer_id,
                                       internal=internal)
            return

        # the compact form is never WAL'd: reconstruction feeds the
        # rebuilt parts through the normal BlockPartMessage path
        # below, so the WAL records exactly what a full-part peer
        # would have logged and replay needs no mempool
        if isinstance(msg, CompactBlockPartMessage):
            try:
                await self._apply_compact_block(msg, peer_id)
            except (PartSetError, ConsensusError) as e:
                self.logger.error("failed to apply compact block",
                                  err=str(e), peer=peer_id)
            return

        # WAL-before-process (reference: state.go:886 handleMsg; internal
        # messages are fsync'd — they may carry our own signatures).
        # During catchup replay the messages are already in the WAL.
        if not self.replay_mode:
            if internal:
                self.wal.write_sync(msg.to_wal())
            else:
                self.wal.write(msg.to_wal())

        if isinstance(msg, ProposalMessage):
            try:
                self._set_proposal(msg.proposal, Timestamp.now())
            except ConsensusError as e:
                self.logger.error("failed to set proposal", err=str(e),
                                  peer=peer_id)
        elif isinstance(msg, BlockPartMessage):
            try:
                added = await self._add_proposal_block_part(msg, peer_id)
            except (PartSetError, ConsensusError) as e:
                self.logger.error("failed to add block part",
                                  err=str(e), peer=peer_id)
        elif isinstance(msg, VoteMessage):
            try:
                await self._try_add_vote(msg.vote, peer_id)
            except (VoteSetError, HeightVoteSetError, VoteError) as e:
                self.logger.error("failed to add vote", err=str(e),
                                  peer=peer_id)
        elif isinstance(msg, AggregateCommitMessage):
            try:
                await self._try_add_aggregate_commit(msg.commit,
                                                     peer_id)
            except ConsensusError as e:
                self.logger.error("failed to add aggregate commit",
                                  err=str(e), peer=peer_id)
        else:
            self.logger.error(f"unknown msg type {type(msg)}")

    async def _handle_timeout(self, ti: TimeoutInfo) -> None:
        """Reference: state.go handleTimeout."""
        rs = self.rs
        if ti.height != rs.height or ti.round < rs.round or \
                (ti.round == rs.round and ti.step < rs.step):
            return
        # create_empty_blocks gating (reference: state.go
        # waiting-for-txs in enterPropose): with
        # create_empty_blocks=false, or an interval that has not yet
        # elapsed, an empty mempool re-arms a short poll instead of
        # burning a full propose/prevote/precommit round on an empty
        # block — at pipelined sub-second intervals the empty-block
        # churn otherwise starves real work.  Checked BEFORE the WAL
        # write so idle polls never bloat the WAL (they carry no
        # state change to replay).
        if ti.step == STEP_NEW_HEIGHT and self._should_wait_for_txs():
            self._schedule_timeout(50 * 1_000_000, ti.height, 0,
                                   STEP_NEW_HEIGHT)
            return
        if not self.replay_mode:
            self.wal.write({"type": "timeout", "height": ti.height,
                            "round": ti.round, "step": ti.step})
        if ti.step == STEP_NEW_HEIGHT:
            await self._enter_new_round(ti.height, 0)
        elif ti.step == STEP_NEW_ROUND:
            await self._enter_propose(ti.height, 0)
        elif ti.step == STEP_PROPOSE:
            self.event_bus.publish_timeout_propose(rs.event_summary())
            await self._enter_prevote(ti.height, ti.round)
        elif ti.step == STEP_PREVOTE_WAIT:
            self.event_bus.publish_timeout_wait(rs.event_summary())
            await self._enter_precommit(ti.height, ti.round)
        elif ti.step == STEP_PRECOMMIT_WAIT:
            self.event_bus.publish_timeout_wait(rs.event_summary())
            await self._enter_precommit(ti.height, ti.round)
            await self._enter_new_round(ti.height, ti.round + 1)

    # ==================================================================
    # state update

    def update_to_state(self, state: SMState) -> None:
        """Reference: state.go updateToState (:660)."""
        rs = self.rs
        if rs.commit_round > -1 and 0 < rs.height and \
                rs.height != state.last_block_height:
            raise ConsensusError(
                f"updateToState expected state height {rs.height} but "
                f"got {state.last_block_height}")
        if self.sm_state is not None and not self.sm_state.is_empty():
            if self.sm_state.last_block_height > 0 and \
                    state.last_block_height <= \
                    self.sm_state.last_block_height:
                self._new_step()
                return

        validators = state.validators
        if state.last_block_height == 0:
            rs.set_last_commit(None)
        elif rs.commit_round > -1 and rs.votes is not None:
            precommits = rs.votes.precommits(rs.commit_round)
            if not precommits.has_two_thirds_majority():
                raise ConsensusError(
                    "wanted to form a commit but precommits lack 2/3+")
            rs.set_last_commit(precommits)
        elif rs.last_commit is None:
            raise ConsensusError(
                f"last commit cannot be empty after initial block "
                f"(H:{state.last_block_height + 1})")

        height = state.last_block_height + 1
        if height == 1:
            height = state.initial_height

        next_block_delay = state.next_block_delay_ns
        if next_block_delay == 0:
            # the padding came from static config, not from the app's
            # next_block_delay decision — adaptivity may shrink it
            next_block_delay = self._commit_padding_ns()
        if rs.commit_time.is_zero():
            start_time = Timestamp.now().add_ns(next_block_delay)
        else:
            start_time = rs.commit_time.add_ns(next_block_delay)

        ext_enabled = state.consensus_params.feature \
            .vote_extensions_enabled(height)
        rs.begin_height(
            height, start_time, validators,
            HeightVoteSet(state.chain_id, height, validators,
                          extensions_enabled=ext_enabled),
            state.last_validators)
        # re-anchor: start_time is wall (a protocol-adjacent value);
        # elapsed-time consumers use the monotonic twin.  The offset
        # is SIGNED — a start_time already in the past (WAL replay,
        # slow commit) must keep reporting real elapsed time
        self._start_time_mono = time.monotonic() + \
            rs.start_time.sub(Timestamp.now()) / 1e9
        self.sm_state = state
        self._new_step()

    async def reconstruct_last_commit_off_loop(
            self, state: SMState) -> None:
        """``_reconstruct_last_commit_if_needed`` on the verification
        staging worker — the blocksync→consensus switch reconstructs
        LastCommit while the p2p loop is live, and the commit's batch
        signature verification (O(validators) native kernel work)
        must not stall it.  Safe off-thread: consensus has not
        started yet at the switch, so RoundState has no other
        writer, and the native kernels release the GIL so the loop
        keeps scheduling while the worker verifies."""
        from ..crypto import pipeline
        await pipeline.run_off_loop(
            self._reconstruct_last_commit_if_needed, state)

    def _reconstruct_last_commit_if_needed(self, state: SMState) -> None:
        """Rebuild LastCommit from the stored seen commit on restart
        (reference: state.go reconstructLastCommit :602)."""
        if state.last_block_height == 0 or self.rs.last_commit is not None:
            return
        ext_enabled = state.consensus_params.feature \
            .vote_extensions_enabled(state.last_block_height)
        if ext_enabled:
            ec = self.block_store.load_block_ext_commit(
                state.last_block_height)
            if ec is None:
                raise ConsensusError(
                    f"failed to reconstruct last extended commit; commit "
                    f"for height {state.last_block_height} not found")
            self.rs.set_last_commit(self._vote_set_from_extended_commit(
                state, ec))
        else:
            sc = self.block_store.load_seen_commit(
                state.last_block_height)
            if sc is None:
                raise ConsensusError(
                    f"failed to reconstruct last commit; seen commit for "
                    f"height {state.last_block_height} not found")
            self.rs.set_last_commit(self._vote_set_from_commit(state, sc))

    def _vote_set_from_commit(self, state: SMState,
                              commit) -> VoteSet:
        """Reference: types Commit.ToVoteSet.  Votes are constructed
        once and shared between the advisory batch pre-verification
        and the serial tally: each vote marshals its sign bytes a
        single time (the per-object memo), and VoteSet.add_vote's
        signature checks hit the verified-triple memo — one batched
        dispatch instead of per-signature verification.

        An AggregateCommit seen commit (blocksync'd node joining
        consensus) has no per-vote signatures to reconstruct: the
        vote set is restored as an aggregate-backed shell that proves
        the majority and re-proposes the stored aggregate
        (VoteSet.from_aggregate_commit)."""
        try:
            vals = self.block_exec.store.load_validators(commit.height)
        except Exception:
            self.logger.debug(
                "no stored validator set; falling back to "
                "state.last_validators", height=commit.height,
                exc_info=True)
            vals = state.last_validators
        if isinstance(commit, AggregateCommit):
            return VoteSet.from_aggregate_commit(
                state.chain_id, commit, vals)
        votes = [commit.get_vote(i)
                 for i, cs in enumerate(commit.signatures)
                 if not cs.absent_flag()]
        self._preverify_votes(state.chain_id, vals, votes)
        vs = VoteSet(state.chain_id, commit.height, commit.round,
                     canonical.PRECOMMIT_TYPE, vals)
        for v in votes:
            vs.add_vote(v)
        return vs

    def _preverify_votes(self, chain_id: str, vals, votes) -> None:
        """Advisory batch pre-verification of constructed votes into
        the verified-triple memo — all three signatures per extended
        vote (see _append_vote_entries).  Verdicts unchanged: lookup
        failures and invalid signatures fall to the serial path's own
        errors."""
        entries = []
        for v in votes:
            try:
                _, val = vals.get_by_address(v.validator_address)
                if val is None or val.pub_key is None:
                    continue
                self._append_vote_entries(entries, v, val.pub_key,
                                          chain_id)
            except Exception:
                self.logger.debug(
                    "vote preverify: validator lookup failed "
                    "(serial tally will report it)", exc_info=True)
                continue
        if len(entries) >= 2:
            vote_mod.preverify_signatures(entries)

    def _vote_set_from_extended_commit(self, state: SMState,
                                       ec: ExtendedCommit) -> VoteSet:
        vals = self.block_exec.store.load_validators(ec.height)
        votes = [ec.get_extended_vote(i)
                 for i, ecs in enumerate(ec.extended_signatures)
                 if not ecs.absent_flag()]
        self._preverify_votes(state.chain_id, vals, votes)
        vs = VoteSet.extended(state.chain_id, ec.height, ec.round,
                              canonical.PRECOMMIT_TYPE, vals)
        for v in votes:
            vs.add_vote(v)
        return vs

    def seconds_since_start(self) -> int:
        """Whole seconds since this height's (wall) start_time,
        measured on the monotonic clock so a wall-clock step cannot
        corrupt the interval (reactor NewRoundStep messages)."""
        return int(time.monotonic() - self._start_time_mono)

    def _trace_step_transition(self) -> None:
        """Close the in-progress step into a flight-recorder span when
        the (height, round, step) triple advances."""
        rs = self.rs
        cur = (rs.height, rs.round, rs.step)
        prev = self._trace_step
        if prev is not None and (prev[0], prev[1], prev[2]) == cur:
            return                     # re-announce of the same step
        now = tracing.now_ns()
        if prev is not None:
            tracing.record_span(
                tracing.CONSENSUS,
                f"step:{STEP_NAMES.get(prev[2], '?')}",
                prev[3], now, height=prev[0], round=prev[1])
        self._trace_step = (*cur, now)

    def _new_step(self) -> None:
        self.wal.write({"type": "round_state",
                        **self.rs.event_summary()})
        self.n_steps += 1
        # height context first and unconditionally: other categories
        # (crypto/p2p/abci) rely on it even when the consensus
        # category itself is filtered out
        tracing.set_height(self.rs.height)
        if tracing.enabled(tracing.CONSENSUS):
            self._trace_step_transition()
        self.event_bus.publish_new_round_step(self.rs.event_summary())
        self.metrics.mark_step(self.rs)
        for hook in self.on_new_step:
            hook(self.rs)

    # ==================================================================
    # timeouts / round scheduling

    def _schedule_round0(self) -> None:
        sleep_ns = max(0, self.rs.start_time.sub(Timestamp.now()))
        self._schedule_timeout(sleep_ns, self.rs.height, 0,
                               STEP_NEW_HEIGHT)

    def _schedule_timeout(self, duration_ns: int, height: int,
                          round_: int, step: int) -> None:
        self.ticker.schedule_timeout(
            TimeoutInfo(duration_ns, height, round_, step))

    # ------------------------------------------------------------------
    # timeout derivation: measured-adaptive when enabled AND the
    # quorum-delay EWMA has data; the static config otherwise.  The
    # per-round escalation deltas always come from the static config
    # so liveness under asynchrony is unchanged (docs/pipeline.md).

    def _propose_timeout_ns(self, round_: int) -> int:
        if self._adaptive is not None:
            base = self._adaptive.propose_timeout_ns()
            if base is not None:
                return base + \
                    self.config.timeout_propose_delta_ns * round_
        return self.config.propose_timeout_ns(round_)

    def _vote_wait_timeout_ns(self, round_: int) -> int:
        if self._adaptive is not None:
            base = self._adaptive.vote_timeout_ns()
            if base is not None:
                return base + self.config.timeout_vote_delta_ns * round_
        return self.config.prevote_timeout_ns(round_)

    def _commit_padding_ns(self) -> int:
        """Static commit padding, adaptively shrunk when measured
        quorum delays say the net is faster than the config."""
        padding = self.config.timeout_commit_ns
        if self._adaptive is not None:
            padding = self._adaptive.commit_padding_ns(padding)
        return padding

    def _should_wait_for_txs(self) -> bool:
        """True while round 0 of a fresh height should hold off
        proposing because the pool is empty (config.wait_for_txs):
        create_empty_blocks=false waits indefinitely; a nonzero
        create_empty_blocks_interval waits until the interval since
        the height's start_time has elapsed.  Replay never waits (the
        WAL drives it), and only round 0 is gated — once any round
        ran, liveness wins."""
        if self.replay_mode or self.rs.round != 0:
            return False
        if not self.config.wait_for_txs():
            return False
        mp = getattr(self.block_exec, "mempool", None)
        if mp is None or mp.size() > 0:
            return False
        if not self.config.create_empty_blocks:
            return True
        interval_s = self.config.create_empty_blocks_interval_ns / 1e9
        return (time.monotonic() - self._start_time_mono) < interval_s

    # ==================================================================
    # step: NewRound

    async def _enter_new_round(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or \
                (rs.round == round_ and rs.step != STEP_NEW_HEIGHT):
            return
        validators = rs.validators
        if rs.round < round_:
            validators = validators.copy()
            validators.increment_proposer_priority(round_ - rs.round)
        rs.begin_round(round_, validators)
        self.metrics.mark_round(round_)
        self.event_bus.publish_new_round(rs.event_summary())
        await self._enter_propose(height, round_)

    # ==================================================================
    # step: Propose

    def _is_proposer(self, address: bytes) -> bool:
        return self.rs.validators.get_proposer().address == address

    async def _enter_propose(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or \
                (rs.round == round_ and rs.step >= STEP_PROPOSE):
            return

        async def done() -> None:
            rs.advance(round_, STEP_PROPOSE)
            self._new_step()
            if self._is_proposal_complete():
                await self._enter_prevote(height, rs.round)

        self._schedule_timeout(
            self._propose_timeout_ns(round_), height, round_,
            STEP_PROPOSE)

        if self.priv_validator is None or \
                self.priv_validator_pub_key is None:
            await done()
            return
        addr = self.priv_validator_pub_key.address()
        if not rs.validators.has_address(addr):
            await done()
            return
        if self._is_proposer(addr):
            if self.decide_proposal_override is not None:
                self.decide_proposal_override(height, round_)
            else:
                await self._decide_proposal(height, round_)
        await done()

    async def _decide_proposal(self, height: int, round_: int) -> None:
        """Reference: defaultDecideProposal."""
        # pipeline barrier: the proposer needs the previous height's
        # app hash / results hash in the new block's header — wait out
        # any in-flight execute/commit before reaping and building
        await self._sync_pipeline()
        rs = self.rs
        if rs.height != height or round_ < rs.round:
            return   # the machine moved on while we waited
        if rs.valid_block is not None:
            block, block_parts = rs.valid_block, rs.valid_block_parts
        else:
            block = await self._create_proposal_block()
            if block is None:
                return
            block_parts = block.make_part_set(BLOCK_PART_SIZE_BYTES)

        self.wal.flush_and_sync()
        prop_block_id = BlockID(hash=block.hash(),
                                part_set_header=block_parts.header())
        proposal = Proposal(
            height=height, round=round_, pol_round=rs.valid_round,
            block_id=prop_block_id, timestamp=block.header.time)
        try:
            await self._pv_sign_proposal(proposal)
            self.metrics.proposal_create_count.add()
        except Exception as e:
            if not self.replay_mode:
                self.logger.error("failed signing proposal",
                                  height=height, err=str(e))
            return
        self.send_internal(ProposalMessage(proposal))
        for i in range(block_parts.total):
            self.send_internal(BlockPartMessage(
                height=rs.height, round=rs.round,
                part=block_parts.get_part(i)))
        self._broadcast(ProposalMessage(proposal))
        # first-sent marker: the proposer-side t0 the fleet report
        # pairs with every other node's proposal_recv (first-seen) to
        # measure proposal propagation per link
        tracing.instant(tracing.CONSENSUS, "proposal_broadcast",
                        height=height, round=round_,
                        parts=block_parts.total,
                        txs=len(block.data.txs))
        # compact-block relay (docs/gossip.md): peers that negotiated
        # it get skeleton + tx hashes and rebuild the parts from
        # their mempool; the part broadcasts below skip them for the
        # grace window, falling back to full parts on a nack or when
        # the grace expires.  Small blocks always ship as parts, and
        # so does every round > 0: a churning round means the fast
        # path already failed once — full parts, no reconstruct race
        # (the recon-gossip nemesis scenario wedged on exactly that
        # under aggressive timeouts).
        if rs.round == 0 and len(block.data.txs) >= COMPACT_MIN_TXS:
            self._broadcast(("compact_block", rs.height, rs.round,
                             block, block_parts.header()))
        for i in range(block_parts.total):
            self._broadcast(BlockPartMessage(
                height=rs.height, round=rs.round,
                part=block_parts.get_part(i)))

    async def _create_proposal_block(self) -> Optional[Block]:
        """Reference: createProposalBlock (sync wrapper over the async
        executor call — the receive routine runs in the loop, so the
        ABCI local client call is executed inline)."""
        rs = self.rs
        if rs.height == self.sm_state.initial_height:
            last_ext_commit = ExtendedCommit()
        elif rs.last_commit is not None and \
                rs.last_commit.has_two_thirds_majority():
            last_ext_commit = rs.last_commit.make_extended_commit(
                self.sm_state.consensus_params.feature
                .vote_extensions_enable_height)
        else:
            self.logger.error(
                "propose step; cannot propose anything without commit "
                "for the previous block")
            return None
        proposer_addr = self.priv_validator_pub_key.address()
        # restart-from-aggregate: no per-vote signatures exist, so the
        # stored aggregate rides through to the block unchanged
        last_agg = getattr(rs.last_commit, "stored_aggregate_commit",
                           None) if rs.last_commit is not None else None
        try:
            return await self.block_exec.create_proposal_block(
                rs.height, self.sm_state, last_ext_commit,
                proposer_addr, last_aggregate_commit=last_agg)
        except Exception as e:
            self.logger.error("unable to create proposal block",
                              err=str(e))
            return None

    def _is_proposal_complete(self) -> bool:
        rs = self.rs
        if rs.proposal is None or rs.proposal_block is None:
            return False
        if rs.proposal.pol_round < 0:
            return True
        pv = rs.votes.prevotes(rs.proposal.pol_round)
        return pv is not None and pv.has_two_thirds_majority()

    # ==================================================================
    # proposal / block part ingestion

    def _set_proposal(self, proposal: Proposal,
                      recv_time: Timestamp) -> None:
        """Reference: defaultSetProposal (:2048)."""
        rs = self.rs
        if rs.proposal is not None or proposal is None:
            return
        if proposal.height != rs.height or proposal.round != rs.round:
            return
        if proposal.pol_round < -1 or \
                (proposal.pol_round >= 0 and
                 proposal.pol_round >= proposal.round):
            raise ConsensusError("invalid proposal POL round")
        proposer = rs.validators.get_proposer()
        if not proposer.pub_key.verify_signature(
                proposal.sign_bytes(self.sm_state.chain_id),
                proposal.signature):
            raise ConsensusError("invalid proposal signature")
        max_bytes = self.sm_state.consensus_params.block.max_bytes
        if max_bytes == -1:
            max_bytes = MAX_BLOCK_SIZE_BYTES
        if proposal.block_id.part_set_header.total > \
                (max_bytes - 1) // BLOCK_PART_SIZE_BYTES + 1:
            raise ConsensusError("proposal has too many parts")

        rs.apply_proposal(proposal, recv_time)
        diff_s = recv_time.sub(proposal.timestamp) / 1e9
        timely = "true"
        if self._pbts_enabled(rs.height):
            sp = self.sm_state.consensus_params.synchrony.in_round(
                proposal.round)
            timely = "true" if proposal.is_timely(
                recv_time, sp) else "false"
        self.metrics.proposal_timestamp_difference.with_labels(
            timely).observe(diff_s)
        tracing.instant(tracing.CONSENSUS, "proposal_received",
                        height=proposal.height, round=proposal.round,
                        parts=proposal.block_id.part_set_header.total)
        self.logger.info("Received proposal", proposal=str(proposal))

    async def _add_proposal_block_part(self, msg: BlockPartMessage,
                                 peer_id: str) -> bool:
        """Reference: addProposalBlockPart (:2129)."""
        rs = self.rs
        if rs.height != msg.height:
            return False
        if rs.proposal_block_parts is None:
            return False
        try:
            added = rs.proposal_block_parts.add_part(msg.part)
        except (PartSetError, ValueError) as e:
            # A part that doesn't match the current part-set header (e.g. a
            # part raced from another round's proposal) is dropped, not a
            # consensus failure — reference state.go:2129-2150 returns
            # ErrPartSetInvalidProof to handleMsg, which only logs it.
            self.logger.debug("Invalid block part", err=str(e), peer=peer_id)
            self.metrics.block_gossip_parts_received.with_labels(
                "false").add()
            return False
        if not added:
            self.metrics.duplicate_block_part.add()
            return False
        self.metrics.block_parts.with_labels(peer_id or "local").add()
        self.metrics.block_gossip_parts_received.with_labels(
            "true").add()
        max_bytes = self.sm_state.consensus_params.block.max_bytes
        if max_bytes == -1:
            max_bytes = MAX_BLOCK_SIZE_BYTES
        if rs.proposal_block_parts.byte_size > max_bytes:
            raise ConsensusError(
                "total size of proposal block parts exceeds block max "
                f"bytes ({rs.proposal_block_parts.byte_size} > "
                f"{max_bytes})")
        if rs.proposal_block_parts.is_complete():
            raw = rs.proposal_block_parts.assemble()
            rs.complete_proposal_block(
                Block.from_proto(decode(pb.BLOCK, raw)))
            tracing.instant(tracing.CONSENSUS, "proposal_complete",
                            height=msg.height,
                            bytes=rs.proposal_block_parts.byte_size)
            self.logger.info(
                "Received complete proposal block",
                height=rs.proposal_block.header.height,
                hash=rs.proposal_block.hash().hex().upper()[:12])
            self.event_bus.publish_complete_proposal(rs.event_summary())
            await self._handle_complete_proposal(msg.height)
        return added

    async def _apply_compact_block(self, msg: CompactBlockPartMessage,
                                   peer_id: str) -> bool:
        """Rebuild the proposal's part set from the local mempool
        (docs/gossip.md).  All-or-nothing: any unresolved tx hash (or
        a skeleton that doesn't re-encode to the advertised part-set
        header) falls back to the existing full-part gossip — the
        sender resumes pushing parts once its grace window expires.
        Safety does not rest on the sender: every rebuilt part goes
        through ``_add_proposal_block_part``, whose merkle proofs
        verify against the proposal's own part-set header."""
        rs = self.rs

        def nack() -> bool:
            # receiver-driven fallback: tell the sender to cancel its
            # grace window and push full parts NOW — waiting out the
            # grace timer can outlive a whole round under aggressive
            # timeouts (the wedge the recon-gossip nemesis scenario
            # caught on its first run)
            self._broadcast(("compact_nack", msg.height, msg.round,
                             peer_id))
            return False

        if rs.height != msg.height:
            return False            # stale height: ignore silently
        if rs.round != msg.round:
            # same height, different round (we churned past, or the
            # compact outran the round-step gossip): reconstruction
            # is moot but the sender must still stop holding parts
            # back — nack so the fallback engages immediately
            return nack()
        parts = rs.proposal_block_parts
        if parts is None:
            return nack()           # reordered ahead of the proposal
        if parts.is_complete():
            return False            # nothing to do
        if parts.header() != msg.part_set_header:
            self.metrics.compact_block_mismatches.add()
            return nack()
        mempool = getattr(self.block_exec, "mempool", None)
        if mempool is None:
            return nack()
        txs = []
        missing = 0
        for h in msg.tx_hashes:
            tx = mempool.get_tx_by_hash(h)
            if tx is None:
                missing += 1
            else:
                txs.append(tx)
        if missing:
            self.metrics.compact_block_misses.add()
            tracing.instant(tracing.CONSENSUS, "compact_block_miss",
                            height=msg.height, missing=missing,
                            total=len(msg.tx_hashes))
            return nack()
        try:
            rebuilt = PartSet.from_data(
                reconstruct_block_bytes(msg.skeleton, txs))
        except Exception as e:
            self.metrics.compact_block_mismatches.add()
            self.logger.info("compact block reconstruct failed",
                             err=str(e), peer=peer_id)
            return nack()
        if rebuilt.header() != msg.part_set_header:
            # non-canonical skeleton or diverging txs: the advertised
            # header cannot be rebuilt — full parts must flow
            self.metrics.compact_block_mismatches.add()
            return nack()
        self.metrics.compact_blocks_reconstructed.add()
        tracing.instant(tracing.CONSENSUS, "compact_block_rebuilt",
                        height=msg.height, parts=rebuilt.total,
                        num_txs=len(txs))
        for i in range(rebuilt.total):
            pm = BlockPartMessage(height=msg.height, round=msg.round,
                                  part=rebuilt.get_part(i))
            if not self.replay_mode:
                self.wal.write(pm.to_wal())
            await self._add_proposal_block_part(pm, peer_id)
        if self.rs.height == msg.height and \
                self.rs.proposal_block_parts is not None and \
                self.rs.proposal_block_parts.is_complete():
            # tell every peer we hold the full block so nobody pushes
            # parts at us (reference: NewValidBlock re-announce)
            self._broadcast(("valid_block",))
            return True
        return False

    async def _handle_complete_proposal(self, height: int) -> None:
        """Reference: handleCompleteProposal (:2217)."""
        rs = self.rs
        prevotes = rs.votes.prevotes(rs.round)
        block_id, has_two_thirds = prevotes.two_thirds_majority()
        if has_two_thirds and not block_id.is_nil() and \
                rs.valid_round < rs.round:
            if rs.proposal_block.hash() == block_id.hash:
                rs.set_valid(rs.round, rs.proposal_block,
                             rs.proposal_block_parts)
        if rs.step <= STEP_PROPOSE and self._is_proposal_complete():
            await self._enter_prevote(height, rs.round)
            if has_two_thirds:
                await self._enter_precommit(height, rs.round)
        elif rs.step == STEP_COMMIT:
            await self._try_finalize_commit(height)

    # ==================================================================
    # step: Prevote

    async def _enter_prevote(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or \
                (rs.round == round_ and rs.step >= STEP_PREVOTE):
            return
        await self._do_prevote(height, round_)
        # the transition seam re-validates monotonicity at the store —
        # the cross-await discipline bftlint's await-atomicity rule
        # checks (the sign/validate awaits above suspend this routine)
        rs.advance(round_, STEP_PREVOTE)
        self._new_step()

    async def _do_prevote(self, height: int, round_: int) -> None:
        """Reference: defaultDoPrevote (:1387)."""
        rs = self.rs
        if rs.proposal is None or rs.proposal_block is None:
            await self._sign_add_vote(canonical.PREVOTE_TYPE, b"",
                                PartSetHeader())
            return

        block_hash = rs.proposal_block.hash()
        psh = rs.proposal_block_parts.header()

        if rs.proposal.pol_round == -1:
            if rs.locked_round == -1:
                if rs.valid_round != -1 and rs.valid_block is not None \
                        and block_hash == rs.valid_block.hash():
                    await self._sign_add_vote(canonical.PREVOTE_TYPE,
                                        block_hash, psh)
                    return
                # PBTS timeliness
                if self._pbts_enabled(height):
                    if rs.proposal.timestamp != \
                            rs.proposal_block.header.time:
                        await self._sign_add_vote(canonical.PREVOTE_TYPE, b"",
                                            PartSetHeader())
                        return
                    sp = self.sm_state.consensus_params.synchrony \
                        .in_round(rs.proposal.round)
                    if not rs.proposal.is_timely(
                            rs.proposal_receive_time, sp):
                        self.logger.info(
                            "Prevote step: proposal not timely; "
                            "prevoting nil")
                        await self._sign_add_vote(canonical.PREVOTE_TYPE, b"",
                                            PartSetHeader())
                        return
                # pipeline barrier: full validation needs the applied
                # previous height (app hash, results hash) and the app
                # itself must be past H-1's Commit before it sees
                # ProcessProposal(H)
                await self._sync_pipeline()
                try:
                    self.block_exec.validate_block(self.sm_state,
                                                   rs.proposal_block)
                except BlockValidationError as e:
                    self.logger.error(
                        "prevote step: invalid block; prevoting nil",
                        err=str(e))
                    await self._sign_add_vote(canonical.PREVOTE_TYPE, b"",
                                        PartSetHeader())
                    return
                is_app_valid = await self.block_exec.process_proposal(
                    rs.proposal_block, self.sm_state)
                self.metrics.proposal_receive_count.with_labels(
                    "accepted" if is_app_valid else "rejected").add()
                if not is_app_valid:
                    self.logger.error(
                        "prevote step: app rejected proposal; "
                        "prevoting nil")
                    await self._sign_add_vote(canonical.PREVOTE_TYPE, b"",
                                        PartSetHeader())
                    return
                await self._sign_add_vote(canonical.PREVOTE_TYPE, block_hash,
                                    psh)
                return
            if rs.locked_block is not None and \
                    block_hash == rs.locked_block.hash():
                await self._sign_add_vote(canonical.PREVOTE_TYPE, block_hash,
                                    psh)
                return
            await self._sign_add_vote(canonical.PREVOTE_TYPE, b"",
                                PartSetHeader())
            return

        # POLRound >= 0
        pv = rs.votes.prevotes(rs.proposal.pol_round)
        block_id, ok = (pv.two_thirds_majority() if pv is not None
                        else (BlockID(), False))
        ok = ok and not block_id.is_nil()
        if ok and block_hash == block_id.hash and \
                rs.proposal.pol_round < rs.round:
            if rs.locked_round < rs.proposal.pol_round:
                await self._sign_add_vote(canonical.PREVOTE_TYPE, block_hash,
                                    psh)
                return
            if rs.locked_block is not None and \
                    block_hash == rs.locked_block.hash():
                await self._sign_add_vote(canonical.PREVOTE_TYPE, block_hash,
                                    psh)
                return
            if rs.locked_round == rs.proposal.pol_round:
                await self._sign_add_vote(canonical.PREVOTE_TYPE, block_hash,
                                    psh)
                return
        await self._sign_add_vote(canonical.PREVOTE_TYPE, b"",
                            PartSetHeader())

    async def _enter_prevote_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or \
                (rs.round == round_ and rs.step >= STEP_PREVOTE_WAIT):
            return
        if not rs.votes.prevotes(round_).has_two_thirds_any():
            raise ConsensusError(
                "entering prevote wait without any +2/3 prevotes")
        rs.advance(round_, STEP_PREVOTE_WAIT)
        self._new_step()
        self._schedule_timeout(self._vote_wait_timeout_ns(round_),
                               height, round_, STEP_PREVOTE_WAIT)

    # ==================================================================
    # step: Precommit

    async def _enter_precommit(self, height: int, round_: int) -> None:
        """Reference: enterPrecommit (:1609)."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or \
                (rs.round == round_ and rs.step >= STEP_PRECOMMIT):
            return

        def done() -> None:
            rs.advance(round_, STEP_PRECOMMIT)
            self._new_step()

        block_id, ok = rs.votes.prevotes(round_).two_thirds_majority()
        if not ok:
            await self._sign_add_vote(canonical.PRECOMMIT_TYPE, b"",
                                PartSetHeader())
            done()
            return

        self.event_bus.publish_polka(rs.event_summary())

        if block_id.is_nil():
            await self._sign_add_vote(canonical.PRECOMMIT_TYPE, b"",
                                PartSetHeader())
            done()
            return

        # +2/3 prevoted a block
        if rs.locked_block is not None and \
                rs.locked_block.hash() == block_id.hash:
            rs.relock(round_)
            self.event_bus.publish_relock(rs.event_summary())
            await self._sign_add_vote(canonical.PRECOMMIT_TYPE, block_id.hash,
                                block_id.part_set_header,
                                block=rs.locked_block)
            done()
            return

        if rs.proposal_block is not None and \
                rs.proposal_block.hash() == block_id.hash:
            # pipeline barrier: validating a block we never prevoted
            # (we may be locking straight off a polka) needs the
            # applied previous height
            await self._sync_pipeline()
            try:
                self.block_exec.validate_block(self.sm_state,
                                               rs.proposal_block)
            except BlockValidationError as e:
                raise ConsensusError(
                    f"+2/3 prevoted for an invalid block: {e}") from e
            rs.lock(round_, rs.proposal_block, rs.proposal_block_parts)
            self.event_bus.publish_lock(rs.event_summary())
            await self._sign_add_vote(canonical.PRECOMMIT_TYPE, block_id.hash,
                                block_id.part_set_header,
                                block=rs.proposal_block)
            done()
            return

        # polka for a block we don't have: fetch it, precommit nil
        if rs.proposal_block_parts is None or \
                not rs.proposal_block_parts.has_header(
                    block_id.part_set_header):
            rs.reset_proposal_parts(block_id.part_set_header)
        await self._sign_add_vote(canonical.PRECOMMIT_TYPE, b"",
                            PartSetHeader())
        done()

    async def _enter_precommit_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or \
                (rs.round == round_ and rs.triggered_timeout_precommit):
            return
        if not rs.votes.precommits(round_).has_two_thirds_any():
            raise ConsensusError(
                "entering precommit wait without any +2/3 precommits")
        rs.mark_timeout_precommit(round_)
        self._new_step()
        self._schedule_timeout(self._vote_wait_timeout_ns(round_),
                               height, round_, STEP_PRECOMMIT_WAIT)

    # ==================================================================
    # step: Commit

    async def _enter_commit(self, height: int, commit_round: int) -> None:
        """Reference: enterCommit (:1743)."""
        rs = self.rs
        if rs.height != height or rs.step >= STEP_COMMIT:
            return

        block_id, ok = rs.votes.precommits(commit_round) \
            .two_thirds_majority()
        if not ok or block_id.is_nil():
            raise ConsensusError("enterCommit expects +2/3 precommits")

        rs.enter_commit(commit_round, Timestamp.now())
        self._new_step()

        if rs.locked_block is not None and \
                rs.locked_block.hash() == block_id.hash:
            rs.adopt_block(rs.locked_block, rs.locked_block_parts)

        if rs.proposal_block is None or \
                rs.proposal_block.hash() != block_id.hash:
            if rs.proposal_block_parts is None or \
                    not rs.proposal_block_parts.has_header(
                        block_id.part_set_header):
                rs.reset_proposal_parts(block_id.part_set_header)
                self.event_bus.publish_valid_block(rs.event_summary())
                # tell peers which parts we ACTUALLY hold (reference:
                # the reactor broadcasts NewValidBlockMessage on
                # EventValidBlock).  Without this, a part that was
                # queued-but-lost before we entered commit is never
                # re-sent — the sender's bookkeeping says delivered —
                # and this node wedges in the commit step forever.
                self._broadcast(("valid_block",))

        await self._try_finalize_commit(height)

    async def _try_finalize_commit(self, height: int) -> None:
        rs = self.rs
        if rs.height != height:
            raise ConsensusError("tryFinalizeCommit height mismatch")
        block_id, ok = rs.votes.precommits(rs.commit_round) \
            .two_thirds_majority()
        if not ok or block_id.is_nil():
            return
        if rs.proposal_block is None or \
                rs.proposal_block.hash() != block_id.hash:
            return
        await self._finalize_commit(height)

    async def _finalize_commit(self, height: int) -> None:
        """Reference: finalizeCommit (:1834), split for the commit
        pipeline (docs/pipeline.md) into

          decide  — validate, save block + seen commit, fsync the WAL
                    EndHeight barrier (synchronous, this method);
          execute — FinalizeBlock/save-responses/app-Commit/mempool
                    update (supervised background task when
                    ``consensus.pipeline_commit``; inline otherwise);
          advance — updateToState + schedule round 0.  Pipelined mode
                    advances on a *provisional* next state so H+1's
                    propose/gossip/vote tally overlap H's execution;
                    the barrier (``_sync_pipeline``) installs the real
                    post-apply state before anything reads it.
        """
        # pipeline depth is 1: H-1's execute/commit must have fully
        # landed before H's begins (also orders the mempool update
        # hand-offs)
        await self._sync_pipeline()
        rs = self.rs
        if rs.height != height or rs.step != STEP_COMMIT:
            return
        block_id, ok = rs.votes.precommits(rs.commit_round) \
            .two_thirds_majority()
        block, block_parts = rs.proposal_block, rs.proposal_block_parts
        if not ok:
            raise ConsensusError("cannot finalize; no 2/3 majority")
        if not block_parts.has_header(block_id.part_set_header):
            raise ConsensusError("proposal parts header != commit header")
        if block.hash() != block_id.hash:
            raise ConsensusError("proposal block != commit hash")
        with tracing.span(tracing.CONSENSUS, "validate_block",
                          height=height):
            self.block_exec.validate_block(self.sm_state, block)

        self.logger.info("Finalizing commit of block",
                         height=height,
                         hash=block.hash().hex().upper()[:12],
                         num_txs=len(block.data.txs))

        fail.fail()    # crash point: before block save (state.go:1872)

        with tracing.span(tracing.CONSENSUS, "save_block",
                          height=height):
            if self.block_store.height < block.header.height:
                precommits = rs.votes.precommits(rs.commit_round)
                seen_ext = precommits.make_extended_commit(
                    self.sm_state.consensus_params.feature
                    .vote_extensions_enable_height)
                if self.sm_state.consensus_params.feature \
                        .vote_extensions_enabled(block.header.height):
                    self.block_store.save_block_with_extended_commit(
                        block, block_parts, seen_ext)
                else:
                    seen = seen_ext.to_commit()
                    # a height decided by an injected/restored
                    # aggregate (catchup) may hold sub-quorum live
                    # votes: persist the VERIFIED aggregate instead,
                    # or restart reconstruction would restore a
                    # majority-less vote set that cannot re-propose
                    agg_seen = precommits.stored_aggregate_commit
                    if agg_seen is not None and \
                            not precommits \
                            .has_two_thirds_votes_for_maj23():
                        seen = agg_seen
                    self.block_store.save_block(block, block_parts,
                                                seen)

        fail.fail()    # crash point: block saved, WAL barrier not yet
                       # written (state.go:1889)

        # fsync'd end-of-height barrier BEFORE ApplyBlock: on crash,
        # replay/handshake re-applies the block.  In pipelined mode
        # every H+1 message the receive routine processes from here on
        # lands in the WAL after this record, so catchup replay sees
        # the same prefix the serial path would have written.
        self.wal.write_end_height(height)

        fail.fail()    # crash point: barrier written, block not applied
                       # (state.go:1911)

        self.metrics.record_commit(block, rs.last_validators,
                                   rs.validators,
                                   block_size=block_parts.byte_size,
                                   commit_round=rs.commit_round)
        state_copy = self.sm_state.copy()
        bid = BlockID(hash=block.hash(),
                      part_set_header=block_parts.header())
        if getattr(self.config, "pipeline_commit", False) and \
                not self.replay_mode:
            self._begin_pipelined_apply(height, bid, block,
                                        block_parts, state_copy,
                                        rs.commit_round)
            next_state = provisional_next_state(self.sm_state, bid,
                                                block)
        else:
            with tracing.span(tracing.CONSENSUS, "apply_block",
                              height=height,
                              num_txs=len(block.data.txs)):
                state_copy = await self.block_exec \
                    .apply_verified_block(state_copy, bid, block,
                                          block.header.height)

            fail.fail()    # crash point: applied, consensus state not
                           # yet advanced (state.go:1933)

            tracing.instant(tracing.CONSENSUS, "commit", height=height,
                            num_txs=len(block.data.txs),
                            round=rs.commit_round,
                            block_bytes=block_parts.byte_size)
            next_state = state_copy
        self.update_to_state(next_state)
        if self.priv_validator is not None:
            self.priv_validator_pub_key = \
                self.priv_validator.get_pub_key()
        self._schedule_round0()

    # ------------------------------------------------------------------
    # commit pipeline (docs/pipeline.md)

    def _begin_pipelined_apply(self, height: int, bid: BlockID, block,
                               block_parts, state_copy,
                               commit_round: int) -> None:
        """Launch the supervised background execute/commit for the
        decided block.  The task never touches RoundState or
        ``sm_state`` — it resolves the barrier future and the receive
        routine (the single writer) installs the result."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        p = _PipelinedCommit(height, fut, time.monotonic())

        async def _apply_task() -> None:
            try:
                with tracing.span(tracing.CONSENSUS, "apply_block",
                                  height=height,
                                  num_txs=len(block.data.txs)):
                    new_state = await self.block_exec \
                        .apply_verified_block(state_copy, bid, block,
                                              block.header.height)
                fail.fail()    # crash point: applied, consensus state
                               # not yet advanced (state.go:1933)
                tracing.instant(tracing.CONSENSUS, "commit",
                                height=height,
                                num_txs=len(block.data.txs),
                                round=commit_round,
                                block_bytes=block_parts.byte_size)
                self.metrics.pipeline_apply_seconds.observe(
                    time.monotonic() - p.t0)
            except asyncio.CancelledError:
                if not fut.done():
                    fut.cancel()
                raise
            except Exception as e:
                # surfaced to every barrier waiter; the receive
                # routine crashes loudly on its next sync, exactly
                # like a serial apply failure
                if not fut.done():
                    fut.set_exception(e)
                raise
            if not fut.done():
                fut.set_result(new_state)

        from ..libs.supervisor import RestartPolicy
        # no restarts: re-running FinalizeBlock after a partial apply
        # would double-execute the block — crash recovery is the WAL
        # barrier + handshake's job, not the supervisor's
        p.task = self.supervisor.spawn(
            _apply_task, name=f"pipeline_apply:{height}",
            kind="consensus_pipeline_apply",
            policy=RestartPolicy(max_restarts=0, window_s=1.0,
                                 backoff_base_s=0.01,
                                 backoff_max_s=0.01))
        self._pipeline = p
        tracing.instant(tracing.CONSENSUS, "pipeline_advance",
                        height=height)

    async def _sync_pipeline(self) -> None:
        """The pipeline barrier: wait for the in-flight execute/commit
        and install the real post-apply state over the provisional
        one.  Called from the receive routine only (the single
        writer), at every step that reads the applied state: our own
        proposal construction, prevote validation / ProcessProposal,
        vote-extension verification, and the next height's finalize."""
        p = self._pipeline
        if p is None:
            return
        t0 = time.monotonic()
        # on failure (or cancellation of this waiter) the pipeline
        # handle stays latched: an apply failure must poison every
        # later barrier too — clearing it here would let a
        # supervisor-restarted receive routine carry on at H+1 with
        # the provisional (pre-apply) state, which is unsound — and a
        # cancelled stop() still needs the handle to drain/abort the
        # background task
        new_state = await p.future
        self._pipeline = None
        self.metrics.pipeline_barrier_wait_seconds.observe(
            time.monotonic() - t0)
        tracing.record_span(tracing.CONSENSUS, "barrier_wait",
                            start_ns=int(t0 * 1e9),
                            height=p.height)
        self._reconcile_applied_state(p.height, new_state)

    def _reconcile_applied_state(self, applied_height: int,
                                 new_state: SMState) -> None:
        """Swap the provisional H+1 state for the real post-apply one.

        The provisional state already fixed the H+1 validator set and
        vote-extension schedule (validator updates from H land at
        H+2), so normally this is a plain assignment.  The one thing a
        committed block CAN change out from under the provisional
        snapshot is a consensus-param update taking effect at H+1 —
        then the height vote set was built under the wrong rules and
        is rebuilt; peers re-gossip any votes already tallied."""
        rs = self.rs
        if rs.height != applied_height + 1:
            raise ConsensusError(
                f"pipeline reconcile: round state at {rs.height}, "
                f"applied height {applied_height}")
        prov = self.sm_state
        prov_ext = prov.consensus_params.feature \
            .vote_extensions_enabled(rs.height)
        real_ext = new_state.consensus_params.feature \
            .vote_extensions_enabled(rs.height)
        prov_vals = prov.validators.hash()
        real_vals = new_state.validators.hash()
        self.sm_state = new_state
        if prov_ext != real_ext or prov_vals != real_vals:
            self.logger.info(
                "pipeline reconcile: consensus params changed at the "
                "pipelined height; rebuilding height vote set",
                height=rs.height, ext_changed=prov_ext != real_ext)
            vals = new_state.validators
            if rs.round > 0:
                # preserve the proposer rotation _enter_new_round
                # applied for the current round — installing round-0
                # priorities here would make this node disagree with
                # its peers about the round's proposer
                vals = vals.copy()
                vals.increment_proposer_priority(rs.round)
            rs.rebuild_votes(
                vals,
                HeightVoteSet(new_state.chain_id, rs.height, vals,
                              extensions_enabled=real_ext))

    # ==================================================================
    # votes

    async def _try_add_vote(self, vote: Vote, peer_id: str) -> bool:
        """Reference: tryAddVote (:2253) — turns conflicting votes into
        evidence."""
        try:
            return await self._add_vote(vote, peer_id)
        except ConflictingVoteError as e:
            if self.priv_validator_pub_key is not None and \
                    vote.validator_address == \
                    self.priv_validator_pub_key.address():
                self.logger.error(
                    "found conflicting vote from ourselves; "
                    "did you unsafe_reset a validator?",
                    height=vote.height, round=vote.round)
                return False
            if self.block_exec.evpool is not None and \
                    hasattr(self.block_exec.evpool,
                            "report_conflicting_votes"):
                self.block_exec.evpool.report_conflicting_votes(
                    e.vote_a, e.vote_b)
            self.logger.info("found and sent conflicting vote to evpool",
                             height=vote.height)
            return False

    def aggregate_commit_relevant(self, agg, peer_id: str = "") \
            -> bool:
        """Cheap (no-crypto) admission screen for the reactor: False
        when an incoming aggregate catchup commit provably cannot be
        ingested — wrong height, already at/past commit, feature off,
        or a known forger peer.  Shedding these BEFORE the input
        queue keeps the queue (the backpressure buffer while a
        verdict barrier is outstanding) for messages that can still
        matter; the authoritative re-check in
        ``_try_add_aggregate_commit`` is unchanged."""
        rs = self.rs
        if not isinstance(agg, AggregateCommit):
            return False
        if self.sm_state is None or \
                not self.sm_state.consensus_params.feature \
                .aggregate_commits_enabled(agg.height):
            return False
        if agg.height != rs.height or rs.step >= STEP_COMMIT:
            return False
        if peer_id and peer_id in self._agg_commit_forgers:
            return False
        return True

    async def _try_add_aggregate_commit(self, agg,
                                        peer_id: str) -> bool:
        """Catchup ingestion on an aggregate-commit chain: a verified
        AggregateCommit for the CURRENT height is this height's +2/3
        precommit evidence — individual votes cannot be reconstructed
        from peers' stores, so the aggregate stands in for them
        (docs/aggregate_commits.md).  The block parts still arrive via
        normal data gossip; entering commit here lets the existing
        parts-complete path finalize."""
        from ..types import validation as types_validation
        rs = self.rs
        # same admission rules the reactor screens with (ONE source
        # of truth) — re-checked here because the reactor's verdict
        # aged in the input queue, and the forger check bounds the
        # attack at one wasted verification per peer identity (the
        # pairing costs ~10 ms at 10k validators; honest peers never
        # send an invalid aggregate — they verified before storing)
        if not self.aggregate_commit_relevant(agg, peer_id):
            return False
        try:
            # off the event loop (crypto/pipeline.py seam): the
            # pairing runs GIL-free on the staging worker while the
            # loop keeps serving p2p/RPC.  RoundState stays
            # consistent across the await — this receive routine is
            # its only writer and it is parked right here.
            from ..crypto import pipeline as _pipeline
            await _pipeline.run_off_loop(
                types_validation.verify_commit,
                self.sm_state.chain_id, rs.validators, agg.block_id,
                agg.height, agg)
        except types_validation.VerificationError as e:
            self.logger.error("invalid aggregate catchup commit",
                              err=str(e), peer=peer_id)
            if peer_id:
                forgers = self._agg_commit_forgers
                forgers[peer_id] = True
                if len(forgers) > self._agg_commit_forgers_max:
                    del forgers[next(iter(forgers))]
            return False
        precommits = rs.votes.precommits(agg.round)
        if precommits is None:
            # the chain decided at a round we never reached locally
            rs.votes.ensure_round_tracked(agg.round)
            precommits = rs.votes.precommits(agg.round)
        if precommits is None or \
                not precommits.inject_aggregate_majority(agg):
            return False
        await self._enter_commit(rs.height, agg.round)
        return True

    async def _add_vote(self, vote: Vote, peer_id: str) -> bool:
        """Reference: addVote (:2299)."""
        rs = self.rs

        # precommit for the previous height (arrives during commit wait)
        if vote.height + 1 == rs.height and \
                vote.type == canonical.PRECOMMIT_TYPE:
            if rs.step != STEP_NEW_HEIGHT:
                return False
            if rs.last_commit is None:
                return False
            added = rs.last_commit.add_vote(vote)
            if not added:
                return False
            self.event_bus.publish_vote(vote)
            skip = (self.sm_state.next_block_delay_ns == 0 and
                    self.config.timeout_commit_ns == 0)
            if skip and rs.last_commit.has_all():
                await self._enter_new_round(rs.height, 0)
            return added

        if vote.height != rs.height:
            return False

        ext_enabled = self.sm_state.consensus_params.feature \
            .vote_extensions_enabled(vote.height)
        if ext_enabled:
            my_addr = self.priv_validator_pub_key.address() \
                if self.priv_validator_pub_key else b""
            if vote.type == canonical.PRECOMMIT_TYPE and \
                    not vote.block_id.is_nil() and \
                    vote.validator_address != my_addr:
                _, val = self.sm_state.validators.get_by_index(
                    vote.validator_index)
                if val is None:
                    raise VoteSetError(
                        f"validator index {vote.validator_index} out of "
                        f"bounds")
                vote.verify_extension(self.sm_state.chain_id,
                                      val.pub_key)
                # pipeline barrier: the app must be past the previous
                # height's Commit before VerifyVoteExtension(H)
                await self._sync_pipeline()
                ok = await self.block_exec.verify_vote_extension(vote)
                self.metrics.vote_extension_receive_count.with_labels(
                    "accepted" if ok else "rejected").add()
                if not ok:
                    raise VoteSetError("invalid vote extension")
        elif vote.extension or vote.extension_signature or \
                vote.non_rp_extension or vote.non_rp_extension_signature:
            raise VoteSetError(
                "received vote with extension while extensions are "
                "disabled")

        vt_label = "prevote" \
            if vote.type == canonical.PREVOTE_TYPE else "precommit"
        if vote.round < rs.round:
            self.metrics.late_votes.with_labels(vt_label).add()
        height = rs.height
        added = rs.votes.add_vote(vote, peer_id)
        if not added:
            self.metrics.duplicate_vote.add()
            return False
        vs = rs.votes.prevotes(vote.round) \
            if vote.type == canonical.PREVOTE_TYPE \
            else rs.votes.precommits(vote.round)
        total_power = rs.validators.total_voting_power()
        if vs is not None and total_power > 0:
            self.metrics.round_voting_power_percent.with_labels(
                vt_label).set(vs.sum / total_power)
        self.event_bus.publish_vote(vote)
        self._broadcast(("has_vote", vote))

        if vote.type == canonical.PREVOTE_TYPE:
            prevotes = rs.votes.prevotes(vote.round)
            block_id, ok = prevotes.two_thirds_majority()
            if ok and rs.proposal is not None:
                proposer = rs.validators.get_proposer() \
                    .address.hex().upper()
                delay_s = vote.timestamp.sub(
                    rs.proposal.timestamp) / 1e9
                self.metrics.quorum_prevote_delay.with_labels(
                    proposer).set(delay_s)
                if (height, vote.round) > self._quorum_delay_observed:
                    self._quorum_delay_observed = (height, vote.round)
                    self.metrics.quorum_prevote_delay_seconds.observe(
                        max(0.0, delay_s))
                    if self._adaptive is not None and \
                            not self.replay_mode:
                        self._adaptive.observe(delay_s)
                if prevotes.has_all():
                    self.metrics.full_prevote_delay.with_labels(
                        proposer).set(delay_s)
                    self.metrics.full_prevote_delay_seconds.observe(
                        max(0.0, delay_s))
            if ok and not block_id.is_nil():
                # update valid block
                if rs.valid_round < vote.round and \
                        vote.round == rs.round:
                    if rs.proposal_block is not None and \
                            rs.proposal_block.hash() == block_id.hash:
                        rs.set_valid(vote.round, rs.proposal_block,
                                     rs.proposal_block_parts)
                    else:
                        rs.drop_proposal_block()
                    if rs.proposal_block_parts is None or \
                            not rs.proposal_block_parts.has_header(
                                block_id.part_set_header):
                        rs.reset_proposal_parts(
                            block_id.part_set_header)
                    self.event_bus.publish_valid_block(
                        rs.event_summary())
                    # reference reactor: EventValidBlock ->
                    # NewValidBlockMessage broadcast (peers learn our
                    # real part bitmap and (re)send what we miss)
                    self._broadcast(("valid_block",))
            if rs.round < vote.round and prevotes.has_two_thirds_any():
                await self._enter_new_round(height, vote.round)
            elif rs.round == vote.round and rs.step >= STEP_PREVOTE:
                block_id, ok = prevotes.two_thirds_majority()
                if ok and (self._is_proposal_complete() or
                           block_id.is_nil()):
                    await self._enter_precommit(height, vote.round)
                elif prevotes.has_two_thirds_any():
                    await self._enter_prevote_wait(height, vote.round)
            elif rs.proposal is not None and \
                    0 <= rs.proposal.pol_round == vote.round:
                if self._is_proposal_complete():
                    await self._enter_prevote(height, rs.round)

        elif vote.type == canonical.PRECOMMIT_TYPE:
            precommits = rs.votes.precommits(vote.round)
            block_id, ok = precommits.two_thirds_majority()
            if ok:
                await self._enter_new_round(height, vote.round)
                await self._enter_precommit(height, vote.round)
                if not block_id.is_nil():
                    await self._enter_commit(height, vote.round)
                    skip = (self.sm_state.next_block_delay_ns == 0 and
                            self.config.timeout_commit_ns == 0)
                    if skip and precommits.has_all():
                        await self._enter_new_round(rs.height, 0)
                else:
                    await self._enter_precommit_wait(height, vote.round)
            elif rs.round <= vote.round and \
                    precommits.has_two_thirds_any():
                await self._enter_new_round(height, vote.round)
                await self._enter_precommit_wait(height, vote.round)
        else:
            raise ConsensusError(f"unexpected vote type {vote.type}")
        return True

    # ==================================================================
    # vote signing

    def _vote_time(self, height: int, msg_type: int = 0) -> Timestamp:
        """Reference: voteTime (:2578) — BFT time floor unless PBTS.

        Aggregate-commit mode zeroes the PRECOMMIT timestamp: every
        for-block precommit must sign the one canonical zero-timestamp
        message so the BLS signatures sum into a single aggregate
        (docs/aggregate_commits.md; params validation guarantees PBTS,
        so no consumer needs per-vote timestamps)."""
        if msg_type == canonical.PRECOMMIT_TYPE and \
                self.sm_state.consensus_params.feature \
                .aggregate_commits_enabled(height):
            return Timestamp.zero()
        if self._pbts_enabled(height):
            return Timestamp.now()
        now = Timestamp.now()
        min_vote_time = now
        rs = self.rs
        if rs.locked_block is not None:
            min_vote_time = rs.locked_block.header.time.add_ns(
                _TIME_IOTA_NS)
        elif rs.proposal_block is not None:
            min_vote_time = rs.proposal_block.header.time.add_ns(
                _TIME_IOTA_NS)
        return now if now.unix_ns() > min_vote_time.unix_ns() \
            else min_vote_time

    def _pbts_enabled(self, height: int) -> bool:
        return self.sm_state.consensus_params.feature.pbts_enabled(
            height)

    async def _pv_sign_vote(self, vote: Vote, sign_ext: bool) -> None:
        """One seam for local (sync) and remote (async) signers."""
        pv = self.priv_validator
        if hasattr(pv, "sign_vote_async"):
            await pv.sign_vote_async(self.sm_state.chain_id, vote,
                                     sign_ext)
        else:
            pv.sign_vote(self.sm_state.chain_id, vote,
                         sign_extension=sign_ext)

    async def _pv_sign_proposal(self, proposal: Proposal) -> None:
        pv = self.priv_validator
        if hasattr(pv, "sign_proposal_async"):
            await pv.sign_proposal_async(self.sm_state.chain_id,
                                         proposal)
        else:
            pv.sign_proposal(self.sm_state.chain_id, proposal)

    async def _sign_vote(self, msg_type: int, hash_: bytes,
                   psh: PartSetHeader,
                   block: Optional[Block]) -> Optional[Vote]:
        """Reference: signVote (:2526)."""
        self.wal.flush_and_sync()
        rs = self.rs
        addr = self.priv_validator_pub_key.address()
        val_idx, _ = rs.validators.get_by_address(addr)
        vote = Vote(
            type=msg_type,
            height=rs.height,
            round=rs.round,
            block_id=BlockID(hash=hash_, part_set_header=psh),
            timestamp=self._vote_time(rs.height, msg_type),
            validator_address=addr,
            validator_index=val_idx,
        )
        ext_enabled = self.sm_state.consensus_params.feature \
            .vote_extensions_enabled(vote.height)
        sign_ext = False
        if msg_type == canonical.PRECOMMIT_TYPE and \
                not vote.block_id.is_nil():
            if ext_enabled:
                if block is None:
                    raise ConsensusError(
                        "need block to extend a non-nil precommit")
                ext, non_rp_ext = await self.block_exec.extend_vote(
                    vote, block, self.sm_state)
                vote.extension = ext
                vote.non_rp_extension = non_rp_ext
                sign_ext = True
        try:
            await self._pv_sign_vote(vote, sign_ext)
        except Exception as e:
            self.logger.error("failed signing vote", err=str(e))
            return None
        return vote

    async def _sign_add_vote(self, msg_type: int, hash_: bytes,
                       psh: PartSetHeader,
                       block: Optional[Block] = None) -> None:
        """Reference: signAddVote (:2605)."""
        if self.priv_validator is None or \
                self.priv_validator_pub_key is None:
            return
        if not self.rs.validators.has_address(
                self.priv_validator_pub_key.address()):
            return
        vote = await self._sign_vote(msg_type, hash_, psh, block)
        if vote is None:
            return
        self.metrics.validator_last_signed_height.set(self.rs.height)
        self.send_internal(VoteMessage(vote))
        self._broadcast(VoteMessage(vote))

    # ==================================================================
    def _broadcast(self, msg) -> None:
        for hook in self.broadcast_hooks:
            try:
                hook(msg)
            except Exception:
                self.logger.error("broadcast hook failed", exc_info=True)

