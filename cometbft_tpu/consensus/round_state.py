"""RoundState: the public snapshot of the consensus internal state.

Reference: internal/consensus/types/round_state.go:67 and the
RoundStepType enum.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..types.block import Block
from ..types.block_id import BlockID
from ..types.part_set import PartSet
from ..types.proposal import Proposal
from ..types.timestamp import Timestamp
from ..types.validator_set import ValidatorSet

# RoundStepType (reference: round_state.go:12-40)
STEP_NEW_HEIGHT = 1
STEP_NEW_ROUND = 2
STEP_PROPOSE = 3
STEP_PREVOTE = 4
STEP_PREVOTE_WAIT = 5
STEP_PRECOMMIT = 6
STEP_PRECOMMIT_WAIT = 7
STEP_COMMIT = 8

STEP_NAMES = {
    STEP_NEW_HEIGHT: "NewHeight",
    STEP_NEW_ROUND: "NewRound",
    STEP_PROPOSE: "Propose",
    STEP_PREVOTE: "Prevote",
    STEP_PREVOTE_WAIT: "PrevoteWait",
    STEP_PRECOMMIT: "Precommit",
    STEP_PRECOMMIT_WAIT: "PrecommitWait",
    STEP_COMMIT: "Commit",
}


@dataclass
class RoundState:
    height: int = 0
    round: int = 0
    step: int = STEP_NEW_HEIGHT
    start_time: Timestamp = field(default_factory=Timestamp.zero)
    commit_time: Timestamp = field(default_factory=Timestamp.zero)

    validators: Optional[ValidatorSet] = None
    proposal: Optional[Proposal] = None
    proposal_receive_time: Timestamp = field(
        default_factory=Timestamp.zero)
    proposal_block: Optional[Block] = None
    proposal_block_parts: Optional[PartSet] = None

    locked_round: int = -1
    locked_block: Optional[Block] = None
    locked_block_parts: Optional[PartSet] = None

    # Last known round with POL for non-nil valid block
    valid_round: int = -1
    valid_block: Optional[Block] = None
    valid_block_parts: Optional[PartSet] = None

    votes: Optional[object] = None    # HeightVoteSet
    commit_round: int = -1
    last_commit: Optional[object] = None  # VoteSet of last height precommits
    last_validators: Optional[ValidatorSet] = None
    triggered_timeout_precommit: bool = False

    def step_name(self) -> str:
        return STEP_NAMES.get(self.step, "Unknown")

    def proposal_block_id(self) -> Optional[BlockID]:
        if self.proposal_block is None or \
                self.proposal_block_parts is None:
            return None
        return BlockID(hash=self.proposal_block.hash(),
                       part_set_header=self.proposal_block_parts.header())

    def event_summary(self) -> dict:
        return {
            "height": self.height, "round": self.round,
            "step": self.step_name(),
        }

    def __str__(self) -> str:
        return (f"RoundState{{{self.height}/{self.round}/"
                f"{self.step_name()}}}")


@dataclass
class TimeoutInfo:
    duration_ns: int
    height: int
    round: int
    step: int

    def __str__(self) -> str:
        return (f"{self.duration_ns / 1e6:.0f}ms@{self.height}/"
                f"{self.round}/{STEP_NAMES.get(self.step)}")
