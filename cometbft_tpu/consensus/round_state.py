"""RoundState: the public snapshot of the consensus internal state.

Reference: internal/consensus/types/round_state.go:67 and the
RoundStepType enum.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..types.block import Block
from ..types.block_id import BlockID
from ..types.part_set import PartSet
from ..types.proposal import Proposal
from ..types.timestamp import Timestamp
from ..types.validator_set import ValidatorSet

# RoundStepType (reference: round_state.go:12-40)
STEP_NEW_HEIGHT = 1
STEP_NEW_ROUND = 2
STEP_PROPOSE = 3
STEP_PREVOTE = 4
STEP_PREVOTE_WAIT = 5
STEP_PRECOMMIT = 6
STEP_PRECOMMIT_WAIT = 7
STEP_COMMIT = 8

STEP_NAMES = {
    STEP_NEW_HEIGHT: "NewHeight",
    STEP_NEW_ROUND: "NewRound",
    STEP_PROPOSE: "Propose",
    STEP_PREVOTE: "Prevote",
    STEP_PREVOTE_WAIT: "PrevoteWait",
    STEP_PRECOMMIT: "Precommit",
    STEP_PRECOMMIT_WAIT: "PrecommitWait",
    STEP_COMMIT: "Commit",
}


@dataclass
class RoundState:
    height: int = 0
    round: int = 0
    step: int = STEP_NEW_HEIGHT
    start_time: Timestamp = field(default_factory=Timestamp.zero)
    commit_time: Timestamp = field(default_factory=Timestamp.zero)

    validators: Optional[ValidatorSet] = None
    proposal: Optional[Proposal] = None
    proposal_receive_time: Timestamp = field(
        default_factory=Timestamp.zero)
    proposal_block: Optional[Block] = None
    proposal_block_parts: Optional[PartSet] = None

    locked_round: int = -1
    locked_block: Optional[Block] = None
    locked_block_parts: Optional[PartSet] = None

    # Last known round with POL for non-nil valid block
    valid_round: int = -1
    valid_block: Optional[Block] = None
    valid_block_parts: Optional[PartSet] = None

    votes: Optional[object] = None    # HeightVoteSet
    commit_round: int = -1
    last_commit: Optional[object] = None  # VoteSet of last height precommits
    last_validators: Optional[ValidatorSet] = None
    triggered_timeout_precommit: bool = False

    # ------------------------------------------------------------------
    # state-transition seam (single-writer discipline, ROADMAP item 4)
    #
    # Every RoundState mutation the consensus machine performs after an
    # await point goes through one of these methods instead of ad-hoc
    # attribute stores.  Each transition re-validates its own
    # preconditions at the moment of the write — the re-check the
    # bftlint await-atomicity rule demands at a cross-await store —
    # so a decision computed before a suspension can never be applied
    # to a round the machine has already left.  With the commit
    # pipeline two heights can be in flight; the receive routine stays
    # the only caller, and these methods make that ownership (and its
    # monotonicity) structural rather than an informal argument.

    class TransitionError(Exception):
        """A transition that would move the round state backwards."""

    def advance(self, round_: int, step: int) -> None:
        """Advance (round, step) within the current height.

        Monotonic: refuses to move backwards — the re-validation at
        the store site that the informal single-writer argument used
        to stand in for."""
        if (round_, step) < (self.round, self.step):
            raise RoundState.TransitionError(
                f"advance({round_}/{STEP_NAMES.get(step)}) would move "
                f"{self} backwards")
        self.round = round_
        self.step = step

    def begin_round(self, round_: int, validators) -> None:
        """enterNewRound mutations: bump the round, install the
        round's proposer-rotated validator set, clear the previous
        round's proposal (rounds > 0), and track the next round's
        votes."""
        if round_ < self.round:
            raise RoundState.TransitionError(
                f"begin_round({round_}) would move {self} backwards")
        self.round = round_
        self.step = STEP_NEW_ROUND
        self.validators = validators
        if round_ != 0:
            self.proposal = None
            self.proposal_receive_time = Timestamp.zero()
            self.proposal_block = None
            self.proposal_block_parts = None
        self.votes.set_round(round_ + 1)   # track next round too
        self.triggered_timeout_precommit = False

    def lock(self, round_: int, block, parts) -> None:
        """Lock on a block (enterPrecommit +2/3-prevotes branch)."""
        if round_ < self.locked_round:
            raise RoundState.TransitionError(
                f"lock({round_}) below locked_round "
                f"{self.locked_round}")
        self.locked_round = round_
        self.locked_block = block
        self.locked_block_parts = parts

    def relock(self, round_: int) -> None:
        """Re-lock the already-locked block at a later round."""
        if self.locked_block is None or round_ < self.locked_round:
            raise RoundState.TransitionError(
                f"relock({round_}) without a valid earlier lock")
        self.locked_round = round_

    def set_valid(self, round_: int, block, parts) -> None:
        """Record the POL (valid) block for round_."""
        if round_ < self.valid_round:
            raise RoundState.TransitionError(
                f"set_valid({round_}) below valid_round "
                f"{self.valid_round}")
        self.valid_round = round_
        self.valid_block = block
        self.valid_block_parts = parts

    def reset_proposal_parts(self, psh) -> None:
        """Forget the (wrong or missing) proposal block and start
        collecting parts for the part-set header peers committed
        to."""
        self.proposal_block = None
        self.proposal_block_parts = PartSet(psh)

    def drop_proposal_block(self) -> None:
        """Forget an assembled proposal block (a quorum formed on a
        different one) while keeping the part collection state."""
        self.proposal_block = None

    def begin_height(self, height: int, start_time, validators,
                     votes, last_validators) -> None:
        """updateToState's reset: a fresh height at round 0 with every
        per-height field cleared."""
        self.height = height
        self.round = 0
        self.step = STEP_NEW_HEIGHT
        self.start_time = start_time
        self.validators = validators
        self.proposal = None
        self.proposal_receive_time = Timestamp.zero()
        self.proposal_block = None
        self.proposal_block_parts = None
        self.locked_round = -1
        self.locked_block = None
        self.locked_block_parts = None
        self.valid_round = -1
        self.valid_block = None
        self.valid_block_parts = None
        self.votes = votes
        self.commit_round = -1
        self.last_validators = last_validators
        self.triggered_timeout_precommit = False

    def adopt_block(self, block, parts) -> None:
        """Adopt a fully-known block (e.g. the locked block on commit
        entry) as the proposal block."""
        self.proposal_block = block
        self.proposal_block_parts = parts

    def set_last_commit(self, vote_set) -> None:
        """Install the previous height's precommits (updateToState /
        WAL-replay reconstruction).  None is legal only before the
        initial block; a VoteSet must actually hold a +2/3 majority —
        the property every later consumer (proposals, last_commit
        gossip) assumes."""
        if vote_set is not None and \
                hasattr(vote_set, "has_two_thirds_majority") and \
                not vote_set.has_two_thirds_majority():
            raise RoundState.TransitionError(
                "set_last_commit: vote set lacks a +2/3 majority")
        self.last_commit = vote_set

    def apply_proposal(self, proposal, recv_time) -> None:
        """Adopt the round's signed proposal (setProposal): at most
        once per round, and only for the CURRENT (height, round) —
        the re-check that a proposal validated before a suspension
        cannot land on a round the machine has already left.  Starts
        part collection when the part-set header isn't known yet."""
        if self.proposal is not None:
            raise RoundState.TransitionError(
                f"apply_proposal: {self} already has a proposal")
        if proposal.height != self.height or \
                proposal.round != self.round:
            raise RoundState.TransitionError(
                f"apply_proposal({proposal.height}/{proposal.round}) "
                f"does not match {self}")
        self.proposal = proposal
        self.proposal_receive_time = recv_time
        if self.proposal_block_parts is None:
            self.proposal_block_parts = PartSet(
                proposal.block_id.part_set_header)

    def complete_proposal_block(self, block) -> None:
        """Install the block assembled from the completed part set."""
        if self.proposal_block_parts is None or \
                not self.proposal_block_parts.is_complete():
            raise RoundState.TransitionError(
                "complete_proposal_block without a complete part set")
        self.proposal_block = block

    def mark_timeout_precommit(self, round_: int) -> None:
        """Record that the precommit-wait timeout was scheduled for
        round_ (enterPrecommitWait), exactly once per round."""
        if round_ < self.round or \
                (round_ == self.round and
                 self.triggered_timeout_precommit):
            raise RoundState.TransitionError(
                f"mark_timeout_precommit({round_}) already triggered "
                f"or behind {self}")
        self.triggered_timeout_precommit = True

    def rebuild_votes(self, validators, votes) -> None:
        """Pipeline reconcile: swap in the rebuilt validator set and
        height vote set after a pipelined apply landed with changed
        consensus params, keeping next-round vote tracking."""
        self.validators = validators
        self.votes = votes
        self.votes.set_round(self.round + 1)

    def enter_commit(self, commit_round: int, commit_time) -> None:
        """Enter the commit step for commit_round."""
        if self.step >= STEP_COMMIT:
            raise RoundState.TransitionError(
                f"enter_commit: {self} already committing")
        self.step = STEP_COMMIT
        self.commit_round = commit_round
        self.commit_time = commit_time

    def step_name(self) -> str:
        return STEP_NAMES.get(self.step, "Unknown")

    def proposal_block_id(self) -> Optional[BlockID]:
        if self.proposal_block is None or \
                self.proposal_block_parts is None:
            return None
        return BlockID(hash=self.proposal_block.hash(),
                       part_set_header=self.proposal_block_parts.header())

    def event_summary(self) -> dict:
        return {
            "height": self.height, "round": self.round,
            "step": self.step_name(),
        }

    def __str__(self) -> str:
        return (f"RoundState{{{self.height}/{self.round}/"
                f"{self.step_name()}}}")


@dataclass
class TimeoutInfo:
    duration_ns: int
    height: int
    round: int
    step: int

    def __str__(self) -> str:
        return (f"{self.duration_ns / 1e6:.0f}ms@{self.height}/"
                f"{self.round}/{STEP_NAMES.get(self.step)}")
