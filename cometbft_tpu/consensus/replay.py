"""Crash recovery: WAL replay + ABCI handshake.

Reference: internal/consensus/replay.go — catchupReplay (:97) re-feeds
WAL messages for the in-flight height; Handshaker (:214) reconciles
app height vs store height at boot and replays missing blocks into the
application.
"""
from __future__ import annotations

from typing import Optional

from ..abci import types as abci
from ..crypto import merkle
from ..libs.log import Logger, new_logger
from ..state.execution import (
    BlockExecutor, build_last_commit_info, update_state,
    validate_validator_updates,
)
from ..state.state import State as SMState
from ..state.store import Store
from ..types.block_id import BlockID
from ..types.genesis import GenesisDoc
from ..types.validator import Validator
from ..types.validator_set import ValidatorSet
from .messages import message_from_wal
from .round_state import TimeoutInfo
from .wal import WAL


class ReplayError(Exception):
    pass


class AppBlockHeightTooLowError(ReplayError):
    pass


class AppBlockHeightTooHighError(ReplayError):
    pass


async def exec_commit_block(proxy_app, block, state_store: Store,
                            initial_height: int,
                            syncing_to_height: int,
                            logger: Logger) -> bytes:
    """Execute + commit a block against the app WITHOUT mutating
    consensus state (reference: state/execution.go ExecCommitBlock)."""
    last_vals = None
    if block.header.height > initial_height:
        last_vals = state_store.load_validators(block.header.height - 1)
    commit_info = abci.CommitInfo()
    if last_vals is not None:
        commit_info = build_last_commit_info(block, last_vals,
                                             initial_height)
    resp = await proxy_app.finalize_block(abci.FinalizeBlockRequest(
        hash=block.hash(),
        next_validators_hash=block.header.next_validators_hash,
        proposer_address=block.header.proposer_address,
        height=block.header.height,
        time=block.header.time,
        decided_last_commit=commit_info,
        txs=list(block.data.txs),
        syncing_to_height=syncing_to_height,
    ))
    if len(block.data.txs) != len(resp.tx_results):
        raise ReplayError(
            "app returned wrong number of tx results during replay")
    await proxy_app.commit()
    return resp.app_hash


class _ReplayProxyApp:
    """Mock consensus connection that serves a saved
    FinalizeBlockResponse (reference: replay_stubs.go newMockProxyApp)."""

    def __init__(self, saved_response: abci.FinalizeBlockResponse):
        self._resp = saved_response

    async def finalize_block(self, req) -> abci.FinalizeBlockResponse:
        return self._resp

    async def commit(self) -> abci.CommitResponse:
        return abci.CommitResponse()

    async def prepare_proposal(self, req):
        raise ReplayError("unexpected PrepareProposal during replay")

    async def process_proposal(self, req):
        raise ReplayError("unexpected ProcessProposal during replay")


class Handshaker:
    """Reconcile app state with store state at boot.

    Reference: replay.go Handshaker (:214) / ReplayBlocks (:284)."""

    def __init__(self, state_store: Store, state: SMState, block_store,
                 gen_doc: GenesisDoc,
                 logger: Optional[Logger] = None):
        self.state_store = state_store
        self.initial_state = state
        self.block_store = block_store
        self.gen_doc = gen_doc
        self.logger = logger if logger is not None else \
            new_logger("handshaker")
        self.n_blocks = 0

    async def handshake(self, app_conns) -> bytes:
        """Info → ReplayBlocks; returns the reconciled app hash."""
        res = await app_conns.query.info(abci.InfoRequest(
            version="", block_version=0, p2p_version=0))
        app_height = res.last_block_height
        app_hash = res.last_block_app_hash
        if app_height < 0:
            raise ReplayError(
                f"got negative last block height {app_height}")
        self.logger.info("ABCI handshake", app_height=app_height,
                         app_hash=app_hash.hex().upper()[:12])
        app_hash = await self.replay_blocks(
            self.initial_state, app_hash, app_height, app_conns)
        self.logger.info("Completed ABCI handshake",
                         app_height=app_height, blocks=self.n_blocks)
        return app_hash

    async def replay_blocks(self, state: SMState, app_hash: bytes,
                            app_height: int, app_conns) -> bytes:
        """Reference: replay.go ReplayBlocks (:284)."""
        store_base = self.block_store.base
        store_height = self.block_store.height
        state_height = state.last_block_height
        self.logger.info("ABCI replay blocks", app_height=app_height,
                         store_height=store_height,
                         state_height=state_height)

        if app_height == 0:
            # genesis: send InitChain
            validators = [Validator.new(v.pub_key, v.power)
                          for v in self.gen_doc.validators]
            val_set = ValidatorSet(validators) if validators else \
                ValidatorSet()
            next_vals = [
                abci.ValidatorUpdate(power=v.voting_power,
                                     pub_key_type=v.pub_key.type(),
                                     pub_key_bytes=v.pub_key.bytes())
                for v in val_set.validators]
            import json as _json
            app_state_bytes = b""
            if self.gen_doc.app_state is not None:
                app_state_bytes = _json.dumps(
                    self.gen_doc.app_state).encode()
            res = await app_conns.consensus.init_chain(
                abci.InitChainRequest(
                    time=self.gen_doc.genesis_time,
                    chain_id=self.gen_doc.chain_id,
                    initial_height=self.gen_doc.initial_height,
                    consensus_params=self.gen_doc.consensus_params,
                    validators=next_vals,
                    app_state_bytes=app_state_bytes,
                ))
            app_hash = res.app_hash
            if state_height == 0:
                if res.app_hash:
                    state.app_hash = res.app_hash
                if res.validators:
                    vals = validate_validator_updates(
                        res.validators,
                        state.consensus_params.validator)
                    state.validators = ValidatorSet(vals)
                    state.next_validators = ValidatorSet(vals)
                    state.next_validators \
                        .increment_proposer_priority(1)
                elif not self.gen_doc.validators:
                    raise ReplayError(
                        "validator set is nil in genesis and still "
                        "empty after InitChain")
                if res.consensus_params is not None:
                    state.consensus_params = state.consensus_params \
                        .update(res.consensus_params)
                    state.version.consensus = type(
                        state.version.consensus)(
                        block=state.version.consensus.block,
                        app=state.consensus_params.version.app)
                state.last_results_hash = \
                    merkle.hash_from_byte_slices([])
                self.state_store.save(state)

        # edge cases on store heights
        if store_height == 0:
            self._assert_app_hash(app_hash, state)
            return app_hash
        if app_height == 0 and state.initial_height < store_base:
            raise AppBlockHeightTooLowError(
                f"app height 0, store base {store_base}")
        if app_height > 0 and app_height < store_base - 1:
            raise AppBlockHeightTooLowError(
                f"app height {app_height}, store base {store_base}")
        if store_height < app_height:
            raise AppBlockHeightTooHighError(
                f"store height {store_height} < app height "
                f"{app_height}")
        if store_height < state_height:
            raise ReplayError(
                f"state height {state_height} > store height "
                f"{store_height}")
        if store_height > state_height + 1:
            raise ReplayError(
                f"store height {store_height} > state height + 1 "
                f"{state_height + 1}")

        if store_height == state_height:
            if app_height < store_height:
                return await self._replay_range(
                    state, app_conns, app_height, store_height,
                    mutate_state=False)
            # all synced up
            self._assert_app_hash(app_hash, state)
            return app_hash

        # store_height == state_height + 1: block saved, state not updated
        if app_height < state_height:
            return await self._replay_range(
                state, app_conns, app_height, store_height,
                mutate_state=True)
        if app_height == state_height:
            # app and state are one behind: replay last block w/ real app
            self.logger.info("Replay last block using real app")
            state = await self._replay_block(state, store_height,
                                             app_conns.consensus)
            return state.app_hash
        if app_height == store_height:
            # app committed but state wasn't saved: mock replay
            saved = self.state_store.load_finalize_block_response(
                store_height)
            if saved is None:
                raise ReplayError(
                    f"no finalize block response for {store_height}")
            if not saved.app_hash:
                saved.app_hash = app_hash
            self.logger.info("Replay last block using mock app")
            state = await self._replay_block(
                state, store_height, _ReplayProxyApp(saved))
            return state.app_hash
        raise ReplayError(
            f"uncovered case: app {app_height}, store {store_height}, "
            f"state {state_height}")

    async def _replay_range(self, state: SMState, app_conns,
                            app_height: int, store_height: int,
                            mutate_state: bool) -> bytes:
        final_block = store_height - 1 if mutate_state else store_height
        first_block = app_height + 1
        if first_block == 1:
            first_block = state.initial_height
        app_hash = b""
        for h in range(first_block, final_block + 1):
            self.logger.info("Applying block", height=h)
            block = self.block_store.load_block(h)
            if block is None:
                raise ReplayError(f"block {h} missing from store")
            if app_hash and block.header.app_hash != app_hash:
                raise ReplayError(
                    f"app hash mismatch replaying height {h}")
            app_hash = await exec_commit_block(
                app_conns.consensus, block, self.state_store,
                self.gen_doc.initial_height, store_height, self.logger)
            self.n_blocks += 1
        if mutate_state:
            state = await self._replay_block(state, store_height,
                                             app_conns.consensus)
            app_hash = state.app_hash
        self._assert_app_hash(app_hash, state)
        return app_hash

    async def _replay_block(self, state: SMState, height: int,
                            proxy_consensus) -> SMState:
        """ApplyBlock through a fresh executor for the final block
        (reference: replay.go replayBlock)."""
        block = self.block_store.load_block(height)
        meta = self.block_store.load_block_meta(height)
        if block is None or meta is None:
            raise ReplayError(f"block {height} missing from store")
        block_exec = BlockExecutor(self.state_store, proxy_consensus,
                                   block_store=self.block_store,
                                   logger=self.logger)
        state = await block_exec.apply_verified_block(
            state, meta.block_id, block, height)
        self.n_blocks += 1
        return state

    def _assert_app_hash(self, app_hash: bytes, state: SMState) -> None:
        if state.app_hash and app_hash != state.app_hash:
            raise ReplayError(
                f"app hash {app_hash.hex()} does not match state app "
                f"hash {state.app_hash.hex()}")


async def catchup_replay(cs, wal_path: str) -> int:
    """Re-feed WAL messages for the in-flight height into a fresh
    ConsensusState (reference: replay.go catchupReplay :97).

    Returns the number of messages replayed.
    """
    height = cs.rs.height
    # ensure no end-height record exists for the CURRENT height (that
    # would mean the block was finalized but the state not yet advanced —
    # the handshake already handled it)
    after_current = WAL.search_for_end_height(wal_path, height)
    if after_current is not None:
        raise ReplayError(
            f"WAL should not contain end-height for {height}")
    tail = WAL.search_for_end_height(wal_path, height - 1)
    if tail is None:
        if height > cs.sm_state.initial_height:
            raise ReplayError(
                f"cannot replay height {height}: WAL has no end-height "
                f"marker for {height - 1}")
        # fresh chain: replay everything in the WAL
        try:
            tail = list(WAL.iter_group(wal_path))
        except FileNotFoundError:
            return 0
    n = 0
    cs.replay_mode = True
    try:
        for record in tail:
            t = record.get("type")
            if t in ("round_state", "end_height"):
                continue
            if t == "timeout":
                # replay timeout-driven step transitions too (reference
                # replay.go:142 dispatches timeoutInfo to handleTimeout) —
                # otherwise a node that crashed right after e.g. a
                # precommit-wait round advance restarts a round behind
                await cs._handle_timeout(TimeoutInfo(
                    duration_ns=0,
                    height=record.get("height", 0),
                    round=record.get("round", 0),
                    step=record.get("step", 0)))
                n += 1
                continue
            msg = message_from_wal(record)
            await cs._handle_msg(msg, "", internal=False)
            n += 1
    finally:
        cs.replay_mode = False
    return n
