"""Measured adaptive consensus timeouts.

The static ``timeout_propose`` (3 s) / ``timeout_vote`` (1 s) defaults
are sized for a hostile WAN; on a healthy net they are pure padding —
QA_r05's 16-node rig spent most of its 7.2 s block interval waiting
out timeouts sized an order of magnitude above the measured quorum
delay.  This module derives the timeouts from the same signal the
``consensus_quorum_prevote_delay_seconds`` histogram records: the
interval between a proposal's timestamp and the earliest prevote that
achieved a quorum.

Formula (docs/pipeline.md):

    p95   = 95th percentile of the last ``window`` quorum delays
    ewma  = max(p95, alpha * p95 + (1 - alpha) * ewma)   (first: p95)
    base  = clamp(max(margin * ewma, p95), floor, ceiling)

The EWMA rises instantly and decays geometrically (the TCP-RTO
shape): delays are only measured on *successful* rounds, so an
estimator that lags upward keeps under-deadlining a net that just
got slower — every churned round it causes produces no sample to
correct it.  QA_r07's rig showed exactly that failure: a fast idle
boot locked the symmetric EWMA low, and the first loaded heights
paid a round-churn tax until enough slow successes dragged it up.

* the propose timeout uses ``margin = 2.0`` (the proposer must build
  AND gossip the block inside it), vote timeouts ``margin = 1.5``,
  the commit padding ``margin = 1.0``;
* ``base`` never shrinks below the current window's measured p95 (a
  timeout below the delay we are actually observing would churn
  rounds), and the per-round escalation deltas from the static config
  still apply so liveness under asynchrony is preserved;
* with no observations (fresh node, WAL replay, a net that has never
  reached quorum) every query returns ``None`` and callers fall back
  to the static config;
* the commit padding only ever *shrinks* the static padding — the
  app's ``next_block_delay`` contract is a minimum spacing decision
  that adaptivity must not inflate.

Off by default (``consensus.adaptive_timeouts``).
"""
from __future__ import annotations

from collections import deque
from typing import Optional

_PROPOSE_MARGIN = 2.0
_VOTE_MARGIN = 1.5
_COMMIT_MARGIN = 1.0


class AdaptiveTimeouts:
    def __init__(self, floor_ns: int, ceiling_ns: int,
                 window: int = 64, alpha: float = 0.25):
        if not 0 < alpha <= 1:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        if floor_ns < 0 or ceiling_ns < floor_ns:
            raise ValueError(
                f"need 0 <= floor <= ceiling, got "
                f"{floor_ns}..{ceiling_ns}")
        self.floor_ns = floor_ns
        self.ceiling_ns = ceiling_ns
        self.alpha = alpha
        self._window: deque[float] = deque(maxlen=window)
        self._ewma_s: Optional[float] = None

    # ------------------------------------------------------------------
    def observe(self, delay_s: float) -> None:
        """Feed one measured quorum-prevote delay (seconds)."""
        self._window.append(max(0.0, float(delay_s)))
        p95 = self.p95_s()
        if self._ewma_s is None:
            self._ewma_s = p95
        else:
            # fast-rise / slow-decay: an estimator below the current
            # p95 snaps up immediately (under-deadlining churns
            # rounds, and churned rounds produce no correcting
            # sample); decay toward a faster net stays geometric
            self._ewma_s = max(p95, self.alpha * p95 +
                               (1.0 - self.alpha) * self._ewma_s)

    @property
    def samples(self) -> int:
        return len(self._window)

    def p95_s(self) -> float:
        """p95 of the current window (0.0 when empty)."""
        if not self._window:
            return 0.0
        xs = sorted(self._window)
        return xs[min(len(xs) - 1, int(0.95 * len(xs)))]

    def ewma_s(self) -> Optional[float]:
        return self._ewma_s

    # ------------------------------------------------------------------
    def _derive_ns(self, margin: float) -> Optional[int]:
        if self._ewma_s is None:
            return None
        base_s = max(margin * self._ewma_s, self.p95_s())
        ns = int(base_s * 1e9)
        return max(self.floor_ns, min(self.ceiling_ns, ns))

    def propose_timeout_ns(self) -> Optional[int]:
        """Round-0 propose timeout; None = use static config."""
        return self._derive_ns(_PROPOSE_MARGIN)

    def vote_timeout_ns(self) -> Optional[int]:
        """Round-0 prevote/precommit wait timeout; None = static."""
        return self._derive_ns(_VOTE_MARGIN)

    def commit_padding_ns(self, static_ns: int) -> int:
        """Post-commit padding before the next height's round 0.

        Adaptivity only ever shrinks the static padding (the app /
        operator set it as a minimum-spacing decision); with no
        measurements the static value passes through unchanged."""
        derived = self._derive_ns(_COMMIT_MARGIN)
        if derived is None:
            return static_ns
        return min(static_ns, derived)
