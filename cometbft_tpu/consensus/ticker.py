"""TimeoutTicker: schedulable per-step consensus timeouts.

Reference: internal/consensus/ticker.go — one active timer; scheduling a
new timeout for a later (h, r, s) replaces the pending one, stale fires
are dropped by comparing (height, round, step).
"""
from __future__ import annotations

import asyncio
from typing import Callable, Optional

from .round_state import TimeoutInfo


class TimeoutTicker:
    def __init__(self, on_timeout: Callable[[TimeoutInfo], None]):
        self._on_timeout = on_timeout
        self._task: Optional[asyncio.Task] = None
        self._current: Optional[TimeoutInfo] = None

    def schedule_timeout(self, ti: TimeoutInfo) -> None:
        """Replace the pending timeout (reference: timeoutRoutine —
        newer (h,r,s) always wins; the old timer is stopped)."""
        cur = self._current
        if cur is not None and self._task is not None and \
                not self._task.done():
            # ignore a schedule that is older than the pending one
            if (ti.height, ti.round, ti.step) < \
                    (cur.height, cur.round, cur.step):
                return
            self._task.cancel()
        self._current = ti
        self._task = asyncio.get_running_loop().create_task(
            self._fire(ti))

    async def _fire(self, ti: TimeoutInfo) -> None:
        try:
            await asyncio.sleep(ti.duration_ns / 1e9)
        except asyncio.CancelledError:
            return
        if self._current is ti:
            self._current = None
        self._on_timeout(ti)

    def stop(self) -> None:
        if self._task is not None and not self._task.done():
            self._task.cancel()
        self._current = None
