"""Consensus reactor: gossips the consensus state over the p2p switch.

Reference: internal/consensus/reactor.go (2022 LoC) — 4 channels
(State/Data/Vote/VoteSetBits), PeerState tracking what each peer has,
and per-peer gossip routines: gossipDataRoutine (:594, proposal block
parts), gossipVotesRoutine (:654), queryMaj23Routine (:718).
"""
from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Optional

from ..libs import tracing
from ..libs.bits import BitArray
from ..libs.log import Logger, new_logger
from ..libs.supervisor import RestartPolicy
from ..p2p.conn import ChannelDescriptor
from ..p2p.switch import Peer, Reactor
from ..types import canonical
from ..types.block_id import BlockID
from ..types.part_set import PartSetHeader
from ..types.commit import AggregateCommit
from .messages import (
    COMPACT_MIN_TXS, FEATURE_AGG_COMMIT, FEATURE_COMPACT_BLOCKS,
    FEATURE_VOTE_BATCH, AggregateCommitMessage,
    BlockPartMessage, CompactBlockNackMessage,
    CompactBlockPartMessage, HasProposalBlockPartMessage,
    HasVoteMessage, NewRoundStepMessage, NewValidBlockMessage,
    ProposalMessage, ProposalPOLMessage, VoteBatchMessage,
    VoteMessage, VoteSetBitsMessage, VoteSetMaj23Message,
    decode_p2p, encode_p2p, make_compact_block,
)
from .round_state import (
    STEP_COMMIT, STEP_NEW_HEIGHT, STEP_PRECOMMIT, STEP_PREVOTE,
    STEP_PROPOSE, RoundState,
)
from .state import ConsensusState

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23


@dataclass
class PeerRoundState:
    """What we believe the peer's round state is (reference:
    cstypes.PeerRoundState)."""
    height: int = 0
    round: int = -1
    step: int = 0
    proposal: bool = False
    proposal_block_parts_header: PartSetHeader = field(
        default_factory=PartSetHeader)
    proposal_block_parts: Optional[BitArray] = None
    proposal_pol_round: int = -1
    proposal_pol: Optional[BitArray] = None
    prevotes: Optional[BitArray] = None
    precommits: Optional[BitArray] = None
    last_commit_round: int = -1
    last_commit: Optional[BitArray] = None
    catchup_commit_round: int = -1
    catchup_commit: Optional[BitArray] = None


class PeerState:
    """Reference: internal/consensus/reactor.go PeerState.

    Owner discipline (the PR-10 RoundState seam, extended here): the
    reactor's receive path and this peer's gossip routines all run on
    the event loop, and every cross-await mutation of ``prs`` (or the
    compact-block protocol state below) goes through these methods —
    each re-validates its height/round precondition at the write, so
    a stale decision computed before a suspension cannot be applied
    to a round the peer has already left.  bftlint's await-atomicity
    rule tracks ``prs.*`` stores the same way it tracks ``self.rs.*``
    (tools/bftlint/checkers/await_atomicity.py)."""

    def __init__(self, peer: Peer):
        self.peer = peer
        self.prs = PeerRoundState()
        # compact-block relay bookkeeping: the (height, round) we last
        # sent this peer the compact form for, and when (monotonic) —
        # full parts are held back for the grace window so the peer
        # gets a chance to reconstruct from its mempool
        self.compact_hr: Optional[tuple] = None
        self.compact_at: float = 0.0
        # the (height, round) the peer sent US the compact form for:
        # it provably holds the complete block, so no routine should
        # push parts at it even before its part bitmap says so
        self.full_block_hr: Optional[tuple] = None
        # aggregate-commit catchup: the height we last shipped this
        # peer an AggregateCommitMessage for, and when (monotonic) —
        # one aggregate replaces the whole per-vote catchup stream,
        # so resends are purely a lost-message safety net
        self.agg_commit_sent_height: int = 0
        self.agg_commit_sent_at: float = 0.0

    # -- compact-block seam (single-writer transition methods) ------
    def mark_compact_sent(self, height: int, round_: int,
                          now: float) -> None:
        self.compact_hr = (height, round_)
        self.compact_at = now

    def clear_compact_grace(self, height: int, round_: int) -> None:
        """The peer nacked our compact form: stop holding parts back
        (the (height, round) check re-validates at the write)."""
        if self.compact_hr == (height, round_):
            self.compact_at = 0.0

    def compact_covers(self, height: int, round_: int, now: float,
                       grace_s: float) -> bool:
        """True while full parts for (height, round) should be held
        back: the compact form went out within the grace window."""
        return self.compact_hr == (height, round_) and \
            (now - self.compact_at) < grace_s

    def mark_peer_has_full_block(self, height: int,
                                 round_: int) -> None:
        self.full_block_hr = (height, round_)

    def peer_has_full_block(self, height: int, round_: int) -> bool:
        return self.full_block_hr == (height, round_)

    def init_catchup_parts(self, height: int,
                           header: PartSetHeader) -> None:
        """Install the stored block's part-set header for catchup
        gossip (re-validating the peer is still on that height)."""
        prs = self.prs
        if prs.height != height:
            return
        prs.proposal_block_parts_header = header
        prs.proposal_block_parts = BitArray(header.total)

    def apply_new_round_step(self, msg: NewRoundStepMessage,
                             num_validators: int) -> None:
        prs = self.prs
        init_height, init_round = prs.height, prs.round
        # snapshot BEFORE resetting: if the peer advanced exactly one
        # height, its old precommits become its new last commit
        # (reference: ApplyNewRoundStepMessage)
        old_precommits = prs.precommits
        if msg.height != prs.height or msg.round != prs.round:
            prs.proposal = False
            prs.proposal_block_parts_header = PartSetHeader()
            prs.proposal_block_parts = None
            prs.proposal_pol_round = -1
            prs.proposal_pol = None
            prs.prevotes = BitArray(num_validators)
            prs.precommits = BitArray(num_validators)
        if prs.height != msg.height:
            if msg.height == init_height + 1 and \
                    msg.last_commit_round == init_round:
                prs.last_commit_round = msg.last_commit_round
                prs.last_commit = old_precommits
            else:
                prs.last_commit_round = msg.last_commit_round
                prs.last_commit = None
            prs.catchup_commit_round = -1
            prs.catchup_commit = None
        prs.height = msg.height
        prs.round = msg.round
        prs.step = msg.step

    def apply_new_valid_block(self, msg: NewValidBlockMessage) -> None:
        prs = self.prs
        if prs.height != msg.height:
            return
        if prs.round != msg.round and not msg.is_commit:
            return
        prs.proposal_block_parts_header = msg.block_part_set_header
        prs.proposal_block_parts = msg.block_parts

    def apply_proposal(self, msg: ProposalMessage) -> None:
        prs = self.prs
        p = msg.proposal
        if prs.height != p.height or prs.round != p.round:
            return
        if prs.proposal:
            return
        prs.proposal = True
        if prs.proposal_block_parts is not None:
            return   # NewValidBlock already set the parts header
        prs.proposal_block_parts_header = p.block_id.part_set_header
        prs.proposal_block_parts = BitArray(
            p.block_id.part_set_header.total)
        prs.proposal_pol_round = p.pol_round
        prs.proposal_pol = None

    def apply_proposal_pol(self, msg: ProposalPOLMessage) -> None:
        prs = self.prs
        if prs.height != msg.height or \
                prs.proposal_pol_round != msg.proposal_pol_round:
            return
        prs.proposal_pol = msg.proposal_pol

    def apply_has_vote(self, msg: HasVoteMessage) -> None:
        if self.prs.height != msg.height:
            return
        self.set_has_vote(msg.height, msg.round, msg.type, msg.index)

    def apply_has_proposal_block_part(
            self, msg: HasProposalBlockPartMessage) -> None:
        prs = self.prs
        if prs.height != msg.height or prs.round != msg.round:
            return
        if prs.proposal_block_parts is not None:
            prs.proposal_block_parts.set_index(msg.index, True)

    def set_has_proposal_block_part(self, height: int, round_: int,
                                    index: int) -> None:
        prs = self.prs
        if prs.height != height or prs.round != round_:
            return
        if prs.proposal_block_parts is not None:
            prs.proposal_block_parts.set_index(index, True)

    def set_has_vote(self, height: int, round_: int, type_: int,
                     index: int) -> None:
        ba = self._votes_bitarray(height, round_, type_)
        if ba is not None:
            ba.set_index(index, True)

    def _votes_bitarray(self, height: int, round_: int,
                        type_: int) -> Optional[BitArray]:
        prs = self.prs
        if prs.height == height:
            if prs.round == round_:
                return prs.prevotes if \
                    type_ == canonical.PREVOTE_TYPE else prs.precommits
            if prs.catchup_commit_round == round_ and \
                    type_ == canonical.PRECOMMIT_TYPE:
                return prs.catchup_commit
            if prs.proposal_pol_round == round_ and \
                    type_ == canonical.PREVOTE_TYPE:
                return prs.proposal_pol
        elif prs.height == height + 1:
            if prs.last_commit_round == round_ and \
                    type_ == canonical.PRECOMMIT_TYPE:
                return prs.last_commit
        return None

    def apply_vote_set_bits(self, msg: VoteSetBitsMessage,
                            our_votes: Optional[BitArray]) -> None:
        """Merge the peer's claimed vote bits (reference:
        ApplyVoteSetBitsMessage — bits we can't verify locally are only
        trusted where they agree with votes we hold)."""
        votes = self._votes_bitarray(msg.height, msg.round, msg.type)
        if votes is None or msg.votes is None:
            return
        if our_votes is None:
            votes.update(msg.votes)
        else:
            other_votes = votes.sub(our_votes)
            has_votes = other_votes.or_(msg.votes)
            votes.update(has_votes)

    def ensure_catchup_commit_round(self, height: int, round_: int,
                                    num_validators: int) -> None:
        prs = self.prs
        if prs.height != height:
            return
        if prs.catchup_commit_round != round_:
            prs.catchup_commit_round = round_
            prs.catchup_commit = BitArray(num_validators)


# per-peer gossip loops: quick bounded restarts; a loop that keeps
# crashing means the peer (or our state for it) is poison, so the
# give-up path drops the peer like the pre-supervisor error handlers
_GOSSIP_RESTART_POLICY = RestartPolicy(
    max_restarts=3, window_s=30.0, backoff_base_s=0.05,
    backoff_max_s=1.0)


class ConsensusReactor(Reactor):
    def __init__(self, cs: ConsensusState,
                 wait_sync: bool = False,
                 logger: Optional[Logger] = None):
        super().__init__("CONSENSUS")
        self.cs = cs
        self.wait_sync = wait_sync   # true while block/state syncing
        if logger is not None:
            self.logger = logger
        self._peer_states: dict[str, PeerState] = {}
        self._gossip_tasks: dict[str, list] = {}   # SupervisedTask
        # one encoded compact proposal per (height, round), shared by
        # every per-peer relay
        self._compact_raw: tuple = (None, b"")
        # wire the state machine's broadcasts through the switch
        cs.broadcast_hooks.append(self._on_cs_broadcast)
        cs.on_new_step.append(self._on_new_step)

    def get_channels(self) -> list[ChannelDescriptor]:
        """Reference: reactor.go StreamDescriptors.  The vote channel
        queue is sized for 100+ validator nets: at 102 signature
        slots per height the old 100-message queue filled inside one
        round (the send_queue_full/send_rate_stall events pinpointed
        it), dropping votes that then cost a maj23 round trip to
        recover."""
        return [
            ChannelDescriptor(id=STATE_CHANNEL, priority=6,
                              send_queue_capacity=200),
            ChannelDescriptor(id=DATA_CHANNEL, priority=10,
                              send_queue_capacity=100),
            ChannelDescriptor(id=VOTE_CHANNEL, priority=7,
                              send_queue_capacity=800),
            ChannelDescriptor(id=VOTE_SET_BITS_CHANNEL, priority=1,
                              send_queue_capacity=2),
        ]

    def get_features(self) -> list[str]:
        feats = []
        if getattr(self.cs.config, "compact_blocks", False):
            feats.append(FEATURE_COMPACT_BLOCKS)
        if getattr(self.cs.config, "vote_batch_max", 0) > 0:
            feats.append(FEATURE_VOTE_BATCH)
        if getattr(self.cs.config, "aggregate_commits_wire", True):
            feats.append(FEATURE_AGG_COMMIT)
        return feats

    def _chain_uses_aggregate_commits(self) -> bool:
        """True once the chain is AT the aggregate-commit activation
        point — the next height's commit will be an AggregateCommit,
        so blocks/catchup from here on carry wire arms a peer without
        aggcommit/1 cannot decode.  An enable height scheduled far in
        the future (param update) does NOT refuse peers early: every
        existing block is still per-signature and fully parseable;
        such peers are re-checked at activation by the gossip loop."""
        sm = self.cs.sm_state
        if sm is None:
            return False
        h = sm.consensus_params.feature.aggregate_commit_enable_height
        return h > 0 and sm.last_block_height + 1 >= h

    def _refuse_no_aggcommit(self, peer: Peer, when: str) -> None:
        """Drop a peer that lacks aggcommit/1 on an active
        aggregate-commit chain (shared by admission-time screening in
        add_peer and the activation re-check in the gossip loop)."""
        self.logger.error(
            "peer lacks aggcommit/1 on an aggregate-commit chain; "
            "dropping", peer=peer.id[:12], when=when)
        if self.switch is not None:
            self.supervisor.spawn(
                lambda: self.switch.stop_peer(
                    peer, "incompatible: no aggcommit/1"),
                name=f"stop_peer:{peer.id[:12]}",
                kind="stop_peer")

    def _peer_compact(self, peer: Peer) -> bool:
        if not getattr(self.cs.config, "compact_blocks", False):
            return False
        has = getattr(peer, "has_feature", None)
        return bool(has and has(FEATURE_COMPACT_BLOCKS))

    def _peer_vote_batch(self, peer: Peer) -> bool:
        if not getattr(self.cs.config, "vote_batch_max", 0):
            return False
        has = getattr(peer, "has_feature", None)
        return bool(has and has(FEATURE_VOTE_BATCH))

    # ------------------------------------------------------------------
    async def add_peer(self, peer: Peer) -> None:
        # once aggregation is ACTIVE a peer that cannot parse
        # AggregateCommit wire arms cannot decode this chain's blocks
        # — refuse it up front rather than let it choke on every
        # block part (capability declared in the handshake like
        # txrecon/compactblocks; ed25519 chains and pre-activation
        # heights admit it, and the gossip loop re-checks at
        # activation)
        if self._chain_uses_aggregate_commits():
            has = getattr(peer, "has_feature", None)
            if not (has and has(FEATURE_AGG_COMMIT)):
                self._refuse_no_aggcommit(peer, when="admission")
                return
        ps = PeerState(peer)
        self._peer_states[peer.id] = ps
        peer.data["consensus_peer_state"] = ps
        # supervisor-owned: a crash in a gossip loop restarts that
        # loop (with a restart metric) instead of silently muting the
        # peer until disconnect
        sup = self.supervisor
        pid = peer.id[:12]

        def _stop_peer_on_giveup(st, exc):
            # restart budget exhausted: the peer is poison — drop it
            # (the pre-supervisor behavior, now after bounded retries);
            # the one-shot teardown is itself supervised so a crash in
            # stop_peer is metered, never silent
            if self.switch is not None:
                sup.spawn(lambda: self.switch.stop_peer(
                    peer, repr(exc)), name=f"stop_peer:{pid}",
                    kind="stop_peer")

        policy = _GOSSIP_RESTART_POLICY
        self._gossip_tasks[peer.id] = [
            sup.spawn(lambda: self._gossip_data_routine(ps),
                      name=f"gossip_data:{pid}",
                      kind="consensus_gossip_data", policy=policy,
                      on_giveup=_stop_peer_on_giveup),
            sup.spawn(lambda: self._gossip_votes_routine(ps),
                      name=f"gossip_votes:{pid}",
                      kind="consensus_gossip_votes", policy=policy,
                      on_giveup=_stop_peer_on_giveup),
            sup.spawn(lambda: self._query_maj23_routine(ps),
                      name=f"query_maj23:{pid}",
                      kind="consensus_query_maj23", policy=policy,
                      on_giveup=_stop_peer_on_giveup),
        ]
        # tell the new peer our current state — but NOT while we're
        # block/state syncing: we drop incoming votes in that mode, and
        # advertising a live round makes peers gossip votes at us and
        # mark them delivered, wedging the round once we join
        # (reference: reactor.go AddPeer gates on !conR.WaitSync();
        # SwitchToConsensus re-announces via the step broadcast)
        if not self.wait_sync:
            peer.send(STATE_CHANNEL,
                      encode_p2p(self._new_round_step_msg()))

    async def remove_peer(self, peer: Peer, reason: str) -> None:
        self._peer_states.pop(peer.id, None)
        for t in self._gossip_tasks.pop(peer.id, []):
            t.cancel()

    # ------------------------------------------------------------------
    async def receive(self, chan_id: int, peer: Peer,
                      msg_bytes: bytes) -> None:
        """Reference: reactor.go Receive (:243)."""
        try:
            msg = decode_p2p(msg_bytes)
        except Exception as e:
            self.logger.error("failed to decode message",
                              peer=peer.id[:12], err=str(e))
            return
        ps = self._peer_states.get(peer.id)
        if ps is None:
            return
        rs = self.cs.rs

        if chan_id == STATE_CHANNEL:
            if isinstance(msg, NewRoundStepMessage):
                ps.apply_new_round_step(
                    msg, self.cs.rs.validators.size()
                    if self.cs.rs.validators else 0)
            elif isinstance(msg, NewValidBlockMessage):
                ps.apply_new_valid_block(msg)
            elif isinstance(msg, HasVoteMessage):
                ps.apply_has_vote(msg)
            elif isinstance(msg, HasProposalBlockPartMessage):
                ps.apply_has_proposal_block_part(msg)
            elif isinstance(msg, VoteSetMaj23Message):
                # record the claim, then reply with our vote bits
                if rs.height != msg.height or rs.votes is None:
                    return
                try:
                    rs.votes.set_peer_maj23(msg.round, msg.type,
                                            peer.id, msg.block_id)
                except Exception as e:
                    self.logger.info("bad VoteSetMaj23",
                                     err=str(e))
                    return
                vs = (rs.votes.prevotes(msg.round)
                      if msg.type == canonical.PREVOTE_TYPE
                      else rs.votes.precommits(msg.round))
                if vs is None:
                    return
                our_votes = vs.bit_array_by_block_id(msg.block_id)
                peer.send(VOTE_SET_BITS_CHANNEL, encode_p2p(
                    VoteSetBitsMessage(
                        height=msg.height, round=msg.round,
                        type=msg.type, block_id=msg.block_id,
                        votes=our_votes or BitArray(0))))
        elif self.wait_sync:
            return   # ignore data/votes while syncing
        elif chan_id == DATA_CHANNEL:
            if isinstance(msg, ProposalMessage):
                # first-seen marker for the fleet critical path: which
                # link delivered the proposal to this node, and when —
                # the state machine's proposal_received instant has no
                # peer attribution (it runs after the input queue)
                tracing.instant(tracing.CONSENSUS, "proposal_recv",
                                height=msg.proposal.height,
                                round=msg.proposal.round,
                                peer=peer.id[:12], chan=chan_id)
                ps.apply_proposal(msg)
                self.cs.send_peer(msg, peer.id)
            elif isinstance(msg, ProposalPOLMessage):
                ps.apply_proposal_pol(msg)
            elif isinstance(msg, BlockPartMessage):
                ps.set_has_proposal_block_part(msg.height, msg.round,
                                               msg.part.index)
                tracing.instant(tracing.CONSENSUS, "block_part_recv",
                                height=msg.height,
                                index=msg.part.index,
                                peer=peer.id[:12], chan=chan_id)
                self._credit_useful_part(chan_id, msg)
                self.cs.send_peer(msg, peer.id)
            elif isinstance(msg, CompactBlockPartMessage):
                # the sender holds the whole block — never push parts
                # back at it; reconstruction itself runs on the state
                # machine's input queue so it is ordered AFTER the
                # ProposalMessage the same peer sent just before it
                ps.mark_peer_has_full_block(msg.height, msg.round)
                tracing.instant(tracing.CONSENSUS,
                                "compact_block_recv",
                                height=msg.height,
                                txs=len(msg.tx_hashes),
                                peer=peer.id[:12], chan=chan_id)
                self.cs.send_peer(msg, peer.id)
            elif isinstance(msg, CompactBlockNackMessage):
                # the peer could not rebuild our compact proposal:
                # cancel its grace window and push every part it
                # lacks right now — the per-peer gossip routine backs
                # this up for anything the queue drops
                ps.clear_compact_grace(msg.height, msg.round)
                tracing.instant(tracing.CONSENSUS,
                                "compact_block_nack",
                                height=msg.height,
                                peer=peer.id[:12], chan=chan_id)
                self._push_parts_now(ps, msg.height, msg.round)
        elif chan_id == VOTE_CHANNEL:
            if isinstance(msg, VoteMessage):
                v = msg.vote
                self._credit_useful_vote(chan_id, ps, v,
                                         len(msg_bytes))
                ps.set_has_vote(v.height, v.round, v.type,
                                v.validator_index)
                tracing.instant(tracing.CONSENSUS, "vote_recv",
                                height=v.height, round=v.round,
                                type=v.type, index=v.validator_index,
                                peer=peer.id[:12], chan=chan_id)
                self.cs.send_peer(msg, peer.id)
            elif isinstance(msg, VoteBatchMessage):
                per = len(msg_bytes) // max(1, len(msg.votes))
                for v in msg.votes:
                    self._credit_useful_vote(chan_id, ps, v, per)
                    ps.set_has_vote(v.height, v.round, v.type,
                                    v.validator_index)
                    tracing.instant(tracing.CONSENSUS, "vote_recv",
                                    height=v.height, round=v.round,
                                    type=v.type,
                                    index=v.validator_index,
                                    peer=peer.id[:12], chan=chan_id)
                # ONE input-queue entry per wire message — expanding
                # the batch here would multiply queue pressure by the
                # batch size and defeat the p2p backpressure (the
                # catchup-storm QueueFull crash the recon nemesis
                # scenario caught); the state machine unpacks it
                self.cs.send_peer(msg, peer.id)
            elif isinstance(msg, AggregateCommitMessage):
                # aggregate-commit catchup: verified (off the event
                # loop — ISSUE 14) and injected as +2/3 precommit
                # evidence by the state machine.  Provably-stale or
                # forger-peer aggregates shed HERE so the input
                # queue — the backpressure buffer while a verdict
                # barrier is outstanding — only carries messages
                # that can still matter.
                if self.cs.aggregate_commit_relevant(msg.commit,
                                                     peer.id):
                    tracing.instant(tracing.CONSENSUS,
                                    "agg_commit_recv",
                                    height=msg.commit.height,
                                    peer=peer.id[:12], chan=chan_id)
                    self.cs.send_peer(msg, peer.id)
                else:
                    tracing.instant(tracing.CONSENSUS,
                                    "agg_commit_shed",
                                    height=msg.commit.height,
                                    peer=peer.id[:12])
        elif chan_id == VOTE_SET_BITS_CHANNEL:
            if isinstance(msg, VoteSetBitsMessage) and \
                    rs.height == msg.height and msg.votes is not None:
                vs = (rs.votes.prevotes(msg.round)
                      if msg.type == canonical.PREVOTE_TYPE
                      else rs.votes.precommits(msg.round))
                our = vs.bit_array_by_block_id(msg.block_id) \
                    if vs is not None else None
                ps.apply_vote_set_bits(msg, our)

    # ------------------------------------------------------------------
    # bytes-useful accounting (docs/gossip.md): credit payload bytes
    # that carried content this node actually lacked

    def _credit_useful(self, chan_id: int, n: int) -> None:
        if n > 0 and self.switch is not None:
            # chan_id is one of this reactor's four claimed channels
            # — a closed set, same boundedness as touch_channel's
            ch_id = f"{chan_id:#x}"
            self.switch.metrics.message_useful_bytes_total \
                .with_labels(ch_id).add(n)

    def _credit_useful_part(self, chan_id: int,
                            msg: BlockPartMessage) -> None:
        rs = self.cs.rs
        if rs.height == msg.height and \
                rs.proposal_block_parts is not None and \
                not rs.proposal_block_parts.has_part(msg.part.index):
            self._credit_useful(chan_id, len(msg.part.bytes_))

    def _credit_useful_vote(self, chan_id: int, ps: PeerState, v,
                            nbytes: int) -> None:
        rs = self.cs.rs
        if rs.height != v.height or rs.votes is None:
            return
        vs = (rs.votes.prevotes(v.round)
              if v.type == canonical.PREVOTE_TYPE
              else rs.votes.precommits(v.round))
        if vs is not None and 0 <= v.validator_index < \
                vs.bit_array().size() and \
                not vs.bit_array().get_index(v.validator_index):
            self._credit_useful(chan_id, nbytes)

    # ------------------------------------------------------------------
    # compact-block proposal relay (docs/gossip.md)

    def _push_parts_now(self, ps: PeerState, height: int,
                        round_: int) -> None:
        """Immediate full-part push after a nack: send every part the
        peer's bitmap lacks (TrySend semantics — drops are retried by
        the gossip routine)."""
        rs = self.cs.rs
        prs = ps.prs
        if rs.height != height or rs.round != round_ or \
                rs.proposal_block_parts is None:
            return
        theirs = prs.proposal_block_parts \
            if (prs.height, prs.round) == (height, round_) else None
        for i in range(rs.proposal_block_parts.total):
            if not rs.proposal_block_parts.has_part(i):
                continue
            if theirs is not None and theirs.get_index(i):
                continue
            part = rs.proposal_block_parts.get_part(i)
            if not ps.peer.send(DATA_CHANNEL, encode_p2p(
                    BlockPartMessage(height=height, round=round_,
                                     part=part))):
                return
            ps.set_has_proposal_block_part(height, round_, i)

    def _send_compact_block(self, ps: PeerState, height: int,
                            round_: int, raw_msg: bytes) -> bool:
        if ps.peer.send(DATA_CHANNEL, raw_msg):
            ps.mark_compact_sent(height, round_, time.monotonic())
            self.cs.metrics.compact_blocks_sent.add()
            return True
        return False

    @property
    def _compact_grace_s(self) -> float:
        return getattr(self.cs.config, "compact_block_grace_ns",
                       0) / 1e9

    # ------------------------------------------------------------------
    # broadcasts from the state machine

    def _on_cs_broadcast(self, msg) -> None:
        if self.switch is None:
            return
        if isinstance(msg, ProposalMessage):
            self.switch.broadcast(DATA_CHANNEL, encode_p2p(msg))
        elif isinstance(msg, tuple) and msg and \
                msg[0] == "compact_nack":
            # reconstruction failed on OUR side: ask the compact's
            # sender for full parts immediately
            _, height, round_, peer_id = msg
            peer = self.switch.peers.get(peer_id)
            if peer is not None:
                peer.send(DATA_CHANNEL, encode_p2p(
                    CompactBlockNackMessage(height=height,
                                            round=round_)))
        elif isinstance(msg, tuple) and msg and \
                msg[0] == "compact_block":
            # our own proposal just went out: compact-capable peers
            # get skeleton + tx hashes instead of the full parts
            _, height, round_, block, psh = msg
            raw = None
            for peer in list(self.switch.peers.values()):
                if not self._peer_compact(peer):
                    continue
                ps = self._peer_states.get(peer.id)
                if ps is None:
                    continue
                if raw is None:
                    raw = encode_p2p(make_compact_block(
                        height, round_, block, psh))
                self._send_compact_block(ps, height, round_, raw)
        elif isinstance(msg, BlockPartMessage):
            raw = encode_p2p(msg)
            now = time.monotonic()
            grace = self._compact_grace_s
            for peer in list(self.switch.peers.values()):
                ps = self._peer_states.get(peer.id)
                if ps is not None and grace > 0 and \
                        ps.compact_covers(msg.height, msg.round, now,
                                          grace):
                    # the peer is reconstructing from the compact
                    # form; the gossip routine resends any part it
                    # still misses once the grace window expires
                    continue
                peer.send(DATA_CHANNEL, raw)
        elif isinstance(msg, VoteMessage):
            v = msg.vote
            self.switch.broadcast(VOTE_CHANNEL, encode_p2p(msg))
            self.switch.broadcast(STATE_CHANNEL, encode_p2p(
                HasVoteMessage(height=v.height, round=v.round,
                               type=v.type, index=v.validator_index)))
        elif isinstance(msg, tuple) and msg and msg[0] == "has_vote":
            v = msg[1]
            self.switch.broadcast(STATE_CHANNEL, encode_p2p(
                HasVoteMessage(height=v.height, round=v.round,
                               type=v.type, index=v.validator_index)))
        elif isinstance(msg, tuple) and msg and msg[0] == "valid_block":
            self.switch.broadcast(STATE_CHANNEL,
                                  encode_p2p(self._valid_block_msg()))

    def _valid_block_msg(self) -> NewValidBlockMessage:
        """Reference: makeRoundStepMessages' NewValidBlockMessage —
        advertises the part-set header we are collecting and the
        bitmap of parts we ACTUALLY hold, so peers (re)send the rest
        even when their delivery bookkeeping says otherwise."""
        rs = self.cs.rs
        parts = rs.proposal_block_parts
        bits = BitArray(parts.total if parts is not None else 0)
        if parts is not None:
            for i, have in enumerate(parts.bit_array()):
                if have:
                    bits.set_index(i, True)
        return NewValidBlockMessage(
            height=rs.height, round=rs.round,
            block_part_set_header=(parts.header() if parts is not None
                                   else PartSetHeader()),
            block_parts=bits,
            is_commit=rs.step == STEP_COMMIT)

    def _new_round_step_msg(self) -> NewRoundStepMessage:
        rs = self.cs.rs
        # monotonic interval (clock-discipline): wall time here broke
        # under wall-clock steps; start_time stays wall only because
        # it derives from protocol timestamps
        return NewRoundStepMessage(
            height=rs.height, round=rs.round, step=rs.step,
            seconds_since_start_time=max(
                0, self.cs.seconds_since_start()),
            last_commit_round=rs.last_commit.round
            if rs.last_commit is not None else -1)

    def _on_new_step(self, rs: RoundState) -> None:
        if self.switch is not None:
            self.switch.broadcast(STATE_CHANNEL,
                                  encode_p2p(self._new_round_step_msg()))

    # ------------------------------------------------------------------
    # gossip routines (reference: reactor.go:594,654,718)

    @property
    def _sleep_s(self) -> float:
        return self.cs.config.peer_gossip_sleep_duration_ns / 1e9

    async def _gossip_data_routine(self, ps: PeerState) -> None:
        peer = ps.peer
        has = getattr(peer, "has_feature", None)
        peer_agg = bool(has and has(FEATURE_AGG_COMMIT))
        try:
            while True:
                # activation re-check: a peer admitted while the
                # enable height was still in the future becomes
                # incompatible the moment the chain reaches it
                # (add_peer only screens peers arriving afterwards)
                if not peer_agg and self._chain_uses_aggregate_commits():
                    self._refuse_no_aggcommit(peer, when="activation")
                    return
                rs = self.cs.rs
                prs = ps.prs
                # send proposal block parts the peer is missing
                if (rs.proposal_block_parts is not None and
                        rs.height == prs.height and
                        rs.round == prs.round and
                        prs.proposal_block_parts is not None and
                        rs.proposal_block_parts.header() ==
                        prs.proposal_block_parts_header):
                    # the peer sent us the compact form — it holds
                    # the whole block; don't echo parts back
                    if ps.peer_has_full_block(rs.height, rs.round):
                        await asyncio.sleep(self._sleep_s)
                        continue
                    # compact-first relay: a compact-capable peer
                    # with no parts yet gets skeleton + tx hashes
                    # once; full parts are held back for the grace
                    # window while it reconstructs (docs/gossip.md)
                    if self._relay_compact_maybe(ps, rs):
                        await asyncio.sleep(self._sleep_s)
                        continue
                    if ps.compact_covers(rs.height, rs.round,
                                         time.monotonic(),
                                         self._compact_grace_s):
                        await asyncio.sleep(self._sleep_s)
                        continue
                    sent = False
                    for i in range(rs.proposal_block_parts.total):
                        if rs.proposal_block_parts.has_part(i) and \
                                not prs.proposal_block_parts \
                                .get_index(i):
                            part = rs.proposal_block_parts.get_part(i)
                            if peer.send(DATA_CHANNEL, encode_p2p(
                                    BlockPartMessage(
                                        height=rs.height,
                                        round=rs.round, part=part))):
                                # seam: re-validates the peer's
                                # (height, round) at the write — the
                                # send above did not suspend, but the
                                # discipline is uniform
                                ps.set_has_proposal_block_part(
                                    rs.height, rs.round, i)
                                sent = True
                            break
                    if sent:
                        await asyncio.sleep(0)  # keep the loop fair
                        continue
                # peer is on an older height: catch up from block store
                if prs.height and prs.height < rs.height and \
                        prs.height >= self.cs.block_store.base:
                    if await self._gossip_catchup(ps):
                        await asyncio.sleep(0)  # keep the loop fair
                        continue
                # send the proposal if peer lacks it
                if (rs.proposal is not None and rs.height == prs.height
                        and rs.round == prs.round and
                        not prs.proposal):
                    sent_prop = peer.send(
                        DATA_CHANNEL,
                        encode_p2p(ProposalMessage(rs.proposal)))
                    if sent_prop:
                        ps.apply_proposal(ProposalMessage(rs.proposal))
                    if rs.proposal.pol_round >= 0:
                        pv = rs.votes.prevotes(rs.proposal.pol_round)
                        if pv is not None:
                            peer.send(DATA_CHANNEL, encode_p2p(
                                ProposalPOLMessage(
                                    height=rs.height,
                                    proposal_pol_round=rs.proposal
                                    .pol_round,
                                    proposal_pol=pv.bit_array())))
                    if sent_prop:
                        await asyncio.sleep(0)  # keep the loop fair
                        continue
                    # send queue full: prs.proposal stays False, so a
                    # bare continue would spin without ever yielding
                    # (a hard event-loop livelock caught by the
                    # nemesis crash/restart scenario) — fall through
                    # to the timed sleep and let the queue drain
                await asyncio.sleep(self._sleep_s)
        except asyncio.CancelledError:
            raise
        # any other exception propagates to the supervisor, which
        # restarts this loop (bounded) and drops the peer on give-up

    def _relay_compact_maybe(self, ps: PeerState, rs) -> bool:
        """Multi-hop compact relay: we assembled the full block (from
        parts or our own reconstruct) and the peer has none of it —
        send the compact form once instead of 64 KiB parts."""
        prs = ps.prs
        if not self._peer_compact(ps.peer):
            return False
        if rs.round != 0:
            return False           # churn rounds: full parts only
        if rs.proposal_block is None or \
                not rs.proposal_block_parts.is_complete():
            return False
        if len(rs.proposal_block.data.txs) < COMPACT_MIN_TXS:
            return False           # small blocks: parts are cheaper
        if ps.compact_hr == (rs.height, rs.round):
            return False           # already offered for this round
        if prs.proposal_block_parts is not None and \
                not prs.proposal_block_parts.is_empty():
            return False           # mid-download: finish with parts
        key = (rs.height, rs.round)
        if self._compact_raw[0] != key:
            self._compact_raw = (key, encode_p2p(make_compact_block(
                rs.height, rs.round, rs.proposal_block,
                rs.proposal_block_parts.header())))
        return self._send_compact_block(ps, rs.height, rs.round,
                                        self._compact_raw[1])

    async def _gossip_catchup(self, ps: PeerState) -> bool:
        """Send a block part from the store for a lagging peer
        (reference: gossipDataForCatchup)."""
        prs = ps.prs
        if prs.proposal_block_parts is None:
            # init from stored block meta
            meta = self.cs.block_store.load_block_meta(prs.height)
            if meta is None:
                return False
            # seam: installs header + bitmap re-validating the height
            ps.init_catchup_parts(prs.height,
                                  meta.block_id.part_set_header)
            if prs.proposal_block_parts is None:
                return False
        for i in range(prs.proposal_block_parts_header.total):
            if not prs.proposal_block_parts.get_index(i):
                part = self.cs.block_store.load_block_part(
                    prs.height, i)
                if part is None:
                    return False
                if ps.peer.send(DATA_CHANNEL, encode_p2p(
                        BlockPartMessage(height=prs.height,
                                         round=prs.round, part=part))):
                    ps.set_has_proposal_block_part(
                        prs.height, prs.round, i)
                    return True
                # peer's send queue is full — let it drain
                return False
        return False

    async def _gossip_votes_routine(self, ps: PeerState) -> None:
        peer = ps.peer
        try:
            while True:
                rs = self.cs.rs
                prs = ps.prs
                # every sent-a-vote branch yields before continuing:
                # the send helpers never suspend (queue puts), so a
                # peer that keeps accepting votes would otherwise
                # busy-spin this coroutine and starve the loop — the
                # PR 1 livelock shape, resurfaced by interprocedural
                # yield-in-loop once it stopped crediting the
                # never-awaiting _gossip_votes_for_height await
                if rs.height == prs.height:
                    if await self._gossip_votes_for_height(rs, ps):
                        await asyncio.sleep(0)
                        continue
                # peer is on the previous height: send our last commit
                if (prs.height != 0 and
                        rs.height == prs.height + 1 and
                        rs.last_commit is not None):
                    if self._pick_send_vote(ps, rs.last_commit):
                        await asyncio.sleep(0)
                        continue
                # peer further behind: send precommits from stored
                # commit
                if (prs.height != 0 and
                        rs.height >= prs.height + 2 and
                        prs.height >= self.cs.block_store.base):
                    commit = self.cs.block_store.load_block_commit(
                        prs.height)
                    if isinstance(commit, AggregateCommit):
                        # aggregate chain: individual votes cannot be
                        # reconstructed — ship the aggregate itself
                        # (once per peer height, resent after a
                        # cooldown as a lost-message safety net)
                        if self._send_aggregate_commit(ps, commit):
                            await asyncio.sleep(0)
                            continue
                    elif commit is not None and \
                            self._pick_send_commit_vote(ps, commit):
                        await asyncio.sleep(0)
                        continue
                await asyncio.sleep(self._sleep_s)
        except asyncio.CancelledError:
            raise
        # crashes propagate to the supervisor (restart, then drop the
        # peer on give-up)

    async def _gossip_votes_for_height(self, rs, ps: PeerState) -> bool:
        """Reference: gossipVotesForHeight."""
        prs = ps.prs
        # peer just committed the previous height: our last commit helps
        # it finish (reference: gossipVotesForHeight lastCommit branch)
        if prs.step == STEP_NEW_HEIGHT and rs.last_commit is not None:
            if self._pick_send_vote(ps, rs.last_commit):
                return True
        if prs.proposal_pol_round != -1:
            pv = rs.votes.prevotes(prs.proposal_pol_round)
            if pv is not None and self._pick_send_vote(ps, pv):
                return True
        if prs.step <= STEP_PROPOSE and prs.round != -1 and \
                prs.round <= rs.round:
            pv = rs.votes.prevotes(prs.round)
            if pv is not None and self._pick_send_vote(ps, pv):
                return True
        if prs.step <= STEP_PREVOTE + 1 and prs.round != -1 and \
                prs.round <= rs.round:
            pv = rs.votes.prevotes(prs.round)
            if pv is not None and self._pick_send_vote(ps, pv):
                return True
        if prs.round != -1 and prs.round <= rs.round:
            pc = rs.votes.precommits(prs.round)
            if pc is not None and self._pick_send_vote(ps, pc):
                return True
        if prs.catchup_commit_round != -1:
            pc = rs.votes.precommits(prs.catchup_commit_round)
            if pc is not None and self._pick_send_vote(ps, pc):
                return True
        return False

    def _pick_send_vote(self, ps: PeerState, vote_set) -> bool:
        """Send votes the peer lacks (reference: PickSendVote).  On a
        votebatch/1 link up to ``consensus.vote_batch_max`` missing
        votes coalesce into one wire message — at 100+ validators the
        one-vote-per-message shape paid an envelope, a framing pass
        and a recv wakeup per signature (the same overhead the
        mempool's tx batching removed in PR 10)."""
        ours = vote_set.bit_array()
        theirs = ps._votes_bitarray(vote_set.height, vote_set.round,
                                    vote_set.signed_msg_type)
        if theirs is None:
            # the peer-state does not track this vote set (reference
            # PickSendVote: nil bitarray -> no pick).  Sending anyway
            # can never be marked delivered — set_has_vote's write
            # drops for untracked sets — so the same votes would
            # re-send every gossip tick forever.  Unbatched that was
            # slow waste; vote batching amplified it 16x into the
            # QA_r08 livelock (315k vote messages across 12 heights
            # saturating the core at rate 50).
            return False
        missing = ours.sub(theirs)
        idx = missing.pick_random()
        if idx is None:
            return False
        batch_max = getattr(self.cs.config, "vote_batch_max", 0) \
            if self._peer_vote_batch(ps.peer) else 1
        if batch_max <= 1:
            vote = vote_set.get_by_index(idx)
            if vote is None:
                return False
            if ps.peer.send(VOTE_CHANNEL,
                            encode_p2p(VoteMessage(vote))):
                ps.set_has_vote(vote.height, vote.round, vote.type,
                                vote.validator_index)
                return True
            return False
        # batched: start at the random pick (keeps the reference's
        # fairness under loss), then sweep the remaining missing bits
        votes = []
        for i in [idx] + [j for j in missing.true_indices()
                          if j != idx]:
            v = vote_set.get_by_index(i)
            if v is not None:
                votes.append(v)
            if len(votes) >= batch_max:
                break
        if not votes:
            return False
        if ps.peer.send(VOTE_CHANNEL,
                        encode_p2p(VoteBatchMessage(votes))):
            self.cs.metrics.vote_batches_sent.add()
            for v in votes:
                ps.set_has_vote(v.height, v.round, v.type,
                                v.validator_index)
            return True
        return False

    _AGG_COMMIT_RESEND_S = 2.0

    def _send_aggregate_commit(self, ps: PeerState, commit) -> bool:
        """Ship the stored AggregateCommit for the peer's height —
        the catchup analogue of _pick_send_commit_vote on aggregate
        chains (one message replaces the per-vote stream)."""
        prs = ps.prs
        now = time.monotonic()
        if ps.agg_commit_sent_height == prs.height and \
                now - ps.agg_commit_sent_at < self._AGG_COMMIT_RESEND_S:
            return False
        if ps.peer.send(VOTE_CHANNEL, encode_p2p(
                AggregateCommitMessage(commit))):
            ps.agg_commit_sent_height = prs.height
            ps.agg_commit_sent_at = now
            return True
        return False

    def _pick_send_commit_vote(self, ps: PeerState, commit) -> bool:
        prs = ps.prs
        ps.ensure_catchup_commit_round(
            prs.height, commit.round,
            len(commit.signatures))
        theirs = prs.catchup_commit
        if theirs is None:
            return False
        for i, sig in enumerate(commit.signatures):
            if sig.absent_flag() or theirs.get_index(i):
                continue
            vote = commit.get_vote(i)
            if ps.peer.send(VOTE_CHANNEL,
                            encode_p2p(VoteMessage(vote))):
                theirs.set_index(i, True)
                return True
        return False

    async def _query_maj23_routine(self, ps: PeerState) -> None:
        """Periodically ask the peer for votes we might be missing
        (reference: queryMaj23Routine)."""
        peer = ps.peer
        sleep_s = self.cs.config \
            .peer_query_maj23_sleep_duration_ns / 1e9
        try:
            while True:
                await asyncio.sleep(sleep_s)
                rs = self.cs.rs
                prs = ps.prs
                # wedge guard: while we sit in the commit step with an
                # incomplete block, periodically re-advertise the part
                # bitmap we ACTUALLY hold.  A part lost on a lossy
                # link after the one-shot commit-entry announcement
                # would otherwise never be re-sent (the sender's
                # bookkeeping says delivered) and this node would stay
                # wedged forever — found by the nemesis faulty-links
                # scenario.
                if rs.step == STEP_COMMIT and \
                        rs.proposal_block_parts is not None and \
                        not rs.proposal_block_parts.is_complete():
                    peer.send(STATE_CHANNEL,
                              encode_p2p(self._valid_block_msg()))
                if rs.height != prs.height or rs.votes is None:
                    continue
                for type_, vs in ((canonical.PREVOTE_TYPE,
                                   rs.votes.prevotes(prs.round)),
                                  (canonical.PRECOMMIT_TYPE,
                                   rs.votes.precommits(prs.round))):
                    if vs is None:
                        continue
                    bid, ok = vs.two_thirds_majority()
                    if ok:
                        peer.send(STATE_CHANNEL, encode_p2p(
                            VoteSetMaj23Message(
                                height=prs.height, round=prs.round,
                                type=type_, block_id=bid)))
        except asyncio.CancelledError:
            raise
        # crashes propagate to the supervisor (restart, then drop the
        # peer on give-up)
