"""Write-ahead log of consensus messages.

Reference: internal/consensus/wal.go — CRC32C + length framed records via
internal/autofile; WriteSync fsync barrier before height end;
SearchForEndHeight for replay.  Record payloads here are canonical JSON
(bytes hex-encoded) — WAL bytes are node-local, only durability and
replayability matter.
"""
from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Iterator, Optional

MAX_MSG_SIZE_BYTES = 1024 * 1024 * 2  # reference: wal.go maxMsgSizeBytes


class WALError(Exception):
    pass


class CorruptWALError(WALError):
    pass


def _frame(payload: bytes) -> bytes:
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return struct.pack(">II", crc, len(payload)) + payload


class WAL:
    """Append-only message log with explicit fsync barriers and file
    rotation.

    Rotation mirrors the reference's autofile group (internal/autofile
    group.go): when the head file exceeds head_size_limit the head is
    renamed to `<path>.NNN` and a fresh head opened; when the group
    exceeds total_size_limit the oldest rotated files are deleted.
    Replay iterates rotated files oldest-first, then the head."""

    def __init__(self, path: str,
                 head_size_limit: int = 4 * 1024 * 1024,
                 total_size_limit: int = 128 * 1024 * 1024):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._path = path
        self._head_size_limit = head_size_limit
        self._total_size_limit = total_size_limit
        self._f = self._open_head()

    def _open_head(self):
        """Open the head for append, truncating any torn tail first.
        A crash mid-write leaves a partial frame at EOF; appending
        after it would make every later (valid) frame unreachable to
        replay, which stops at the first bad frame."""
        if os.path.exists(self._path):
            with open(self._path, "rb") as f:
                data = f.read()
            good = _scan_valid_prefix(data)
            if good < len(data):
                # keep a forensics copy of the cut bytes (mirrors
                # repair_wal_file's .corrupted stash)
                with open(self._path + ".corrupted", "ab") as f:
                    f.write(data[good:])
                with open(self._path, "r+b") as f:
                    f.truncate(good)
        return open(self._path, "ab")

    @property
    def path(self) -> str:
        return self._path

    def reopen(self) -> None:
        """Re-acquire the head-file handle.  Required after
        repair_wal_file: repair may rename the head to .corrupted and
        recreate it, and an already-open append handle would keep
        writing to the renamed inode."""
        try:
            self._f.close()
        except OSError:
            pass
        self._f = self._open_head()

    def write(self, msg: dict) -> None:
        """Buffered append (reference: WAL.Write for peer messages)."""
        payload = json.dumps(msg, separators=(",", ":"),
                             sort_keys=True).encode()
        if len(payload) > MAX_MSG_SIZE_BYTES:
            raise WALError(f"msg is too big: {len(payload)} bytes")
        self._f.write(_frame(payload))
        if self._f.tell() > self._head_size_limit:
            self._rotate()

    def _rotate(self) -> None:
        """Head -> numbered group file; enforce the total size cap."""
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        existing = WAL.group_files(self._path)[:-1]   # without head
        nxt = 0
        if existing:
            nxt = int(existing[-1].rsplit(".", 1)[1]) + 1
        os.replace(self._path, f"{self._path}.{nxt:03d}")
        self._f = open(self._path, "ab")
        # prune oldest rotated files beyond the total limit
        files = WAL.group_files(self._path)[:-1]
        total = sum(os.path.getsize(f) for f in files)
        for f in files:
            if total <= self._total_size_limit:
                break
            total -= os.path.getsize(f)
            os.remove(f)

    def write_sync(self, msg: dict) -> None:
        """Append + flush + fsync (reference: WAL.WriteSync — used before
        signing our own messages and at height boundaries)."""
        self.write(msg)
        self.flush_and_sync()

    def flush_and_sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def write_end_height(self, height: int) -> None:
        """The fsync'd end-of-height barrier (reference:
        EndHeightMessage, state.go:1901-1911)."""
        self.write_sync({"type": "end_height", "height": height})

    def close(self) -> None:
        try:
            self.flush_and_sync()
        except ValueError:
            pass
        self._f.close()

    # ------------------------------------------------------------------
    @staticmethod
    def group_files(path: str) -> list[str]:
        """Rotated files (oldest first) + the head file, existing only."""
        d = os.path.dirname(path) or "."
        base = os.path.basename(path)
        rotated = []
        if os.path.isdir(d):
            for name in os.listdir(d):
                if name.startswith(base + "."):
                    suffix = name[len(base) + 1:]
                    if suffix.isdigit():
                        rotated.append(os.path.join(d, name))
        rotated.sort(key=lambda f: int(f.rsplit(".", 1)[1]))
        out = rotated
        if os.path.exists(path):
            out = rotated + [path]
        return out

    @staticmethod
    def iter_group(path: str, strict: bool = False) -> Iterator[dict]:
        """All messages across the rotated group, oldest first."""
        for f in WAL.group_files(path):
            yield from WAL.iter_messages(f, strict=strict)

    @staticmethod
    def iter_messages(path: str, strict: bool = False) -> Iterator[dict]:
        """Decode records; on a torn tail (crash mid-write) stop unless
        strict."""
        with open(path, "rb") as f:
            data = f.read()
        good = _scan_valid_prefix(data)
        pos = 0
        while pos < good:
            crc, length = struct.unpack(">II", data[pos:pos + 8])
            yield json.loads(data[pos + 8:pos + 8 + length])
            pos += 8 + length
        if good < len(data):
            # distinguish a torn tail (clean-stop unless strict) from
            # mid-file corruption (always an error)
            tail = len(data) - good
            if tail >= 8:
                crc, length = struct.unpack(">II",
                                            data[good:good + 8])
                if length <= MAX_MSG_SIZE_BYTES and \
                        len(data) - good - 8 >= length:
                    raise CorruptWALError(
                        f"crc mismatch at offset {good}")
                if length > MAX_MSG_SIZE_BYTES:
                    raise CorruptWALError(
                        f"frame too large: {length}")
            if strict:
                raise CorruptWALError("truncated frame")

    @staticmethod
    def search_for_end_height(path: str, height: int
                              ) -> Optional[list[dict]]:
        """Messages AFTER the end-height marker for `height`, or None if
        the marker is absent (reference: SearchForEndHeight)."""
        if not WAL.group_files(path):
            return None
        found = False
        out: list[dict] = []
        for msg in WAL.iter_group(path):
            if found:
                out.append(msg)
            elif msg.get("type") == "end_height" and \
                    msg.get("height") == height:
                found = True
        return out if found else None


def _scan_valid_prefix(data: bytes) -> int:
    """Byte offset of the first invalid frame (== len(data) when all
    frames are intact).  THE corruption rule — iter_messages and repair
    share it so replay and repair always agree on the cut point."""
    pos = 0
    n = len(data)
    while pos < n:
        if n - pos < 8:
            return pos
        crc, length = struct.unpack(">II", data[pos:pos + 8])
        if length > MAX_MSG_SIZE_BYTES or n - pos - 8 < length:
            return pos
        payload = data[pos + 8:pos + 8 + length]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            return pos
        pos += 8 + length
    return pos


def repair_wal_file(path: str) -> int:
    """Repair the WAL GROUP: truncate the first file containing a
    corrupt frame and drop every later file — nothing after a corrupt
    frame can be trusted as a contiguous record (reference:
    consensus/wal.go repair driven by state.go OnStart's corruption
    retry).  Corrupt content is stashed in .corrupted files.  Returns
    bytes dropped."""
    import shutil
    dropped = 0
    cut = False
    for f_path in WAL.group_files(path):
        if cut:
            dropped += os.path.getsize(f_path)
            shutil.move(f_path, f_path + ".corrupted")
            continue
        with open(f_path, "rb") as f:
            data = f.read()
        good = _scan_valid_prefix(data)
        if good < len(data):
            cut = True
            dropped += len(data) - good
            shutil.copy(f_path, f_path + ".corrupted")
            with open(f_path, "r+b") as f:
                f.truncate(good)
    # the head file must exist for reopen even if it was dropped
    if not os.path.exists(path):
        open(path, "ab").close()
    return dropped


class NilWAL:
    """No-op WAL (reference: nilWAL)."""
    path = ""

    def write(self, msg: dict) -> None:
        pass

    def write_sync(self, msg: dict) -> None:
        pass

    def flush_and_sync(self) -> None:
        pass

    def write_end_height(self, height: int) -> None:
        pass

    def close(self) -> None:
        pass
