"""The Tendermint consensus state machine and its support machinery.

Reference: internal/consensus/ — State (the algorithm), Reactor (gossip),
WAL, replay/handshake, HeightVoteSet, TimeoutTicker.
"""
