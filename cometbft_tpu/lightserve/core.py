"""Proof-serving RPC method bodies.

These are the handlers behind the ``light_block`` / ``multiproof`` /
``abci_query_batch`` routes in rpc/core.py — kept here so the proof
machinery has one home and rpc/core.py stays a thin method table.

JSON conventions follow the rest of the RPC surface: int64s as
strings, hashes hex-upper, tx bytes base64.
"""
from __future__ import annotations

import base64
import json

from ..crypto import merkle

# ABCI query path the batched+proven key lookup rides on.  Served by
# apps that maintain a provable state tree (the kvstore does); apps
# that do not simply answer it with a non-OK code and the RPC layer
# degrades to per-key queries without a proof.
MULTISTORE_PATH = "/multistore"


def _rpc_error(code: int, message: str):
    from ..rpc.server import RPCError
    return RPCError(code, message)


def parse_indices(indices) -> list[int]:
    """A comma-separated index list URI/JSON param ("0,5,17"); lists
    of ints pass through.  Empty string = empty key set."""
    if isinstance(indices, (list, tuple)):
        return [int(i) for i in indices]
    s = str(indices).strip()
    if not s:
        return []
    try:
        return [int(p) for p in s.split(",") if p.strip() != ""]
    except ValueError:
        raise _rpc_error(-32602, f"invalid indices {indices!r}")


# ---------------------------------------------------------------------------
# light_block: one response per skipping-sync hop


async def light_block(env, height) -> dict:
    """Signed header + validator set in one round trip — the unit of
    skipping verification (reference: the statesync LightBlock proto;
    the HTTP provider otherwise stitches /commit + /validators)."""
    from ..rpc import core as rpc_core
    from ..types import genesis as genesis_types
    h = rpc_core._normalize_height(env, height)
    meta = env.block_store.load_block_meta(h)
    commit = env.block_store.load_block_commit(h)
    if commit is None:
        commit = env.block_store.load_seen_commit(h)
    if meta is None or commit is None:
        raise _rpc_error(-32603, f"no light block at height {h}")
    vals = env.state_store.load_validators(h)
    if vals is None:
        raise _rpc_error(-32603, f"no validator set at height {h}")
    return {
        "height": str(h),
        "light_block": {
            "signed_header": {
                "header": rpc_core._header_json(meta.header),
                "commit": rpc_core._commit_json(commit),
            },
            "validator_set": {
                "validators": [
                    {"address": v.address.hex().upper(),
                     "pub_key": genesis_types.pub_key_to_json(v.pub_key),
                     "voting_power": str(v.voting_power),
                     "proposer_priority": str(v.proposer_priority)}
                    for v in vals.validators],
                "total": str(vals.size()),
            },
        },
    }


# ---------------------------------------------------------------------------
# multiproof: many txs of one block under one compact proof


async def tx_multiproof(env, height, indices) -> dict:
    """Compact multiproof that the txs at ``indices`` are the block's
    txs at those positions, against the header's data_hash.  A light
    client that verified the header (light_block + verify_to_height)
    checks the whole batch with Multiproof.verify over the tx digests
    (the tree's items, per types/tx.py txs_hash) — one response where
    per-tx /tx?prove=true would ship one Proof each."""
    from ..rpc import core as rpc_core
    from ..types.tx import hash_each
    h = rpc_core._normalize_height(env, height)
    block = env.block_store.load_block(h)
    if block is None:
        raise _rpc_error(-32603, f"block at height {h} not found")
    txs = block.data.txs
    idx = parse_indices(indices)
    if idx and (min(idx) < 0 or max(idx) >= len(txs)):
        raise _rpc_error(
            -32602,
            f"tx index out of range [0, {len(txs)}) at height {h}")
    # data_hash is the merkle root over per-tx sha256 digests
    # (types/tx.py txs_hash) — the digests are the tree's ITEMS, so
    # they get the usual leaf-prefix hash on the way in
    root, mp = merkle.multiproof_from_byte_slices(hash_each(txs), idx)
    return {
        "height": str(h),
        "total": str(len(txs)),
        "indices": mp.indices,
        "data_hash": root.hex().upper(),
        "txs": [base64.b64encode(txs[i]).decode() for i in mp.indices],
        "multiproof": mp.to_dict(),
    }


# ---------------------------------------------------------------------------
# abci_query_batch: many app keys per round trip


def _query_json(res) -> dict:
    return {
        "code": res.code, "log": res.log, "info": res.info,
        "index": str(res.index),
        "key": base64.b64encode(res.key).decode(),
        "value": base64.b64encode(res.value).decode(),
        "height": str(res.height), "codespace": res.codespace,
    }


def _parse_keys(data) -> list[bytes]:
    from ..rpc.core import _decode_hex_or_str
    if isinstance(data, (list, tuple)):
        return [_decode_hex_or_str(d) for d in data]
    s = str(data)
    return [_decode_hex_or_str(type(data)(p))
            for p in s.split(",") if p != ""]


async def abci_query_batch(env, path, data, height, prove) -> dict:
    """N abci_query calls in one response.  With prove=true the app is
    asked once, via MULTISTORE_PATH, for all keys plus a single
    compact multiproof over its state tree — existence for present
    keys, non-inclusion arms for absent ones — whose root is the
    app_hash committed by the header at proof.header_height (the
    statetree is the kvstore's storage engine, so the chain
    header.app_hash -> root -> key closes).  Apps without a provable
    store answer per key with proof=null."""
    from ..abci import types as abci
    from ..rpc.core import _parse_bool
    keys = _parse_keys(data)
    if not keys:
        raise _rpc_error(-32602, "no keys provided")
    try:
        h = int(height)
    except (TypeError, ValueError):
        raise _rpc_error(-32602, f"invalid height {height!r}")
    if _parse_bool(prove):
        req = json.dumps(
            {"keys": [k.hex() for k in keys]}).encode()
        res = await env.node.app_conns.query.query(abci.QueryRequest(
            data=req, path=MULTISTORE_PATH, height=h, prove=True))
        if res.code == 0 and res.value:
            return _batch_from_multistore(keys, res)
    responses = []
    for k in keys:
        res = await env.node.app_conns.query.query(abci.QueryRequest(
            data=k, path=str(path), height=h, prove=False))
        responses.append(_query_json(res))
    return {"responses": responses, "proof": None}


def _batch_from_multistore(keys: list[bytes], res) -> dict:
    """Shape the app's one-shot multistore answer: per-key responses
    (preserving request order) + the shared proof envelope."""
    st = json.loads(res.value)
    found = {bytes.fromhex(k): bytes.fromhex(v)
             for k, v in zip(st["keys"], st["values"])}
    responses = []
    for k in keys:
        v = found.get(k)
        responses.append({
            "code": 0,
            "log": "exists" if v is not None else "does not exist",
            "info": "", "index": "-1",
            "key": base64.b64encode(k).decode(),
            "value": base64.b64encode(v or b"").decode(),
            "height": str(res.height), "codespace": "",
        })
    proof = {
        "root": st["root"].upper(),
        "total": str(st["total"]),
        "indices": list(st["indices"]),
        "missing": list(st.get("missing", [])),
        "multiproof": st["multiproof"],
    }
    # statetree envelope extras: the version/header binding and the
    # self-contained leaves + non-inclusion arms clients verify with
    # verify_kv_multiproof / light.state_proof.verify_state_proof
    for field in ("version", "header_height", "keys", "values",
                  "absent"):
        if field in st:
            proof[field] = st[field]
    return {"responses": responses, "proof": proof}


def verify_kv_multiproof(proof: dict, keys_values: list,
                         absent_keys: list = (),
                         verified_header=None) -> None:
    """Client-side check of an abci_query_batch proof envelope:
    every (key, value) in ``keys_values`` exists and every key in
    ``absent_keys`` does not, at the proven version.  Pass the
    ``verified_header`` whose app_hash commits the root (its height
    must equal the proof's header_height) to chain the proof to
    consensus; without it only the envelope's own root is checked
    (membership, not commitment).  Raises ValueError on mismatch."""
    if "keys" in proof:
        from ..statetree import verify_proof_envelope
        expected_root = None
        if verified_header is not None:
            if int(proof["header_height"]) != verified_header.height:
                raise ValueError(
                    f"proof targets header height "
                    f"{proof['header_height']}, verified header is "
                    f"{verified_header.height}")
            expected_root = verified_header.app_hash
        verify_proof_envelope(proof, present=keys_values,
                              absent=absent_keys,
                              expected_root=expected_root)
        return
    # pre-statetree envelope: caller supplies the leaves in proof
    # index order; absent keys are unprovable in this format
    if absent_keys:
        raise ValueError(
            "proof envelope has no non-inclusion arms")
    mp = merkle.Multiproof.from_dict(proof["multiproof"])
    leaves = [merkle.value_op_leaf(k, v) for k, v in keys_values]
    mp.verify(bytes.fromhex(proof["root"]), leaves)
