"""lightserve: the proof-serving read path (ROADMAP item 3).

The write path (consensus) commits blocks; this package turns those
immutable artifacts into a product surface sized for millions of light
clients:

  * ``core.py``  — RPC method bodies for ``light_block`` (one response
    with everything a skipping-sync hop needs), ``multiproof`` (one
    compact proof covering many txs of a block, "Compact Merkle
    Multiproofs" in PAPERS.md), and ``abci_query_batch`` (many app keys
    per round trip, with a single state multiproof when the app can
    serve one);
  * ``cache.py`` — a height-keyed response cache: results at heights
    strictly below the chain tip are immutable, so thousands of
    concurrent light clients replaying the same sync path hit RAM, not
    the stores.

docs/light_proofs.md documents the proof formats, the skipping-sync
trust model and the cache semantics.
"""
from .cache import Metrics, ResponseCache  # noqa: F401
