"""Height-keyed RPC response cache.

Responses derived from a committed block at height h < chain tip are
immutable: the block, its commit, the light block, and any multiproof
over its txs can never change (the tip itself can — its canonical
commit may still be replaced by a later-seen one — so the tip is never
cached).  The cache is a byte-bounded LRU over the JSON-ready response
dicts the RPC handlers build, keyed by (method, height, params), with
hit/miss/eviction counters and an entry-size histogram on the node
registry so operators can size ``rpc.cache_max_bytes`` from a scrape.

Single-threaded by construction: the RPC server and every handler run
on the node's event loop, so no lock is needed (same argument as the
rest of the node's in-memory state).
"""
from __future__ import annotations

import json
from collections import OrderedDict
from typing import Optional

DEFAULT_MAX_BYTES = 32 * 1024 * 1024


class Metrics:
    """lightserve cache metric family on the node registry."""

    def __init__(self, registry):
        self.hits = registry.counter(
            "lightserve", "cache_hits_total",
            "RPC response cache hits (immutable height-keyed "
            "responses served from memory).")
        self.misses = registry.counter(
            "lightserve", "cache_misses_total",
            "RPC response cache misses (response built from the "
            "stores; cacheable ones are inserted).")
        self.evictions = registry.counter(
            "lightserve", "cache_evictions_total",
            "RPC response cache entries evicted to stay under "
            "rpc.cache_max_bytes.")
        self.entries = registry.gauge(
            "lightserve", "cache_entries",
            "RPC response cache resident entry count.")
        self.bytes = registry.gauge(
            "lightserve", "cache_bytes",
            "RPC response cache resident size in (approximate "
            "serialized) bytes.")
        self.entry_bytes = registry.histogram(
            "lightserve", "cache_entry_bytes",
            "Serialized size distribution of cached RPC responses.",
            buckets=(256, 1024, 4096, 16384, 65536, 262144, 1048576))


class ResponseCache:
    """Byte-bounded LRU of immutable RPC responses.

    ``get``/``put`` keys are (method, height, extra) where ``extra``
    is the hashable remainder of the request (e.g. the canonical
    indices of a multiproof).  ``put`` refuses heights at or above
    ``latest`` — only settled history is immutable — and refuses
    single entries larger than 1/8 of the budget so one giant block
    cannot flush the whole working set.
    """

    def __init__(self, max_bytes: int = DEFAULT_MAX_BYTES,
                 metrics: Optional[Metrics] = None):
        self.max_bytes = max_bytes
        self.metrics = metrics
        self._entries: OrderedDict[tuple, tuple[int, object]] = \
            OrderedDict()
        self._bytes = 0
        # plain counters mirror the metrics so in-process harnesses
        # (QA, tests) can read stats without scraping the registry
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # ------------------------------------------------------------------
    def get(self, method: str, height: int, extra=()) -> Optional[object]:
        key = (method, height, extra)
        entry = self._entries.get(key)
        if entry is None:
            self.misses += 1
            if self.metrics is not None:
                self.metrics.misses.add()
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        if self.metrics is not None:
            self.metrics.hits.add()
        return entry[1]

    def put(self, method: str, height: int, extra, value,
            latest_height: int) -> bool:
        """Insert iff the response is immutable (height < latest) and
        fits the budget.  Returns whether it was cached."""
        if self.max_bytes <= 0 or height >= latest_height or height < 1:
            return False
        key = (method, height, extra)
        if key in self._entries:
            return True
        try:
            size = len(json.dumps(value))
        except (TypeError, ValueError):
            return False            # non-JSON response: not ours
        if size > self.max_bytes // 8:
            return False
        self._entries[key] = (size, value)
        self._bytes += size
        if self.metrics is not None:
            self.metrics.entry_bytes.observe(size)
        while self._bytes > self.max_bytes and self._entries:
            _, (osize, _) = self._entries.popitem(last=False)
            self._bytes -= osize
            self.evictions += 1
            if self.metrics is not None:
                self.metrics.evictions.add()
        if self.metrics is not None:
            self.metrics.entries.set(len(self._entries))
            self.metrics.bytes.set(self._bytes)
        return True

    def heights(self, method: Optional[str] = None) -> set[int]:
        """Distinct heights with resident entries (optionally for one
        method).  The statetree's pruning pins these: a height the
        cache can still serve must keep its committed version so a
        follow-up prove=true query stays answerable."""
        return {h for m, h, _ in self._entries
                if method is None or m == method}

    # ------------------------------------------------------------------
    def __len__(self) -> int:
        return len(self._entries)

    @property
    def size_bytes(self) -> int:
        return self._bytes

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {
            "entries": len(self._entries),
            "bytes": self._bytes,
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "hit_rate": (self.hits / total) if total else 0.0,
        }

    def clear(self) -> None:
        self._entries.clear()
        self._bytes = 0
        if self.metrics is not None:
            self.metrics.entries.set(0)
            self.metrics.bytes.set(0)
