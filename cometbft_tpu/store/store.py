"""BlockStore: blocks by parts, commits, seen/extended commits, pruning.

Reference: store/store.go:46 (BlockStore struct + methods) and
store/db_key_layout.go.  Key layout here is the v2-style ordered layout:
a prefix byte followed by fixed-width big-endian integers, so height
ranges scan in order on any ordered-KV backend.
"""
from __future__ import annotations

import struct
import threading
from typing import Optional

from ..db import DB, Batch
from ..types.block import Block, BlockMeta
from ..types.block_id import BlockID
from ..types.commit import AggregateCommit, Commit, ExtendedCommit
from ..types.part_set import Part, PartSet
from ..wire import pb, encode, decode

_META = b"\x00"        # height -> BlockMeta
_PART = b"\x01"        # height,part -> Part
_COMMIT = b"\x02"      # height -> Commit (the +2/3 canonical commit)
_SEEN_COMMIT = b"\x03"  # height -> locally seen commit
_EXT_COMMIT = b"\x04"  # height -> ExtendedCommit
_HASH = b"\x05"        # block hash -> height
_STATE = b"\x06"       # base/height bookkeeping


def _h(height: int) -> bytes:
    return struct.pack(">q", height)


# Commit rows hold either kind: per-signature Commit proto bytes, or
# an AggregateCommit proto behind a marker prefix (0xff is an invalid
# proto tag byte — field 31 / wire type 7 — so the two encodings can
# never collide).  Local storage only; the wire forms live in
# pb.BLOCK / pb.SIGNED_HEADER optional fields.
_AGG_COMMIT_PREFIX = b"\xff\x01"


def _encode_commit_row(commit) -> bytes:
    if isinstance(commit, AggregateCommit):
        return _AGG_COMMIT_PREFIX + encode(pb.AGGREGATE_COMMIT,
                                           commit.to_proto())
    return encode(pb.COMMIT, commit.to_proto())


def _decode_commit_row(raw: bytes):
    if raw.startswith(_AGG_COMMIT_PREFIX):
        return AggregateCommit.from_proto(
            decode(pb.AGGREGATE_COMMIT,
                   raw[len(_AGG_COMMIT_PREFIX):]))
    return Commit.from_proto(decode(pb.COMMIT, raw))


def _meta_key(height: int) -> bytes:
    return _META + _h(height)


def _part_key(height: int, index: int) -> bytes:
    return _PART + _h(height) + struct.pack(">I", index)


def _commit_key(height: int) -> bytes:
    return _COMMIT + _h(height)


def _seen_commit_key(height: int) -> bytes:
    return _SEEN_COMMIT + _h(height)


def _ext_commit_key(height: int) -> bytes:
    return _EXT_COMMIT + _h(height)


def _hash_key(h: bytes) -> bytes:
    return _HASH + h


class BlockStoreError(Exception):
    pass


class BlockStore:
    """Stores the block parts, metas and commits for each height in
    [base, height]."""

    def __init__(self, db: DB):
        self._db = db
        self._lock = threading.RLock()
        raw = db.get(_STATE)
        if raw:
            self._base, self._height = struct.unpack(">qq", raw)
        else:
            self._base, self._height = 0, 0

    @property
    def base(self) -> int:
        with self._lock:
            return self._base

    @property
    def height(self) -> int:
        with self._lock:
            return self._height

    def size(self) -> int:
        with self._lock:
            return self._height - self._base + 1 if self._height else 0

    def _save_store_state(self, batch: Optional[Batch] = None) -> None:
        raw = struct.pack(">qq", self._base, self._height)
        if batch is not None:
            batch.set(_STATE, raw)
        else:
            self._db.set(_STATE, raw)

    # ------------------------------------------------------------------
    def save_block(self, block: Block, parts: PartSet,
                   seen_commit: Commit) -> None:
        """Persist block parts, meta, commits (reference: SaveBlock)."""
        self._save_block(block, parts, seen_commit, ext_commit=None)

    def save_block_with_extended_commit(
            self, block: Block, parts: PartSet,
            seen_ext_commit: ExtendedCommit) -> None:
        """Reference: SaveBlockWithExtendedCommit (store.go:625) — keeps
        extensions for height-H PrepareProposal; refuses to persist a
        commit with missing extension signatures (poison prevention)."""
        seen_ext_commit.ensure_extensions(True)
        self._save_block(block, parts, seen_ext_commit.to_commit(),
                         ext_commit=seen_ext_commit)

    def _save_block(self, block: Block, parts: PartSet,
                    seen_commit: Commit,
                    ext_commit: Optional[ExtendedCommit]) -> None:
        if block is None:
            raise BlockStoreError("cannot save nil block")
        height = block.header.height
        with self._lock:
            expected = self._height + 1 if self._height else height
            if height != expected:
                raise BlockStoreError(
                    f"cannot save block at height {height}, "
                    f"expected {expected}")
            if not parts.is_complete():
                raise BlockStoreError(
                    "cannot save block with incomplete part set")
            batch = self._db.new_batch()
            block_meta = BlockMeta(
                block_id=BlockID(hash=block.hash(),
                                 part_set_header=parts.header()),
                block_size=parts.byte_size,
                header=block.header,
                num_txs=len(block.data.txs),
            )
            batch.set(_meta_key(height),
                      encode(pb.BLOCK_META, block_meta.to_proto()))
            for i in range(parts.total):
                part = parts.get_part(i)
                batch.set(_part_key(height, i),
                          encode(pb.PART, part.to_proto()))
            if block.last_commit is not None:
                batch.set(_commit_key(height - 1),
                          _encode_commit_row(block.last_commit))
            batch.set(_seen_commit_key(height),
                      _encode_commit_row(seen_commit))
            if ext_commit is not None:
                batch.set(_ext_commit_key(height),
                          encode(pb.EXTENDED_COMMIT,
                                 ext_commit.to_proto()))
            batch.set(_hash_key(block.hash()), _h(height))
            if self._base == 0:
                self._base = height
            self._height = height
            self._save_store_state(batch)
            batch.write_sync()

    def save_seen_commit_standalone(self, commit: Commit) -> None:
        """Persist only a seen commit, without a block — the statesync
        bootstrap artifact blocksync needs to start verifying from the
        snapshot height (reference: store.go SaveSeenCommit, used by the
        statesync reactor's Bootstrap)."""
        with self._lock:
            batch = self._db.new_batch()
            batch.set(_seen_commit_key(commit.height),
                      _encode_commit_row(commit))
            # advance height so blocksync resumes AFTER the snapshot;
            # base points at the FIRST block we will actually store
            # (H+1) — advertising base=H would promise a block we can
            # never serve
            if self._height < commit.height:
                self._height = commit.height
            if self._base <= commit.height:
                self._base = commit.height + 1
            self._save_store_state(batch)
            batch.write_sync()

    # ------------------------------------------------------------------
    def load_block_meta(self, height: int) -> Optional[BlockMeta]:
        raw = self._db.get(_meta_key(height))
        if raw is None:
            return None
        return BlockMeta.from_proto(decode(pb.BLOCK_META, raw))

    def load_block_meta_by_hash(self, block_hash: bytes
                                ) -> Optional[BlockMeta]:
        raw = self._db.get(_hash_key(block_hash))
        if raw is None:
            return None
        return self.load_block_meta(struct.unpack(">q", raw)[0])

    def load_block(self, height: int) -> Optional[Block]:
        meta = self.load_block_meta(height)
        if meta is None:
            return None
        data = bytearray()
        for i in range(meta.block_id.part_set_header.total):
            part = self.load_block_part(height, i)
            if part is None:
                return None
            data += part.bytes_
        return Block.from_proto(decode(pb.BLOCK, bytes(data)))

    def load_block_by_hash(self, block_hash: bytes) -> Optional[Block]:
        raw = self._db.get(_hash_key(block_hash))
        if raw is None:
            return None
        return self.load_block(struct.unpack(">q", raw)[0])

    def load_block_part(self, height: int, index: int) -> Optional[Part]:
        raw = self._db.get(_part_key(height, index))
        if raw is None:
            return None
        return Part.from_proto(decode(pb.PART, raw))

    def load_block_commit(self, height: int
                          ) -> Commit | AggregateCommit | None:
        raw = self._db.get(_commit_key(height))
        if raw is None:
            return None
        return _decode_commit_row(raw)

    def load_seen_commit(self, height: int
                         ) -> Commit | AggregateCommit | None:
        raw = self._db.get(_seen_commit_key(height))
        if raw is None:
            return None
        return _decode_commit_row(raw)

    def load_block_ext_commit(self, height: int
                              ) -> Optional[ExtendedCommit]:
        raw = self._db.get(_ext_commit_key(height))
        if raw is None:
            return None
        return ExtendedCommit.from_proto(
            decode(pb.EXTENDED_COMMIT, raw))

    # ------------------------------------------------------------------
    def prune_blocks(self, retain_height: int) -> tuple[int, int]:
        """Remove blocks below retain_height; returns (pruned,
        new_base_of_evidence) (reference: PruneBlocks)."""
        with self._lock:
            if retain_height <= self._base:
                return 0, self._base
            if retain_height > self._height:
                raise BlockStoreError(
                    "cannot prune beyond the latest height "
                    f"{self._height}")
            pruned = 0
            batch = self._db.new_batch()
            for h in range(self._base, retain_height):
                meta = self.load_block_meta(h)
                if meta is not None:
                    batch.delete(_meta_key(h))
                    batch.delete(_hash_key(meta.block_id.hash))
                    for i in range(meta.block_id.part_set_header.total):
                        batch.delete(_part_key(h, i))
                    pruned += 1
                # _commit_key(h) holds the canonical commit FOR block h
                batch.delete(_commit_key(h))
                batch.delete(_seen_commit_key(h))
                batch.delete(_ext_commit_key(h))
            self._base = retain_height
            self._save_store_state(batch)
            batch.write()
            return pruned, self._base

    def delete_latest_block(self) -> None:
        """Rollback support: remove the highest block (reference:
        DeleteLatestBlock)."""
        with self._lock:
            h = self._height
            if h == 0:
                raise BlockStoreError("no blocks to delete")
            meta = self.load_block_meta(h)
            batch = self._db.new_batch()
            if meta is not None:
                batch.delete(_hash_key(meta.block_id.hash))
                for i in range(meta.block_id.part_set_header.total):
                    batch.delete(_part_key(h, i))
            batch.delete(_meta_key(h))
            # the canonical commit FOR h (stored when h+1 was saved, so
            # normally absent for the head; deleted defensively)
            batch.delete(_commit_key(h))
            batch.delete(_seen_commit_key(h))
            batch.delete(_ext_commit_key(h))
            self._height = h - 1
            if self._base > self._height:
                self._base = self._height
            self._save_store_state(batch)
            batch.write_sync()
