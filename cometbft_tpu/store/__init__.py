"""Block storage."""
from .store import BlockStore, BlockStoreError

__all__ = ["BlockStore", "BlockStoreError"]
