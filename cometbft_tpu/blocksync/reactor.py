"""Blocksync reactor: serve and fetch committed blocks.

Reference: internal/blocksync/reactor.go (:611) — BlocksyncChannel 0x40;
verifies the first block's commit with VerifyCommitLight using the
SECOND block's LastCommit, then ApplyBlock; switches to consensus when
caught up.
"""
from __future__ import annotations

import asyncio
from typing import Callable, Optional

from ..libs.log import Logger, new_logger
from ..p2p.conn import ChannelDescriptor
from ..p2p.switch import Peer, Reactor
from ..state.state import State as SMState
from ..types.block import Block
from ..types.block_id import BlockID
from ..types.commit import ExtendedCommit
from ..state.validation import BlockValidationError
from ..types.validation import VerificationError, verify_commit_light
from ..wire import pb, encode, decode
from ..wire.proto import F, Msg
from .pool import BlockPool

BLOCKSYNC_CHANNEL = 0x40
_STATUS_UPDATE_INTERVAL_S = 2.0
_SWITCH_TO_CONSENSUS_INTERVAL_S = 0.2

BLOCK_REQUEST = Msg("cometbft.blocksync.v2.BlockRequest",
                    F(1, "height", "int64"))
NO_BLOCK_RESPONSE = Msg("cometbft.blocksync.v2.NoBlockResponse",
                        F(1, "height", "int64"))
STATUS_REQUEST = Msg("cometbft.blocksync.v2.StatusRequest")
STATUS_RESPONSE = Msg("cometbft.blocksync.v2.StatusResponse",
                      F(1, "height", "int64"), F(2, "base", "int64"))
BLOCK_RESPONSE = Msg(
    "cometbft.blocksync.v2.BlockResponse",
    F(1, "block", "msg", msg=pb.BLOCK),
    F(2, "ext_commit", "msg", msg=pb.EXTENDED_COMMIT),
)
MESSAGE = Msg(
    "cometbft.blocksync.v2.Message",
    F(1, "block_request", "msg", msg=BLOCK_REQUEST),
    F(2, "no_block_response", "msg", msg=NO_BLOCK_RESPONSE),
    F(3, "block_response", "msg", msg=BLOCK_RESPONSE),
    F(4, "status_request", "msg", msg=STATUS_REQUEST),
    F(5, "status_response", "msg", msg=STATUS_RESPONSE),
)


class BlocksyncReactor(Reactor):
    def __init__(self, state: SMState, block_exec, block_store,
                 active: bool,
                 on_caught_up: Optional[Callable] = None,
                 logger: Optional[Logger] = None,
                 metrics=None):
        """on_caught_up(state, height) fires once when sync completes
        (the node switches to consensus there — reference:
        SwitchToConsensus)."""
        super().__init__("BLOCKSYNC")
        if logger is not None:
            self.logger = logger
        from .metrics import Metrics
        self.metrics = metrics if metrics is not None else Metrics()
        self.state = state
        self.block_exec = block_exec
        self.block_store = block_store
        self.active = active
        self.on_caught_up = on_caught_up
        self.pool: Optional[BlockPool] = None
        self._tasks: list = []   # SupervisedTask handles

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(id=BLOCKSYNC_CHANNEL, priority=5,
                                  send_queue_capacity=1000)]

    # ------------------------------------------------------------------
    async def start_sync(self) -> None:
        """Begin syncing (reference: OnStart when blocksync enabled).
        Both routines (and the pool's requester loop) are
        supervisor-owned: a crash restarts the loop instead of
        silently wedging the sync."""
        self.pool = BlockPool(
            self.block_store.height + 1
            if self.block_store.height else
            max(self.state.initial_height, 1),
            send_request=self._send_block_request,
            ban_peer=self._ban_peer,
            supervisor=self.supervisor)
        self.pool.start()
        self.metrics.syncing.set(1)
        self._tasks = [
            self.supervisor.spawn(lambda: self._sync_routine(),
                                  name="blocksync_sync",
                                  kind="blocksync_sync"),
            self.supervisor.spawn(lambda: self._status_routine(),
                                  name="blocksync_status",
                                  kind="blocksync_status"),
        ]

    async def stop_sync(self) -> None:
        if self.pool is not None:
            self.pool.stop()
        for t in self._tasks:
            t.cancel()
        self._tasks = []

    # ------------------------------------------------------------------
    async def add_peer(self, peer: Peer) -> None:
        # announce our range; ask for theirs
        peer.send(BLOCKSYNC_CHANNEL, encode(MESSAGE, {
            "status_response": {
                "height": self.block_store.height,
                "base": self.block_store.base}}))
        if self.active:
            peer.send(BLOCKSYNC_CHANNEL,
                      encode(MESSAGE, {"status_request": {}}))

    async def remove_peer(self, peer: Peer, reason: str) -> None:
        if self.pool is not None:
            self.pool.remove_peer(peer.id)

    async def receive(self, chan_id: int, peer: Peer,
                      msg_bytes: bytes) -> None:
        d = decode(MESSAGE, msg_bytes)
        if "block_request" in d:
            await self._respond_to_block_request(
                peer, d["block_request"].get("height", 0))
        elif "status_request" in d:
            peer.send(BLOCKSYNC_CHANNEL, encode(MESSAGE, {
                "status_response": {
                    "height": self.block_store.height,
                    "base": self.block_store.base}}))
        elif "status_response" in d and self.pool is not None:
            sr = d["status_response"]
            self.pool.set_peer_range(peer.id, sr.get("base", 0),
                                     sr.get("height", 0))
        elif "block_response" in d and self.pool is not None:
            br = d["block_response"]
            if br.get("block") is None:
                return
            block = Block.from_proto(br["block"])
            ec = ExtendedCommit.from_proto(br["ext_commit"]) \
                if br.get("ext_commit") is not None else None
            self.pool.add_block(peer.id, block, ec, len(msg_bytes))
        elif "no_block_response" in d:
            pass   # peer doesn't have it; timeouts handle reassignment

    async def _respond_to_block_request(self, peer: Peer,
                                        height: int) -> None:
        block = self.block_store.load_block(height)
        if block is None:
            peer.send(BLOCKSYNC_CHANNEL, encode(MESSAGE, {
                "no_block_response": {"height": height}}))
            return
        resp: dict = {"block": block.to_proto()}
        ec = self.block_store.load_block_ext_commit(height)
        if ec is not None:
            resp["ext_commit"] = ec.to_proto()
        peer.send(BLOCKSYNC_CHANNEL,
                  encode(MESSAGE, {"block_response": resp}))

    # ------------------------------------------------------------------
    def _send_block_request(self, peer_id: str, height: int) -> bool:
        peer = self.switch.peers.get(peer_id) if self.switch else None
        if peer is None:
            return False
        return peer.send(BLOCKSYNC_CHANNEL, encode(MESSAGE, {
            "block_request": {"height": height}}))

    def _ban_peer(self, peer_id: str, reason: str) -> None:
        if self.switch is None:
            return
        peer = self.switch.peers.get(peer_id)
        if peer is not None:
            # supervised one-shot teardown (AST-checked invariant)
            self.supervisor.spawn(
                lambda: self.switch.stop_peer(peer, reason),
                name=f"stop_peer:{peer_id[:12]}", kind="stop_peer")

    # ------------------------------------------------------------------
    async def _status_routine(self) -> None:
        try:
            while True:
                if self.switch is not None:
                    self.switch.broadcast(
                        BLOCKSYNC_CHANNEL,
                        encode(MESSAGE, {"status_request": {}}))
                await asyncio.sleep(_STATUS_UPDATE_INTERVAL_S)
        except asyncio.CancelledError:
            raise

    async def _sync_routine(self) -> None:
        """Verify-then-apply loop (reference: poolRoutine /
        processBlock)."""
        caught_up_since: float = 0.0
        try:
            while True:
                pool = self.pool
                if pool is None:
                    return
                # park until a block arrives / the head advances; the
                # 250ms fallback drives the caught-up grace check
                await pool.wait_apply()
                # caught up?  Require it to HOLD across more than one
                # status-broadcast round so a single early low-height
                # StatusResponse can't end the sync prematurely
                # (reference: switchToConsensusTicker + grace period).
                now = asyncio.get_running_loop().time()
                if pool.peers and pool.is_caught_up():
                    if caught_up_since == 0.0:
                        caught_up_since = now
                    elif now - caught_up_since > \
                            2 * _STATUS_UPDATE_INTERVAL_S:
                        self.logger.info(
                            "blocksync complete; switching to "
                            "consensus", height=pool.height - 1)
                        await self._finish_sync(pool)
                        return
                else:
                    caught_up_since = 0.0

                first, second, first_ext = pool.peek_two_blocks()
                if first is None or second is None:
                    continue
                first_parts = first.make_part_set()
                first_id = BlockID(hash=first.hash(),
                                   part_set_header=first_parts.header())
                try:
                    # the second block's LastCommit certifies the first
                    if second.last_commit is None:
                        raise VerificationError("missing last commit")
                    verify_commit_light(
                        self.state.chain_id, self.state.validators,
                        first_id, first.header.height,
                        second.last_commit)
                    # the commit only certifies the header hash; validate
                    # the full block (data/evidence hashes, header wiring)
                    # before persisting/executing it — reference:
                    # internal/blocksync/reactor.go:552 ValidateBlock
                    self.block_exec.validate_block(self.state, first)
                except (VerificationError, BlockValidationError) as e:
                    self.logger.error("invalid block in sync",
                                      height=first.header.height,
                                      err=str(e))
                    pool.redo_request(first.header.height, str(e))
                    pool.redo_request(first.header.height + 1, str(e))
                    continue

                seen_commit = second.last_commit
                ext_enabled = self.state.consensus_params.feature \
                    .vote_extensions_enabled(first.header.height)
                if ext_enabled:
                    if first_ext is None:
                        self.logger.error(
                            "peer sent block without extended commit "
                            "while extensions are enabled",
                            height=first.header.height)
                        pool.redo_request(first.header.height,
                                          "missing extended commit")
                        continue
                    try:
                        # reference reactor.go:565 — never persist an
                        # extended commit missing extension signatures
                        first_ext.ensure_extensions(True)
                    except Exception as e:
                        self.logger.error(
                            "peer sent extended commit with missing "
                            "extension signatures",
                            height=first.header.height, err=str(e))
                        pool.redo_request(first.header.height, str(e))
                        continue
                    self.block_store.save_block_with_extended_commit(
                        first, first_parts, first_ext)
                else:
                    self.block_store.save_block(first, first_parts,
                                                seen_commit)
                self.state = await self.block_exec.apply_verified_block(
                    self.state, first_id, first,
                    pool.max_peer_height())
                self.metrics.latest_block_height.set(
                    first.header.height)
                self.metrics.num_txs.set(len(first.data.txs))
                self.metrics.total_txs.add(len(first.data.txs))
                self.metrics.block_size_bytes.set(
                    first_parts.byte_size)
                pool.pop_request()
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self.logger.error("sync routine failed", err=str(e))
            raise

    async def _finish_sync(self, pool) -> None:
        """Hand off to consensus WITHOUT cancelling the task running
        this method — a pending self-cancellation would abort the
        switch at its first real suspension point."""
        height = pool.height - 1
        pool.stop()
        self.metrics.syncing.set(0)
        self.pool = None
        current = asyncio.current_task()
        for t in self._tasks:
            if getattr(t, "runner", t) is not current:
                t.cancel()
        self._tasks = []
        if self.on_caught_up is not None:
            await self.on_caught_up(self.state, height)
