"""Blocksync (fast sync): catch up by downloading committed blocks."""
from .reactor import BlocksyncReactor
from .pool import BlockPool

__all__ = ["BlocksyncReactor", "BlockPool"]
