"""BlockPool: parallel in-flight block requests from multiple peers.

Reference: internal/blocksync/pool.go (:888) — requester state machines
(one per in-flight height), up to 20 pending requests per peer, timeout
and ban logic, PeekTwoBlocks/PopRequest for the verify-then-apply loop.
"""
from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..libs.log import Logger, new_logger
from ..types.block import Block
from ..types.commit import ExtendedCommit

MAX_PENDING_REQUESTS_PER_PEER = 20
_REQUEST_TIMEOUT_S = 10.0
_MAX_TOTAL_REQUESTERS = 600


@dataclass
class _PoolPeer:
    peer_id: str
    base: int = 0
    height: int = 0
    num_pending: int = 0
    timeout_at: float = 0.0


@dataclass
class _Requester:
    height: int
    peer_id: str = ""
    block: Optional[Block] = None
    ext_commit: Optional[ExtendedCommit] = None
    requested_at: float = 0.0


class BlockPool:
    """send_request(peer_id, height) is the reactor's hook; the pool is
    driven by the reactor calling add_block / remove_peer /
    set_peer_range and the sync loop calling peek/pop."""

    def __init__(self, start_height: int,
                 send_request: Callable[[str, int], bool],
                 ban_peer: Callable[[str, str], None],
                 logger: Optional[Logger] = None,
                 supervisor=None):
        self.height = start_height      # next height to sync
        self._send_request = send_request
        self._ban_peer = ban_peer
        self.logger = logger if logger is not None else \
            new_logger("blockpool")
        self.peers: dict[str, _PoolPeer] = {}
        self.requesters: dict[int, _Requester] = {}
        self._supervisor = supervisor
        self._task = None   # asyncio.Task or SupervisedTask
        self.is_running = False
        # event-driven requester loop (reference: the pool blocks on
        # channel events, internal/blocksync/pool.go makeRequestersRoutine);
        # a slow fallback tick covers the time-based timeout scan
        self._wake = asyncio.Event()
        # separate wakeup for the reactor's verify-then-apply loop
        self._apply_wake = asyncio.Event()

    def _wakeup(self) -> None:
        self._wake.set()

    async def wait_apply(self, timeout: float = 0.25) -> None:
        """Park the apply loop until a block lands or the pool head
        advances (fallback tick covers the caught-up transition)."""
        try:
            await asyncio.wait_for(self._apply_wake.wait(), timeout)
        except asyncio.TimeoutError:
            pass
        self._apply_wake.clear()

    # ------------------------------------------------------------------
    def start(self) -> None:
        self.is_running = True
        if self._supervisor is not None:
            self._task = self._supervisor.spawn(
                lambda: self._make_requesters_routine(),
                name="blockpool_requesters",
                kind="blockpool_requesters")
        else:
            self._task = asyncio.get_running_loop().create_task(
                self._make_requesters_routine())

    def stop(self) -> None:
        self.is_running = False
        if self._task is not None:
            self._task.cancel()

    # ------------------------------------------------------------------
    def set_peer_range(self, peer_id: str, base: int,
                       height: int) -> None:
        """Reference: SetPeerRange — from StatusResponse."""
        p = self.peers.get(peer_id)
        if p is None:
            p = _PoolPeer(peer_id=peer_id)
            self.peers[peer_id] = p
        p.base, p.height = base, height
        self._wakeup()                    # new capacity / taller peer

    def remove_peer(self, peer_id: str) -> None:
        self.peers.pop(peer_id, None)
        for r in self.requesters.values():
            if r.peer_id == peer_id and r.block is None:
                r.peer_id = ""
                r.requested_at = 0.0
        self._wakeup()                    # orphaned requesters to reassign

    def max_peer_height(self) -> int:
        return max((p.height for p in self.peers.values()), default=0)

    def is_caught_up(self) -> bool:
        """Reference: IsCaughtUp — within one block of the best peer."""
        if not self.peers:
            return False
        return self.height >= self.max_peer_height()

    # ------------------------------------------------------------------
    def add_block(self, peer_id: str, block: Block,
                  ext_commit: Optional[ExtendedCommit],
                  block_size: int) -> None:
        """Reference: AddBlock — only accepted from the requested
        peer."""
        r = self.requesters.get(block.header.height)
        if r is None:
            return
        if r.peer_id != peer_id:
            return
        if r.block is not None:
            return
        r.block = block
        r.ext_commit = ext_commit
        p = self.peers.get(peer_id)
        if p is not None and p.num_pending > 0:
            p.num_pending -= 1
        self._wakeup()                    # freed per-peer capacity
        self._apply_wake.set()            # maybe two blocks ready now

    def redo_request(self, height: int, reason: str) -> None:
        """Block at `height` failed verification: ban the sender and
        re-request from someone else (reference: RedoRequest)."""
        r = self.requesters.get(height)
        if r is None:
            return
        if r.peer_id:
            self._ban_peer(r.peer_id, reason)
            self.remove_peer(r.peer_id)
        r.peer_id = ""
        r.block = None
        r.ext_commit = None
        r.requested_at = 0.0
        self._wakeup()

    def peek_two_blocks(self):
        """(first, second, first_ext_commit) at pool.height and +1."""
        first = self.requesters.get(self.height)
        second = self.requesters.get(self.height + 1)
        return (first.block if first else None,
                second.block if second else None,
                first.ext_commit if first else None)

    def pop_request(self) -> None:
        """First block was applied: advance (reference: PopRequest)."""
        self.requesters.pop(self.height, None)
        self.height += 1
        self._wakeup()                    # room for a new requester
        self._apply_wake.set()            # next pair may be complete

    # ------------------------------------------------------------------
    async def _make_requesters_routine(self) -> None:
        try:
            while self.is_running:
                self._retry_timeouts()
                self._spawn_requesters()
                try:
                    await asyncio.wait_for(self._wake.wait(), 0.25)
                except asyncio.TimeoutError:
                    pass                  # fallback tick: timeout scan
                self._wake.clear()
        except asyncio.CancelledError:
            raise

    def _retry_timeouts(self) -> None:
        now = time.monotonic()
        for r in self.requesters.values():
            if r.block is None and r.peer_id and \
                    now - r.requested_at > _REQUEST_TIMEOUT_S:
                self.logger.info("block request timed out",
                                 height=r.height, peer=r.peer_id[:12])
                slow = r.peer_id
                self._ban_peer(slow, "block request timed out")
                self.remove_peer(slow)

    def _spawn_requesters(self) -> None:
        max_total = min(_MAX_TOTAL_REQUESTERS,
                        len(self.peers) *
                        MAX_PENDING_REQUESTS_PER_PEER)
        next_height = self.height
        while len(self.requesters) < max_total:
            while next_height in self.requesters:
                next_height += 1
            if self.peers and \
                    next_height > self.max_peer_height():
                break
            self.requesters[next_height] = _Requester(
                height=next_height)
            next_height += 1
        # assign unassigned requesters to available peers
        for r in self.requesters.values():
            if r.block is not None or r.peer_id:
                continue
            peer = self._pick_peer(r.height)
            if peer is None:
                continue
            if self._send_request(peer.peer_id, r.height):
                r.peer_id = peer.peer_id
                r.requested_at = time.monotonic()
                peer.num_pending += 1

    def _pick_peer(self, height: int) -> Optional[_PoolPeer]:
        for p in self.peers.values():
            if p.num_pending >= MAX_PENDING_REQUESTS_PER_PEER:
                continue
            if p.base <= height <= p.height:
                return p
        return None
