"""Blocksync metrics (reference: internal/blocksync/metrics.gen.go)."""
from __future__ import annotations

from typing import Optional

from ..libs import metrics as libmetrics


class Metrics:
    def __init__(self, registry: Optional[libmetrics.Registry] = None):
        m = registry if registry is not None else libmetrics.Registry()
        self.syncing = m.gauge(
            "blocksync", "syncing",
            "Whether or not a node is block syncing. 1 if yes, 0 if "
            "no.")
        self.num_txs = m.gauge(
            "blocksync", "num_txs",
            "Number of transactions in the latest block.")
        self.total_txs = m.counter(
            "blocksync", "total_txs",
            "Total number of transactions fast-synced.")
        self.block_size_bytes = m.gauge(
            "blocksync", "block_size_bytes",
            "Size of the latest block.")
        self.latest_block_height = m.gauge(
            "blocksync", "latest_block_height",
            "The latest block height.")
