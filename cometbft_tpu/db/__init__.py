"""Embedded key-value store.

Reference: db/db.go:24 — the DB interface (Get/Has/Set/Delete/Iterator/
Batch/Compact), sole backend PebbleDB, plus the prefixdb wrapper.  The
TPU build's persistent backend is SQLite (stdlib, single-writer, WAL) —
an ordered-KV engine of the same durability class, with no native-build
dependency; MemDB backs tests and ephemeral configs.
"""
from .db import DB, Batch, DBError, MemDB, SQLiteDB, PrefixDB, new_db

__all__ = ["DB", "Batch", "DBError", "MemDB", "SQLiteDB", "PrefixDB",
           "new_db"]
