"""DB interface + MemDB + SQLiteDB + PrefixDB.

Reference: db/db.go (interface), db/pebbledb.go (persistent impl),
db/prefixdb.go (namespace wrapper).  Iteration is byte-ordered over
[start, end) like the reference's iterators.
"""
from __future__ import annotations

import abc
import bisect
import os
import sqlite3
import threading
from typing import Iterator, Optional


class DBError(Exception):
    pass


class Batch:
    """Write batch applied atomically (reference: db.Batch)."""

    def __init__(self, db: "DB"):
        self._db = db
        self._ops: list[tuple[str, bytes, Optional[bytes]]] = []
        self._written = False

    def set(self, key: bytes, value: bytes) -> None:
        self._check(key, value)
        self._ops.append(("set", bytes(key), bytes(value)))

    def delete(self, key: bytes) -> None:
        self._check(key, b"x")
        self._ops.append(("del", bytes(key), None))

    @staticmethod
    def _check(key: bytes, value: bytes) -> None:
        if key is None or len(key) == 0:
            raise DBError("key cannot be empty")
        if value is None:
            raise DBError("value cannot be nil")

    def write(self) -> None:
        if self._written:
            raise DBError("batch already written")
        self._db._apply_batch(self._ops)
        self._written = True

    def write_sync(self) -> None:
        if self._written:
            raise DBError("batch already written")
        self._db._apply_batch(self._ops, sync=True)
        self._written = True

    def close(self) -> None:
        self._ops = []


class DB(abc.ABC):
    @abc.abstractmethod
    def get(self, key: bytes) -> Optional[bytes]: ...

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    @abc.abstractmethod
    def set(self, key: bytes, value: bytes) -> None: ...

    def set_sync(self, key: bytes, value: bytes) -> None:
        self.set(key, value)

    @abc.abstractmethod
    def delete(self, key: bytes) -> None: ...

    def delete_sync(self, key: bytes) -> None:
        self.delete(key)

    @abc.abstractmethod
    def iterator(self, start: Optional[bytes] = None,
                 end: Optional[bytes] = None
                 ) -> Iterator[tuple[bytes, bytes]]:
        """Ascending byte-ordered iteration over [start, end)."""

    @abc.abstractmethod
    def reverse_iterator(self, start: Optional[bytes] = None,
                         end: Optional[bytes] = None
                         ) -> Iterator[tuple[bytes, bytes]]:
        """Descending iteration over [start, end)."""

    def new_batch(self) -> Batch:
        return Batch(self)

    @abc.abstractmethod
    def _apply_batch(self, ops, sync: bool = False) -> None: ...

    def close(self) -> None:
        pass

    def compact(self, start: Optional[bytes] = None,
                end: Optional[bytes] = None) -> None:
        pass


class MemDB(DB):
    """In-memory ordered map (reference: test/ephemeral use)."""

    def __init__(self):
        self._m: dict[bytes, bytes] = {}
        self._keys: list[bytes] = []   # sorted
        self._lock = threading.RLock()

    def get(self, key: bytes) -> Optional[bytes]:
        self._check_key(key)
        with self._lock:
            return self._m.get(bytes(key))

    def set(self, key: bytes, value: bytes) -> None:
        self._check_key(key)
        if value is None:
            raise DBError("value cannot be nil")
        k = bytes(key)
        with self._lock:
            if k not in self._m:
                bisect.insort(self._keys, k)
            self._m[k] = bytes(value)

    def delete(self, key: bytes) -> None:
        self._check_key(key)
        k = bytes(key)
        with self._lock:
            if k in self._m:
                del self._m[k]
                i = bisect.bisect_left(self._keys, k)
                if i < len(self._keys) and self._keys[i] == k:
                    self._keys.pop(i)

    @staticmethod
    def _check_key(key: bytes) -> None:
        if key is None or len(key) == 0:
            raise DBError("key cannot be empty")

    def _range_keys(self, start: Optional[bytes],
                    end: Optional[bytes]) -> list[bytes]:
        with self._lock:
            lo = bisect.bisect_left(self._keys, start) if start else 0
            hi = bisect.bisect_left(self._keys, end) if end is not None \
                else len(self._keys)
            return self._keys[lo:hi]

    def iterator(self, start=None, end=None):
        for k in self._range_keys(start, end):
            v = self._m.get(k)
            if v is not None:
                yield k, v

    def reverse_iterator(self, start=None, end=None):
        for k in reversed(self._range_keys(start, end)):
            v = self._m.get(k)
            if v is not None:
                yield k, v

    def _apply_batch(self, ops, sync: bool = False) -> None:
        with self._lock:
            for op, k, v in ops:
                if op == "set":
                    self.set(k, v)
                else:
                    self.delete(k)


class SQLiteDB(DB):
    """Persistent ordered-KV on SQLite in WAL mode.

    The reference's persistence class is PebbleDB (LSM); SQLite WAL gives
    the same crash-safe ordered-KV contract from the Python stdlib.
    """

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.RLock()
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv "
                "(k BLOB PRIMARY KEY, v BLOB NOT NULL) WITHOUT ROWID")
            self._conn.commit()

    def get(self, key: bytes) -> Optional[bytes]:
        MemDB._check_key(key)
        with self._lock:
            row = self._conn.execute(
                "SELECT v FROM kv WHERE k = ?", (bytes(key),)).fetchone()
        return row[0] if row else None

    def set(self, key: bytes, value: bytes) -> None:
        MemDB._check_key(key)
        if value is None:
            raise DBError("value cannot be nil")
        with self._lock:
            self._conn.execute(
                "INSERT INTO kv (k, v) VALUES (?, ?) "
                "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                (bytes(key), bytes(value)))
            self._conn.commit()

    def set_sync(self, key: bytes, value: bytes) -> None:
        self.set(key, value)
        with self._lock:
            self._conn.execute("PRAGMA wal_checkpoint(FULL)")

    def delete(self, key: bytes) -> None:
        MemDB._check_key(key)
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE k = ?",
                               (bytes(key),))
            self._conn.commit()

    def iterator(self, start=None, end=None):
        q, args = "SELECT k, v FROM kv", []
        conds = []
        if start:
            conds.append("k >= ?")
            args.append(bytes(start))
        if end is not None:
            conds.append("k < ?")
            args.append(bytes(end))
        if conds:
            q += " WHERE " + " AND ".join(conds)
        q += " ORDER BY k ASC"
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        yield from ((bytes(k), bytes(v)) for k, v in rows)

    def reverse_iterator(self, start=None, end=None):
        rows = list(self.iterator(start, end))
        yield from reversed(rows)

    def _apply_batch(self, ops, sync: bool = False) -> None:
        with self._lock:
            cur = self._conn.cursor()
            for op, k, v in ops:
                if op == "set":
                    cur.execute(
                        "INSERT INTO kv (k, v) VALUES (?, ?) "
                        "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                        (k, v))
                else:
                    cur.execute("DELETE FROM kv WHERE k = ?", (k,))
            self._conn.commit()
            if sync:
                self._conn.execute("PRAGMA wal_checkpoint(FULL)")

    def close(self) -> None:
        with self._lock:
            self._conn.close()

    def compact(self, start=None, end=None) -> None:
        with self._lock:
            self._conn.execute("PRAGMA incremental_vacuum")
            self._conn.commit()


class PrefixDB(DB):
    """Namespace wrapper (reference: db/prefixdb.go)."""

    def __init__(self, db: DB, prefix: bytes):
        self._db = db
        self._prefix = bytes(prefix)

    def _k(self, key: bytes) -> bytes:
        return self._prefix + key

    def get(self, key: bytes) -> Optional[bytes]:
        return self._db.get(self._k(key))

    def set(self, key: bytes, value: bytes) -> None:
        self._db.set(self._k(key), value)

    def set_sync(self, key: bytes, value: bytes) -> None:
        self._db.set_sync(self._k(key), value)

    def delete(self, key: bytes) -> None:
        self._db.delete(self._k(key))

    def iterator(self, start=None, end=None):
        p = self._prefix
        s = p + (start or b"")
        e = p + end if end is not None else _prefix_end(p)
        for k, v in self._db.iterator(s, e):
            yield k[len(p):], v

    def reverse_iterator(self, start=None, end=None):
        p = self._prefix
        s = p + (start or b"")
        e = p + end if end is not None else _prefix_end(p)
        for k, v in self._db.reverse_iterator(s, e):
            yield k[len(p):], v

    def _apply_batch(self, ops, sync: bool = False) -> None:
        self._db._apply_batch(
            [(op, self._k(k), v) for op, k, v in ops], sync)


def _prefix_end(prefix: bytes) -> Optional[bytes]:
    """Smallest byte string greater than every key with this prefix."""
    b = bytearray(prefix)
    while b:
        if b[-1] < 0xFF:
            b[-1] += 1
            return bytes(b)
        b.pop()
    return None


def new_db(name: str, backend: str = "sqlite",
           db_dir: str = ".") -> DB:
    """Reference: db.NewDB — backend registry."""
    if backend in ("memdb", "mem"):
        return MemDB()
    if backend in ("sqlite", "pebbledb", "goleveldb"):
        return SQLiteDB(os.path.join(db_dir, f"{name}.db"))
    raise DBError(f"unknown db backend {backend!r}")
