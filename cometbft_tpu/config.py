"""Node configuration tree.

Reference: config/config.go:93 — Config{Base,RPC,P2P,Mempool,StateSync,
BlockSync,Consensus,Storage,TxIndex,Instrumentation}, defaults (:111) and
TestConfig (:128).  Durations are nanoseconds (ints) for determinism.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field

_MS = 1_000_000
_S = 1_000_000_000


@dataclass
class BaseConfig:
    chain_id: str = ""
    home: str = "."
    moniker: str = "anonymous"
    proxy_app: str = "kvstore"
    abci: str = "builtin"
    db_backend: str = "sqlite"
    db_dir: str = "data"
    log_level: str = "info"
    genesis_file: str = "config/genesis.json"
    priv_validator_key_file: str = "config/priv_validator_key.json"
    priv_validator_state_file: str = "data/priv_validator_state.json"
    # when set, the node listens here for an external remote signer and
    # uses it instead of the file-backed key (reference:
    # config.Base.PrivValidatorListenAddr)
    priv_validator_laddr: str = ""
    node_key_file: str = "config/node_key.json"
    filter_peers: bool = False
    # record the ABCI call trace for the grammar checker
    # (reference: the e2e app's request recording)
    abci_grammar_trace: bool = False
    # per-call deadline for remote (socket/grpc) ABCI transports so a
    # wedged app cannot hang consensus forever; 0 disables.  The
    # consensus-path methods (FinalizeBlock, PrepareProposal, ...) get
    # 6x this budget; read-only calls retry on transient transport
    # errors up to abci_call_retries times.
    abci_call_timeout_ns: int = 20 * _S
    abci_call_retries: int = 2
    # LRU cap for the shared sig -> (addr, sign_bytes) verification
    # cache (types/signature_cache.py); unbounded growth under
    # sustained traffic was the alternative
    signature_cache_size: int = 10_000

    def path(self, rel: str) -> str:
        return rel if os.path.isabs(rel) else os.path.join(self.home, rel)


@dataclass
class RPCConfig:
    laddr: str = "tcp://127.0.0.1:26657"
    max_open_connections: int = 900
    max_subscription_clients: int = 100
    max_subscriptions_per_client: int = 5
    timeout_broadcast_tx_commit_ns: int = 10 * _S
    max_body_bytes: int = 1_000_000
    max_header_bytes: int = 1 << 20
    unsafe: bool = False      # enables dial_seeds/dial_peers/
                              # unsafe_flush_mempool (reference:
                              # config.go RPCConfig.Unsafe)
    # lightserve response cache budget: immutable height-keyed RPC
    # responses (blocks, commits, light blocks, multiproofs below the
    # tip) held in RAM; 0 disables (docs/light_proofs.md)
    cache_max_bytes: int = 32 * 1024 * 1024


@dataclass
class GRPCConfig:
    """gRPC data-companion API (reference: config.go GRPCConfig —
    grpc.laddr plus a separate privileged endpoint whose pruning
    service lets an external companion drive retain heights)."""
    laddr: str = ""                       # e.g. "tcp://127.0.0.1:26670"
    version_service_enabled: bool = True
    block_service_enabled: bool = True
    block_results_service_enabled: bool = True
    privileged_laddr: str = ""            # e.g. "tcp://127.0.0.1:26671"
    pruning_service_enabled: bool = False


@dataclass
class P2PConfig:
    laddr: str = "tcp://0.0.0.0:26656"
    external_address: str = ""
    seeds: str = ""
    persistent_peers: str = ""
    addr_book_file: str = "config/addrbook.json"
    addr_book_strict: bool = True
    max_num_inbound_peers: int = 40
    max_num_outbound_peers: int = 10
    flush_throttle_timeout_ns: int = 10 * _MS
    max_packet_msg_payload_size: int = 1024
    send_rate: int = 5_120_000
    recv_rate: int = 5_120_000
    pex: bool = True
    seed_mode: bool = False
    private_peer_ids: str = ""
    allow_duplicate_ip: bool = False
    handshake_timeout_ns: int = 20 * _S
    dial_timeout_ns: int = 3 * _S


@dataclass
class MempoolConfig:
    recheck: bool = True
    recheck_timeout_ns: int = 1 * _S
    broadcast: bool = True
    size: int = 5000
    max_txs_bytes: int = 64 * 1024 * 1024
    cache_size: int = 10000
    keep_invalid_txs_in_cache: bool = False
    max_tx_bytes: int = 1024 * 1024
    # incremental recheck: after a commit, re-run CheckTx only for
    # pooled txs whose app-reported state keys overlap the committed
    # block's keys (CheckTxResponse/ExecTxResult.recheck_keys), plus
    # any tx not revalidated within recheck_max_age_blocks heights
    # (the bounded-age watermark — the backstop when the app reports
    # no keys, and the cap on how stale any entry's validation may
    # get).  False restores the full-pool recheck.
    recheck_incremental: bool = True
    recheck_max_age_blocks: int = 12
    # CheckTx calls issued concurrently during a recheck pass (the
    # async socket client pipelines them; the local client serializes
    # on its own lock, so this only bounds gather fan-out)
    recheck_batch_size: int = 64
    # have/want set-reconciliation gossip (docs/gossip.md): instead of
    # flooding raw txs, advertise short salted tx-hash summaries
    # (TxHave), let peers pull only what they miss (TxWant -> Txs).
    # Handshake-negotiated per link ("txrecon/1"); a peer that does
    # not advertise the capability gets the flood path unchanged.
    gossip_reconciliation: bool = True
    # max short ids per TxHave/TxWant message (bounds message size:
    # 256 ids = 2 KiB of summary for up to 256 txs)
    recon_advert_max_ids: int = 256
    # how long a pulled tx may stay in flight before the want is
    # re-issued to another peer that advertised it
    recon_want_timeout_ns: int = 1 * _S
    # brand-new LOCAL txs (RPC submissions, no gossip sender) are
    # pushed in full to ~this many peers immediately so first-hop
    # latency does not pay an advertise/pull round trip; everyone
    # else learns of them via summaries
    recon_push_peers: int = 2
    # heights per reconciliation salt epoch: the short-hash salt
    # derives from the epoch index, so all nodes near the same height
    # agree on it (summaries stay comparable across peers) while
    # rotation still bounds the lifetime of any engineered collision
    recon_salt_epoch_blocks: int = 16


@dataclass
class StateSyncConfig:
    enable: bool = False
    rpc_servers: list[str] = field(default_factory=list)
    trust_height: int = 0
    trust_hash: str = ""
    trust_period_ns: int = 168 * 3600 * _S
    discovery_time_ns: int = 15 * _S
    chunk_request_timeout_ns: int = 10 * _S
    chunk_fetchers: int = 4
    temp_dir: str = ""


@dataclass
class BlockSyncConfig:
    enable: bool = True


@dataclass
class ConsensusConfig:
    wal_file: str = "data/cs.wal/wal"
    # reference: config.go:1255-1259
    timeout_propose_ns: int = 3000 * _MS
    timeout_propose_delta_ns: int = 500 * _MS
    timeout_vote_ns: int = 1000 * _MS
    timeout_vote_delta_ns: int = 500 * _MS
    timeout_commit_ns: int = 0        # deprecated; app next_block_delay
    skip_timeout_commit: bool = False
    double_sign_check_height: int = 0
    create_empty_blocks: bool = True
    create_empty_blocks_interval_ns: int = 0
    peer_gossip_sleep_duration_ns: int = 100 * _MS
    peer_query_maj23_sleep_duration_ns: int = 2 * _S
    # pipelined commit (docs/pipeline.md): run FinalizeBlock/apply/
    # app-Commit/mempool-update of height H in a supervised background
    # task while the round state advances to H+1 and keeps processing
    # proposal/vote gossip; steps that need H's applied state (our own
    # proposal, prevote validation, H+1's finalize) wait on an
    # explicit pipeline barrier.  Replay always runs serial.
    pipeline_commit: bool = True
    # adaptive timeouts (docs/pipeline.md): derive propose/vote
    # timeouts and the commit padding from an EWMA of the measured
    # p95 quorum-prevote delay instead of the static values above,
    # clamped to [floor, ceiling]; static config is the fallback
    # while no delays have been measured (fresh node, replay).
    adaptive_timeouts: bool = False
    adaptive_timeout_floor_ns: int = 200 * _MS
    adaptive_timeout_ceiling_ns: int = 10 * _S
    # compact-block proposal relay (docs/gossip.md): gossip a decided
    # proposal as header skeleton + ordered tx hashes; receivers
    # rebuild the part set from their mempool and fall back to full
    # BlockPartMessage gossip for anything they cannot resolve.
    # Handshake-negotiated per link ("compactblocks/1").
    compact_blocks: bool = True
    # after sending a peer the compact form, how long to hold off
    # pushing full parts at it (the reconstruct-or-fallback window)
    compact_block_grace_ns: int = 250 * _MS
    # coalesce up to this many missing votes per wire message on the
    # vote channel for peers that negotiated "votebatch/1"
    # (0 = always single-vote messages)
    vote_batch_max: int = 16
    # advertise "aggcommit/1" in the handshake: this build can parse
    # AggregateCommit wire arms (docs/aggregate_commits.md).  Whether
    # a chain actually USES aggregate commits is consensus-param
    # driven (feature.aggregate_commit_enable_height), not config;
    # on such a chain peers lacking the capability are refused.
    aggregate_commits_wire: bool = True

    def propose_timeout_ns(self, round_: int) -> int:
        return self.timeout_propose_ns + \
            self.timeout_propose_delta_ns * round_

    def prevote_timeout_ns(self, round_: int) -> int:
        return self.timeout_vote_ns + self.timeout_vote_delta_ns * round_

    def precommit_timeout_ns(self, round_: int) -> int:
        return self.timeout_vote_ns + self.timeout_vote_delta_ns * round_

    def wait_for_txs(self) -> bool:
        return not self.create_empty_blocks or \
            self.create_empty_blocks_interval_ns > 0


@dataclass
class StorageConfig:
    discard_abci_responses: bool = False
    pruning_interval_ns: int = 10 * _S


@dataclass
class TxIndexConfig:
    indexer: str = "kv"          # kv | psql | null
    # for the psql sink: database target (an embedded-engine path in
    # this build; reference: config.go TxIndexConfig.PsqlConn)
    psql_conn: str = ""


@dataclass
class InstrumentationConfig:
    prometheus: bool = False
    prometheus_listen_addr: str = ":26660"
    pprof_listen_addr: str = ""
    namespace: str = "cometbft"
    # flight recorder (libs/tracing.py): always-on ring-buffer span
    # tracing, dumped on supervisor give-up / nemesis safety failures
    # and served at the /trace RPC.  trace_categories is a comma list
    # ("consensus,crypto,..."); empty enables every category.
    trace_enabled: bool = True
    trace_buffer_size: int = 4096
    trace_categories: str = ""
    # where automatic flight dumps (supervisor give-up, nemesis
    # safety violations, /debug/pprof/trace?dump=1) land; empty means
    # the node's data dir (never the process CWD)
    dump_dir: str = ""
    # clock-anchor refresh cadence: how often the recorder pairs a
    # monotonic reading with wall time so tools/fleet_report.py can
    # align this node's timeline with the rest of the fleet
    trace_anchor_interval_s: float = 30.0
    # event-loop lag sampler (libs/health.py) cadence; 0 disables —
    # feeds cometbft_node_event_loop_lag_seconds and /health's p95
    loop_lag_interval_s: float = 0.25


@dataclass
class Config:
    base: BaseConfig = field(default_factory=BaseConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    grpc: GRPCConfig = field(default_factory=GRPCConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    statesync: StateSyncConfig = field(default_factory=StateSyncConfig)
    blocksync: BlockSyncConfig = field(default_factory=BlockSyncConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    tx_index: TxIndexConfig = field(default_factory=TxIndexConfig)
    instrumentation: InstrumentationConfig = field(
        default_factory=InstrumentationConfig)


class ConfigError(Exception):
    pass


def validate_basic(cfg: Config) -> None:
    """Per-section sanity checks (reference: config.go ValidateBasic on
    every sub-config, called from the root command).  Raises
    ConfigError with the offending section.key."""
    if cfg.base.db_backend not in ("memdb", "mem", "sqlite",
                                   "goleveldb", "pebbledb"):
        raise ConfigError(
            f"base.db_backend: unknown backend {cfg.base.db_backend!r}")
    if cfg.base.abci_call_timeout_ns < 0 or \
            cfg.base.abci_call_retries < 0:
        raise ConfigError(
            "base.abci_call_timeout/abci_call_retries cannot be "
            "negative")
    if cfg.rpc.max_body_bytes <= 0:
        raise ConfigError("rpc.max_body_bytes must be positive")
    if cfg.rpc.timeout_broadcast_tx_commit_ns <= 0:
        raise ConfigError(
            "rpc.timeout_broadcast_tx_commit must be positive")
    if cfg.p2p.send_rate < 0 or cfg.p2p.recv_rate < 0:
        raise ConfigError("p2p.send_rate/recv_rate cannot be negative")
    if cfg.p2p.max_num_inbound_peers < 0 or \
            cfg.p2p.max_num_outbound_peers < 0:
        raise ConfigError("p2p peer limits cannot be negative")
    if cfg.mempool.size <= 0:
        raise ConfigError("mempool.size must be positive")
    if cfg.mempool.max_tx_bytes <= 0:
        raise ConfigError("mempool.max_tx_bytes must be positive")
    if cfg.mempool.max_txs_bytes < 0:
        raise ConfigError("mempool.max_txs_bytes cannot be negative")
    if cfg.statesync.enable:
        if not cfg.statesync.rpc_servers:
            raise ConfigError(
                "statesync.rpc_servers required when statesync enabled")
        if cfg.statesync.trust_height <= 0:
            raise ConfigError(
                "statesync.trust_height required when statesync enabled")
        try:
            bytes.fromhex(cfg.statesync.trust_hash)
        except ValueError:
            raise ConfigError(
                "statesync.trust_hash must be hex") from None
        if not cfg.statesync.trust_hash:
            raise ConfigError(
                "statesync.trust_hash required when statesync enabled")
        if cfg.statesync.trust_period_ns <= 0:
            raise ConfigError("statesync.trust_period must be positive")
    for name in ("timeout_propose_ns", "timeout_propose_delta_ns",
                 "timeout_vote_ns", "timeout_vote_delta_ns",
                 "peer_gossip_sleep_duration_ns",
                 "peer_query_maj23_sleep_duration_ns"):
        if getattr(cfg.consensus, name) < 0:
            raise ConfigError(f"consensus.{name} cannot be negative")
    if cfg.consensus.create_empty_blocks_interval_ns < 0:
        raise ConfigError(
            "consensus.create_empty_blocks_interval cannot be negative")
    if cfg.consensus.adaptive_timeout_floor_ns < 0 or \
            cfg.consensus.adaptive_timeout_ceiling_ns < \
            cfg.consensus.adaptive_timeout_floor_ns:
        raise ConfigError(
            "consensus.adaptive_timeout_floor/ceiling must satisfy "
            "0 <= floor <= ceiling")
    if cfg.mempool.recheck_max_age_blocks <= 0:
        raise ConfigError(
            "mempool.recheck_max_age_blocks must be positive")
    if cfg.mempool.recheck_batch_size <= 0:
        raise ConfigError(
            "mempool.recheck_batch_size must be positive")
    if cfg.mempool.recon_advert_max_ids <= 0:
        raise ConfigError(
            "mempool.recon_advert_max_ids must be positive")
    if cfg.mempool.recon_want_timeout_ns <= 0:
        raise ConfigError(
            "mempool.recon_want_timeout must be positive")
    if cfg.mempool.recon_push_peers < 0:
        raise ConfigError(
            "mempool.recon_push_peers cannot be negative")
    if cfg.mempool.recon_salt_epoch_blocks <= 0:
        raise ConfigError(
            "mempool.recon_salt_epoch_blocks must be positive")
    if cfg.consensus.compact_block_grace_ns < 0:
        raise ConfigError(
            "consensus.compact_block_grace cannot be negative")
    if cfg.consensus.vote_batch_max < 0:
        raise ConfigError(
            "consensus.vote_batch_max cannot be negative")
    if cfg.tx_index.indexer not in ("kv", "psql", "null"):
        raise ConfigError(
            f"tx_index.indexer must be kv|psql|null, "
            f"got {cfg.tx_index.indexer!r}")
    if cfg.instrumentation.prometheus and \
            not cfg.instrumentation.prometheus_listen_addr:
        raise ConfigError(
            "instrumentation.prometheus_listen_addr required when "
            "prometheus enabled")
    if cfg.instrumentation.trace_buffer_size <= 0:
        raise ConfigError(
            "instrumentation.trace_buffer_size must be positive")
    if cfg.instrumentation.trace_anchor_interval_s <= 0:
        raise ConfigError(
            "instrumentation.trace_anchor_interval_s must be "
            "positive")
    if cfg.instrumentation.loop_lag_interval_s < 0:
        raise ConfigError(
            "instrumentation.loop_lag_interval_s cannot be negative")
    if cfg.base.signature_cache_size <= 0:
        raise ConfigError(
            "base.signature_cache_size must be positive")


def default_config() -> Config:
    return Config()


def test_config() -> Config:
    """Reference: config.go TestConfig (:128) — tight timeouts."""
    cfg = Config()
    cfg.consensus.timeout_propose_ns = 40 * _MS
    cfg.consensus.timeout_propose_delta_ns = 1 * _MS
    cfg.consensus.timeout_vote_ns = 10 * _MS
    cfg.consensus.timeout_vote_delta_ns = 1 * _MS
    cfg.consensus.timeout_commit_ns = 0
    cfg.base.db_backend = "memdb"
    return cfg
