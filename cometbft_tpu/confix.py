"""Config tooling: view / get / set / diff / migrate.

Reference: internal/confix (the `cometbft config` command group) —
upgrade a node's persisted config across versions, show effective
values, and edit keys in place.  The persisted file here is the JSON
override tree read by cmd._load_config (section -> {key: value});
this module normalizes it against the live dataclass schema.
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any, Optional

from .config import Config

# legacy-key renames across config versions (reference:
# confix/migrations — e.g. v0.34 fast_sync -> blocksync.enable,
# v0.38 timeout_prevote/timeout_precommit folded into timeout_vote)
_RENAMES: dict[tuple[str, str], tuple[str, str]] = {
    ("base", "fast_sync"): ("blocksync", "enable"),
    ("consensus", "timeout_prevote"): ("consensus", "timeout_vote_ns"),
    ("consensus", "timeout_precommit"): ("consensus",
                                         "timeout_vote_ns"),
}

# keys the reference dropped entirely (confix removes them)
_DROPPED: set[tuple[str, str]] = {
    ("mempool", "version"),
    ("blocksync", "version"),
    ("fastsync", "version"),
    ("p2p", "upnp"),
}

_DUR_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*(ns|us|ms|s|m|h)\s*$")
_DUR_NS = {"ns": 1, "us": 1_000, "ms": 1_000_000, "s": 1_000_000_000,
           "m": 60 * 1_000_000_000, "h": 3600 * 1_000_000_000}


def parse_duration_ns(v: Any) -> Optional[int]:
    """Go-style duration string ("500ms", "3s", "1h") or bare number
    of seconds -> nanoseconds; None if not a duration."""
    if isinstance(v, bool):
        return None
    if isinstance(v, (int, float)):
        return int(v * 1_000_000_000)
    if isinstance(v, str):
        m = _DUR_RE.match(v)
        if m:
            return int(float(m.group(1)) * _DUR_NS[m.group(2)])
    return None


def config_path(home: str) -> str:
    return os.path.join(home, "config", "config.json")


def load_overrides(home: str) -> dict:
    path = config_path(home)
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        return json.load(f)


def save_overrides(home: str, overrides: dict) -> None:
    path = config_path(home)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(overrides, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def apply_overrides(cfg: Config, overrides: dict) -> Config:
    """Apply a section->key override tree onto a Config (the single
    loader used by the CLI, the node, and set_value validation)."""
    for section, values in overrides.items():
        target = getattr(cfg, section, None)
        if target is None:
            continue
        for k, v in (values or {}).items():
            if hasattr(target, k):
                setattr(target, k, v)
    return cfg


def effective_config(home: str) -> Config:
    cfg = Config()
    cfg.base.home = home
    return apply_overrides(cfg, load_overrides(home))


def config_to_dict(cfg: Config) -> dict:
    return {f.name: dataclasses.asdict(getattr(cfg, f.name))
            for f in dataclasses.fields(cfg)}


def diff_from_defaults(home: str) -> dict:
    """Overrides that differ from the built-in defaults, plus entries
    the schema doesn't know (reference: confix diff)."""
    defaults = config_to_dict(Config())
    out: dict = {}
    for section, values in load_overrides(home).items():
        dsec = defaults.get(section)
        for k, v in (values or {}).items():
            if dsec is None or k not in dsec:
                out.setdefault(section, {})[k] = {
                    "value": v, "status": "unknown"}
            elif dsec[k] != v:
                out.setdefault(section, {})[k] = {
                    "value": v, "default": dsec[k],
                    "status": "changed"}
    return out


def migrate(home: str, dry_run: bool = False) -> list[str]:
    """Normalize the persisted overrides against the current schema:
    apply renames, convert duration strings to _ns integers, drop
    dead keys.  Returns a human-readable change log (reference:
    confix migrate, which rewrites the TOML through a plan)."""
    overrides = load_overrides(home)
    schema = config_to_dict(Config())
    log: list[str] = []
    # (sec, key, value, legacy?) after rename/convert; applied in two
    # passes so an EXPLICIT new-style key always beats a legacy alias
    # that maps onto it, regardless of file order
    resolved: list[tuple[str, str, Any, bool]] = []
    for section, values in overrides.items():
        for k, v in (values or {}).items():
            sec, key, legacy = section, k, False
            if (sec, key) in _DROPPED:
                log.append(f"dropped {sec}.{key} (obsolete)")
                continue
            if (sec, key) in _RENAMES:
                nsec, nkey = _RENAMES[(sec, key)]
                log.append(f"renamed {sec}.{key} -> {nsec}.{nkey}")
                sec, key, legacy = nsec, nkey, True
            dsec = schema.get(sec)
            if dsec is None:
                log.append(f"dropped {sec}.{key} (unknown section)")
                continue
            if key not in dsec:
                # a duration key may have lost its _ns suffix
                if key + "_ns" in dsec:
                    ns = parse_duration_ns(v)
                    if ns is not None:
                        log.append(
                            f"converted {sec}.{key}={v!r} -> "
                            f"{sec}.{key}_ns={ns}")
                        key, v, legacy = key + "_ns", ns, True
                    else:
                        log.append(
                            f"dropped {sec}.{key} (bad duration "
                            f"{v!r})")
                        continue
                else:
                    log.append(f"dropped {sec}.{key} (unknown key)")
                    continue
            elif key.endswith("_ns") and isinstance(v, str):
                ns = parse_duration_ns(v)
                if ns is None:
                    log.append(f"dropped {sec}.{key} (bad duration "
                               f"{v!r})")
                    continue
                log.append(f"converted {sec}.{key}={v!r} -> {ns}")
                v = ns
            resolved.append((sec, key, v, legacy))
    new: dict = {}
    for want_legacy in (False, True):
        for sec, key, v, legacy in resolved:
            if legacy != want_legacy:
                continue
            dest = new.setdefault(sec, {})
            if key in dest and dest[key] != v:
                log.append(f"conflict: kept {sec}.{key}="
                           f"{dest[key]!r}, ignored legacy value "
                           f"{v!r}")
                continue
            dest[key] = v
    if not dry_run and (log or overrides != new):
        save_overrides(home, new)
    return log


def get_value(home: str, dotted: str) -> Any:
    section, _, key = dotted.partition(".")
    cfg = effective_config(home)
    target = getattr(cfg, section, None)
    if target is None or not hasattr(target, key):
        raise KeyError(dotted)
    return getattr(target, key)


def set_value(home: str, dotted: str, raw: str) -> Any:
    """Persist one key (reference: confix set).  The value is parsed
    as JSON when possible, as a duration for _ns keys, else kept as a
    string."""
    section, _, key = dotted.partition(".")
    schema = config_to_dict(Config())
    if section not in schema or key not in schema[section]:
        raise KeyError(dotted)
    try:
        value: Any = json.loads(raw)
    except json.JSONDecodeError:
        value = raw
    if key.endswith("_ns") and isinstance(value, str):
        ns = parse_duration_ns(value)
        if ns is None:
            raise ValueError(f"{dotted}: bad duration {raw!r}")
        value = ns
    # type + semantic checks BEFORE persisting: a value the node
    # would refuse to boot with must be rejected here
    default = schema[section][key]
    if default is not None and value is not None:
        if isinstance(default, bool) != isinstance(value, bool) or \
                not isinstance(value, (type(default), int)
                               if isinstance(default, float)
                               else type(default)):
            raise ValueError(
                f"{dotted}: expected {type(default).__name__}, "
                f"got {type(value).__name__}")
    overrides = load_overrides(home)
    overrides.setdefault(section, {})[key] = value
    from .config import ConfigError, validate_basic
    try:
        validate_basic(apply_overrides(Config(), overrides))
    except ConfigError as e:
        raise ValueError(f"{dotted}: rejected by validation: {e}")
    save_overrides(home, overrides)
    return value
