"""cometbft_tpu — a TPU-native BFT consensus framework.

A ground-up rebuild of the capabilities of CometBFT (Tendermint-lineage BFT
consensus engine; reference: 0xElder/cometbft) designed TPU-first:

- The signature-verification / vote-tallying hot path (per-vote ed25519 verify
  in ``VoteSet.add_vote``, commit batch verification in
  ``types.validation.verify_commit``) runs as data-parallel JAX/XLA kernels on
  TPU — limb-decomposed Curve25519 arithmetic vectorized over thousands of
  signatures, sharded over a device mesh for very large validator sets.
- The engine around it (consensus state machine, ABCI boundary, p2p gossip,
  mempool, stores, light client, RPC, CLI) is an asyncio host runtime mirroring
  the reference's reactor architecture (reference: node/node.go,
  internal/consensus/state.go).

Layout (mirrors SURVEY.md §1 layer map):
  libs/      service lifecycle, logging, pubsub, bits       (ref: libs/, internal/)
  crypto/    keys, ed25519, merkle, tmhash, batch dispatch  (ref: crypto/)
  ops/       JAX/Pallas TPU kernels (fe25519, ed25519, sha) (ref: none — TPU-native)
  parallel/  mesh/sharding for multi-chip batch verify      (ref: none — TPU-native)
  types/     Block, Vote, Commit, ValidatorSet, VoteSet     (ref: types/)
  abci/      Application interface, clients, kvstore app    (ref: abci/, proxy/)
  consensus/ state machine, WAL, replay, reactor            (ref: internal/consensus/)
  mempool/   CList mempool with lanes, reactor              (ref: mempool/)
  p2p/       secret connection, mconn, switch, pex          (ref: p2p/)
  state/     BlockExecutor, state store, validation         (ref: state/)
  store/     block store                                    (ref: store/)
  light/     light client, verifier, detector               (ref: light/)
  rpc/       JSON-RPC server/clients, core methods          (ref: rpc/)
  node/      node assembly                                  (ref: node/)
  cmd/       CLI                                            (ref: cmd/cometbft/)
  config/    config tree + TOML                             (ref: config/)
  privval/   file signer w/ double-sign protection          (ref: privval/)
  db/        embedded KV (sqlite-backed + memdb)            (ref: db/)
"""

__version__ = "0.1.0"

# Protocol versions (reference: version/version.go:21)
BLOCK_PROTOCOL = 11
P2P_PROTOCOL = 9
ABCI_SEMVER = "2.0.0"
