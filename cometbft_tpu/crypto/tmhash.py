"""SHA-256 and the 20-byte truncated variant used for addresses.

Reference: crypto/tmhash/hash.go — Sum (32 bytes), SumTruncated (20 bytes).
"""
import hashlib

SIZE = 32
TRUNCATED_SIZE = 20


def sum(b: bytes) -> bytes:  # noqa: A001 - mirrors reference name
    return hashlib.sha256(b).digest()


def sum_truncated(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()[:TRUNCATED_SIZE]
