"""Tiled, overlapped signature-verification pipeline (CPU seam).

ROADMAP item 2b: the e2e verification path serializes host staging
with kernel execution — on the measured TPU window the 10k-sig path
was 452 ms e2e against 116 ms device-only, and on the CPU backend a
10k native batch blocks whatever thread dispatches it for ~170 ms.
This module makes verification a pipeline instead of a blocking call:

  * the batch splits into pad-bucket tiles (default 4096 lanes, the
    kernel ladder's mid bucket — small enough that one bad signature
    bisects inside its own tile, large enough that the Pippenger MSM
    keeps most of its batch efficiency);
  * each tile dispatches through the native TILE KERNEL
    (``ed25519_batch_verify_tile``: packed-blob calling convention,
    cached fe_sqr decompression, signed-digit MSM with mixed bucket
    adds — KERNEL_NOTES round 6), measured ~1.3x faster e2e than the
    monolithic dispatch at 10k signatures on the 1-vCPU rig even
    before any thread overlap;
  * tile i's kernel runs on a dedicated worker thread (the native
    entry points release the GIL) while the staging thread packs
    tile i+1's blobs, pre-decompresses its uncached pubkeys
    (``ed25519_stage_pubs``) and applies tile i-1's verdict — on a
    multi-core host the phases genuinely overlap; on the 1-vCPU QA
    rig the win is that the *event loop* is never the thread paying
    for any of it;
  * a tile that rejects bisects WITHIN the tile via the shared
    ``keys.bisect_bad`` — one bad signature re-checks O(log tile)
    subsets instead of re-verifying the whole batch;
  * ``verify_async`` hands the entire pipeline to the staging worker
    and returns an awaitable verdict future, so consensus submits a
    vote-storm burst and keeps draining gossip until the verdict
    barrier (consensus/state.py).

The phase split is observed into the same
``crypto_kernel_dispatch_seconds`` histogram the ops dispatcher uses
(kernel label "native"), so /metrics shows host_prep overlapping
kernel_execute for CPU tiles exactly as it does for TPU buckets, and
each pipeline run records its measured overlap ratio
(sum of phase durations / wall clock — > 1.0 means phases ran
concurrently).
"""
from __future__ import annotations

import os
import secrets
import struct
import time
from typing import Callable, Optional, Sequence

from ..libs import tracing
from ..libs.workers import SupervisedWorker
from .keys import bisect_bad

# ---------------------------------------------------------------------
# tile geometry

_DEFAULT_TILE = 4096


def tile_size() -> int:
    """Pipeline tile in lanes (COMETBFT_TPU_VERIFY_TILE overrides).
    4096 is a pad-bucket shape (ops/ed25519_jax._BASE_BUCKETS), so
    CPU tiles and TPU tiles label the same histogram buckets."""
    try:
        t = int(os.environ.get("COMETBFT_TPU_VERIFY_TILE",
                               str(_DEFAULT_TILE)))
    except ValueError:
        return _DEFAULT_TILE
    return t if t >= 64 else _DEFAULT_TILE


def tile_plan(n: int, tile: Optional[int] = None) -> list:
    """[(start, end), ...] covering n lanes in BALANCED slices of at
    most ``tile`` lanes: 10k at tile 4096 plans three ~3334-lane
    tiles, not 4096+4096+1808.  Balancing matters twice — the
    pipeline's overlap window is bounded by its narrowest tile, and
    the signed-digit MSM's per-tile bucket sweep amortizes best when
    no tile is small (measured ~3% fewer point adds at the 10k
    shape)."""
    t = tile or tile_size()
    if n <= 0:
        return []
    ntiles = -(-n // t)
    size = -(-n // ntiles)
    return [(s, min(s + size, n)) for s in range(0, n, size)]


# ---------------------------------------------------------------------
# workers (lazy singletons).  Two threads, each single-worker:
#   * stage  — runs whole async-submitted pipelines (and the verdict
#              barrier work), keeping the event loop out of it;
#   * kernel — runs the GIL-free kernel call of the current tile so
#              the staging side can prep the next tile concurrently.

_STAGE: Optional[SupervisedWorker] = None
_KERNEL: Optional[SupervisedWorker] = None


def _stage_worker() -> SupervisedWorker:
    global _STAGE
    if _STAGE is None:
        _STAGE = SupervisedWorker("verify_stage")
    return _STAGE


def _kernel_worker() -> SupervisedWorker:
    global _KERNEL
    if _KERNEL is None:
        _KERNEL = SupervisedWorker("verify_kernel")
    return _KERNEL


def reset_workers() -> None:
    """Test hook: stop and discard the singleton workers."""
    global _STAGE, _KERNEL
    for w in (_STAGE, _KERNEL):
        if w is not None:
            w.stop()
    _STAGE = _KERNEL = None


def submit(fn: Callable, *args):
    """Run ``fn(*args)`` on the staging worker; returns a concurrent
    Future."""
    return _stage_worker().submit(fn, *args)


def run_off_loop(fn: Callable, *args):
    """Awaitable for ``fn(*args)`` executed on the staging worker —
    the consensus/reactor seam for moving a synchronous verification
    off the event loop.  Must be awaited from a running loop."""
    import asyncio
    return asyncio.wrap_future(submit(fn, *args))


# ---------------------------------------------------------------------
# metrics

_DISPATCH_HIST = None
_OVERLAP_HIST = None
_TILE_REJECTS = None


def _dispatch_histogram():
    """The SAME family ops/ed25519_jax registers (the registry dedupes
    by name) — declared here too because this module must not import
    the jax stack to label CPU tiles."""
    global _DISPATCH_HIST
    if _DISPATCH_HIST is None:
        from ..libs import metrics as libmetrics
        _DISPATCH_HIST = libmetrics.DEFAULT.histogram(
            "crypto", "kernel_dispatch_seconds",
            "ed25519 kernel dispatch phases (host_prep / "
            "kernel_execute) in seconds, by kernel, pad bucket and "
            "warm-shape flag.",
            labels=("phase", "kernel", "pad_bucket", "warm"),
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 5.0, 30.0, 120.0))
    return _DISPATCH_HIST


def overlap_histogram():
    """Measured overlap ratio per pipeline run: (host_prep wall +
    kernel_execute wall + verdict_apply wall) / pipeline wall.  1.0 =
    fully serial; the headroom above 1.0 is the dispatch cost the
    overlap removed (2.0 = perfect two-phase overlap)."""
    global _OVERLAP_HIST
    if _OVERLAP_HIST is None:
        from ..libs import metrics as libmetrics
        _OVERLAP_HIST = libmetrics.DEFAULT.histogram(
            "crypto", "verify_overlap_ratio",
            "Per-pipeline-run overlap ratio: summed phase wall time "
            "divided by pipeline wall time (1.0 = serial, higher = "
            "phases genuinely overlapped).",
            buckets=(0.5, 0.8, 0.9, 1.0, 1.05, 1.1, 1.25, 1.5, 1.75,
                     2.0, 2.5))
    return _OVERLAP_HIST


def _tile_reject_counter():
    global _TILE_REJECTS
    if _TILE_REJECTS is None:
        from ..libs import metrics as libmetrics
        _TILE_REJECTS = libmetrics.DEFAULT.counter(
            "crypto", "verify_tile_rejects",
            "Pipeline tiles whose batch equation rejected and were "
            "bisected within the tile (per-tile attribution keeps "
            "one bad signature from re-verifying the whole batch).")
    return _TILE_REJECTS


# ---------------------------------------------------------------------
# the CPU pipeline

def _pack_tile(chunk) -> tuple:
    """(pub, msg, sig) triples -> the tile kernel's packed-blob
    layout: pubs 32n || msgs concatenated || lens u32-LE || sigs 64n.
    Four contiguous buffers replace 3n PyObject extractions per
    dispatch — this is the "sign-bytes packing" half of host_prep."""
    pubs = b"".join(it[0] for it in chunk)
    msgs = b"".join(it[1] for it in chunk)
    lens = struct.pack(f"<{len(chunk)}I",
                       *(len(it[1]) for it in chunk))
    sigs = b"".join(it[2] for it in chunk)
    return pubs, msgs, lens, sigs


def _tile_holds(native, chunk) -> bool:
    """One tile through the best available native entry: the tile
    kernel (packed blobs, signed-digit MSM, cached fe_sqr
    decompression) when this module build has it, else the legacy
    monolithic entry on the tile's items."""
    z = secrets.token_bytes(16 * len(chunk))
    if hasattr(native, "ed25519_batch_verify_tile"):
        pubs, msgs, lens, sigs = _pack_tile(chunk)
        return bool(native.ed25519_batch_verify_tile(
            pubs, msgs, lens, sigs, z))
    return bool(native.ed25519_batch_verify(list(chunk), z))


def verify_items_pipelined(
        native, items: Sequence, verify_one: Callable[[int], bool],
        tile: Optional[int] = None) -> tuple:
    """Tiled + overlapped batch verification of raw (pub, msg, sig)
    byte triples through the native tile kernel.

    Staging (blob packing, randomizer generation, pubkey decompress
    pre-staging) and the verdict apply/bisection of tile i-1 run on
    the calling thread while tile i's kernel runs GIL-free on the
    kernel worker.  A rejecting tile bisects with fresh randomizers
    via ``keys.bisect_bad`` — attribution never leaves the tile.
    ``verify_one(i)`` is the caller's exact single-signature check
    (batch-index i).

    Returns (all_ok, mask) — the BatchVerifier.Verify contract.
    """
    n = len(items)
    if n == 0:
        return True, []
    t = tile or tile_size()
    pad_bucket = str(t)
    plan = tile_plan(n, t)
    mask = [True] * n
    hist = _dispatch_histogram()
    worker = _kernel_worker()
    has_tile_kernel = hasattr(native, "ed25519_batch_verify_tile")
    can_stage = hasattr(native, "ed25519_stage_pubs")
    t_run0 = time.perf_counter()
    phase_s = 0.0

    def kernel_call(chunk, blobs, staged, z):
        k0 = tracing.now_ns()
        if blobs is not None and staged is not None:
            ok = bool(native.ed25519_batch_verify_tile(*blobs, z,
                                                       staged))
        elif blobs is not None:
            ok = bool(native.ed25519_batch_verify_tile(*blobs, z))
        else:
            ok = bool(native.ed25519_batch_verify(chunk, z))
        return ok, k0, tracing.now_ns()

    def stage(lo, hi):
        p0 = tracing.now_ns()
        chunk = list(items[lo:hi])
        z = secrets.token_bytes(16 * len(chunk))
        blobs = _pack_tile(chunk) if has_tile_kernel else None
        staged = None
        if blobs is not None and can_stage:
            # resolve this tile's A points (cache-backed decompress),
            # GIL-free: on a multi-core host this runs while the
            # PREVIOUS tile's MSM owns the kernel worker, so the
            # kernel call receives every A point pre-staged
            staged = native.ed25519_stage_pubs(blobs[0])
        p1 = tracing.now_ns()
        hist.with_labels("host_prep", "native", pad_bucket,
                         "1").observe((p1 - p0) / 1e9)
        tracing.record_span(tracing.CRYPTO, "host_prep", p0, p1,
                            batch=hi - lo, bucket=t)
        return chunk, blobs, staged, z, (p1 - p0) / 1e9

    def settle(lo, hi, chunk, fut):
        ok, k0, k1 = fut.result()
        hist.with_labels("kernel_execute", "native", pad_bucket,
                         "1").observe((k1 - k0) / 1e9)
        tracing.record_span(tracing.CRYPTO, "kernel_execute", k0, k1,
                            batch=hi - lo, bucket=t, kernel="native")
        if ok:
            return (k1 - k0) / 1e9
        # per-tile attribution: bisect INSIDE the tile with fresh
        # randomizers per subset; exact verify decides singletons
        _tile_reject_counter().add()
        a0 = time.perf_counter()
        sub = [True] * len(chunk)

        def subset_holds(idxs):
            return _tile_holds(native, [chunk[i] for i in idxs])

        bisect_bad(list(range(len(chunk))), sub, subset_holds,
                   lambda i: verify_one(lo + i))
        for i, good in enumerate(sub):
            if not good:
                mask[lo + i] = False
        return (k1 - k0) / 1e9 + (time.perf_counter() - a0)

    # software pipeline: stage tile i+1 while tile i's kernel runs on
    # the worker; settle tile i (verdict + bisection) before tile
    # i+1's verdict is needed
    inflight = None                      # (lo, hi, chunk, future)
    for lo, hi in plan:
        chunk, blobs, staged, z, prep_s = stage(lo, hi)
        phase_s += prep_s
        fut = worker.submit(kernel_call, chunk, blobs, staged, z)
        if inflight is not None:
            phase_s += settle(*inflight)
        inflight = (lo, hi, chunk, fut)
    if inflight is not None:
        phase_s += settle(*inflight)

    wall = time.perf_counter() - t_run0
    if wall > 0 and len(plan) > 1:
        overlap_histogram().observe(phase_s / wall)
    return all(mask), mask


__all__ = ["tile_size", "tile_plan", "verify_items_pipelined",
           "submit", "run_off_loop", "overlap_histogram",
           "reset_workers"]
