"""Pure-python secp256k1 ECDSA — dependency gate for the OpenSSL path.

Backs crypto/secp256k1.py and crypto/secp256k1eth.py when the
``cryptography`` package is absent (the import used to take down
crypto/encoding.py and everything above it).  Jacobian-coordinate
point arithmetic keeps sign/verify at a few ms; signatures use the
RFC 6979 deterministic nonce, which interoperates with (and is
indistinguishable on the wire from) the OpenSSL signer.

Not constant-time — acceptable for a fallback whose key types are
cold paths here (the consensus hot path is ed25519/bls12381).
"""
from __future__ import annotations

import hashlib
import hmac

P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
_GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8
G = (_GX, _GY)

# affine points are (x, y) tuples; None is the point at infinity


def _jac_double(pt):
    x, y, z = pt
    if not y:
        return (0, 0, 0)
    ysq = (y * y) % P
    s = (4 * x * ysq) % P
    m = (3 * x * x) % P          # a == 0 for secp256k1
    nx = (m * m - 2 * s) % P
    ny = (m * (s - nx) - 8 * ysq * ysq) % P
    nz = (2 * y * z) % P
    return (nx, ny, nz)


def _jac_add(p, q):
    if not p[1]:
        return q
    if not q[1]:
        return p
    u1 = (p[0] * q[2] * q[2]) % P
    u2 = (q[0] * p[2] * p[2]) % P
    s1 = (p[1] * q[2] ** 3) % P
    s2 = (q[1] * p[2] ** 3) % P
    if u1 == u2:
        if s1 != s2:
            return (0, 0, 1)     # inverse points -> infinity
        return _jac_double(p)
    h = (u2 - u1) % P
    r = (s2 - s1) % P
    h2 = (h * h) % P
    h3 = (h * h2) % P
    u1h2 = (u1 * h2) % P
    nx = (r * r - h3 - 2 * u1h2) % P
    ny = (r * (u1h2 - nx) - s1 * h3) % P
    nz = (h * p[2] * q[2]) % P
    return (nx, ny, nz)


def _jac_from_affine(pt):
    return (pt[0], pt[1], 1)


def _jac_to_affine(pt):
    x, y, z = pt
    if not y or not z:
        return None
    zinv = pow(z, P - 2, P)
    zinv2 = (zinv * zinv) % P
    return ((x * zinv2) % P, (y * zinv2 * zinv) % P)


def scalar_mult(k: int, pt) -> tuple[int, int] | None:
    """k * pt (affine in, affine out; None = infinity)."""
    if pt is None or k % N == 0:
        return None
    k %= N
    acc = (0, 0, 1)
    add = _jac_from_affine(pt)
    while k:
        if k & 1:
            acc = _jac_add(acc, add)
        add = _jac_double(add)
        k >>= 1
    return _jac_to_affine(acc)


def _mod_sqrt(a: int) -> int | None:
    """sqrt mod P (P % 4 == 3)."""
    r = pow(a, (P + 1) // 4, P)
    return r if (r * r) % P == a % P else None


def decode_point(raw: bytes) -> tuple[int, int]:
    """Parse a 33-byte compressed or 65-byte uncompressed point,
    verifying curve membership."""
    if len(raw) == 33 and raw[0] in (2, 3):
        x = int.from_bytes(raw[1:], "big")
        if x >= P:
            raise ValueError("x out of range")
        y = _mod_sqrt((pow(x, 3, P) + 7) % P)
        if y is None:
            raise ValueError("not a curve point")
        if (y & 1) != (raw[0] & 1):
            y = P - y
        return (x, y)
    if len(raw) == 65 and raw[0] == 4:
        x = int.from_bytes(raw[1:33], "big")
        y = int.from_bytes(raw[33:], "big")
        if x >= P or y >= P or (y * y - pow(x, 3, P) - 7) % P:
            raise ValueError("not a curve point")
        return (x, y)
    raise ValueError("malformed point encoding")


def encode_compressed(pt: tuple[int, int]) -> bytes:
    return bytes([2 + (pt[1] & 1)]) + pt[0].to_bytes(32, "big")


def encode_uncompressed(pt: tuple[int, int]) -> bytes:
    return b"\x04" + pt[0].to_bytes(32, "big") + \
        pt[1].to_bytes(32, "big")


def pub_point(d: int) -> tuple[int, int]:
    return scalar_mult(d, G)


# ---------------------------------------------------------------------
# ECDSA

def _rfc6979_k(d: int, digest: bytes):
    """Deterministic nonce stream (RFC 6979, SHA-256)."""
    x = d.to_bytes(32, "big")
    v = b"\x01" * 32
    k = b"\x00" * 32
    mac = lambda key, msg: hmac.new(key, msg,            # noqa: E731
                                    hashlib.sha256).digest()
    k = mac(k, v + b"\x00" + x + digest)
    v = mac(k, v)
    k = mac(k, v + b"\x01" + x + digest)
    v = mac(k, v)
    while True:
        v = mac(k, v)
        cand = int.from_bytes(v, "big")
        if 0 < cand < N:
            yield cand
        k = mac(k, v + b"\x00")
        v = mac(k, v)


def sign(d: int, digest: bytes) -> tuple[int, int]:
    """(r, s) over a 32-byte digest; the caller low-S-normalizes."""
    z = int.from_bytes(digest, "big")
    for k in _rfc6979_k(d, digest):
        pt = scalar_mult(k, G)
        r = pt[0] % N
        if not r:
            continue
        s = (pow(k, N - 2, N) * (z + r * d)) % N
        if s:
            return r, s


def verify(pub: tuple[int, int], digest: bytes, r: int,
           s: int) -> bool:
    if not (0 < r < N and 0 < s < N):
        return False
    z = int.from_bytes(digest, "big")
    w = pow(s, N - 2, N)
    u1 = (z * w) % N
    u2 = (r * w) % N
    pt = _jac_add(
        _jac_from_affine(scalar_mult(u1, G)) if u1 else (0, 0, 1),
        _jac_from_affine(scalar_mult(u2, pub)) if u2 else (0, 0, 1))
    aff = _jac_to_affine(pt)
    if aff is None:
        return False
    return aff[0] % N == r
