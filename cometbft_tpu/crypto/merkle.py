"""RFC-6962-style binary merkle tree with domain-separated hashing, proofs,
and chained proof operators.

Reference: crypto/merkle/tree.go (HashFromByteSlices, leaf/inner prefixes,
getSplitPoint), crypto/merkle/proof.go (Proof.Verify, aunts),
crypto/merkle/proof_op.go (ProofOperators for IAVL-style chained proofs).

A JAX-vectorized tree hash for large leaf counts lives in ops/merkle_jax.py.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

from .tmhash import sum as _sha256

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def leaf_hash(leaf: bytes) -> bytes:
    return _sha256(LEAF_PREFIX + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha256(INNER_PREFIX + left + right)


def empty_hash() -> bytes:
    return _sha256(b"")


def _split_point(n: int) -> int:
    """Largest power of two strictly less than n (reference: tree.go:89)."""
    if n < 1:
        raise ValueError("n must be >= 1")
    b = 1 << (n.bit_length() - 1)
    return b // 2 if b == n else b


def hash_from_byte_slices(items: Sequence[bytes]) -> bytes:
    """Merkle root of items (reference: crypto/merkle/tree.go:11).
    Large trees route through the C++ fast path when available."""
    n = len(items)
    if n >= 8:
        from ._native_loader import load
        # never compile on this path — it runs inside the consensus
        # loop; the node pre-builds at startup (prebuild_async)
        native = load(allow_build=False)
        if native is not None:
            try:
                return native.merkle_root(list(items))
            except TypeError:
                pass        # non-bytes items: python path raises too
    if n == 0:
        return empty_hash()
    if n == 1:
        return leaf_hash(items[0])
    k = _split_point(n)
    return inner_hash(hash_from_byte_slices(items[:k]),
                      hash_from_byte_slices(items[k:]))


@dataclass
class Proof:
    """Merkle inclusion proof (reference: crypto/merkle/proof.go)."""
    total: int
    index: int
    leaf_hash: bytes
    aunts: list[bytes] = field(default_factory=list)

    def verify(self, root: bytes, leaf: bytes) -> None:
        if self.total < 0:
            raise ValueError("proof total must be >= 0")
        if self.index < 0:
            raise ValueError("proof index must be >= 0")
        lh = leaf_hash(leaf)
        if lh != self.leaf_hash:
            raise ValueError("invalid leaf hash")
        computed = self.compute_root_hash()
        if computed != root:
            raise ValueError("invalid proof: root mismatch")

    def compute_root_hash(self) -> bytes:
        return _compute_from_aunts(self.index, self.total, self.leaf_hash,
                                   self.aunts)

    def to_dict(self) -> dict:
        return {"total": self.total, "index": self.index,
                "leaf_hash": self.leaf_hash.hex(),
                "aunts": [a.hex() for a in self.aunts]}

    @classmethod
    def from_dict(cls, d: dict) -> "Proof":
        return cls(total=d["total"], index=d["index"],
                   leaf_hash=bytes.fromhex(d["leaf_hash"]),
                   aunts=[bytes.fromhex(a) for a in d["aunts"]])


def _compute_from_aunts(index: int, total: int, lh: bytes,
                        aunts: Sequence[bytes]) -> bytes:
    if index >= total or index < 0 or total <= 0:
        raise ValueError("invalid index/total")
    if total == 1:
        if aunts:
            raise ValueError("unexpected aunts for single leaf")
        return lh
    if not aunts:
        raise ValueError("missing aunts")
    k = _split_point(total)
    if index < k:
        left = _compute_from_aunts(index, k, lh, aunts[:-1])
        return inner_hash(left, aunts[-1])
    right = _compute_from_aunts(index - k, total - k, lh, aunts[:-1])
    return inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: Sequence[bytes]) -> tuple[bytes, list[Proof]]:
    """Root + one inclusion proof per item (reference: proof.go:40).
    Leaf hashing is batched through the C++ fast path when available
    (part-set splitting runs this on every proposal block)."""
    from ._native_loader import batched_hashes
    hashes = batched_hashes("leaf_hashes", items)
    if hashes is None:
        hashes = [leaf_hash(it) for it in items]
    trails, root_node = _trails_from_leaf_hashes(hashes)
    root = root_node.hash if root_node else empty_hash()
    proofs = []
    for i, trail in enumerate(trails):
        proofs.append(Proof(total=len(items), index=i,
                            leaf_hash=trail.hash,
                            aunts=trail.flatten_aunts()))
    return root, proofs


class _Node:
    __slots__ = ("hash", "parent", "left", "right")

    def __init__(self, h: bytes):
        self.hash = h
        self.parent = None
        self.left = None   # sibling trail nodes, reference naming
        self.right = None

    def flatten_aunts(self) -> list[bytes]:
        aunts = []
        node = self
        while node is not None:
            if node.left is not None:
                aunts.append(node.left.hash)
            elif node.right is not None:
                aunts.append(node.right.hash)
            node = node.parent
        return aunts


def _trails_from_leaf_hashes(hashes: Sequence[bytes]):
    n = len(hashes)
    if n == 0:
        return [], None
    if n == 1:
        node = _Node(hashes[0])
        return [node], node
    k = _split_point(n)
    lefts, left_root = _trails_from_leaf_hashes(hashes[:k])
    rights, right_root = _trails_from_leaf_hashes(hashes[k:])
    root = _Node(inner_hash(left_root.hash, right_root.hash))
    left_root.parent = root
    left_root.right = right_root
    right_root.parent = root
    right_root.left = left_root
    return lefts + rights, root


# -- compact multiproofs ----------------------------------------------------
# One proof object covering many leaves of one tree, sharing the
# interior hashes every per-leaf Proof would repeat ("Compact Merkle
# Multiproofs", PAPERS.md).  Layout: the proven leaf positions
# (`indices`, canonical sorted-unique) plus the roots of every maximal
# subtree containing NO proven leaf (`aunts`), emitted in the
# deterministic left-to-right order a pre-order walk of the RFC-6962
# split-point tree visits them.  Verification replays the same walk,
# consuming leaf hashes at proven positions and aunts everywhere else,
# so builder and verifier agree on the order by construction and the
# proof needs no per-aunt position tags.


@dataclass
class Multiproof:
    """Compact inclusion proof for several leaves of one merkle tree.

    Wire parity with Proof: ints for total/indices, hex hashes in
    to_dict/from_dict.  ``verify`` takes the raw leaf values (what the
    caller fetched and wants proven) in ``indices`` order and raises
    ValueError on any mismatch, like Proof.verify."""
    total: int
    indices: list[int] = field(default_factory=list)
    aunts: list[bytes] = field(default_factory=list)

    def validate_basic(self) -> None:
        if self.total < 0:
            raise ValueError("multiproof total must be >= 0")
        prev = -1
        for i in self.indices:
            if i <= prev:
                raise ValueError(
                    "multiproof indices must be sorted and unique")
            prev = i
        if self.indices and self.indices[-1] >= self.total:
            raise ValueError("multiproof index out of range")

    def verify(self, root: bytes, leaves: Sequence[bytes]) -> None:
        """Verify ``leaves`` (raw tree ITEMS, aligned with
        ``indices``) against ``root``; each gets the RFC-6962 leaf
        prefix hash on the way in.  NOTE: for the tx tree the items
        are the per-tx sha256 digests (types/tx.py txs_hash) — pass
        the digests HERE, they are not yet leaf hashes.  Use
        verify_hashes only with true leaf-prefix hashes
        (``leaf_hash(item)``)."""
        from ._native_loader import batched_hashes
        hashes = batched_hashes("leaf_hashes", list(leaves))
        if hashes is None:
            hashes = [leaf_hash(leaf) for leaf in leaves]
        self.verify_hashes(root, hashes)

    def verify_hashes(self, root: bytes,
                      leaf_hashes: Sequence[bytes]) -> None:
        computed = self.compute_root_hash(leaf_hashes)
        if computed != root:
            raise ValueError("invalid multiproof: root mismatch")

    def compute_root_hash(self, leaf_hashes: Sequence[bytes]) -> bytes:
        self.validate_basic()
        if len(leaf_hashes) != len(self.indices):
            raise ValueError(
                f"multiproof expects {len(self.indices)} leaves, "
                f"got {len(leaf_hashes)}")
        aunts = iter(self.aunts)
        hashes = iter(leaf_hashes)
        pos = 0                       # next unconsumed index pointer

        def walk(lo: int, hi: int) -> bytes:
            nonlocal pos
            if pos >= len(self.indices) or self.indices[pos] >= hi:
                # no proven leaf in [lo, hi): one pre-supplied subtree
                # root covers the whole range
                try:
                    return next(aunts)
                except StopIteration:
                    raise ValueError(
                        "invalid multiproof: missing aunts") from None
            if hi - lo == 1:
                pos += 1
                return next(hashes)
            k = lo + _split_point(hi - lo)
            left = walk(lo, k)
            right = walk(k, hi)
            return inner_hash(left, right)

        if self.total == 0:
            if self.aunts or self.indices:
                raise ValueError(
                    "unexpected aunts/indices for empty tree")
            return empty_hash()
        out = walk(0, self.total)
        if pos != len(self.indices):
            raise ValueError("invalid multiproof: unconsumed indices")
        try:
            next(aunts)
        except StopIteration:
            return out
        raise ValueError("invalid multiproof: unconsumed aunts")

    def to_dict(self) -> dict:
        return {"total": self.total, "indices": list(self.indices),
                "aunts": [a.hex() for a in self.aunts]}

    @classmethod
    def from_dict(cls, d: dict) -> "Multiproof":
        return cls(total=d["total"], indices=list(d["indices"]),
                   aunts=[bytes.fromhex(a) for a in d["aunts"]])


def _root_from_leaf_hashes(hashes: Sequence[bytes]) -> bytes:
    if len(hashes) == 1:
        return hashes[0]
    k = _split_point(len(hashes))
    return inner_hash(_root_from_leaf_hashes(hashes[:k]),
                      _root_from_leaf_hashes(hashes[k:]))


def root_from_leaf_hashes(hashes: Sequence[bytes]) -> bytes:
    """Merkle root over pre-hashed leaves (``leaf_hash(item)`` each).
    The statetree caches kv leaf hashes across commits and recomputes
    only the changed ones, so the root builder must accept hashes
    directly rather than re-hash every item per block."""
    if not hashes:
        return empty_hash()
    return _root_from_leaf_hashes(hashes)


def multiproof_from_byte_slices(
        items: Sequence[bytes],
        indices: Sequence[int]) -> tuple[bytes, Multiproof]:
    """Root + one compact proof for the leaves at ``indices``.

    Input indices may arrive unsorted/duplicated (a batch of client
    keys); the proof carries the canonical sorted-unique form and
    callers supply leaves in that order.  Every untargeted subtree is
    hashed exactly once, so building is O(n) regardless of how many
    leaves are proven."""
    from ._native_loader import batched_hashes
    hashes = batched_hashes("leaf_hashes", items)
    if hashes is None:
        hashes = [leaf_hash(it) for it in items]
    return multiproof_from_leaf_hashes(hashes, indices)


def multiproof_from_leaf_hashes(
        hashes: Sequence[bytes],
        indices: Sequence[int]) -> tuple[bytes, Multiproof]:
    """Multiproof over pre-hashed leaves (tx digests, kv bindings)."""
    total = len(hashes)
    idx = sorted(set(indices))
    if idx and (idx[0] < 0 or idx[-1] >= total):
        raise ValueError(
            f"multiproof index out of range [0, {total})")
    if total == 0:
        return empty_hash(), Multiproof(total=0)
    aunts: list[bytes] = []
    pos = 0

    def build(lo: int, hi: int) -> bytes:
        nonlocal pos
        if pos >= len(idx) or idx[pos] >= hi:
            h = _root_from_leaf_hashes(hashes[lo:hi])
            aunts.append(h)
            return h
        if hi - lo == 1:
            pos += 1
            return hashes[lo]
        k = lo + _split_point(hi - lo)
        left = build(lo, k)
        right = build(k, hi)
        return inner_hash(left, right)

    root = build(0, total)
    return root, Multiproof(total=total, indices=idx, aunts=aunts)


# -- chained proof operators (reference: crypto/merkle/proof_op.go) ---------

def _uvarint(n: int) -> bytes:
    """Uvarint length prefix (reference: crypto/merkle/types.go:30
    encodeByteSlice)."""
    from ..wire.proto import encode_uvarint
    return encode_uvarint(n)

def value_op_leaf(key: bytes, value: bytes) -> bytes:
    """The <key, value-hash> leaf binding shared by ValueOp proofs and
    the kvstore state multiproof (reference: proof_value.go:89-102 —
    encodeByteSlice(key) + encodeByteSlice(sha256(value)))."""
    vhash = _sha256(value)
    return _uvarint(len(key)) + key + _uvarint(len(vhash)) + vhash


class ProofOperator:
    def run(self, values: list[bytes]) -> list[bytes]:
        raise NotImplementedError

    def get_key(self) -> bytes:
        raise NotImplementedError


@dataclass
class ValueOp(ProofOperator):
    """Proves leaf value inclusion under a root (reference: proof_value.go)."""
    key: bytes
    proof: Proof

    def run(self, values: list[bytes]) -> list[bytes]:
        if len(values) != 1:
            raise ValueError("ValueOp expects one value")
        lh = leaf_hash(value_op_leaf(self.key, values[0]))
        if lh != self.proof.leaf_hash:
            raise ValueError("leaf hash mismatch")
        return [self.proof.compute_root_hash()]

    def get_key(self) -> bytes:
        return self.key


class ProofOperators(list):
    def verify(self, root: bytes, keypath: Sequence[bytes],
               args: list[bytes]) -> None:
        keys = list(keypath)
        for op in self:
            key = op.get_key()
            if key:
                if not keys or keys[-1] != key:
                    raise ValueError(f"key mismatch on {key!r}")
                keys.pop()
            args = op.run(args)
        if args[0] != root:
            raise ValueError("root mismatch after proof chain")
        if keys:
            raise ValueError("unconsumed keypath")

    def verify_value(self, root: bytes, keypath: Sequence[bytes],
                     value: bytes) -> None:
        self.verify(root, keypath, [value])
