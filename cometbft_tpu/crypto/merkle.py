"""RFC-6962-style binary merkle tree with domain-separated hashing, proofs,
and chained proof operators.

Reference: crypto/merkle/tree.go (HashFromByteSlices, leaf/inner prefixes,
getSplitPoint), crypto/merkle/proof.go (Proof.Verify, aunts),
crypto/merkle/proof_op.go (ProofOperators for IAVL-style chained proofs).

A JAX-vectorized tree hash for large leaf counts lives in ops/merkle_jax.py.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

from .tmhash import sum as _sha256

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def leaf_hash(leaf: bytes) -> bytes:
    return _sha256(LEAF_PREFIX + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha256(INNER_PREFIX + left + right)


def empty_hash() -> bytes:
    return _sha256(b"")


def _split_point(n: int) -> int:
    """Largest power of two strictly less than n (reference: tree.go:89)."""
    if n < 1:
        raise ValueError("n must be >= 1")
    b = 1 << (n.bit_length() - 1)
    return b // 2 if b == n else b


def hash_from_byte_slices(items: Sequence[bytes]) -> bytes:
    """Merkle root of items (reference: crypto/merkle/tree.go:11).
    Large trees route through the C++ fast path when available."""
    n = len(items)
    if n >= 8:
        from ._native_loader import load
        # never compile on this path — it runs inside the consensus
        # loop; the node pre-builds at startup (prebuild_async)
        native = load(allow_build=False)
        if native is not None:
            try:
                return native.merkle_root(list(items))
            except TypeError:
                pass        # non-bytes items: python path raises too
    if n == 0:
        return empty_hash()
    if n == 1:
        return leaf_hash(items[0])
    k = _split_point(n)
    return inner_hash(hash_from_byte_slices(items[:k]),
                      hash_from_byte_slices(items[k:]))


@dataclass
class Proof:
    """Merkle inclusion proof (reference: crypto/merkle/proof.go)."""
    total: int
    index: int
    leaf_hash: bytes
    aunts: list[bytes] = field(default_factory=list)

    def verify(self, root: bytes, leaf: bytes) -> None:
        if self.total < 0:
            raise ValueError("proof total must be >= 0")
        if self.index < 0:
            raise ValueError("proof index must be >= 0")
        lh = leaf_hash(leaf)
        if lh != self.leaf_hash:
            raise ValueError("invalid leaf hash")
        computed = self.compute_root_hash()
        if computed != root:
            raise ValueError("invalid proof: root mismatch")

    def compute_root_hash(self) -> bytes:
        return _compute_from_aunts(self.index, self.total, self.leaf_hash,
                                   self.aunts)

    def to_dict(self) -> dict:
        return {"total": self.total, "index": self.index,
                "leaf_hash": self.leaf_hash.hex(),
                "aunts": [a.hex() for a in self.aunts]}

    @classmethod
    def from_dict(cls, d: dict) -> "Proof":
        return cls(total=d["total"], index=d["index"],
                   leaf_hash=bytes.fromhex(d["leaf_hash"]),
                   aunts=[bytes.fromhex(a) for a in d["aunts"]])


def _compute_from_aunts(index: int, total: int, lh: bytes,
                        aunts: Sequence[bytes]) -> bytes:
    if index >= total or index < 0 or total <= 0:
        raise ValueError("invalid index/total")
    if total == 1:
        if aunts:
            raise ValueError("unexpected aunts for single leaf")
        return lh
    if not aunts:
        raise ValueError("missing aunts")
    k = _split_point(total)
    if index < k:
        left = _compute_from_aunts(index, k, lh, aunts[:-1])
        return inner_hash(left, aunts[-1])
    right = _compute_from_aunts(index - k, total - k, lh, aunts[:-1])
    return inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: Sequence[bytes]) -> tuple[bytes, list[Proof]]:
    """Root + one inclusion proof per item (reference: proof.go:40).
    Leaf hashing is batched through the C++ fast path when available
    (part-set splitting runs this on every proposal block)."""
    from ._native_loader import batched_hashes
    hashes = batched_hashes("leaf_hashes", items)
    if hashes is None:
        hashes = [leaf_hash(it) for it in items]
    trails, root_node = _trails_from_leaf_hashes(hashes)
    root = root_node.hash if root_node else empty_hash()
    proofs = []
    for i, trail in enumerate(trails):
        proofs.append(Proof(total=len(items), index=i,
                            leaf_hash=trail.hash,
                            aunts=trail.flatten_aunts()))
    return root, proofs


class _Node:
    __slots__ = ("hash", "parent", "left", "right")

    def __init__(self, h: bytes):
        self.hash = h
        self.parent = None
        self.left = None   # sibling trail nodes, reference naming
        self.right = None

    def flatten_aunts(self) -> list[bytes]:
        aunts = []
        node = self
        while node is not None:
            if node.left is not None:
                aunts.append(node.left.hash)
            elif node.right is not None:
                aunts.append(node.right.hash)
            node = node.parent
        return aunts


def _trails_from_leaf_hashes(hashes: Sequence[bytes]):
    n = len(hashes)
    if n == 0:
        return [], None
    if n == 1:
        node = _Node(hashes[0])
        return [node], node
    k = _split_point(n)
    lefts, left_root = _trails_from_leaf_hashes(hashes[:k])
    rights, right_root = _trails_from_leaf_hashes(hashes[k:])
    root = _Node(inner_hash(left_root.hash, right_root.hash))
    left_root.parent = root
    left_root.right = right_root
    right_root.parent = root
    right_root.left = left_root
    return lefts + rights, root


# -- chained proof operators (reference: crypto/merkle/proof_op.go) ---------

def _uvarint(n: int) -> bytes:
    """Uvarint length prefix (reference: crypto/merkle/types.go:30
    encodeByteSlice)."""
    from ..wire.proto import encode_uvarint
    return encode_uvarint(n)

class ProofOperator:
    def run(self, values: list[bytes]) -> list[bytes]:
        raise NotImplementedError

    def get_key(self) -> bytes:
        raise NotImplementedError


@dataclass
class ValueOp(ProofOperator):
    """Proves leaf value inclusion under a root (reference: proof_value.go)."""
    key: bytes
    proof: Proof

    def run(self, values: list[bytes]) -> list[bytes]:
        if len(values) != 1:
            raise ValueError("ValueOp expects one value")
        vhash = _sha256(values[0])
        # leaf binds <key, value-hash> as length-prefixed pair
        # (reference: proof_value.go:89-102 encodeByteSlice(key)+(vhash))
        kv = _uvarint(len(self.key)) + self.key + _uvarint(len(vhash)) + vhash
        lh = leaf_hash(kv)
        if lh != self.proof.leaf_hash:
            raise ValueError("leaf hash mismatch")
        return [self.proof.compute_root_hash()]

    def get_key(self) -> bytes:
        return self.key


class ProofOperators(list):
    def verify(self, root: bytes, keypath: Sequence[bytes],
               args: list[bytes]) -> None:
        keys = list(keypath)
        for op in self:
            key = op.get_key()
            if key:
                if not keys or keys[-1] != key:
                    raise ValueError(f"key mismatch on {key!r}")
                keys.pop()
            args = op.run(args)
        if args[0] != root:
            raise ValueError("root mismatch after proof chain")
        if keys:
            raise ValueError("unconsumed keypath")

    def verify_value(self, root: bytes, keypath: Sequence[bytes],
                     value: bytes) -> None:
        self.verify(root, keypath, [value])
