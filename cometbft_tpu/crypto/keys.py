"""Key and signature interfaces.

Reference: crypto/crypto.go:23-55 — PubKey (Address/Bytes/VerifySignature/Type),
PrivKey (Bytes/Sign/PubKey/Type), BatchVerifier (Add / Verify -> (bool, []bool)).
"""
from __future__ import annotations

import abc
from typing import Sequence

from . import tmhash

# 20-byte address (truncated SHA-256 of raw pubkey bytes);
# reference: crypto/crypto.go AddressHash.
ADDRESS_SIZE = tmhash.TRUNCATED_SIZE


def address_hash(b: bytes) -> bytes:
    return tmhash.sum_truncated(b)


class PubKey(abc.ABC):
    @abc.abstractmethod
    def address(self) -> bytes: ...

    @abc.abstractmethod
    def bytes(self) -> bytes: ...

    @abc.abstractmethod
    def verify_signature(self, msg: bytes, sig: bytes) -> bool: ...

    @abc.abstractmethod
    def type(self) -> str: ...

    def __eq__(self, other) -> bool:
        return isinstance(other, PubKey) and self.type() == other.type() \
            and self.bytes() == other.bytes()

    def __hash__(self) -> int:
        return hash((self.type(), self.bytes()))

    def __repr__(self) -> str:
        return f"PubKey{{{self.type()}:{self.bytes().hex().upper()[:16]}}}"


class PrivKey(abc.ABC):
    @abc.abstractmethod
    def bytes(self) -> bytes: ...

    @abc.abstractmethod
    def sign(self, msg: bytes) -> bytes: ...

    @abc.abstractmethod
    def pub_key(self) -> PubKey: ...

    @abc.abstractmethod
    def type(self) -> str: ...


class BatchVerifier(abc.ABC):
    """Accumulate (pubkey, msg, sig) triples, then verify all at once.

    Reference: crypto/crypto.go:47-55. Verify returns (all_valid, per_sig_valid).
    """

    @abc.abstractmethod
    def add(self, pub_key: PubKey, msg: bytes, sig: bytes) -> None: ...

    @abc.abstractmethod
    def verify(self) -> tuple[bool, Sequence[bool]]: ...

    def verify_async(self):
        """Awaitable verdict future: ``verify()`` runs on the shared
        verification staging worker, so the awaiting event loop never
        pays for the batch (the native kernels release the GIL; large
        ed25519 batches additionally pipeline pad-bucket tiles inside
        ``verify()`` — crypto/pipeline.py).  Every wrapper
        (Traced/Guarded) keeps its synchronous semantics: the wrapped
        ``verify()`` is what executes on the worker.  Must be awaited
        from a running loop."""
        from .pipeline import run_off_loop
        return run_off_loop(self.verify)


def bisect_bad(idxs: list, mask: list, subset_holds, verify_one) -> None:
    """Shared batch-reject bisection (ed25519 CPU batch + BLS RLC):
    ``idxs`` is a subset whose batch equation already failed — split,
    re-check each half with ``subset_holds(half_idxs)`` (which MUST
    draw fresh randomizers per call, so a subset that only passed by
    randomizer collision upstream cannot keep passing down the
    bisection), and descend only into failing halves; k bad
    signatures cost O(k log n) subset checks instead of a whole-group
    per-signature sweep.  A failing singleton goes straight to
    ``verify_one(i)`` — running the subset equation on one item first
    would pay the full batch-check cost to learn what the exact check
    answers anyway.  ``mask[i]`` is cleared for each bad item."""
    if len(idxs) == 1:
        i = idxs[0]
        mask[i] = verify_one(i)
        return
    mid = len(idxs) // 2
    for half in (idxs[:mid], idxs[mid:]):
        if len(half) == 1 or not subset_holds(half):
            bisect_bad(half, mask, subset_holds, verify_one)
