"""Key and signature interfaces.

Reference: crypto/crypto.go:23-55 — PubKey (Address/Bytes/VerifySignature/Type),
PrivKey (Bytes/Sign/PubKey/Type), BatchVerifier (Add / Verify -> (bool, []bool)).
"""
from __future__ import annotations

import abc
from typing import Sequence

from . import tmhash

# 20-byte address (truncated SHA-256 of raw pubkey bytes);
# reference: crypto/crypto.go AddressHash.
ADDRESS_SIZE = tmhash.TRUNCATED_SIZE


def address_hash(b: bytes) -> bytes:
    return tmhash.sum_truncated(b)


class PubKey(abc.ABC):
    @abc.abstractmethod
    def address(self) -> bytes: ...

    @abc.abstractmethod
    def bytes(self) -> bytes: ...

    @abc.abstractmethod
    def verify_signature(self, msg: bytes, sig: bytes) -> bool: ...

    @abc.abstractmethod
    def type(self) -> str: ...

    def __eq__(self, other) -> bool:
        return isinstance(other, PubKey) and self.type() == other.type() \
            and self.bytes() == other.bytes()

    def __hash__(self) -> int:
        return hash((self.type(), self.bytes()))

    def __repr__(self) -> str:
        return f"PubKey{{{self.type()}:{self.bytes().hex().upper()[:16]}}}"


class PrivKey(abc.ABC):
    @abc.abstractmethod
    def bytes(self) -> bytes: ...

    @abc.abstractmethod
    def sign(self, msg: bytes) -> bytes: ...

    @abc.abstractmethod
    def pub_key(self) -> PubKey: ...

    @abc.abstractmethod
    def type(self) -> str: ...


class BatchVerifier(abc.ABC):
    """Accumulate (pubkey, msg, sig) triples, then verify all at once.

    Reference: crypto/crypto.go:47-55. Verify returns (all_valid, per_sig_valid).
    """

    @abc.abstractmethod
    def add(self, pub_key: PubKey, msg: bytes, sig: bytes) -> None: ...

    @abc.abstractmethod
    def verify(self) -> tuple[bool, Sequence[bool]]: ...
