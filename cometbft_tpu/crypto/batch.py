"""Batch-verifier dispatch: key type + configured backend -> BatchVerifier.

Reference: crypto/batch/batch.go — CreateBatchVerifier (:10),
SupportsBatchVerifier (:21); only ed25519 supports batching.

TPU-native addition: a process-global backend selector (the `crypto.backend`
config key from BASELINE.json's north star). Backends:
  * "tpu"  — JAX/XLA data-parallel verifier (ops/ed25519_jax.py); used when a
             TPU (or any JAX device) is available. Falls back to "cpu" when
             JAX import or device init fails.
  * "cpu"  — per-signature OpenSSL loop (crypto/ed25519.py).

Every verifier this module hands out also answers ``verify_async()``
(keys.BatchVerifier): an awaitable verdict future whose work runs on
the shared verification staging worker (crypto/pipeline.py) — the
Traced/Guarded wrappers keep their synchronous semantics because the
wrapped ``verify()`` is exactly what executes off-loop, and large
ed25519 CPU batches additionally pipeline pad-bucket tiles inside it
(overlapped host_prep / GIL-free kernel, per-tile reject bisection).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Optional

from ..libs import tracing
from . import ed25519
from .keys import BatchVerifier, PubKey

# ---------------------------------------------------------------------
# metrics v2: batch-verify latency distribution, labeled by backend and
# pad bucket.  Registered lazily on the process-global registry
# (libs.metrics.DEFAULT) because verifiers are created deep in the
# verification paths with no node context; the node's /metrics merges
# DEFAULT in.  The pad buckets mirror ops/ed25519_jax._BUCKETS — the
# power-of-two-ish shapes the kernel compiles once per — so CPU and
# TPU observations of the same batch size share a label value.

PAD_BUCKETS = (64, 1024, 4096, 10240, 16384)

_VERIFY_HIST = None
_PAD_BUCKET_FN = None


def register_pad_bucket_fn(fn) -> None:
    """ops/ed25519_jax registers its live _bucket on import so label
    values track measured bucket refinement (the kernel ladder can
    grow finer buckets at runtime; this module must not import the
    jax stack at process start to find out)."""
    global _PAD_BUCKET_FN
    _PAD_BUCKET_FN = fn


def pad_bucket(n: int) -> int:
    """The padded lane count a batch of n signatures dispatches at
    (mirrors ops/ed25519_jax._bucket; asserted equal in
    tests/test_metrics_contract.py)."""
    if _PAD_BUCKET_FN is not None:
        return _PAD_BUCKET_FN(n)
    for b in PAD_BUCKETS:
        if n <= b:
            return b
    return PAD_BUCKETS[-1]


def verify_seconds_histogram():
    """The process-global batch-verify latency histogram."""
    global _VERIFY_HIST
    if _VERIFY_HIST is None:
        from ..libs import metrics as libmetrics
        _VERIFY_HIST = libmetrics.DEFAULT.histogram(
            "crypto", "batch_verify_seconds",
            "Batch signature verification latency in seconds, by "
            "dispatch backend and kernel pad bucket.",
            labels=("backend", "pad_bucket"),
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5))
    return _VERIFY_HIST


def _observe_verify(backend: str, n: int, elapsed_s: float) -> None:
    verify_seconds_histogram().with_labels(
        backend, str(pad_bucket(n))).observe(elapsed_s)

_backend: Optional[str] = None
_auto_probe: Optional[str] = None   # cached auto-detection result
_probe_thread: Optional[threading.Thread] = None
_probe_result: Optional[str] = None


def _platform_probe() -> None:
    """Resolve the default JAX backend in a daemon thread: device
    init can block for minutes on a pooled/tunneled TPU, and a node
    must not hang its first CheckTx on that."""
    global _probe_result
    try:
        import jax
        _probe_result = \
            "tpu" if jax.default_backend() == "tpu" else "cpu"
    except Exception:
        _probe_result = "cpu"


def set_backend(name: str) -> None:
    """Select the batch-verification backend: 'tpu', 'cpu', or 'auto'."""
    global _backend
    if name not in ("tpu", "cpu", "auto"):
        raise ValueError(f"unknown crypto backend {name!r}")
    _backend = None if name == "auto" else name


def get_backend() -> str:
    global _auto_probe
    if _backend is not None:
        return _backend
    env = os.environ.get("COMETBFT_TPU_CRYPTO_BACKEND")
    if env:
        env = env.lower()
        if env in ("tpu", "cpu"):
            return env
        if env != "auto":
            raise ValueError(
                f"COMETBFT_TPU_CRYPTO_BACKEND={env!r}: expected tpu|cpu|auto")
    # auto: the kernel path only pays off on an actual TPU — on a
    # CPU-only box the XLA kernel is orders of magnitude slower than
    # the OpenSSL loop, so importability of jax is NOT the signal;
    # the resolved platform is
    global _auto_probe, _probe_thread
    if _auto_probe is None:
        if _probe_thread is None:
            _probe_thread = threading.Thread(
                target=_platform_probe, daemon=True)
            _probe_thread.start()
        # grace period only: the CPU path serves correctly while a
        # slow device claim resolves in the background — blocking a
        # node's first commit verification on the pool would invert
        # the probe's purpose
        _probe_thread.join(timeout=float(os.environ.get(
            "COMETBFT_TPU_PROBE_TIMEOUT", "2")))
        if _probe_result is None:
            return "cpu"    # probe unresolved; retry next call
        _auto_probe = _probe_result
    return _auto_probe


# bls12381.KEY_TYPE, spelled locally so this module does not import
# the (native-backed) bls12381 stack at process start; asserted equal
# in tests/test_batch_grouped.py
_BLS_KEY_TYPE = "bls12_381"


def supports_batch_verifier(pub_key: PubKey) -> bool:
    """ed25519 (reference: batch.go:21) and — beyond the reference,
    which drives blst strictly per-signature — bls12381 via the
    random-linear-combination pairings-product verifier."""
    return pub_key.type() in (ed25519.KEY_TYPE, _BLS_KEY_TYPE)


def batch_verify_by_type(entries) -> list:
    """Best-effort batch verification of (pub_key, msg, sig) triples
    grouped by key type.  Returns a per-entry list: True/False for
    entries a batch verifier judged, None for entries it could not
    (unsupported key type, malformed input, singleton group, verifier
    error) — callers treat None as "verify it yourself".  Never
    raises.  (types/validation.py's grouped commit path keeps its own
    walk because it must interleave caching and lowest-failing-index
    error semantics; this helper serves advisory callers like the
    vote-burst pre-verification.)"""
    out = [None] * len(entries)
    groups: dict[str, tuple] = {}
    for i, (pub_key, msg, sig) in enumerate(entries):
        try:
            if not supports_batch_verifier(pub_key):
                continue
            kt = pub_key.type()
            entry = groups.get(kt)
            if entry is None:
                entry = (create_batch_verifier(pub_key), [])
                groups[kt] = entry
            entry[0].add(pub_key, msg, sig)
            entry[1].append(i)
        except Exception:
            continue
    for bv, idxs in groups.values():
        if len(idxs) < 2:
            continue
        try:
            _, mask = bv.verify()
        except Exception:
            continue
        for i, good in zip(idxs, mask):
            out[i] = bool(good)
    return out


# --- TPU dispatch circuit breaker ------------------------------------
# A failed kernel compile/dispatch on this platform is deterministic
# per process (e.g. the Pallas TPU kernel on a GPU or unknown
# accelerator): without a breaker the dispatch re-attempted — and
# re-paid — the failed compile on EVERY batch (ADVICE r5 #1).  The
# first non-transient failure latches the breaker open and every later
# batch goes straight to the CPU verifier; transient faults (pooled
# TPU hiccups) open it for a timeout and then re-probe once.  Breaker
# state is exported on the process-global metrics registry.

_TPU_BREAKER = None


def tpu_breaker():
    """The process-global breaker guarding TPU kernel dispatch."""
    global _TPU_BREAKER
    if _TPU_BREAKER is None:
        from ..libs import metrics as libmetrics
        from ..libs.breaker import CircuitBreaker
        from ..libs.breaker import Metrics as BreakerMetrics
        _TPU_BREAKER = CircuitBreaker(
            "crypto_tpu_kernel", failure_threshold=1,
            reset_timeout_s=float(os.environ.get(
                "COMETBFT_TPU_BREAKER_RESET_S", "300")),
            metrics=BreakerMetrics(libmetrics.DEFAULT))
    return _TPU_BREAKER


def reset_tpu_breaker() -> None:
    """Test hook: discard the process-global breaker."""
    global _TPU_BREAKER
    _TPU_BREAKER = None


_TRANSIENT_MARKERS = ("timeout", "timed out", "deadline", "unavailable",
                      "resource_exhausted", "connection", "aborted")


def _is_transient_kernel_error(e: BaseException) -> bool:
    """Conservative classification: connection/timeout shapes re-probe
    after a cooldown; anything else (compile/lowering/platform errors)
    is deterministic for this process and latches the breaker."""
    if isinstance(e, (TimeoutError, ConnectionError)):
        return True
    s = f"{type(e).__name__}: {e}".lower()
    return any(m in s for m in _TRANSIENT_MARKERS)


class GuardedTpuBatchVerifier(BatchVerifier):
    """TPU batch verifier behind the process-global circuit breaker.

    verify() attempts the JAX/XLA kernel only while the breaker
    admits it; a dispatch failure records against the breaker (latched
    open for non-transient faults, so the failing kernel is attempted
    at most once per process) and the SAME batch falls back to the CPU
    verifier — callers always get a verdict."""

    def __init__(self, breaker=None):
        self._breaker = breaker if breaker is not None else tpu_breaker()
        self._items: list[tuple[PubKey, bytes, bytes]] = []

    def add(self, pub_key: PubKey, msg: bytes, sig: bytes) -> None:
        if pub_key.type() != ed25519.KEY_TYPE:
            raise TypeError("GuardedTpuBatchVerifier requires ed25519 keys")
        if len(sig) != 64:
            raise ValueError("malformed signature")
        self._items.append((pub_key, bytes(msg), bytes(sig)))

    def __len__(self) -> int:
        return len(self._items)

    def verify(self):
        br = self._breaker
        attempted_tpu = False
        if br.allow():
            attempted_tpu = True
            t0 = time.perf_counter()
            try:
                with tracing.span(tracing.CRYPTO, "batch_verify",
                                  batch=len(self._items),
                                  backend="tpu"):
                    from ..ops.ed25519_jax import verify_batch
                    out = verify_batch([(pk.bytes(), m, s)
                                        for pk, m, s in self._items])
            except Exception as e:  # noqa: BLE001 — fall back below
                br.record_failure(
                    latch=not _is_transient_kernel_error(e))
            else:
                br.record_success()
                _observe_verify("tpu", len(self._items),
                                time.perf_counter() - t0)
                return out
        t0 = time.perf_counter()
        with tracing.span(tracing.CRYPTO, "batch_verify",
                          batch=len(self._items), backend="cpu",
                          fallback=attempted_tpu):
            cpu = ed25519.CpuBatchVerifier()
            for pk, m, s in self._items:
                cpu.add(pk, m, s)
            out = cpu.verify()
        _observe_verify("cpu", len(self._items),
                        time.perf_counter() - t0)
        return out


class TracedBatchVerifier(BatchVerifier):
    """Flight-recorder span around any BatchVerifier's dispatch —
    every batch shows up in /trace with its size and backend label."""

    def __init__(self, inner: BatchVerifier, backend: str):
        self._inner = inner
        self._backend = backend

    def add(self, pub_key: PubKey, msg: bytes, sig: bytes) -> None:
        self._inner.add(pub_key, msg, sig)

    def __len__(self) -> int:
        try:
            return len(self._inner)
        except TypeError:   # verifier without __len__ (bls)
            return len(getattr(self._inner, "_items", ()))

    def verify(self):
        n = len(self)
        t0 = time.perf_counter()
        with tracing.span(tracing.CRYPTO, "batch_verify",
                          batch=n, backend=self._backend):
            out = self._inner.verify()
        _observe_verify(self._backend, n, time.perf_counter() - t0)
        return out


def create_batch_verifier(pub_key: PubKey) -> BatchVerifier:
    """Reference: batch.go:10 — errors for unsupported key types."""
    if pub_key.type() == _BLS_KEY_TYPE:
        from . import bls12381
        return TracedBatchVerifier(bls12381.Bls12381BatchVerifier(),
                                   "bls_native")
    if pub_key.type() != ed25519.KEY_TYPE:
        raise ValueError(f"batch verification unsupported for {pub_key.type()}")
    if get_backend() == "tpu":
        return GuardedTpuBatchVerifier()   # traces internally
    return TracedBatchVerifier(ed25519.CpuBatchVerifier(), "cpu")
