"""Batch-verifier dispatch: key type + configured backend -> BatchVerifier.

Reference: crypto/batch/batch.go — CreateBatchVerifier (:10),
SupportsBatchVerifier (:21); only ed25519 supports batching.

TPU-native addition: a process-global backend selector (the `crypto.backend`
config key from BASELINE.json's north star). Backends:
  * "tpu"  — JAX/XLA data-parallel verifier (ops/ed25519_jax.py); used when a
             TPU (or any JAX device) is available. Falls back to "cpu" when
             JAX import or device init fails.
  * "cpu"  — per-signature OpenSSL loop (crypto/ed25519.py).
"""
from __future__ import annotations

import os
from typing import Optional

from . import ed25519
from .keys import BatchVerifier, PubKey

_backend: Optional[str] = None
_auto_probe: Optional[str] = None   # cached auto-detection result


def set_backend(name: str) -> None:
    """Select the batch-verification backend: 'tpu', 'cpu', or 'auto'."""
    global _backend
    if name not in ("tpu", "cpu", "auto"):
        raise ValueError(f"unknown crypto backend {name!r}")
    _backend = None if name == "auto" else name


def get_backend() -> str:
    global _auto_probe
    if _backend is not None:
        return _backend
    env = os.environ.get("COMETBFT_TPU_CRYPTO_BACKEND")
    if env:
        env = env.lower()
        if env in ("tpu", "cpu"):
            return env
        if env != "auto":
            raise ValueError(
                f"COMETBFT_TPU_CRYPTO_BACKEND={env!r}: expected tpu|cpu|auto")
    if _auto_probe is None:
        try:
            from ..ops import ed25519_jax  # noqa: F401
            _auto_probe = "tpu"
        except Exception:
            _auto_probe = "cpu"
    return _auto_probe


def supports_batch_verifier(pub_key: PubKey) -> bool:
    """Only ed25519 supports batching (reference: batch.go:21)."""
    return pub_key.type() == ed25519.KEY_TYPE


def create_batch_verifier(pub_key: PubKey) -> BatchVerifier:
    """Reference: batch.go:10 — errors for unsupported key types."""
    if pub_key.type() != ed25519.KEY_TYPE:
        raise ValueError(f"batch verification unsupported for {pub_key.type()}")
    if get_backend() == "tpu":
        try:
            from ..ops.ed25519_jax import TpuBatchVerifier
            return TpuBatchVerifier()
        except Exception:
            pass
    return ed25519.CpuBatchVerifier()
