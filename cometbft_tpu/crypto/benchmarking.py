"""Shared key-operation benchmarking helpers.

Reference: crypto/internal/benchmarking/bench.go — one harness every key
type reuses for sign/verify throughput measurements (consumed by
crypto/ed25519/bench_test.go and friends; BASELINE.md row 'ed25519
sign/verify/batch-verify rate').
"""
from __future__ import annotations

import time
from typing import Callable

from .keys import PrivKey


def bench_sign(priv: PrivKey, msg_len: int = 128,
               iters: int = 200) -> float:
    """Signatures per second."""
    msg = bytes(range(256)) * (msg_len // 256 + 1)
    msg = msg[:msg_len]
    t0 = time.perf_counter()
    for _ in range(iters):
        priv.sign(msg)
    return iters / (time.perf_counter() - t0)


def bench_verify(priv: PrivKey, msg_len: int = 128,
                 iters: int = 200) -> float:
    """Verifications per second (single-sig path)."""
    msg = b"m" * msg_len
    sig = priv.sign(msg)
    pub = priv.pub_key()
    t0 = time.perf_counter()
    for _ in range(iters):
        assert pub.verify_signature(msg, sig)
    return iters / (time.perf_counter() - t0)


def bench_batch_verify(gen_priv: Callable[[], PrivKey],
                       batch_size: int = 64,
                       iters: int = 3) -> float:
    """Batched signatures verified per second via the engine's
    BatchVerifier dispatch (crypto/batch.py)."""
    from . import batch as crypto_batch
    items = []
    for i in range(batch_size):
        sk = gen_priv()
        msg = b"batch-%d" % i
        items.append((sk.pub_key(), msg, sk.sign(msg)))
    t0 = time.perf_counter()
    for _ in range(iters):
        bv = crypto_batch.create_batch_verifier(items[0][0])
        for pub, msg, sig in items:
            bv.add(pub, msg, sig)
        ok, _ = bv.verify()
        assert ok
    return batch_size * iters / (time.perf_counter() - t0)
