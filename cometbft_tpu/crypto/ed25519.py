"""ed25519 keys — the default validator key type.

Reference: crypto/ed25519/ed25519.go — curve25519-voi with ZIP-215
verification semantics (:36-44), LRU expanded-pubkey cache of size 4096
(:62-68), batch verification (:189-222).

Design here:
  * Signing and the fast path of single verification use OpenSSL via the
    ``cryptography`` package (same performance class as the reference's Go).
  * OpenSSL implements cofactorless RFC-8032 verification; ZIP-215 is strictly
    more permissive (cofactored + permissive point decoding), so an OpenSSL
    "accept" is always a ZIP-215 "accept". On OpenSSL "reject" we re-check
    with the exact ZIP-215 golden model so consensus-visible semantics match
    the reference byte-for-byte.
  * Batch verification dispatches to the TPU backend (ops.ed25519_jax) when
    available, falling back to a CPU loop. See crypto/batch.py for dispatch.
"""
from __future__ import annotations

import secrets
from collections import OrderedDict
from typing import Sequence

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    _HAVE_OPENSSL = True
except ImportError:
    # Gate the missing dependency instead of dying at import: some
    # containers ship without the OpenSSL bindings, which used to take
    # down EVERY module that (transitively) imports this one.  The
    # exact pure-python ZIP-215 model signs/verifies, with the native
    # C++ batch equation as the single-verify fast-accept path.
    _HAVE_OPENSSL = False

    class InvalidSignature(Exception):
        pass

    Ed25519PrivateKey = Ed25519PublicKey = None  # type: ignore[assignment]

from . import _ed25519_ref as ref
from .keys import BatchVerifier, PrivKey, PubKey, address_hash, bisect_bad

KEY_TYPE = "ed25519"
PUB_KEY_SIZE = 32
PRIV_KEY_SIZE = 64  # seed || pubkey, matching the reference's 64-byte privkey
SIGNATURE_SIZE = 64

# LRU cache of parsed OpenSSL pubkey objects
# (reference: cachedVerification LRU, size 4096, ed25519.go:62-68)
_CACHE_SIZE = 4096
_pub_cache: OrderedDict[bytes, Ed25519PublicKey] = OrderedDict()


def _cached_openssl_pub(raw: bytes) -> Ed25519PublicKey:
    k = _pub_cache.get(raw)
    if k is None:
        k = Ed25519PublicKey.from_public_bytes(raw)
        _pub_cache[raw] = k
        if len(_pub_cache) > _CACHE_SIZE:
            _pub_cache.popitem(last=False)
    else:
        _pub_cache.move_to_end(raw)
    return k


class Ed25519PubKey(PubKey):
    __slots__ = ("_raw", "_addr")

    def __init__(self, raw: bytes):
        if len(raw) != PUB_KEY_SIZE:
            raise ValueError(f"ed25519 pubkey must be {PUB_KEY_SIZE} bytes")
        self._raw = bytes(raw)
        self._addr: bytes | None = None

    def address(self) -> bytes:
        if self._addr is None:
            self._addr = address_hash(self._raw)
        return self._addr

    def bytes(self) -> bytes:
        return self._raw

    def type(self) -> str:
        return KEY_TYPE

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        # NOTE: an OpenSSL reject falls back to the exact-but-slow Python
        # ZIP-215 model (required for consensus-identical semantics: a
        # cofactorless reject may still be a cofactored accept when A/R have
        # torsion components, which cannot be detected cheaply). This makes
        # invalid signatures ~1000x costlier than valid ones — an
        # amplification lever that the native/ C++ ZIP-215 verifier
        # (planned; see SURVEY §7 hard parts) removes by making the exact
        # check fast in both directions.
        if len(sig) != SIGNATURE_SIZE:
            return False
        if not _HAVE_OPENSSL:
            return _verify_without_openssl(self._raw, msg, sig)
        try:
            _cached_openssl_pub(self._raw).verify(sig, msg)
            return True
        except InvalidSignature:
            # ZIP-215 is strictly more permissive than OpenSSL's cofactorless
            # check; re-verify with the exact golden model on reject.
            return ref.verify(self._raw, msg, sig)
        except ValueError:
            # invalid point encoding for OpenSSL; ZIP-215 may still accept
            return ref.verify(self._raw, msg, sig)


def _verify_without_openssl(raw_pub: bytes, msg: bytes,
                            sig: bytes) -> bool:
    """Single-signature verify when the OpenSSL bindings are absent:
    fast-accept through the native C++ batch equation (one item), with
    the exact-but-slow python ZIP-215 model deciding rejects — the
    same accept/reject contract as the CpuBatchVerifier path."""
    native = _native_msm()
    if native is not None:
        try:
            if native.ed25519_batch_verify(
                    [(raw_pub, msg, sig)], secrets.token_bytes(16)):
                return True
        except Exception:
            pass   # malformed shapes fall through to the exact model
    return ref.verify(raw_pub, msg, sig)


class Ed25519PrivKey(PrivKey):
    __slots__ = ("_seed", "_pub", "_ossl")

    def __init__(self, raw: bytes):
        # accept 32-byte seed or 64-byte seed||pub (reference format)
        if len(raw) == 64:
            raw = raw[:32]
        if len(raw) != 32:
            raise ValueError("ed25519 privkey must be 32-byte seed or 64 bytes")
        self._seed = bytes(raw)
        if _HAVE_OPENSSL:
            self._ossl = Ed25519PrivateKey.from_private_bytes(
                self._seed)
            from cryptography.hazmat.primitives.serialization import (
                Encoding, PublicFormat,
            )
            self._pub = self._ossl.public_key().public_bytes(
                Encoding.Raw, PublicFormat.Raw)
        else:
            self._ossl = None
            self._pub = ref.public_key(self._seed)

    def bytes(self) -> bytes:
        return self._seed + self._pub  # 64-byte reference layout

    def sign(self, msg: bytes) -> bytes:
        if self._ossl is None:
            return ref.sign(self._seed, msg)
        return self._ossl.sign(msg)

    def pub_key(self) -> Ed25519PubKey:
        return Ed25519PubKey(self._pub)

    def type(self) -> str:
        return KEY_TYPE


def gen_priv_key() -> Ed25519PrivKey:
    return Ed25519PrivKey(secrets.token_bytes(32))


def gen_priv_key_from_secret(secret: bytes) -> Ed25519PrivKey:
    """Deterministic key from a secret (reference: GenPrivKeyFromSecret —
    seed = SHA-256(secret))."""
    from . import tmhash
    return Ed25519PrivKey(tmhash.sum(secret))


class CpuBatchVerifier(BatchVerifier):
    """CPU batch verifier — the reference's actual CPU design: a
    random-linear-combination batch equation over one Pippenger
    multi-scalar multiplication (crypto/ed25519/ed25519.go:189-222;
    curve25519-voi does the same multi-exponentiation internally),
    implemented in C (native/ed25519_msm.hpp, ~4.8x the per-signature
    OpenSSL loop at 10k signatures on one core).  On batch reject —
    or when the native module is unavailable — each signature is
    verified individually to produce the exact validity mask, the
    same fallback contract as the TPU path.

    Batches larger than one pipeline tile (crypto/pipeline.py,
    default 4096) verify as a tiled pipeline through the native tile
    kernel: tile i runs GIL-free on the kernel worker while this
    thread packs and stages tile i+1 and settles tile i-1, and a
    reject bisects WITHIN its tile — one bad signature in a 10k
    burst re-checks O(log tile) subsets instead of the whole batch.
    Measured at the 10k-distinct-key commit-burst shape on the
    1-vCPU rig: 145 ms vs 187 ms monolithic (perf_baseline
    ed25519_pipelined_dispatch).  ``monolithic=True`` pins the
    pre-pipeline single-dispatch path (perf_lab's comparison arm).
    """

    def __init__(self, monolithic: bool = False):
        self._items: list[tuple[Ed25519PubKey, bytes, bytes]] = []
        self._monolithic = monolithic

    def add(self, pub_key: PubKey, msg: bytes, sig: bytes) -> None:
        if not isinstance(pub_key, Ed25519PubKey):
            raise TypeError("CpuBatchVerifier requires ed25519 keys")
        if len(sig) != SIGNATURE_SIZE:
            raise ValueError("malformed signature")
        self._items.append((pub_key, bytes(msg), bytes(sig)))

    def __len__(self) -> int:
        return len(self._items)

    def _verify_one(self, i: int) -> bool:
        pk, m, s = self._items[i]
        return pk.verify_signature(m, s)

    def verify(self) -> tuple[bool, Sequence[bool]]:
        n = len(self._items)
        if n >= 2:
            native = _native_msm()
            if native is not None:
                raw = [(pk.bytes(), m, s) for pk, m, s in self._items]
                try:
                    from . import pipeline
                    if not self._monolithic and \
                            n > pipeline.tile_size():
                        return pipeline.verify_items_pipelined(
                            native, raw, self._verify_one)
                    if self._batch_holds(native, raw):
                        return True, [True] * n
                    # batch rejected: bisect with the native batch
                    # equation (fresh randomizers per subset) so k bad
                    # signatures cost O(k log n) subset checks, not a
                    # whole-group per-signature sweep
                    mask = [True] * n
                    bisect_bad(
                        list(range(n)), mask,
                        lambda half: self._batch_holds(
                            native, [raw[i] for i in half]),
                        self._verify_one)
                    return all(mask), mask
                except Exception:
                    pass    # malformed shapes fall through per-sig
        per = [pk.verify_signature(m, s) for pk, m, s in self._items]
        return all(per), per

    @staticmethod
    def _batch_holds(native, raw) -> bool:
        z = secrets.token_bytes(16 * len(raw))
        return bool(native.ed25519_batch_verify(raw, z))


_NATIVE_MSM = False         # False = unprobed, None = unavailable


def _native_msm():
    global _NATIVE_MSM
    if _NATIVE_MSM is False:
        try:
            from . import _native_loader
            mod = _native_loader.load()
            _NATIVE_MSM = mod if (
                mod is not None and
                hasattr(mod, "ed25519_batch_verify")) else None
        except Exception:
            _NATIVE_MSM = None
    return _NATIVE_MSM
