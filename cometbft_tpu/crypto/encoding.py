"""PubKey ↔ proto PublicKey conversion + key-type registry.

Reference: crypto/encoding/codec.go — oneof sum keyed by key type
(proto/cometbft/crypto/v1/keys.proto: ed25519=1, secp256k1=2, bls12381=3,
secp256k1eth=4) — and internal/keytypes/keytypes.go:15 (name -> constructor
registry used by genesis / testnet key-type flags).
"""
from __future__ import annotations

from . import bls12381, ed25519, secp256k1, secp256k1eth
from .keys import PrivKey, PubKey

# proto oneof field name per key type
_FIELD_BY_TYPE = {
    "ed25519": "ed25519",
    "secp256k1": "secp256k1",
    "bls12_381": "bls12381",
    "secp256k1eth": "secp256k1eth",
}


class EncodingError(Exception):
    pass


def pub_key_to_proto(pk: PubKey) -> dict:
    field = _FIELD_BY_TYPE.get(pk.type())
    if field is None:
        raise EncodingError(f"unsupported key type {pk.type()}")
    return {field: pk.bytes()}


def pub_key_from_proto(d: dict) -> PubKey:
    try:
        if "ed25519" in d:
            return ed25519.Ed25519PubKey(d["ed25519"])
        if "secp256k1" in d:
            return secp256k1.Secp256k1PubKey(d["secp256k1"])
        if "bls12381" in d:
            return bls12381.Bls12381PubKey(d["bls12381"])
        if "secp256k1eth" in d:
            return secp256k1eth.Secp256k1EthPubKey(d["secp256k1eth"])
    except ValueError as e:
        raise EncodingError(str(e)) from None
    raise EncodingError(f"unsupported proto pubkey {sorted(d)}")


def pub_key_from_type_and_bytes(key_type: str, raw: bytes) -> PubKey:
    """Reference: crypto/encoding codec + internal/keytypes registry."""
    try:
        if key_type == ed25519.KEY_TYPE:
            return ed25519.Ed25519PubKey(raw)
        if key_type == secp256k1.KEY_TYPE:
            return secp256k1.Secp256k1PubKey(raw)
        if key_type == bls12381.KEY_TYPE:
            return bls12381.Bls12381PubKey(raw)
        if key_type == secp256k1eth.KEY_TYPE:
            return secp256k1eth.Secp256k1EthPubKey(raw)
    except ValueError as e:
        raise EncodingError(str(e)) from None
    raise EncodingError(f"unsupported key type {key_type}")


# amino-JSON type names per key type (reference: cmtjson.RegisterType in
# crypto/{ed25519,secp256k1,bls12381}) — the single source for genesis
# JSON, privval key files, and show-validator output
AMINO_PUBKEY_NAMES = {
    "ed25519": "tendermint/PubKeyEd25519",
    "secp256k1": "tendermint/PubKeySecp256k1",
    "bls12_381": "cometbft/PubKeyBls12_381",
    "secp256k1eth": "cometbft/PubKeySecp256k1eth",
}
AMINO_PRIVKEY_NAMES = {
    "ed25519": "tendermint/PrivKeyEd25519",
    "secp256k1": "tendermint/PrivKeySecp256k1",
    "bls12_381": "cometbft/PrivKeyBls12_381",
    "secp256k1eth": "cometbft/PrivKeySecp256k1eth",
}


# --- key-type registry (internal/keytypes/keytypes.go) ----------------------

_GENERATORS = {
    ed25519.KEY_TYPE: ed25519.gen_priv_key,
    secp256k1.KEY_TYPE: secp256k1.gen_priv_key,
    bls12381.KEY_TYPE: bls12381.gen_priv_key,
    secp256k1eth.KEY_TYPE: secp256k1eth.gen_priv_key,
}


def supported_key_types() -> list[str]:
    return sorted(_GENERATORS)


def gen_priv_key_by_type(key_type: str) -> PrivKey:
    gen = _GENERATORS.get(key_type)
    if gen is None:
        raise EncodingError(f"unsupported key type {key_type}; "
                            f"supported: {supported_key_types()}")
    return gen()


def priv_key_from_type_and_bytes(key_type: str, raw: bytes) -> PrivKey:
    try:
        if key_type == ed25519.KEY_TYPE:
            return ed25519.Ed25519PrivKey(raw)
        if key_type == secp256k1.KEY_TYPE:
            return secp256k1.Secp256k1PrivKey(raw)
        if key_type == bls12381.KEY_TYPE:
            return bls12381.Bls12381PrivKey(raw)
        if key_type == secp256k1eth.KEY_TYPE:
            return secp256k1eth.Secp256k1EthPrivKey(raw)
    except ValueError as e:
        raise EncodingError(str(e)) from None
    raise EncodingError(f"unsupported key type {key_type}")
