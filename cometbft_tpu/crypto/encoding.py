"""PubKey ↔ proto PublicKey conversion.

Reference: crypto/encoding/codec.go — oneof sum keyed by key type
(proto/cometbft/crypto/v1/keys.proto: ed25519=1, secp256k1=2, bls12381=3,
secp256k1eth=4).
"""
from __future__ import annotations

from . import ed25519
from .keys import PubKey

# proto oneof field name per key type
_FIELD_BY_TYPE = {
    "ed25519": "ed25519",
    "secp256k1": "secp256k1",
    "bls12_381": "bls12381",
    "secp256k1eth": "secp256k1eth",
}


class EncodingError(Exception):
    pass


def pub_key_to_proto(pk: PubKey) -> dict:
    field = _FIELD_BY_TYPE.get(pk.type())
    if field is None:
        raise EncodingError(f"unsupported key type {pk.type()}")
    return {field: pk.bytes()}


def pub_key_from_proto(d: dict) -> PubKey:
    if "ed25519" in d:
        return ed25519.Ed25519PubKey(d["ed25519"])
    raise EncodingError(f"unsupported proto pubkey {sorted(d)}")


def pub_key_from_type_and_bytes(key_type: str, raw: bytes) -> PubKey:
    """Reference: crypto/encoding codec + internal/keytypes registry."""
    if key_type == "ed25519":
        return ed25519.Ed25519PubKey(raw)
    raise EncodingError(f"unsupported key type {key_type}")
