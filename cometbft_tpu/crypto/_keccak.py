"""Legacy Keccak-256 (pre-NIST padding), as used by Ethereum addresses.

From-scratch Keccak-f[1600] sponge over the public FIPS-202 permutation
with the ORIGINAL Keccak domain padding (0x01), which differs from NIST
SHA3-256's 0x06 — hashlib.sha3_256 therefore cannot be used here.
Reference consumer: crypto/secp256k1eth (go-ethereum crypto.Keccak256).
"""
from __future__ import annotations

_ROUNDS = 24
_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]
_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]
_MASK = (1 << 64) - 1


def _rol(x: int, n: int) -> int:
    n %= 64
    return ((x << n) | (x >> (64 - n))) & _MASK


def _keccak_f(state: list[int]) -> None:
    """In-place Keccak-f[1600] on a 5x5 lane list (index x*5+y)."""
    for rnd in range(_ROUNDS):
        # theta
        c = [state[x * 5] ^ state[x * 5 + 1] ^ state[x * 5 + 2] ^
             state[x * 5 + 3] ^ state[x * 5 + 4] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rol(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                state[x * 5 + y] ^= d[x]
        # rho + pi
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y * 5 + (2 * x + 3 * y) % 5] = _rol(
                    state[x * 5 + y], _ROT[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                state[x * 5 + y] = b[x * 5 + y] ^ (
                    (~b[(x + 1) % 5 * 5 + y]) & b[(x + 2) % 5 * 5 + y]
                ) & _MASK
        # iota
        state[0] ^= _RC[rnd]


def keccak256(data: bytes) -> bytes:
    """Legacy Keccak-256: rate 136 bytes, padding 0x01...0x80."""
    rate = 136
    state = [0] * 25
    # pad
    padded = bytearray(data)
    pad_len = rate - (len(padded) % rate)
    padded += b"\x01" + b"\x00" * (pad_len - 2) + b"\x80" \
        if pad_len >= 2 else b"\x81"
    # absorb
    for off in range(0, len(padded), rate):
        block = padded[off:off + rate]
        for i in range(rate // 8):
            lane = int.from_bytes(block[i * 8:(i + 1) * 8], "little")
            x, y = i % 5, i // 5
            state[x * 5 + y] ^= lane
        _keccak_f(state)
    # squeeze 32 bytes
    out = b""
    for i in range(4):
        x, y = i % 5, i // 5
        out += state[x * 5 + y].to_bytes(8, "little")
    return out
