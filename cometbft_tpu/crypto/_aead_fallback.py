"""Self-contained ChaCha20-Poly1305 AEAD + X25519 + HKDF-SHA256.

Dependency gate for p2p/secret_connection.py: containers without the
``cryptography`` package used to lose the entire p2p stack at import
time.  This module implements the three primitives the handshake
needs from the standard library plus numpy (already a hard dependency
via jax):

* ChaCha20 (RFC 8439) — numpy-vectorized across blocks; a 1044-byte
  secret-connection frame is one 17-block batch, ~100 µs.
* Poly1305 — the classic one-big-int Horner chain mod 2^130-5.
* X25519 (RFC 7748) — constant-structure Montgomery ladder in python
  ints; only runs twice per connection handshake.
* HKDF-SHA256 (RFC 5869) — stdlib hmac.

Outputs are bit-identical to the OpenSSL-backed implementations, so
nodes with and without the ``cryptography`` package interoperate.
"""
from __future__ import annotations

import hashlib
import hmac as _hmac
import secrets
import struct

import numpy as np


class AEADInvalidTag(Exception):
    pass


# ---------------------------------------------------------------------
# HKDF-SHA256 (RFC 5869)

def hkdf_sha256(ikm: bytes, salt: bytes, info: bytes,
                length: int) -> bytes:
    prk = _hmac.new(salt or b"\x00" * 32, ikm, hashlib.sha256).digest()
    out = b""
    t = b""
    i = 1
    while len(out) < length:
        t = _hmac.new(prk, t + info + bytes([i]),
                      hashlib.sha256).digest()
        out += t
        i += 1
    return out[:length]


# ---------------------------------------------------------------------
# ChaCha20 (RFC 8439) — state rows vectorized over the block axis

_CONSTANTS = np.array([0x61707865, 0x3320646e, 0x79622d32, 0x6b206574],
                      dtype=np.uint32)


def _rotl(x: np.ndarray, n: int) -> np.ndarray:
    return (x << np.uint32(n)) | (x >> np.uint32(32 - n))


def _quarter(x, a, b, c, d) -> None:
    x[a] += x[b]; x[d] = _rotl(x[d] ^ x[a], 16)     # noqa: E702
    x[c] += x[d]; x[b] = _rotl(x[b] ^ x[c], 12)     # noqa: E702
    x[a] += x[b]; x[d] = _rotl(x[d] ^ x[a], 8)      # noqa: E702
    x[c] += x[d]; x[b] = _rotl(x[b] ^ x[c], 7)      # noqa: E702


def _chacha20_keystream(key: bytes, counter: int, nonce: bytes,
                        nbytes: int) -> np.ndarray:
    nblocks = (nbytes + 63) // 64
    state = np.empty((16, nblocks), dtype=np.uint32)
    state[:4] = _CONSTANTS[:, None]
    state[4:12] = np.frombuffer(key, dtype="<u4")[:, None]
    state[12] = (counter + np.arange(nblocks)).astype(np.uint32)
    state[13:16] = np.frombuffer(nonce, dtype="<u4")[:, None]
    x = state.copy()
    with np.errstate(over="ignore"):
        for _ in range(10):
            _quarter(x, 0, 4, 8, 12)
            _quarter(x, 1, 5, 9, 13)
            _quarter(x, 2, 6, 10, 14)
            _quarter(x, 3, 7, 11, 15)
            _quarter(x, 0, 5, 10, 15)
            _quarter(x, 1, 6, 11, 12)
            _quarter(x, 2, 7, 8, 13)
            _quarter(x, 3, 4, 9, 14)
        x += state
    # serialize per block: (16, n) -> (n, 16) little-endian words
    ks = np.ascontiguousarray(x.T).view(np.uint8).reshape(-1)
    return ks[:nbytes]


# ---------------------------------------------------------------------
# Poly1305

_P1305 = (1 << 130) - 5
_CLAMP = 0x0ffffffc0ffffffc0ffffffc0fffffff


def _poly1305(otk: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(otk[:16], "little") & _CLAMP
    s = int.from_bytes(otk[16:32], "little")
    acc = 0
    for i in range(0, len(msg), 16):
        blk = msg[i:i + 16]
        n = int.from_bytes(blk, "little") + (1 << (8 * len(blk)))
        acc = ((acc + n) * r) % _P1305
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(b: bytes) -> bytes:
    return b"\x00" * (-len(b) % 16)


class ChaCha20Poly1305:
    """RFC 8439 AEAD with the ``cryptography`` package's surface:
    encrypt(nonce, data, aad) -> ct||tag, decrypt raises on a bad
    tag.  Prefers the native C++ seal/open (µs per frame); the numpy
    path below is the no-compiler fallback (~ms per frame)."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("ChaCha20Poly1305 key must be 32 bytes")
        self._key = bytes(key)
        self._native = _native_aead()

    # -- numpy path: one keystream pass covers OTK (block 0) + data --
    def _seal_py(self, nonce: bytes, data: bytes,
                 aad: bytes) -> bytes:
        ks = _chacha20_keystream(self._key, 0, nonce,
                                 64 + len(data))
        otk = ks[:32].tobytes()
        ct = (np.frombuffer(data, dtype=np.uint8) ^
              ks[64:]).tobytes()
        return ct + self._tag(otk, ct, aad)

    @staticmethod
    def _tag(otk: bytes, ct: bytes, aad: bytes) -> bytes:
        mac_data = (aad + _pad16(aad) + ct + _pad16(ct) +
                    struct.pack("<QQ", len(aad), len(ct)))
        return _poly1305(otk, mac_data)

    def encrypt(self, nonce: bytes, data: bytes,
                aad: bytes | None) -> bytes:
        aad = aad or b""
        if self._native is not None:
            return self._native.chacha20poly1305_seal(
                self._key, nonce, aad, data)
        return self._seal_py(nonce, data, aad)

    def decrypt(self, nonce: bytes, data: bytes,
                aad: bytes | None) -> bytes:
        aad = aad or b""
        if len(data) < 16:
            raise AEADInvalidTag("ciphertext shorter than the tag")
        if self._native is not None:
            pt = self._native.chacha20poly1305_open(
                self._key, nonce, aad, data)
            if pt is None:
                raise AEADInvalidTag("authentication failed")
            return pt
        ct, tag = data[:-16], data[-16:]
        ks = _chacha20_keystream(self._key, 0, nonce, 64 + len(ct))
        if not _hmac.compare_digest(
                self._tag(ks[:32].tobytes(), ct, aad), tag):
            raise AEADInvalidTag("authentication failed")
        return (np.frombuffer(ct, dtype=np.uint8) ^ ks[64:]).tobytes()


_NATIVE_AEAD = False        # False = unprobed, None = unavailable


def _native_aead():
    global _NATIVE_AEAD
    if _NATIVE_AEAD is False:
        try:
            from . import _native_loader
            mod = _native_loader.load()
            _NATIVE_AEAD = mod if (
                mod is not None and
                hasattr(mod, "chacha20poly1305_seal")) else None
        except Exception:
            _NATIVE_AEAD = None
    return _NATIVE_AEAD


# ---------------------------------------------------------------------
# X25519 (RFC 7748)

_P = 2 ** 255 - 19
_A24 = 121665


def _decode_scalar(k: bytes) -> int:
    b = bytearray(k)
    b[0] &= 248
    b[31] &= 127
    b[31] |= 64
    return int.from_bytes(b, "little")


def x25519(scalar: bytes, u_bytes: bytes) -> bytes:
    """Montgomery-ladder scalar multiplication on Curve25519."""
    k = _decode_scalar(scalar)
    u = int.from_bytes(u_bytes, "little") & ((1 << 255) - 1)
    x1, x2, z2, x3, z3 = u, 1, 0, u, 1
    swap = 0
    for t in reversed(range(255)):
        k_t = (k >> t) & 1
        if swap ^ k_t:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t
        a = (x2 + z2) % _P
        aa = (a * a) % _P
        b = (x2 - z2) % _P
        bb = (b * b) % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = (d * a) % _P
        cb = (c * b) % _P
        x3 = (da + cb) % _P
        x3 = (x3 * x3) % _P
        z3 = (da - cb) % _P
        z3 = (u * z3 * z3) % _P
        x2 = (aa * bb) % _P
        z2 = (e * (aa + _A24 * e)) % _P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    out = (x2 * pow(z2, _P - 2, _P)) % _P
    return out.to_bytes(32, "little")


_BASEPOINT = (9).to_bytes(32, "little")


def x25519_keypair() -> tuple[bytes, bytes]:
    priv = secrets.token_bytes(32)
    return priv, x25519(priv, _BASEPOINT)
