"""BLS12-381 curve arithmetic: field tower, pairing, hash-to-curve.

From-scratch implementation of the public BLS12-381 parameters (the curve
behind the reference's blst dependency — crypto/bls12381/key_bls12381.go).
Structure follows the standard construction:

  Fq  = GF(p),  p = BLS12-381 base field prime (381 bits)
  Fq2 = Fq[u]/(u^2 + 1)
  Fq6 = Fq2[v]/(v^3 - (u+1))
  Fq12 = Fq6[w]/(w^2 - v)

  E  : y^2 = x^3 + 4       over Fq   (G1)
  E' : y^2 = x^3 + 4(u+1)  over Fq2  (G2, D-twist; untwist via w^2, w^3)

Pairing: optimal-ate Miller loop in affine coordinates over E(Fq12) with a
naive final exponentiation f^((p^12-1)/r) — this module is the GOLDEN
MODEL: simple, auditable formulas that the optimized C++ port
(native/bls12381.hpp: projective Fq2 Miller loop, cyclotomic squaring,
psi-endomorphism subgroup/cofactor fast paths) is differentially tested
against.  This key type never batches (reference crypto/batch/batch.go
is ed25519-only), so the host-side single-verify path is the workload.

Hash-to-curve implements the full RFC-9380
BLS12381G2_XMD:SHA-256_SSWU_RO_ ciphersuite: expand_message_xmd,
simplified SWU onto the 3-isogenous curve, and the degree-3 isogeny to
E — with the isogeny DERIVED OFFLINE from the curve parameters via
Vélu's formulas rather than copied constant tables (see the SSWU
section below and its re-derivation test).  Signatures are
byte-compatible with blst-class stacks.
"""
from __future__ import annotations

import hashlib


# --- native fast path -------------------------------------------------------
# The C++ port (native/bls12381.hpp) mirrors this module's formulas
# exactly and is differentially tested against it; the hot verify-side
# entry points below delegate when the module is built.  Point wire
# format: raw affine big-endian coordinates, b"" = infinity.

_NATIVE_CHECKED: dict = {}


def _native():
    from ._native_loader import load
    mod = load(allow_build=False)
    if mod is None or not hasattr(mod, "bls_pairings_product_is_one"):
        return None
    # run the module's algebra self-check once per build before any
    # verdict is produced; a bad build (miscompilation, platform
    # quirk) falls back to the python golden model instead of
    # silently returning wrong pairing verdicts
    ok = _NATIVE_CHECKED.get(id(mod))
    if ok is None:
        ok = bool(getattr(mod, "bls_selftest", lambda: False)())
        _NATIVE_CHECKED[id(mod)] = ok
    return mod if ok else None


def _g1_raw(pt) -> bytes:
    if pt is None:
        return b""
    return pt[0].to_bytes(48, "big") + pt[1].to_bytes(48, "big")


def _g1_unraw(b: bytes):
    if b == b"":
        return None
    return (int.from_bytes(b[:48], "big"),
            int.from_bytes(b[48:], "big"))


def _g2_raw(pt) -> bytes:
    if pt is None:
        return b""
    (x0, x1), (y0, y1) = pt
    return (x0.to_bytes(48, "big") + x1.to_bytes(48, "big") +
            y0.to_bytes(48, "big") + y1.to_bytes(48, "big"))


def _g2_unraw(b: bytes):
    if b == b"":
        return None
    return ((int.from_bytes(b[:48], "big"),
             int.from_bytes(b[48:96], "big")),
            (int.from_bytes(b[96:144], "big"),
             int.from_bytes(b[144:], "big")))

# --- base field -------------------------------------------------------------

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R_ORDER = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# BLS parameter x (negative); p, r, cofactors are polynomials in it.
X_PARAM = -0xD201000000010000

# G1 cofactor h1 = (x-1)^2 / 3; G2 cofactor h2 = (x^8 - 4x^7 + 5x^6 - 4x^4
# + 6x^3 - 4x^2 - 4x + 13) / 9 (standard BLS12 cofactor polynomials).
_x = X_PARAM
H1 = (_x - 1) ** 2 // 3
H2 = (_x**8 - 4 * _x**7 + 5 * _x**6 - 4 * _x**4 + 6 * _x**3
      - 4 * _x**2 - 4 * _x + 13) // 9


# --- field tower ------------------------------------------------------------
# Elements are plain tuples; all ops are module functions (keeps the pure-
# Python pairing inside its latency budget — class dispatch is ~3x slower).
#
# Fq:  int in [0, P)
# Fq2: (c0, c1)            c0 + c1*u
# Fq6: (a0, a1, a2)        ai in Fq2;  a0 + a1*v + a2*v^2
# Fq12:(b0, b1)            bi in Fq6;  b0 + b1*w

def f2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def f2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def f2_neg(a):
    return (-a[0] % P, -a[1] % P)


def f2_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = a0 * b0
    t1 = a1 * b1
    # Karatsuba: (a0+a1)(b0+b1) - t0 - t1
    return ((t0 - t1) % P, ((a0 + a1) * (b0 + b1) - t0 - t1) % P)


def f2_sqr(a):
    a0, a1 = a
    # (a0+a1)(a0-a1) + 2*a0*a1*u
    return ((a0 + a1) * (a0 - a1) % P, 2 * a0 * a1 % P)


def f2_muls(a, s: int):
    return (a[0] * s % P, a[1] * s % P)


def f2_inv(a):
    a0, a1 = a
    d = pow(a0 * a0 + a1 * a1, -1, P)
    return (a0 * d % P, -a1 * d % P)


def f2_conj(a):
    return (a[0], -a[1] % P)


F2_ZERO = (0, 0)
F2_ONE = (1, 0)
XI = (1, 1)          # v^3 = xi = 1 + u, the Fq6 non-residue


def f2_mul_xi(a):
    a0, a1 = a
    return ((a0 - a1) % P, (a0 + a1) % P)


def f6_add(a, b):
    return (f2_add(a[0], b[0]), f2_add(a[1], b[1]), f2_add(a[2], b[2]))


def f6_sub(a, b):
    return (f2_sub(a[0], b[0]), f2_sub(a[1], b[1]), f2_sub(a[2], b[2]))


def f6_neg(a):
    return (f2_neg(a[0]), f2_neg(a[1]), f2_neg(a[2]))


def f6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = f2_mul(a0, b0)
    t1 = f2_mul(a1, b1)
    t2 = f2_mul(a2, b2)
    c0 = f2_add(t0, f2_mul_xi(f2_sub(f2_mul(f2_add(a1, a2), f2_add(b1, b2)),
                                     f2_add(t1, t2))))
    c1 = f2_add(f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)),
                       f2_add(t0, t1)), f2_mul_xi(t2))
    c2 = f2_add(f2_sub(f2_mul(f2_add(a0, a2), f2_add(b0, b2)),
                       f2_add(t0, t2)), t1)
    return (c0, c1, c2)


def f6_sqr(a):
    return f6_mul(a, a)


def f6_mul_v(a):
    # (a0 + a1 v + a2 v^2) * v = xi*a2 + a0 v + a1 v^2
    return (f2_mul_xi(a[2]), a[0], a[1])


def f6_inv(a):
    a0, a1, a2 = a
    c0 = f2_sub(f2_sqr(a0), f2_mul_xi(f2_mul(a1, a2)))
    c1 = f2_sub(f2_mul_xi(f2_sqr(a2)), f2_mul(a0, a1))
    c2 = f2_sub(f2_sqr(a1), f2_mul(a0, a2))
    t = f2_inv(f2_add(f2_mul(a0, c0),
                      f2_mul_xi(f2_add(f2_mul(a2, c1), f2_mul(a1, c2)))))
    return (f2_mul(c0, t), f2_mul(c1, t), f2_mul(c2, t))


F6_ZERO = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE = (F2_ONE, F2_ZERO, F2_ZERO)


def f12_add(a, b):
    return (f6_add(a[0], b[0]), f6_add(a[1], b[1]))


def f12_sub(a, b):
    return (f6_sub(a[0], b[0]), f6_sub(a[1], b[1]))


def f12_neg(a):
    return (f6_neg(a[0]), f6_neg(a[1]))


def f12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = f6_mul(a0, b0)
    t1 = f6_mul(a1, b1)
    c0 = f6_add(t0, f6_mul_v(t1))
    c1 = f6_sub(f6_mul(f6_add(a0, a1), f6_add(b0, b1)), f6_add(t0, t1))
    return (c0, c1)


def f12_sqr(a):
    return f12_mul(a, a)


def f12_inv(a):
    a0, a1 = a
    t = f6_inv(f6_sub(f6_sqr(a0), f6_mul_v(f6_sqr(a1))))
    return (f6_mul(a0, t), f6_neg(f6_mul(a1, t)))


def f12_conj(a):
    """Conjugation a0 - a1*w = a^(p^6): the cheap Frobenius power."""
    return (a[0], f6_neg(a[1]))


F12_ZERO = (F6_ZERO, F6_ZERO)
F12_ONE = (F6_ONE, F6_ZERO)
F12_W = (F6_ZERO, F6_ONE)                      # the generator w


def f12_pow(a, e: int):
    if e < 0:
        a, e = f12_inv(a), -e
    out = F12_ONE
    while e:
        if e & 1:
            out = f12_mul(out, a)
        a = f12_sqr(a)
        e >>= 1
    return out


def f12_from_f2(c):
    """Embed Fq2 into Fq12 (constant coefficient)."""
    return ((c, F2_ZERO, F2_ZERO), F6_ZERO)


def f12_eq(a, b):
    return a == b


# --- generic affine curve ops ----------------------------------------------
# Points are (x, y) tuples over one of the tower fields; None = infinity.
# E_K: y^2 = x^3 + b for the appropriate b per field. Verification-only code:
# not constant-time, which matches the reference's verify-side usage.

class _Ops:
    """Field-op bundle so one affine implementation serves Fq/Fq2/Fq12."""

    __slots__ = ("add", "sub", "mul", "sqr", "neg", "inv", "b", "zero")

    def __init__(self, add, sub, mul, sqr, neg, inv, b, zero):
        self.add, self.sub, self.mul, self.sqr = add, sub, mul, sqr
        self.neg, self.inv, self.b, self.zero = neg, inv, b, zero


def _fq_add(a, b):
    return (a + b) % P


def _fq_sub(a, b):
    return (a - b) % P


def _fq_mul(a, b):
    return a * b % P


def _fq_sqr(a):
    return a * a % P


def _fq_neg(a):
    return -a % P


def _fq_inv(a):
    return pow(a, -1, P)


G1_OPS = _Ops(_fq_add, _fq_sub, _fq_mul, _fq_sqr, _fq_neg, _fq_inv, 4, 0)
G2_B = f2_muls(XI, 4)                           # 4(1+u)
G2_OPS = _Ops(f2_add, f2_sub, f2_mul, f2_sqr, f2_neg, f2_inv, G2_B, F2_ZERO)
G12_OPS = _Ops(f12_add, f12_sub, f12_mul, f12_sqr, f12_neg, f12_inv,
               ((((4, 0), F2_ZERO, F2_ZERO), F6_ZERO)), F12_ZERO)


def pt_on_curve(ops, pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return ops.sqr(y) == ops.add(ops.mul(ops.sqr(x), x), ops.b)


def pt_neg(ops, pt):
    if pt is None:
        return None
    return (pt[0], ops.neg(pt[1]))


def pt_double(ops, pt):
    if pt is None:
        return None
    x, y = pt
    if y == ops.zero:
        return None
    m = ops.mul(_muli(ops, ops.sqr(x), 3), ops.inv(_muli(ops, y, 2)))
    nx = ops.sub(ops.sqr(m), _muli(ops, x, 2))
    ny = ops.sub(ops.mul(m, ops.sub(x, nx)), y)
    return (nx, ny)


def _muli(ops, a, k: int):
    """a * small-int k within any tower field."""
    if ops is G1_OPS:
        return a * k % P
    out = a
    for _ in range(k - 1):
        out = ops.add(out, a)
    return out


def pt_add(ops, p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 == y2:
            return pt_double(ops, p1)
        return None
    m = ops.mul(ops.sub(y2, y1), ops.inv(ops.sub(x2, x1)))
    nx = ops.sub(ops.sub(ops.sqr(m), x1), x2)
    ny = ops.sub(ops.mul(m, ops.sub(x1, nx)), y1)
    return (nx, ny)


def pt_sum(ops, pts):
    """Sum an iterable of points (None entries = infinity, skipped).

    The native path runs a pairwise batched-inversion tree
    (~6 field muls per addition) over one concatenated blob — the
    aggregate-commit pubkey sum is O(n) in exactly these adds, so at
    10k validators this is ~10 ms where the affine python loop is
    ~500 ms."""
    pts = [p for p in pts if p is not None]
    if not pts:
        return None
    native = _native()
    if native is not None and ops in (G1_OPS, G2_OPS):
        try:
            if ops is G1_OPS:
                raw = native.bls_g1_sum(b"".join(
                    _g1_raw(p) for p in pts))
                return _g1_unraw(raw)
            raw = native.bls_g2_sum(b"".join(_g2_raw(p) for p in pts))
            return _g2_unraw(raw)
        except (ValueError, OverflowError):
            pass    # out-of-domain coords: python path handles
    acc = None
    for p in pts:
        acc = pt_add(ops, acc, p)
    return acc


def pt_mul(ops, pt, k: int):
    if k < 0:
        return pt_mul(ops, pt_neg(ops, pt), -k)
    if pt is not None and k:
        native = _native()
        if native is not None and ops in (G1_OPS, G2_OPS):
            kb = k.to_bytes((k.bit_length() + 7) // 8, "big")
            try:
                if ops is G1_OPS:
                    return _g1_unraw(
                        native.bls_g1_mul(_g1_raw(pt), kb))
                return _g2_unraw(native.bls_g2_mul(_g2_raw(pt), kb))
            except (ValueError, OverflowError):
                pass    # out-of-domain coords: python path handles
    out = None
    while k:
        if k & 1:
            out = pt_add(ops, out, pt)
        pt = pt_double(ops, pt)
        k >>= 1
    return out


# --- standard generators ----------------------------------------------------

G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN = (
    (0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
     0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E),
    (0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
     0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE),
)


# --- subgroup / membership --------------------------------------------------

def g1_in_subgroup(pt) -> bool:
    native = _native()
    if native is not None:
        try:
            return native.bls_g1_in_subgroup(_g1_raw(pt))
        except (ValueError, OverflowError):
            pass    # non-reduced coords: the python path's domain
    return pt_on_curve(G1_OPS, pt) and pt_mul(G1_OPS, pt, R_ORDER) is None


def g2_in_subgroup(pt) -> bool:
    native = _native()
    if native is not None:
        try:
            return native.bls_g2_in_subgroup(_g2_raw(pt))
        except (ValueError, OverflowError):
            pass
    return pt_on_curve(G2_OPS, pt) and pt_mul(G2_OPS, pt, R_ORDER) is None


# --- pairing ----------------------------------------------------------------

# untwist E'(Fq2) -> E(Fq12): (x', y') -> (x'/w^2, y'/w^3); w^6 = xi.
_W2_INV = f12_inv(f12_mul(F12_W, F12_W))
_W3_INV = f12_inv(f12_mul(f12_mul(F12_W, F12_W), F12_W))


def untwist(pt):
    if pt is None:
        return None
    x, y = pt
    return (f12_mul(f12_from_f2(x), _W2_INV),
            f12_mul(f12_from_f2(y), _W3_INV))


def g1_to_fq12(pt):
    if pt is None:
        return None
    return (f12_from_f2((pt[0], 0)), f12_from_f2((pt[1], 0)))


def _line(p1, p2, t):
    """Affine line through p1,p2 (or tangent) evaluated at t, in Fq12."""
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = t
    if x1 != x2:
        m = f12_mul(f12_sub(y2, y1), f12_inv(f12_sub(x2, x1)))
    elif y1 == y2:
        m = f12_mul(f12_mul(f12_sqr(x1), ((((3, 0), F2_ZERO, F2_ZERO),
                                           F6_ZERO))),
                    f12_inv(f12_add(y1, y1)))
    else:
        return f12_sub(xt, x1)
    return f12_sub(f12_mul(m, f12_sub(xt, x1)), f12_sub(yt, y1))


_ATE_LOOP = abs(X_PARAM)
_ATE_BITS = _ATE_LOOP.bit_length() - 2          # skip the leading bit


def miller_loop(q, p):
    """q, p in E(Fq12) (q from untwist(G2), p embedded G1). Returns the
    un-exponentiated Miller value."""
    if q is None or p is None:
        return F12_ONE
    r = q
    f = F12_ONE
    for i in range(_ATE_BITS, -1, -1):
        f = f12_mul(f12_sqr(f), _line(r, r, p))
        r = pt_double(G12_OPS, r)
        if (_ATE_LOOP >> i) & 1:
            f = f12_mul(f, _line(r, q, p))
            r = pt_add(G12_OPS, r, q)
    # x < 0: conjugate (f^(p^6)), the standard negative-x adjustment.
    return f12_conj(f)


_FINAL_EXP = (P**12 - 1) // R_ORDER


def final_exponentiation(f):
    # easy part f^(p^6 - 1): conj(f) * f^-1 — collapses to the cyclotomic
    # subgroup and makes the remaining pow cheaper to reason about.
    f = f12_mul(f12_conj(f), f12_inv(f))
    # (p^2 + 1) and hard part folded into one straightforward pow; naive but
    # correct (exponent is ((p^12-1)/r) / (p^6-1) * (p^6-1) handled above by
    # dividing the full exponent).
    return f12_pow(f, _FINAL_EXP // (P**6 - 1))


def pairings_product_is_one(pairs) -> bool:
    """prod e(P_i, Q_i) == 1, with P_i in G1 (affine Fq), Q_i in G2 (affine
    Fq2). One shared final exponentiation."""
    pairs = list(pairs)  # generators must survive the native-path attempt
    native = _native()
    if native is not None:
        try:
            return native.bls_pairings_product_is_one(
                [(_g1_raw(p), _g2_raw(q)) for p, q in pairs])
        except (ValueError, OverflowError):
            pass    # out-of-domain coords: python path handles
    f = F12_ONE
    for p1, q2 in pairs:
        if p1 is None or q2 is None:
            continue
        f = f12_mul(f, miller_loop(untwist(q2), g1_to_fq12(p1)))
    return final_exponentiation(f) == F12_ONE


# --- serialization (ZCash flag format) --------------------------------------
# Top three bits of the first byte: 0x80 compressed, 0x40 infinity, 0x20
# lexicographically-larger y (compressed only).

def _y_is_larger_fq(y: int) -> bool:
    return y > (P - 1) // 2


def _y_is_larger_fq2(y) -> bool:
    c0, c1 = y
    if c1 != 0:
        return _y_is_larger_fq(c1)
    return _y_is_larger_fq(c0)


def g1_compress(pt) -> bytes:
    if pt is None:
        return bytes([0xC0]) + bytes(47)
    x, y = pt
    flags = 0x80 | (0x20 if _y_is_larger_fq(y) else 0)
    b = bytearray(x.to_bytes(48, "big"))
    b[0] |= flags
    return bytes(b)


def g1_serialize(pt) -> bytes:
    """Uncompressed 96 bytes (blst P1Affine.Serialize)."""
    if pt is None:
        return bytes([0x40]) + bytes(95)
    x, y = pt
    return x.to_bytes(48, "big") + y.to_bytes(48, "big")


def _sqrt_fq(a: int):
    # p % 4 == 3
    r = pow(a, (P + 1) // 4, P)
    return r if r * r % P == a else None


def _sqrt_fq2(a):
    """Square root in Fq2 via the norm trick (p % 4 == 3)."""
    c0, c1 = a
    if c1 == 0:
        r = _sqrt_fq(c0)
        if r is not None:
            return (r, 0)
        # a = c0 with c0 non-square: sqrt is purely imaginary: (i*t)^2 = -t^2
        r = _sqrt_fq(-c0 % P)
        return None if r is None else (0, r)
    alpha = _sqrt_fq((c0 * c0 + c1 * c1) % P)
    if alpha is None:
        return None
    delta = (c0 + alpha) * pow(2, -1, P) % P
    x0 = _sqrt_fq(delta)
    if x0 is None:
        delta = (c0 - alpha) * pow(2, -1, P) % P
        x0 = _sqrt_fq(delta)
        if x0 is None:
            return None
    x1 = c1 * pow(2 * x0, -1, P) % P
    out = (x0, x1)
    return out if f2_sqr(out) == a else None


def g1_uncompress(data: bytes):
    """Compressed 48 bytes -> point (raises ValueError)."""
    if len(data) != 48:
        raise ValueError("bad G1 compressed length")
    native = _native()
    if native is not None and hasattr(native, "bls_g1_uncompress"):
        raw = native.bls_g1_uncompress(data)   # ValueError propagates
        return None if raw is None else _g1_unraw(raw)
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed flag in compressed G1")
    if flags & 0x40:
        if any(data[1:]) or flags & 0x3F:
            raise ValueError("bad G1 infinity encoding")
        return None
    x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if x >= P:
        raise ValueError("G1 x out of range")
    y = _sqrt_fq((x * x % P * x + 4) % P)
    if y is None:
        raise ValueError("G1 x not on curve")
    if _y_is_larger_fq(y) != bool(flags & 0x20):
        y = -y % P
    return (x, y)


def g1_deserialize(data: bytes):
    """Uncompressed 96 bytes -> point (raises ValueError)."""
    if len(data) != 96:
        raise ValueError("bad G1 uncompressed length")
    flags = data[0]
    if flags & 0x80:
        # a 96-byte blob with the compressed flag set is NOT a valid
        # uncompressed encoding — accepting it would make pubkey bytes
        # (and the addresses hashed from them) malleable
        raise ValueError("compressed flag in uncompressed G1 encoding")
    if flags & 0x40:
        if any(data[1:]):
            raise ValueError("bad G1 infinity encoding")
        return None
    x = int.from_bytes(data[:48], "big")
    y = int.from_bytes(data[48:], "big")
    if x >= P or y >= P:
        raise ValueError("G1 coordinate out of range")
    pt = (x, y)
    if not pt_on_curve(G1_OPS, pt):
        raise ValueError("G1 point not on curve")
    return pt


def g2_compress(pt) -> bytes:
    if pt is None:
        return bytes([0xC0]) + bytes(95)
    (x0, x1), y = pt
    flags = 0x80 | (0x20 if _y_is_larger_fq2(y) else 0)
    b = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    b[0] |= flags
    return bytes(b)


def g2_uncompress(data: bytes):
    if len(data) != 96:
        raise ValueError("bad G2 compressed length")
    native = _native()
    if native is not None and hasattr(native, "bls_g2_uncompress"):
        raw = native.bls_g2_uncompress(data)   # ValueError propagates
        return None if raw is None else _g2_unraw(raw)
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed flag in compressed G2")
    if flags & 0x40:
        if any(data[1:]) or flags & 0x3F:
            raise ValueError("bad G2 infinity encoding")
        return None
    x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("G2 x out of range")
    x = (x0, x1)
    y = _sqrt_fq2(f2_add(f2_mul(f2_sqr(x), x), G2_B))
    if y is None:
        raise ValueError("G2 x not on curve")
    if _y_is_larger_fq2(y) != bool(flags & 0x20):
        y = f2_neg(y)
    return (x, y)


# --- hash to G2 -------------------------------------------------------------

def expand_message_xmd(msg: bytes, dst: bytes, length: int) -> bytes:
    """RFC 9380 §5.3.1 with SHA-256."""
    if len(dst) > 255:
        raise ValueError("DST too long")
    b_in_bytes = 32
    ell = (length + b_in_bytes - 1) // b_in_bytes
    if ell > 255:
        raise ValueError("expand_message_xmd length too large")
    dst_prime = dst + bytes([len(dst)])
    z_pad = bytes(64)                       # SHA-256 block size
    l_i_b = length.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    b1 = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out = [b1]
    prev = b1
    for i in range(2, ell + 1):
        prev = hashlib.sha256(
            bytes(a ^ b for a, b in zip(b0, prev))
            + bytes([i]) + dst_prime).digest()
        out.append(prev)
    return b"".join(out)[:length]


def hash_to_field_fq2(msg: bytes, dst: bytes, count: int):
    """RFC 9380 §5.2: count elements of Fq2, L=64."""
    ln = 64
    data = expand_message_xmd(msg, dst, count * 2 * ln)
    out = []
    for i in range(count):
        c0 = int.from_bytes(data[2 * i * ln:(2 * i + 1) * ln], "big") % P
        c1 = int.from_bytes(data[(2 * i + 1) * ln:(2 * i + 2) * ln], "big") % P
        out.append((c0, c1))
    return out


def _sgn0_fq2(a) -> int:
    c0, c1 = a
    s0 = c0 % 2
    z0 = c0 == 0
    return s0 | (z0 and c1 % 2)


# --- RFC 9380 §8.8.2: BLS12381G2_XMD:SHA-256_SSWU_RO_ ----------------------
#
# The simplified SWU map lands on the 3-isogenous curve
#   E': y^2 = x^3 + A'x + B',  A' = 240i,  B' = 1012(1+i),  Z = -(2+i)
# and the degree-3 isogeny E' -> E (y^2 = x^3 + 4(1+i)) carries it to
# G2's curve.  The isogeny is DERIVED OFFLINE with Vélu's formulas
# from the curve parameters alone (no copied constant tables):
#
#   * the unique Fq2-rational order-3 x-coordinate on E' is the single
#     Fq2 root of the division polynomial
#     psi3(x) = 3x^4 + 6A'x^2 + 12B'x - A'^2  (via gcd(psi3, x^(p^2)-x);
#     re-derived and asserted in tests/test_crypto.py)
#   * Vélu with kernel {O, (x0, ±y0)} gives a 3-isogeny onto
#     y^2 = x^3 + 2916(1+i) = x^3 + 3^6·4(1+i); the isomorphism
#     (x, y) -> (x/9, y/27) lands exactly on E.  The leading
#     coefficient 1/9 mod p of the composed x-numerator equals
#     RFC 9380's k_(1,3) constant, confirming this is the RFC's map.
#
# Cofactor clearing uses h_eff = h2·(3z^2 - 3) (RFC 9380 §8.8.2),
# validated against the closed form from the curve's z parameter.

SSWU_A = (0, 240)
SSWU_B = (1012, 1012)
SSWU_Z = (P - 2, P - 1)                    # -(2 + i)

# Vélu kernel x0 (derived as documented above; see the re-derivation
# test) and the induced isogeny coefficients
ISO3_X0 = (P - 6, 6)        # = -(6, -6): the single Fq2 root of psi3
_iso_t = f2_muls(f2_add(f2_muls(f2_sqr(ISO3_X0), 3), SSWU_A), 2)
_iso_u = f2_muls(
    f2_add(f2_mul(f2_sqr(ISO3_X0), ISO3_X0),
           f2_add(f2_mul(SSWU_A, ISO3_X0), SSWU_B)), 4)
_INV9 = (pow(9, P - 2, P), 0)
_INV27 = (pow(27, P - 2, P), 0)

H_EFF = H2 * (3 * X_PARAM * X_PARAM - 3)


def _sswu_g2(u):
    """Simplified SWU for E' (RFC 9380 §6.6.2)."""
    u2 = f2_sqr(u)
    zu2 = f2_mul(SSWU_Z, u2)
    tv1 = f2_add(f2_sqr(zu2), zu2)         # Z^2 u^4 + Z u^2
    if tv1 == (0, 0):
        x1 = f2_mul(SSWU_B, f2_inv(f2_mul(SSWU_Z, SSWU_A)))
    else:
        x1 = f2_mul(
            f2_mul(f2_neg(SSWU_B), f2_inv(SSWU_A)),
            f2_add((1, 0), f2_inv(tv1)))
    gx1 = f2_add(f2_mul(f2_sqr(x1), x1),
                 f2_add(f2_mul(SSWU_A, x1), SSWU_B))
    y = _sqrt_fq2(gx1)
    if y is not None:
        x = x1
    else:
        x = f2_mul(zu2, x1)
        gx2 = f2_add(f2_mul(f2_sqr(x), x),
                     f2_add(f2_mul(SSWU_A, x), SSWU_B))
        y = _sqrt_fq2(gx2)
        if y is None:                       # pragma: no cover
            raise RuntimeError("SSWU: neither gx1 nor gx2 square")
    if _sgn0_fq2(y) != _sgn0_fq2(u):
        y = f2_neg(y)
    return (x, y)


def _iso3_g2(pt):
    """The Vélu 3-isogeny E' -> E composed with (x,y) -> (x/9, y/27)."""
    if pt is None:
        return None
    xp, yp = pt
    d = f2_sub(xp, ISO3_X0)
    if d == (0, 0):                         # kernel point -> infinity
        return None                         # pragma: no cover
    inv_d3 = f2_inv(f2_mul(f2_sqr(d), d))
    inv_d2 = f2_mul(inv_d3, d)
    # x_out = x + t/d + u/d^2 ; y_out = y (1 - t/d^2 - 2u/d^3)
    xn = f2_add(xp, f2_add(f2_mul(_iso_t, f2_mul(inv_d2, d)),
                           f2_mul(_iso_u, inv_d2)))
    yn = f2_mul(yp, f2_sub(
        (1, 0), f2_add(f2_mul(_iso_t, inv_d2),
                       f2_mul(f2_muls(_iso_u, 2), inv_d3))))
    # The isomorphism from y^2 = x^3 + 2916(1+i) down to E is
    # (x, y) -> (x/z^2, y/z^3) for z = ±3; both are valid and differ
    # only in the sign of y (equivalently: ±phi share kernel and
    # x-map, so the k_(1,3) check cannot distinguish them).  RFC
    # 9380's iso_map is the z = -3 branch — pinned by the appendix
    # J.10.1 expected-output vectors in tests/test_crypto.py, which
    # a flipped sign fails (output would be -P for every message,
    # breaking cross-stack verify while passing every property test).
    return (f2_mul(xn, _INV9), f2_neg(f2_mul(yn, _INV27)))


def _map_to_curve_g2(u):
    """RFC 9380 map_to_curve for G2: SSWU onto E', then the 3-isogeny."""
    return _iso3_g2(_sswu_g2(u))


def hash_to_g2(msg: bytes, dst: bytes):
    """hash_to_curve for the BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_
    ciphersuite (reference: crypto/bls12381/key_bls12381.go DST /
    blst's HashToG2)."""
    native = _native()
    if native is not None:
        return _g2_unraw(native.bls_hash_to_g2(msg, dst))
    u0, u1 = hash_to_field_fq2(msg, dst, 2)
    q = pt_add(G2_OPS, _map_to_curve_g2(u0), _map_to_curve_g2(u1))
    return pt_mul(G2_OPS, q, H_EFF)         # clear cofactor (h_eff)
