"""ASCII armor: text-safe encoding for keys and sensitive blobs.

Reference: crypto/armor/armor.go — OpenPGP-style armor blocks
(-----BEGIN <type>-----, base64 body with CRC24 checksum, headers).
"""
from __future__ import annotations

import base64


class ArmorError(Exception):
    pass


_CRC24_INIT = 0xB704CE
_CRC24_POLY = 0x1864CFB


def _crc24(data: bytes) -> int:
    crc = _CRC24_INIT
    for b in data:
        crc ^= b << 16
        for _ in range(8):
            crc <<= 1
            if crc & 0x1000000:
                crc ^= _CRC24_POLY
    return crc & 0xFFFFFF


def encode_armor(block_type: str, headers: dict[str, str],
                 data: bytes) -> str:
    lines = [f"-----BEGIN {block_type}-----"]
    for k in sorted(headers):
        lines.append(f"{k}: {headers[k]}")
    if headers:
        lines.append("")
    b64 = base64.b64encode(data).decode()
    for i in range(0, len(b64), 64):
        lines.append(b64[i:i + 64])
    crc = base64.b64encode(
        _crc24(data).to_bytes(3, "big")).decode()
    lines.append(f"={crc}")
    lines.append(f"-----END {block_type}-----")
    return "\n".join(lines) + "\n"


def decode_armor(armor_str: str) -> tuple[str, dict[str, str], bytes]:
    """-> (block_type, headers, data); raises ArmorError."""
    lines = [ln.rstrip("\r") for ln in armor_str.strip().split("\n")]
    if not lines or not lines[0].startswith("-----BEGIN ") or \
            not lines[0].endswith("-----"):
        raise ArmorError("missing BEGIN line")
    block_type = lines[0][len("-----BEGIN "):-len("-----")]
    if not lines[-1].startswith(f"-----END {block_type}"):
        raise ArmorError("missing or mismatched END line")
    body = lines[1:-1]
    headers: dict[str, str] = {}
    i = 0
    while i < len(body) and ":" in body[i]:
        k, _, v = body[i].partition(":")
        headers[k.strip()] = v.strip()
        i += 1
    if i < len(body) and body[i] == "":
        i += 1
    crc_expected = None
    b64_parts = []
    for ln in body[i:]:
        if ln.startswith("="):
            crc_expected = ln[1:]
        elif ln:
            b64_parts.append(ln)
    try:
        data = base64.b64decode("".join(b64_parts), validate=True)
    except Exception as e:
        raise ArmorError(f"bad base64 body: {e}") from None
    if crc_expected is not None:
        got = base64.b64encode(_crc24(data).to_bytes(3, "big")).decode()
        if got != crc_expected:
            raise ArmorError("CRC24 checksum mismatch")
    return block_type, headers, data
