"""secp256k1eth: Ethereum-compatible secp256k1 keys.

Reference: crypto/secp256k1eth/secp256k1eth.go (behind the secp256k1eth
build tag; noop stub otherwise — this build enables it unconditionally).
Differences from the Cosmos secp256k1 type:
  * Address = last 20 bytes of Keccak-256(uncompressed pubkey sans 0x04
    prefix) — the Ethereum address rule (go-ethereum crypto.PubkeyToAddress);
  * pubkey serialized UNCOMPRESSED (65 bytes, 0x04 || X || Y);
  * signatures are 64-byte R || S over Keccak-256(msg), lower-S enforced.
"""
from __future__ import annotations

import secrets

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        Prehashed,
        decode_dss_signature,
        encode_dss_signature,
    )
    from cryptography.hazmat.primitives.serialization import (
        Encoding,
        PublicFormat,
    )
    _HAVE_OPENSSL = True
except ImportError:
    # dependency gate — see crypto/secp256k1.py
    _HAVE_OPENSSL = False

from . import _secp256k1_math as _sp
from ._keccak import keccak256
from .keys import PrivKey, PubKey

KEY_TYPE = "secp256k1eth"
ENABLED = True
PRIV_KEY_SIZE = 32
PUB_KEY_SIZE = 65          # uncompressed: 0x04 || X || Y
SIG_SIZE = 64

_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_HALF_N = _N // 2
if _HAVE_OPENSSL:
    _CURVE = ec.SECP256K1()
    # ECDSA over an externally-computed Keccak-256 digest: SHA-256 here
    # only names a 32-byte digest length for the Prehashed wrapper
    _PREHASHED = ec.ECDSA(Prehashed(hashes.SHA256()))


class Secp256k1EthPubKey(PubKey):
    __slots__ = ("_raw", "_pk")

    def __init__(self, raw: bytes):
        if len(raw) != PUB_KEY_SIZE or raw[0] != 0x04:
            raise ValueError(
                f"secp256k1eth pubkey must be {PUB_KEY_SIZE} bytes "
                f"starting 0x04")
        self._raw = bytes(raw)
        self._pk = None

    def address(self) -> bytes:
        """Ethereum rule: Keccak-256(X||Y)[12:]."""
        return keccak256(self._raw[1:])[12:]

    def bytes(self) -> bytes:
        return self._raw

    def type(self) -> str:
        return KEY_TYPE

    def _parsed(self):
        if self._pk is None:
            self._pk = ec.EllipticCurvePublicKey.from_encoded_point(
                _CURVE, self._raw)
        return self._pk

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIG_SIZE:
            return False
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if not (0 < r < _N) or not (0 < s < _N) or s > _HALF_N:
            return False
        if not _HAVE_OPENSSL:
            try:
                return _sp.verify(_sp.decode_point(self._raw),
                                  keccak256(msg), r, s)
            except ValueError:
                return False
        try:
            self._parsed().verify(encode_dss_signature(r, s),
                                  keccak256(msg), _PREHASHED)
            return True
        except (InvalidSignature, ValueError):
            return False


class Secp256k1EthPrivKey(PrivKey):
    __slots__ = ("_raw", "_sk", "_d")

    def __init__(self, raw: bytes):
        if len(raw) != PRIV_KEY_SIZE:
            raise ValueError(
                f"secp256k1eth privkey must be {PRIV_KEY_SIZE} bytes")
        d = int.from_bytes(raw, "big")
        if not (0 < d < _N):
            raise ValueError("secp256k1eth privkey scalar out of range")
        self._raw = bytes(raw)
        self._d = d
        self._sk = ec.derive_private_key(d, _CURVE) \
            if _HAVE_OPENSSL else None

    def bytes(self) -> bytes:
        return self._raw

    def sign(self, msg: bytes) -> bytes:
        if self._sk is None:
            r, s = _sp.sign(self._d, keccak256(msg))
        else:
            der = self._sk.sign(keccak256(msg), _PREHASHED)
            r, s = decode_dss_signature(der)
        if s > _HALF_N:
            s = _N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    def pub_key(self) -> Secp256k1EthPubKey:
        if self._sk is None:
            return Secp256k1EthPubKey(_sp.encode_uncompressed(
                _sp.pub_point(self._d)))
        raw = self._sk.public_key().public_bytes(
            Encoding.X962, PublicFormat.UncompressedPoint)
        return Secp256k1EthPubKey(raw)

    def type(self) -> str:
        return KEY_TYPE


def gen_priv_key() -> Secp256k1EthPrivKey:
    while True:
        raw = secrets.token_bytes(PRIV_KEY_SIZE)
        d = int.from_bytes(raw, "big")
        if 0 < d < _N:
            return Secp256k1EthPrivKey(raw)
