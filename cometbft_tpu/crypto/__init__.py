"""Crypto layer: key/signature interfaces, ed25519, merkle, hashing.

Reference: crypto/crypto.go — PubKey, PrivKey, BatchVerifier contracts.
"""
from .keys import PubKey, PrivKey, BatchVerifier, address_hash
from . import tmhash

__all__ = ["PubKey", "PrivKey", "BatchVerifier", "address_hash", "tmhash"]
