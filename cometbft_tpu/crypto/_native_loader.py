"""Build/load the C++ fast-path module (cometbft_tpu._native).

The native source lives in native/ at the repo root; it is compiled
on first use with g++ (no external deps — SHA-256 is self-contained)
and cached next to this package.  Pure-Python implementations remain
the fallback everywhere, gated by COMETBFT_TPU_NATIVE=0.
"""
# bftlint: disable-file=blocking-in-async
# Justified: every blocking call here (cpuinfo probe, freshness tag
# read, g++ subprocess) runs at most once per process — load() is
# memoized via _mod/_failed, hot paths call load(allow_build=False)
# which never compiles, and the node pre-builds in a worker thread at
# startup.  Without this, the interprocedural may_block summary would
# taint every async caller of batched_hashes with an unreachable
# build chain.
from __future__ import annotations

import os
import subprocess
import sysconfig
import threading
from typing import Optional

_mod = None
_failed = False
_build_lock = threading.Lock()


def _source_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "native")


def _target_path() -> str:
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "_native" + suffix)


def _sources() -> list[str]:
    d = _source_dir()
    return [os.path.join(d, "_native.cpp"),
            os.path.join(d, "sha256.hpp"),
            os.path.join(d, "sha256_ni.hpp"),
            os.path.join(d, "sha512.hpp"),
            os.path.join(d, "sha512_mb.hpp"),
            os.path.join(d, "bls12381.hpp"),
            os.path.join(d, "ed25519_msm.hpp"),
            os.path.join(d, "chacha20poly1305.hpp")]


def _host_tag() -> str:
    """Fingerprint of this machine's CPU features.  The module is
    built with -march=native, so a cached .so copied to a different
    CPU (container image, rsync'd tree) must be treated as STALE and
    rebuilt — importing it could SIGILL, which no except clause can
    catch."""
    import hashlib
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return hashlib.sha256(
                        line.encode()).hexdigest()[:16]
    except OSError:
        pass
    import platform
    return hashlib.sha256(
        platform.processor().encode()).hexdigest()[:16]


def _target_fresh() -> bool:
    """True when the built module exists, is newer than EVERY native
    source file (missing sources count as stale, not error), and was
    built on a machine with this CPU's feature set."""
    try:
        t = os.path.getmtime(_target_path())
        if not all(t >= os.path.getmtime(s) for s in _sources()):
            return False
        with open(_target_path() + ".host") as f:
            return f.read().strip() == _host_tag()
    except OSError:
        return False


def _build() -> Optional[str]:
    """Compile to a temp file and atomically rename into place, under
    a lock — a concurrent load(allow_build=False) must never see a
    half-written .so."""
    src = _sources()[0]
    if not os.path.exists(src):
        return None
    target = _target_path()
    with _build_lock:
        if _target_fresh():
            return target
        include = sysconfig.get_paths()["include"]
        tmp = target + f".build-{os.getpid()}"
        base = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                f"-I{include}", f"-I{_source_dir()}", src, "-o", tmp]
        # -march=native is safe here (the module is always built on
        # the machine that runs it) and buys ~15% on the Montgomery
        # bigint paths; retry portable if the flag is rejected
        for cmd in (base[:1] + ["-march=native"] + base[1:], base):
            try:
                subprocess.run(cmd, check=True, capture_output=True,
                               timeout=120)
                os.replace(tmp, target)
                with open(target + ".host", "w") as f:
                    f.write(_host_tag())
                return target
            except (OSError, subprocess.SubprocessError):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
        return None


def load(allow_build: bool = True):
    """The _native module, or None (no compiler / disabled).

    With allow_build=False this never shells out to g++ — it only
    imports an already-built module.  Hot paths (merkle hashing runs
    inside the consensus loop) use that form; the node pre-builds in
    a thread at startup, and CLIs/tests build on first use."""
    global _mod, _failed
    if _mod is not None:
        return _mod
    if _failed or os.environ.get("COMETBFT_TPU_NATIVE", "1") == "0":
        return None
    if not _target_fresh():
        if not allow_build:
            return None
        if _build() is None:
            _failed = True
            return None
    try:
        from cometbft_tpu import _native  # noqa: F401
        _mod = _native
    except ImportError:
        _failed = True
        _mod = None
    return _mod


def batched_hashes(fn_name: str, items,
                   min_items: int = 8) -> Optional[list]:
    """Run one of the module's batch digest functions (sha256_many /
    leaf_hashes) and split the concatenated 32-byte output — or None
    when the batch is small, the module isn't built yet (never builds
    here: hot paths), or the items aren't plain bytes."""
    if len(items) < min_items:
        return None
    mod = load(allow_build=False)
    if mod is None:
        return None
    try:
        cat = getattr(mod, fn_name)(list(items))
    except TypeError:
        return None
    return [cat[i * 32:(i + 1) * 32] for i in range(len(items))]


def prebuild_async() -> None:
    """Kick the g++ build on a daemon thread (node startup calls this
    so the first big merkle hash never blocks the event loop)."""
    import threading
    threading.Thread(target=load, daemon=True,
                     name="native-build").start()
