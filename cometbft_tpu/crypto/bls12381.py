"""BLS12-381 keys (minimal-pubkey-size: pubkeys in G1, signatures in G2).

Reference: crypto/bls12381/key_bls12381.go —
  * PrivKey 32 bytes (blst.KeyGen / SecretKey.Serialize), Sign = compressed
    G2 point over hash_to_g2(msg, dstMinPk) (key_bls12381.go:112-116).
  * PubKey = 96-byte *uncompressed* G1 serialization (P1Affine.Serialize;
    const.go PubKeySize=96), KeyValidate = subgroup + non-infinity check
    (key_bls12381.go:158-169).
  * Address = SumTruncated(pubkey serialize) (key_bls12381.go:172-177).
  * VerifySignature group-checks the signature but allows infinity, since an
    aggregate can be infinite (key_bls12381.go:179-192).
  * DST "BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_NUL_" (key_bls12381.go:31).

Aggregate path (BASELINE config #5): aggregate_signatures /
fast_aggregate_verify / aggregate_verify mirror the blst aggregate API the
reference links against.

KeyGen follows draft-irtf-cfrg-bls-signature (HKDF loop), as blst does.
"""
from __future__ import annotations

import hashlib
import hmac
import secrets
from typing import Optional, Sequence

from . import _bls12381_math as m
from . import tmhash
from .keys import BatchVerifier, PrivKey, PubKey, bisect_bad

KEY_TYPE = "bls12_381"
PRIV_KEY_SIZE = 32
PUB_KEY_SIZE = 96           # uncompressed G1
SIGNATURE_SIZE = 96         # compressed G2
DST = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_NUL_"

ENABLED = True


class DeserializationError(ValueError):
    pass


class InfinitePubKeyError(ValueError):
    pass


def _hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    return hmac.new(salt, ikm, hashlib.sha256).digest()


def _hkdf_expand(prk: bytes, info: bytes, length: int) -> bytes:
    out = b""
    t = b""
    i = 1
    while len(out) < length:
        t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        out += t
        i += 1
    return out[:length]


def keygen(ikm: bytes, key_info: bytes = b"") -> int:
    """draft-irtf-cfrg-bls-signature KeyGen (the algorithm behind
    blst.KeyGen, key_bls12381.go:66-74)."""
    if len(ikm) < 32:
        raise ValueError("IKM must be at least 32 bytes")
    salt = b"BLS-SIG-KEYGEN-SALT-"
    length = 48
    sk = 0
    while sk == 0:
        salt = hashlib.sha256(salt).digest()
        prk = _hkdf_extract(salt, ikm + b"\x00")
        okm = _hkdf_expand(prk, key_info + length.to_bytes(2, "big"), length)
        sk = int.from_bytes(okm, "big") % m.R_ORDER
    return sk


class Bls12381PubKey(PubKey):
    __slots__ = ("_raw", "_pt")

    def __init__(self, raw: bytes):
        """Validates: deserializable, on curve, in G1 subgroup, not infinity
        (reference NewPublicKeyFromBytes + KeyValidate)."""
        if len(raw) != PUB_KEY_SIZE:
            raise DeserializationError(
                f"bls12381 pubkey must be {PUB_KEY_SIZE} bytes, got {len(raw)}")
        try:
            pt = m.g1_deserialize(raw)
        except ValueError as e:
            raise DeserializationError(str(e)) from None
        if pt is None:
            raise InfinitePubKeyError("bls12381: pubkey is infinite")
        if not m.g1_in_subgroup(pt):
            raise DeserializationError("bls12381: pubkey not in G1 subgroup")
        self._raw = bytes(raw)
        self._pt = pt

    @classmethod
    def _from_point_unchecked(cls, pt) -> "Bls12381PubKey":
        """Internal: wrap an already-validated G1 point (skips the subgroup
        check — for aggregation over keys validated at an earlier boundary,
        e.g. genesis load or the 10k-aggregate bench)."""
        self = object.__new__(cls)
        self._raw = m.g1_serialize(pt)
        self._pt = pt
        return self

    def address(self) -> bytes:
        return tmhash.sum_truncated(self._raw)

    def bytes(self) -> bytes:
        return self._raw

    def type(self) -> str:
        return KEY_TYPE

    def point(self):
        return self._pt

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        """e(pk, H(m)) == e(G1, sig); signature is group-checked but may be
        infinite (aggregates can be — key_bls12381.go:185-188)."""
        sig_pt = _parse_signature(sig)
        if sig_pt is False:
            return False
        if sig_pt is None:
            return False    # infinity never verifies a single message
        hm = m.hash_to_g2(msg, DST)
        # e(pk, H(m)) * e(-G1, sig) == 1
        return m.pairings_product_is_one(
            [(self._pt, hm), (m.pt_neg(m.G1_OPS, m.G1_GEN), sig_pt)])


def _parse_signature(sig: bytes):
    """Compressed G2 -> point | None (infinity) | False (invalid)."""
    if len(sig) != SIGNATURE_SIZE:
        return False
    try:
        pt = m.g2_uncompress(sig)
    except ValueError:
        return False
    if pt is not None and not m.g2_in_subgroup(pt):
        return False
    return pt


class Bls12381PrivKey(PrivKey):
    __slots__ = ("_sk",)

    def __init__(self, raw: bytes):
        if len(raw) != PRIV_KEY_SIZE:
            raise DeserializationError(
                f"bls12381 privkey must be {PRIV_KEY_SIZE} bytes, got {len(raw)}")
        sk = int.from_bytes(raw, "big")
        if not (0 < sk < m.R_ORDER):
            raise DeserializationError("bls12381 privkey scalar out of range")
        self._sk = sk

    def bytes(self) -> bytes:
        return self._sk.to_bytes(PRIV_KEY_SIZE, "big")

    def sign(self, msg: bytes) -> bytes:
        hm = m.hash_to_g2(msg, DST)
        return m.g2_compress(m.pt_mul(m.G2_OPS, hm, self._sk))

    def pub_key(self) -> Bls12381PubKey:
        pt = m.pt_mul(m.G1_OPS, m.G1_GEN, self._sk)
        return Bls12381PubKey(m.g1_serialize(pt))

    def type(self) -> str:
        return KEY_TYPE


def gen_priv_key() -> Bls12381PrivKey:
    return gen_priv_key_from_secret(secrets.token_bytes(32))


def gen_priv_key_from_secret(secret: bytes) -> Bls12381PrivKey:
    """Reference GenPrivKeyFromSecret (key_bls12381.go:66-74): non-32-byte
    secrets are SHA-256'd into the KeyGen seed."""
    if len(secret) != 32:
        secret = hashlib.sha256(secret).digest()
    sk = keygen(secret)
    return Bls12381PrivKey(sk.to_bytes(PRIV_KEY_SIZE, "big"))


# --- aggregate API (blst P2Aggregate surface) -------------------------------

def aggregate_signatures(sigs: Sequence[bytes]) -> bytes:
    """Sum compressed-G2 signatures; raises on any invalid input."""
    if not sigs:
        raise ValueError("no signatures to aggregate")
    pts = []
    for sig in sigs:
        pt = _parse_signature(sig)
        if pt is False:
            raise ValueError("invalid signature in aggregate")
        pts.append(pt)
    return m.g2_compress(m.pt_sum(m.G2_OPS, pts))


# the name the aggregate-commit layer uses (ISSUE 13); same operation
aggregate = aggregate_signatures


def aggregate_pub_keys(
        pub_keys: Sequence[Bls12381PubKey]) -> Bls12381PubKey:
    """Sum already-validated pubkeys into one aggregate key.

    This is the only O(n) residue of aggregate-commit verification —
    G1 point *adds*, not pairings — and it runs through the native
    batched-inversion tree (~10 ms at 10k keys).  The result may not
    itself pass KeyValidate (a sum can in principle be infinity), so
    it is wrapped unchecked; verify_aggregate rejects an infinite
    aggregate key."""
    if not pub_keys:
        raise ValueError("no pubkeys to aggregate")
    # a pubkey's stored serialization IS the raw x||y be48 layout the
    # sum consumes (validated non-infinity at construction)
    return aggregate_pub_keys_raw(
        b"".join(pk.bytes() for pk in pub_keys))


def aggregate_pub_keys_raw(blob: bytes) -> Bls12381PubKey:
    """Sum pubkeys given as concatenated 96-byte raw serializations
    (the layout Bls12381PubKey.bytes() stores) — the zero-copy form
    of aggregate_pub_keys for callers that keep a raw table."""
    if not blob:
        raise ValueError("no pubkeys to aggregate")
    native = m._native()
    if native is not None:
        return Bls12381PubKey._from_point_unchecked(
            m._g1_unraw(native.bls_g1_sum(blob)))
    pts = [m._g1_unraw(blob[i:i + 96])
           for i in range(0, len(blob), 96)]
    return Bls12381PubKey._from_point_unchecked(
        m.pt_sum(m.G1_OPS, pts))


def verify_aggregate(agg_pub_key: Bls12381PubKey, msg: bytes,
                     agg_sig: bytes) -> bool:
    """O(1) verification of an aggregate signature over ONE shared
    message: e(agg_pk, H(m)) == e(G1, agg_sig) — 2 Miller loops + one
    final exponentiation regardless of how many signers were summed
    into agg_pk.  The aggregate-commit verify path (types/validation)
    lands here after the cached G1 pubkey sum."""
    pk_pt = agg_pub_key.point()
    if pk_pt is None:
        return False        # infinite aggregate key never verifies
    sig_pt = _parse_signature(agg_sig)
    if sig_pt is False or sig_pt is None:
        return False
    hm = m.hash_to_g2(msg, DST)
    return m.pairings_product_is_one(
        [(pk_pt, hm), (m.pt_neg(m.G1_OPS, m.G1_GEN), sig_pt)])


def fast_aggregate_verify(pub_keys: Sequence[Bls12381PubKey], msg: bytes,
                          sig: bytes) -> bool:
    """All signers over ONE message: aggregate pubkeys in G1 (cheap), then a
    single pairing check — the 10k-validator aggregate path of BASELINE
    config #5."""
    if not pub_keys:
        return False
    sig_pt = _parse_signature(sig)
    if sig_pt is False or sig_pt is None:
        return False
    agg = m.pt_sum(m.G1_OPS, [pk.point() for pk in pub_keys])
    if agg is None:
        return False
    hm = m.hash_to_g2(msg, DST)
    return m.pairings_product_is_one(
        [(agg, hm), (m.pt_neg(m.G1_OPS, m.G1_GEN), sig_pt)])


# --- aggregate-pubkey cache -------------------------------------------------
# Stable validator sets re-verify aggregate commits with the SAME
# (valset, signer bitmap) over and over — one cache hit skips the G1
# point-sum entirely, leaving the constant 2-Miller-loop pairing as
# the whole cost of commit verification (docs/aggregate_commits.md).

_AGG_PK_METRICS = None


def _agg_pk_metrics():
    global _AGG_PK_METRICS
    if _AGG_PK_METRICS is None:
        from ..libs import metrics as libmetrics
        me = libmetrics.DEFAULT
        _AGG_PK_METRICS = (
            me.counter("crypto", "agg_pubkey_cache_hits",
                       "Aggregate-pubkey cache hits (G1 point-sum "
                       "skipped)."),
            me.counter("crypto", "agg_pubkey_cache_misses",
                       "Aggregate-pubkey cache misses (G1 point-sum "
                       "performed)."),
            me.counter("crypto", "agg_pubkey_cache_evictions",
                       "Aggregate-pubkey cache LRU evictions."),
        )
    return _AGG_PK_METRICS


class AggregatePubKeyCache:
    """LRU of aggregate pubkeys keyed (valset_hash, signer_bitmap).

    The key binds the SUM to the exact validator set revision and
    signer subset — a validator-set change rotates valset_hash, so
    stale sums can never serve a new set."""

    def __init__(self, capacity: int = 64):
        from collections import OrderedDict
        self.capacity = max(1, capacity)
        self._m: "OrderedDict[tuple[bytes, bytes], Bls12381PubKey]" = \
            OrderedDict()

    def get(self, valset_hash: bytes,
            signer_bitmap: bytes) -> Optional[Bls12381PubKey]:
        hits, misses, _ = _agg_pk_metrics()
        key = (valset_hash, signer_bitmap)
        pk = self._m.get(key)
        if pk is not None:
            self._m.move_to_end(key)
            hits.add()
        else:
            misses.add()
        return pk

    def put(self, valset_hash: bytes, signer_bitmap: bytes,
            pk: Bls12381PubKey) -> None:
        """Callers insert only AFTER the aggregate signature verified
        against this sum — a stream of forged (bitmap, signature)
        pairs must not be able to evict the honest entries."""
        self._m[(valset_hash, signer_bitmap)] = pk
        if len(self._m) > self.capacity:
            self._m.popitem(last=False)
            _agg_pk_metrics()[2].add()

    def __len__(self) -> int:
        return len(self._m)


_AGG_PK_CACHE: Optional[AggregatePubKeyCache] = None


def aggregate_pubkey_cache() -> AggregatePubKeyCache:
    """Process-global cache instance (the verify paths have no node
    context — same pattern as the signature cache metrics)."""
    global _AGG_PK_CACHE
    if _AGG_PK_CACHE is None:
        _AGG_PK_CACHE = AggregatePubKeyCache()
    return _AGG_PK_CACHE


class Bls12381BatchVerifier(BatchVerifier):
    """Batch verification of INDEPENDENT (pubkey, msg, sig) triples via
    a random-linear-combination pairings product:

        prod_i e([z_i]pk_i, H(m_i)) * e(-G1, sum_i [z_i]sig_i) == 1

    with fresh random 128-bit nonzero z_i, so n+1 Miller loops share
    ONE final exponentiation instead of n independent 2-pairing
    checks (~1.7x per signature on this box; the z_i randomizers make
    accepting any invalid subset as hard as breaking co-CDH, the same
    argument as the ed25519 batch equation).

    This goes beyond the reference seam: crypto/batch/batch.go:21
    supports batching only for ed25519 — blst's cgo surface is used
    strictly per-signature (crypto/bls12381/key_bls12381.go:179-192).
    The verify() contract matches crypto/crypto.go:47: (all_valid,
    per-signature mask), with a per-signature fallback on batch
    failure to identify the invalid entries exactly.
    """

    def __init__(self):
        self._items: list[tuple[Bls12381PubKey, bytes, bytes]] = []

    def add(self, pub_key: PubKey, msg: bytes, sig: bytes) -> None:
        if not isinstance(pub_key, Bls12381PubKey):
            raise ValueError("bls12381 batch verifier needs bls12381 keys")
        self._items.append((pub_key, msg, sig))

    def verify(self) -> tuple[bool, list[bool]]:
        n = len(self._items)
        if n == 0:
            return False, []
        parsed = []
        for _, _, sig in self._items:
            pt = _parse_signature(sig)
            parsed.append(None if pt is False or pt is None else pt)
        if n >= 2 and all(pt is not None for pt in parsed):
            if self._rlc_holds(range(n), parsed):
                return True, [True] * n
            # batch rejected: bisect — re-run the RLC product on each
            # half and descend only into failing halves, so a commit
            # with k byzantine signatures costs O(k log n) subset
            # products instead of n full 2-pairing verifications
            # (every byzantine-sig commit used to re-verify the WHOLE
            # group per signature)
            mask = [True] * n
            bisect_bad(
                list(range(n)), mask,
                lambda half: self._rlc_holds(half, parsed),
                lambda i: self._items[i][0].verify_signature(
                    self._items[i][1], self._items[i][2]))
            return all(mask), mask
        # degenerate (singleton / malformed sigs): per signature
        mask = [pk.verify_signature(msg, sig)
                for pk, msg, sig in self._items]
        return all(mask), mask

    def _rlc_holds(self, idxs, parsed) -> bool:
        """The random-linear-combination pairings product over a
        subset of items: fresh 128-bit randomizers every call, so a
        subset that only passed by randomizer collision upstream
        cannot keep passing down the bisection."""
        pairs = []
        zsigs = []
        for i in idxs:
            pk, msg, _ = self._items[i]
            z = 1 | secrets.randbits(128)
            pairs.append((m.pt_mul(m.G1_OPS, pk.point(), z),
                          m.hash_to_g2(msg, DST)))
            zsigs.append(m.pt_mul(m.G2_OPS, parsed[i], z))
        agg_zsig = m.pt_sum(m.G2_OPS, zsigs)
        if agg_zsig is None:
            return False
        pairs.append((m.pt_neg(m.G1_OPS, m.G1_GEN), agg_zsig))
        return m.pairings_product_is_one(pairs)


def aggregate_verify(pub_keys: Sequence[Bls12381PubKey],
                     msgs: Sequence[bytes], sig: bytes) -> bool:
    """Distinct-message aggregate: prod e(pk_i, H(m_i)) == e(G1, sig).
    Messages must be pairwise distinct (rogue-message rule)."""
    if not pub_keys or len(pub_keys) != len(msgs):
        return False
    if len(set(msgs)) != len(msgs):
        return False
    sig_pt = _parse_signature(sig)
    if sig_pt is False or sig_pt is None:
        return False
    pairs = [(pk.point(), m.hash_to_g2(msg, DST))
             for pk, msg in zip(pub_keys, msgs)]
    pairs.append((m.pt_neg(m.G1_OPS, m.G1_GEN), sig_pt))
    return m.pairings_product_is_one(pairs)
