"""Pure-Python ed25519 with ZIP-215 verification semantics.

This is the *golden model* for the TPU kernels in ``cometbft_tpu.ops`` and the
semantic reference for verification behavior. The reference engine verifies
with curve25519-voi under ZIP-215 rules (reference: crypto/ed25519/ed25519.go:36-44):

  * S must be canonical (S < L); non-canonical S is rejected.
  * A and R encodings are accepted permissively: y >= p is allowed, and
    "negative zero" x-coordinates are allowed.
  * The *cofactored* equation is used: [8]S·B == [8]R + [8]k·A, so small-order
    components never affect the verdict, and batch verification is consistent
    with single verification.

Arithmetic uses Python big ints — slow, but exact; used only in tests and as
the fallback/per-sig path when a batch fails.
"""
from __future__ import annotations

import hashlib
import secrets
from typing import Iterable, Sequence

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1) mod p

# Base point B
_BY = (4 * pow(5, P - 2, P)) % P


def _recover_x(y: int, sign: int) -> int | None:
    """Recover x from y and the sign bit; permissive (ZIP-215) rules.

    Returns None if y^2-1 / (d*y^2+1) is not a square (invalid encoding).
    Accepts x == 0 with sign == 1 ("negative zero") per ZIP-215.
    """
    yy = (y * y) % P
    u = (yy - 1) % P
    v = (D * yy + 1) % P
    # candidate root: x = u * v^3 * (u * v^7)^((p-5)/8)
    v3 = (v * v % P) * v % P
    v7 = (v3 * v3 % P) * v % P
    x = (u * v3 % P) * pow(u * v7 % P, (P - 5) // 8, P) % P
    vxx = (v * x % P) * x % P
    if vxx == u:
        pass
    elif vxx == (P - u) % P:
        x = (x * SQRT_M1) % P
    else:
        return None
    if (x & 1) != sign:
        x = (P - x) % P
    return x


def decompress(s: bytes) -> tuple[int, int] | None:
    """Decode a 32-byte point encoding under ZIP-215 permissive rules.

    Non-canonical y (y >= p) is accepted: y is reduced mod p.
    """
    if len(s) != 32:
        return None
    n = int.from_bytes(s, "little")
    sign = n >> 255
    y = (n & ((1 << 255) - 1)) % P  # permissive: reduce non-canonical y
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y)


def compress(pt: tuple[int, int]) -> bytes:
    x, y = pt
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


# -- group ops (affine via extended coordinates internally) -----------------

def _ext(pt):
    x, y = pt
    return (x, y, 1, x * y % P)


def _unext(e):
    X, Y, Z, _ = e
    zi = pow(Z, P - 2, P)
    return (X * zi % P, Y * zi % P)


def _ext_add(p, q):
    # add-2008-hwcd-3 (unified, complete for a=-1 twisted Edwards)
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = (Y1 - X1) * (Y2 - X2) % P
    B = (Y1 + X1) * (Y2 + X2) % P
    C = 2 * D * T1 % P * T2 % P
    Dd = 2 * Z1 * Z2 % P
    E = B - A
    F = Dd - C
    G = Dd + C
    H = B + A
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def _ext_double(p):
    return _ext_add(p, p)


def point_add(p, q):
    return _unext(_ext_add(_ext(p), _ext(q)))


def scalar_mult(k: int, pt) -> tuple[int, int]:
    e = _ext(pt)
    acc = (0, 1, 1, 0)  # identity
    while k > 0:
        if k & 1:
            acc = _ext_add(acc, e)
        e = _ext_double(e)
        k >>= 1
    return _unext(acc)


B = scalar_mult(1, (_recover_x(_BY, 0), _BY))  # base point affine
IDENT = (0, 1)


def is_identity_cofactored(pt) -> bool:
    """True iff [8]pt == identity (pt is in the small-order subgroup)."""
    e = _ext(pt)
    for _ in range(3):
        e = _ext_double(e)
    x, y = _unext(e)
    return x == 0 and y == 1


# -- hashing / scalars -------------------------------------------------------

def sha512_mod_l(*parts: bytes) -> int:
    h = hashlib.sha512()
    for part in parts:
        h.update(part)
    return int.from_bytes(h.digest(), "little") % L


# -- key ops -----------------------------------------------------------------

def secret_expand(seed: bytes) -> tuple[int, bytes]:
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def public_key(seed: bytes) -> bytes:
    a, _ = secret_expand(seed)
    return compress(scalar_mult(a, B))


def sign(seed: bytes, msg: bytes) -> bytes:
    a, prefix = secret_expand(seed)
    A = compress(scalar_mult(a, B))
    r = sha512_mod_l(prefix, msg)
    Rp = scalar_mult(r, B)
    Rb = compress(Rp)
    k = sha512_mod_l(Rb, A, msg)
    s = (r + k * a) % L
    return Rb + s.to_bytes(32, "little")


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """ZIP-215 single verification: cofactored, permissive A/R decoding."""
    if len(sig) != 64 or len(pub) != 32:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:  # non-canonical S rejected
        return False
    A = decompress(pub)
    if A is None:
        return False
    R = decompress(sig[:32])
    if R is None:
        return False
    k = sha512_mod_l(sig[:32], pub, msg)
    # [8](S·B - R - k·A) == identity
    sB = scalar_mult(s, B)
    kA = scalar_mult(k, A)
    neg = lambda p: ((P - p[0]) % P, p[1])
    chk = point_add(sB, point_add(neg(R), neg(kA)))
    return is_identity_cofactored(chk)


def batch_verify(
    items: Sequence[tuple[bytes, bytes, bytes]],
    rand_fn=None,
) -> tuple[bool, list[bool]]:
    """Batch verification with 128-bit randomizers.

    Checks [8](−(Σ z_i s_i mod L)·B + Σ z_i·R_i + Σ (z_i k_i mod L)·A_i) == 0.
    On failure, falls back to per-signature verification to produce the
    per-sig validity vector (reference: crypto/ed25519/ed25519.go:220 — voi's
    batch verifier does the same fallback internally).
    """
    if rand_fn is None:
        rand_fn = lambda: secrets.randbits(128) | 1
    n = len(items)
    if n == 0:
        return True, []
    decoded = []
    ok_shape = True
    for pub, msg, sig in items:
        if len(sig) != 64 or len(pub) != 32:
            ok_shape = False
            break
        s = int.from_bytes(sig[32:], "little")
        if s >= L:
            ok_shape = False
            break
        A = decompress(pub)
        R = decompress(sig[:32])
        if A is None or R is None:
            ok_shape = False
            break
        k = sha512_mod_l(sig[:32], pub, msg)
        decoded.append((A, R, s, k))
    if ok_shape:
        s_acc = 0
        pts = []  # (scalar, point) terms
        for A, R, s, k in decoded:
            z = rand_fn()
            s_acc = (s_acc + z * s) % L
            pts.append((z, R))
            pts.append((z * k % L, A))
        acc = _ext(scalar_mult((L - s_acc) % L, B))
        for z, pt in pts:
            acc = _ext_add(acc, _ext(scalar_mult(z, pt)))
        if is_identity_cofactored(_unext(acc)):
            return True, [True] * n
    # fallback: identify invalid signatures individually
    per = [verify(pub, msg, sig) for pub, msg, sig in items]
    return all(per), per
