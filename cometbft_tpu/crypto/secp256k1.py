"""secp256k1 ECDSA keys (Cosmos-style).

Reference: crypto/secp256k1/secp256k1.go —
  * PrivKey 32 bytes, Sign = ECDSA over SHA-256(msg), 64-byte R||S output in
    lower-S form (secp256k1.go:120-131).
  * PubKey = 33-byte compressed point (secp256k1.go:137-143).
  * Address = RIPEMD160(SHA256(compressed pubkey)) — Bitcoin style
    (secp256k1.go:148-172).
  * VerifySignature rejects signatures not in lower-S form (malleability;
    secp256k1.go:188-218).

Scalar/point heavy lifting is delegated to OpenSSL via `cryptography`
(the host-CPU fast path; this key type never batches — reference
crypto/batch/batch.go supports ed25519 only), with R||S <-> DER conversion
and low-S normalization done here.
"""
from __future__ import annotations

import hashlib
import secrets

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        Prehashed,
        decode_dss_signature,
        encode_dss_signature,
    )
    _HAVE_OPENSSL = True
except ImportError:
    # dependency gate: the pure-python RFC 6979 signer/verifier in
    # _secp256k1_math carries this (cold-path) key type instead of
    # the import killing crypto/encoding.py and everything above it
    _HAVE_OPENSSL = False

from . import _secp256k1_math as _sp
from .keys import PrivKey, PubKey

KEY_TYPE = "secp256k1"
PRIV_KEY_SIZE = 32
PUB_KEY_SIZE = 33          # compressed: 02/03 parity byte + x-coordinate
SIG_SIZE = 64              # R || S

# Curve order (reference: secp256k1.S256().N).
_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_HALF_N = _N // 2

if _HAVE_OPENSSL:
    _CURVE = ec.SECP256K1()
    _PREHASHED_SHA256 = ec.ECDSA(Prehashed(hashes.SHA256()))


def _sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


class Secp256k1PubKey(PubKey):
    __slots__ = ("_raw", "_pk")

    def __init__(self, raw: bytes):
        if len(raw) != PUB_KEY_SIZE:
            raise ValueError(
                f"secp256k1 pubkey must be {PUB_KEY_SIZE} bytes, got {len(raw)}")
        self._raw = bytes(raw)
        self._pk = None  # parsed lazily: parse failures surface in verify

    def address(self) -> bytes:
        """Bitcoin-style RIPEMD160(SHA256(pubkey)). Ref secp256k1.go:148."""
        h = hashlib.new("ripemd160")
        h.update(_sha256(self._raw))
        return h.digest()

    def bytes(self) -> bytes:
        return self._raw

    def type(self) -> str:
        return KEY_TYPE

    def _parsed(self):
        if self._pk is None:
            self._pk = ec.EllipticCurvePublicKey.from_encoded_point(
                _CURVE, self._raw)
        return self._pk

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        """64-byte R||S; rejects high-S (malleable) signatures.

        Reference: secp256k1.go:188-218 VerifySignature.
        """
        if len(sig) != SIG_SIZE:
            return False
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if not (0 < r < _N) or not (0 < s < _N) or s > _HALF_N:
            return False
        if not _HAVE_OPENSSL:
            try:
                return _sp.verify(_sp.decode_point(self._raw),
                                  _sha256(msg), r, s)
            except ValueError:
                return False
        try:
            der = encode_dss_signature(r, s)
            self._parsed().verify(der, _sha256(msg), _PREHASHED_SHA256)
            return True
        except (InvalidSignature, ValueError):
            return False


class Secp256k1PrivKey(PrivKey):
    __slots__ = ("_raw", "_sk", "_d")

    def __init__(self, raw: bytes):
        if len(raw) != PRIV_KEY_SIZE:
            raise ValueError(
                f"secp256k1 privkey must be {PRIV_KEY_SIZE} bytes, got {len(raw)}")
        d = int.from_bytes(raw, "big")
        if not (0 < d < _N):
            raise ValueError("secp256k1 privkey scalar out of range")
        self._raw = bytes(raw)
        self._d = d
        self._sk = ec.derive_private_key(d, _CURVE) \
            if _HAVE_OPENSSL else None

    def bytes(self) -> bytes:
        return self._raw

    def sign(self, msg: bytes) -> bytes:
        """ECDSA over SHA-256(msg); returns R||S with S normalized to the
        lower half-order. Ref secp256k1.go:120-131."""
        if self._sk is None:
            r, s = _sp.sign(self._d, _sha256(msg))
        else:
            der = self._sk.sign(_sha256(msg), _PREHASHED_SHA256)
            r, s = decode_dss_signature(der)
        if s > _HALF_N:
            s = _N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    def pub_key(self) -> Secp256k1PubKey:
        if self._sk is None:
            return Secp256k1PubKey(_sp.encode_compressed(
                _sp.pub_point(self._d)))
        from cryptography.hazmat.primitives.serialization import (
            Encoding, PublicFormat,
        )
        raw = self._sk.public_key().public_bytes(
            Encoding.X962, PublicFormat.CompressedPoint)
        return Secp256k1PubKey(raw)

    def type(self) -> str:
        return KEY_TYPE


def gen_priv_key() -> Secp256k1PrivKey:
    """Random scalar in (0, N). Ref secp256k1.go:62-88."""
    while True:
        raw = secrets.token_bytes(PRIV_KEY_SIZE)
        d = int.from_bytes(raw, "big")
        if 0 < d < _N:
            return Secp256k1PrivKey(raw)


def gen_priv_key_from_secret(secret: bytes) -> Secp256k1PrivKey:
    """Deterministic: k = (SHA256(secret) mod (N-1)) + 1.
    Ref secp256k1.go:93-118 GenPrivKeySecp256k1."""
    fe = int.from_bytes(_sha256(secret), "big")
    d = fe % (_N - 1) + 1
    return Secp256k1PrivKey(d.to_bytes(32, "big"))
