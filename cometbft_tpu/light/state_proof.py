"""Verified-header state proofs: the light client's end of the chain
``header.app_hash -> statetree root -> key/value``.

The verifier (verifier.py) establishes trust in a header through
sequential or skipping verification; this module spends that trust on
an ``abci_query_batch`` proof envelope.  The binding is height-exact:
the header at height H commits the app state AFTER block H-1 (ABCI
app_hash lag), so an envelope proven at tree version V verifies
against the header at height V+1 — ``proof.header_height`` — and
nothing else.  A stale-version proof, however internally consistent,
fails the app_hash comparison here.
"""
from __future__ import annotations

from typing import Iterable

from ..statetree import verify_proof_envelope


def verify_state_proof(verified_header, proof: dict,
                       present: Iterable[tuple[bytes, bytes]] = (),
                       absent: Iterable[bytes] = ()) -> None:
    """Check a proof envelope against a consensus-verified header:
    every (key, value) in ``present`` exists and every key in
    ``absent`` does not, in the state the header's app_hash commits.
    ``verified_header`` is a types.block.Header the caller already
    verified (light.verify / verify_adjacent / verify_non_adjacent)
    — this function takes the header, never a bare root, so the
    trust chain cannot be short-circuited.  Raises ValueError on any
    mismatch."""
    if "header_height" not in proof:
        raise ValueError(
            "proof envelope has no header binding (pre-statetree "
            "server?) — cannot chain to a verified header")
    if int(proof["header_height"]) != verified_header.height:
        raise ValueError(
            f"proof targets header height {proof['header_height']}, "
            f"verified header is {verified_header.height}")
    verify_proof_envelope(proof, present=present, absent=absent,
                          expected_root=verified_header.app_hash)
