"""Light-client header verification.

Reference: light/verifier.go — VerifyAdjacent (valhash continuity + 2/3
commit, :92), VerifyNonAdjacent (1/3 trust on the old valset then 2/3 on
the new, :30), shared SignatureCache across the two checks (:55-57),
trust-period expiry (:191), VerifyBackwards.

Both commit verifications ride the batch seam — with the TPU backend a
1000-validator bisection hop is two padded device batches (baseline #3).
"""
from __future__ import annotations

from typing import Optional

from ..types.block import SignedHeader
from ..types.signature_cache import SignatureCache
from ..types.timestamp import Timestamp
from ..types.validation import (
    Fraction, NotEnoughVotingPowerError, VerificationError,
    verify_commit_light, verify_commit_light_trusting,
)
from ..types.validator_set import ValidatorSet

DEFAULT_TRUST_LEVEL = Fraction(1, 3)


class LightClientError(Exception):
    pass


class OldHeaderExpiredError(LightClientError):
    pass


class InvalidHeaderError(LightClientError):
    pass


class NewValSetCantBeTrustedError(LightClientError):
    pass


def validate_trust_level(lvl: Fraction) -> None:
    """Allowed range [1/3, 1] (reference: ValidateTrustLevel)."""
    if (lvl.numerator * 3 < lvl.denominator or
            lvl.numerator > lvl.denominator or lvl.denominator == 0):
        raise LightClientError(f"trust level must be in [1/3, 1]: {lvl}")


def header_expired(h: SignedHeader, trusting_period_ns: int,
                   now: Timestamp) -> bool:
    expiration = h.header.time.add_ns(trusting_period_ns)
    return expiration.unix_ns() <= now.unix_ns()


def _verify_new_header_and_vals(
        untrusted_header: SignedHeader, untrusted_vals: ValidatorSet,
        trusted_header: SignedHeader, now: Timestamp,
        max_clock_drift_ns: int) -> None:
    untrusted_header.validate_basic(trusted_header.header.chain_id)
    if untrusted_header.height <= trusted_header.height:
        raise InvalidHeaderError(
            f"header height not monotonic: got {untrusted_header.height},"
            f" trusted {trusted_header.height}")
    if untrusted_header.header.time.unix_ns() <= \
            trusted_header.header.time.unix_ns():
        raise InvalidHeaderError("header time not monotonic")
    if untrusted_header.header.time.unix_ns() >= \
            now.add_ns(max_clock_drift_ns).unix_ns():
        raise InvalidHeaderError("header time exceeds max clock drift")
    if untrusted_header.header.validators_hash != untrusted_vals.hash():
        raise InvalidHeaderError(
            "header validators hash does not match given validator set")


def verify_adjacent(trusted_header: SignedHeader,
                    untrusted_header: SignedHeader,
                    untrusted_vals: ValidatorSet,
                    trusting_period_ns: int, now: Timestamp,
                    max_clock_drift_ns: int,
                    cache: Optional[SignatureCache] = None) -> None:
    """Reference: VerifyAdjacent (:92).

    The commit check dispatches through types/validation.py, which
    routes >= 2 same-type signatures into crypto.batch's
    Traced/Guarded batch verifiers (TPU kernel behind the breaker,
    CPU RLC otherwise) and falls back per-signature below the batch
    threshold.  A caller-supplied SignatureCache (one per sync in
    light/client.py verify_to_height) lets overlapping validator sets
    across hops skip re-verification entirely."""
    if untrusted_header.height != trusted_header.height + 1:
        raise LightClientError("headers must be adjacent in height")
    if header_expired(trusted_header, trusting_period_ns, now):
        raise OldHeaderExpiredError(
            f"trusted header expired at "
            f"{trusted_header.header.time.add_ns(trusting_period_ns)}")
    _verify_new_header_and_vals(untrusted_header, untrusted_vals,
                                trusted_header, now, max_clock_drift_ns)
    if untrusted_header.header.validators_hash != \
            trusted_header.header.next_validators_hash:
        raise InvalidHeaderError(
            "header validators hash does not match trusted header's "
            "next validators hash")
    try:
        verify_commit_light(
            trusted_header.header.chain_id, untrusted_vals,
            untrusted_header.commit.block_id, untrusted_header.height,
            untrusted_header.commit, cache=cache)
    except VerificationError as e:
        raise InvalidHeaderError(str(e)) from e


def verify_non_adjacent(trusted_header: SignedHeader,
                        trusted_vals: ValidatorSet,
                        untrusted_header: SignedHeader,
                        untrusted_vals: ValidatorSet,
                        trusting_period_ns: int, now: Timestamp,
                        max_clock_drift_ns: int,
                        trust_level: Fraction = DEFAULT_TRUST_LEVEL,
                        cache: Optional[SignatureCache] = None
                        ) -> None:
    """Reference: VerifyNonAdjacent (:30).  Both commit checks ride
    the batch seam (see verify_adjacent); with no caller cache a
    fresh one still spans the two checks here, mirroring the
    reference's shared SignatureCache (:55-57)."""
    if untrusted_header.height == trusted_header.height + 1:
        raise LightClientError("headers must be non-adjacent in height")
    if header_expired(trusted_header, trusting_period_ns, now):
        raise OldHeaderExpiredError("trusted header expired")
    _verify_new_header_and_vals(untrusted_header, untrusted_vals,
                                trusted_header, now, max_clock_drift_ns)

    if cache is None:
        cache = SignatureCache()
    # 1/3+ of the trusted valset must have signed the new commit.
    # For an aggregate commit the signer bitmap indexes the NEW
    # valset (hash-checked above), so it rides along as signer_vals.
    try:
        verify_commit_light_trusting(
            trusted_header.header.chain_id, trusted_vals,
            untrusted_header.commit, trust_level, cache=cache,
            signer_vals=untrusted_vals)
    except NotEnoughVotingPowerError as e:
        raise NewValSetCantBeTrustedError(str(e)) from e
    except VerificationError as e:
        # e.g. a wrong signature: invalid header, NOT a trust-range
        # miss — bisecting on it would never converge (reference:
        # VerifyNonAdjacent wraps both checks in ErrInvalidHeader)
        raise InvalidHeaderError(str(e)) from e
    # 2/3+ of the new valset must have signed — LAST check: untrusted
    # valsets can be made large to DoS the light client
    try:
        verify_commit_light(
            trusted_header.header.chain_id, untrusted_vals,
            untrusted_header.commit.block_id, untrusted_header.height,
            untrusted_header.commit, cache=cache)
    except VerificationError as e:
        raise InvalidHeaderError(str(e)) from e


def verify(trusted_header: SignedHeader, trusted_vals: ValidatorSet,
           untrusted_header: SignedHeader,
           untrusted_vals: ValidatorSet, trusting_period_ns: int,
           now: Timestamp, max_clock_drift_ns: int,
           trust_level: Fraction = DEFAULT_TRUST_LEVEL,
           cache: Optional[SignatureCache] = None) -> None:
    """Reference: Verify (:130)."""
    if untrusted_header.height != trusted_header.height + 1:
        verify_non_adjacent(trusted_header, trusted_vals,
                            untrusted_header, untrusted_vals,
                            trusting_period_ns, now,
                            max_clock_drift_ns, trust_level,
                            cache=cache)
    else:
        verify_adjacent(trusted_header, untrusted_header,
                        untrusted_vals, trusting_period_ns, now,
                        max_clock_drift_ns, cache=cache)


def verify_backwards(untrusted_header, trusted_header) -> None:
    """Reference: VerifyBackwards — untrusted at height-1 of trusted."""
    untrusted_header.validate_basic()
    if untrusted_header.chain_id != trusted_header.chain_id:
        raise InvalidHeaderError("header belongs to another chain")
    if untrusted_header.time.unix_ns() >= \
            trusted_header.time.unix_ns():
        raise InvalidHeaderError(
            "expected older header time to be before newer header time")
    if untrusted_header.hash() != trusted_header.last_block_id.hash:
        raise InvalidHeaderError(
            "older header hash does not match trusted header's last "
            "block id")
