"""Light client: header verification with sequential or skipping
(bisection) modes, provider abstraction, trusted store, attack detection.
"""
from .state_proof import verify_state_proof
from .verifier import (
    DEFAULT_TRUST_LEVEL, LightClientError, header_expired,
    validate_trust_level, verify, verify_adjacent, verify_backwards,
    verify_non_adjacent,
)

__all__ = [
    "DEFAULT_TRUST_LEVEL", "LightClientError", "header_expired",
    "validate_trust_level", "verify", "verify_adjacent",
    "verify_backwards", "verify_non_adjacent", "verify_state_proof",
]
