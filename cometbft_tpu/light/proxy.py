"""Light verifying RPC proxy: serve RPC from a full node, but verify
everything verifiable against light-client-checked headers.

Reference: light/rpc/client.go (the verifying wrapper) + light/proxy
(the stand-alone `cometbft light` daemon).  Header-derived responses
(commit, validators, block, blockchain) are only returned after the
light client has verified the enclosing header chain; mempool
broadcasts and status pass through.
"""
from __future__ import annotations

import base64
from typing import Optional

from ..db.db import MemDB
from ..libs.log import Logger, new_logger
from ..rpc.client import HTTPClient, RPCClientError
from ..types.timestamp import Timestamp
from .client import Client as LightClient, TrustOptions
from .provider import HttpProvider
from .store import TrustedStore


class LightProxyError(Exception):
    pass


class VerifyingClient:
    """RPC client surface whose header-derived answers are verified
    (reference: light/rpc/client.go)."""

    def __init__(self, light_client: LightClient, node: HTTPClient):
        self.light = light_client
        self.node = node

    async def latest_height(self) -> int:
        st = await self.node.status()
        return int(st["sync_info"]["latest_block_height"])

    async def commit(self, height: int = 0):
        h = height or await self.latest_height()
        lb = await self.light.verify_light_block_at_height(h)
        return lb.signed_header

    async def validators(self, height: int):
        lb = await self.light.verify_light_block_at_height(height)
        return lb.validator_set

    async def block(self, height: int) -> dict:
        """Raw block JSON: header checked against the verified light
        block AND body checked against the header's data/commit hashes
        (reference: light/rpc runs Block.ValidateBasic + hash checks, so
        a malicious primary can't attach forged txs to a real header)."""
        res = await self.node.block(height)
        from ..rpc.client import commit_from_json, header_from_json
        from ..types.block import Data
        hdr = header_from_json(res["block"]["header"])
        lb = await self.light.verify_light_block_at_height(hdr.height)
        if lb.signed_header.header.hash() != hdr.hash():
            raise LightProxyError(
                f"block {hdr.height} from node does not match the "
                f"verified header")
        txs = [base64.b64decode(t) for t in
               (res["block"].get("data") or {}).get("txs", [])]
        if Data(txs=txs).hash() != hdr.data_hash:
            raise LightProxyError(
                f"block {hdr.height} data does not hash to the "
                f"verified data_hash")
        lc_json = res["block"].get("last_commit")
        if hdr.height > 1:
            # a nil last_commit above height 1 is itself invalid
            # (reference Block.ValidateBasic) — a stripped field must
            # not bypass the hash check
            if lc_json is None:
                raise LightProxyError(
                    f"block {hdr.height} is missing last_commit")
            if commit_from_json(lc_json).hash() != \
                    hdr.last_commit_hash:
                raise LightProxyError(
                    f"block {hdr.height} last_commit does not hash to "
                    f"the verified last_commit_hash")
        return res

    async def abci_query(self, path: str, data: bytes) -> dict:
        # NOTE: reference verifies merkle proofs against app_hash; the
        # kvstore app emits no proofs, so this passes through unverified
        return await self.node.abci_query(path, data)


class LightProxy:
    """The `cometbft light` daemon: verifying proxy over RPC
    (reference: light/proxy/proxy.go)."""

    def __init__(self, chain_id: str, primary: str,
                 witnesses: list[str], trust_height: int,
                 trust_hash: bytes, listen_addr: str,
                 trust_period_ns: int = 168 * 3600 * 10**9,
                 logger: Optional[Logger] = None):
        self.chain_id = chain_id
        self.primary_addr = primary
        self.witness_addrs = witnesses
        self.trust_height = trust_height
        self.trust_hash = trust_hash
        self.listen_addr = listen_addr
        self.trust_period_ns = trust_period_ns
        self.logger = logger or new_logger("light-proxy")
        self.client: Optional[VerifyingClient] = None
        self._server = None

    async def start(self) -> None:
        providers = [HttpProvider(a, self.chain_id)
                     for a in [self.primary_addr] + self.witness_addrs]
        light = LightClient(
            self.chain_id,
            TrustOptions(period_ns=self.trust_period_ns,
                         height=self.trust_height,
                         header_hash=self.trust_hash),
            providers[0], providers[1:], TrustedStore(MemDB()))
        await light.initialize()
        node = HTTPClient(self.primary_addr)
        self.client = VerifyingClient(light, node)

        from ..config import RPCConfig
        from ..rpc.server import RPCServer
        cfg = RPCConfig()
        cfg.laddr = self.listen_addr
        self._server = RPCServer(None, cfg, routes=self._routes())
        await self._server.start()
        self.logger.info("light proxy serving verified RPC",
                         addr=self._server.listen_addr,
                         primary=self.primary_addr)

    async def stop(self) -> None:
        if self._server is not None:
            await self._server.stop()

    @property
    def rpc_listen_addr(self) -> str:
        return self._server.listen_addr if self._server else ""

    def _routes(self) -> dict:
        from ..rpc import core as rpc_core
        c = self.client
        node = c.node

        async def _health():
            return {}

        async def _status():
            st = await node.status()
            st["node_info"] = st.get("node_info", {})
            st["node_info"]["moniker"] = "light-proxy"
            return st

        async def _commit(height="0"):
            lb = await c.light.verify_light_block_at_height(
                int(height) or await _latest_height())
            return {"signed_header": {
                "header": rpc_core._header_json(
                    lb.signed_header.header),
                "commit": rpc_core._commit_json(
                    lb.signed_header.commit)},
                "canonical": True}

        async def _latest_height():
            st = await node.status()
            return int(st["sync_info"]["latest_block_height"])

        async def _validators(height="0", page="1", per_page="100"):
            h = int(height) or await _latest_height()
            vals = await c.validators(h)
            from ..types import genesis as genesis_types
            page_i = max(1, int(page))
            per = min(100, max(1, int(per_page)))
            sel = vals.validators[(page_i - 1) * per:page_i * per]
            return {"block_height": str(h), "validators": [
                {"address": v.address.hex().upper(),
                 "pub_key": genesis_types.pub_key_to_json(v.pub_key),
                 "voting_power": str(v.voting_power),
                 "proposer_priority": str(v.proposer_priority)}
                for v in sel],
                "count": str(len(sel)), "total": str(vals.size())}

        async def _block(height="0"):
            return await c.block(int(height) or await _latest_height())

        async def _abci_query(path="", data="", height="0",
                              prove=False):
            return await node.call("abci_query", path=path, data=data,
                                   height=height, prove=prove)

        async def _broadcast(method, tx):
            return await node.call(method, tx=tx)

        return {
            "health": _health,
            "status": _status,
            "commit": lambda height="0": _commit(height),
            "validators": _validators,
            "block": lambda height="0": _block(height),
            "abci_query": _abci_query,
            "broadcast_tx_sync": lambda tx="":
                _broadcast("broadcast_tx_sync", tx),
            "broadcast_tx_async": lambda tx="":
                _broadcast("broadcast_tx_async", tx),
            "broadcast_tx_commit": lambda tx="":
                _broadcast("broadcast_tx_commit", tx),
        }
