"""Light client: trusted-store-backed header tracker.

Reference: light/client.go (:1179) — sequential or skipping (bisection)
verification against a primary provider, witness cross-checking
(detector.go), trust-period handling, backwards verification below the
trusted root.
"""
from __future__ import annotations

from typing import Optional

from ..libs.log import Logger, new_logger
from ..types.block import LightBlock
from ..types.evidence import LightClientAttackEvidence
from ..types.signature_cache import SignatureCache
from ..types.timestamp import Timestamp
from ..types.validation import Fraction
from .provider import LightBlockNotFoundError, Provider, ProviderError
from .store import TrustedStore
from .verifier import (
    DEFAULT_TRUST_LEVEL, LightClientError, header_expired,
    validate_trust_level, verify, verify_backwards,
)

_S = 1_000_000_000
DEFAULT_MAX_CLOCK_DRIFT_NS = 10 * _S

SEQUENTIAL = "sequential"
SKIPPING = "skipping"


class DivergenceError(LightClientError):
    """A witness disagrees with the primary — possible attack
    (reference: detector.go ErrConflictingHeaders)."""

    def __init__(self, witness: Provider, evidence=None):
        super().__init__(f"witness {witness.id()} diverges from primary")
        self.witness = witness
        self.evidence = evidence


class TrustOptions:
    """Reference: light.TrustOptions — period + (height, hash) root."""

    def __init__(self, period_ns: int, height: int, header_hash: bytes):
        self.period_ns = period_ns
        self.height = height
        self.hash = header_hash


class Client:
    def __init__(self, chain_id: str, trust_options: TrustOptions,
                 primary: Provider, witnesses: list[Provider],
                 trusted_store: TrustedStore,
                 verification_mode: str = SKIPPING,
                 trust_level: Fraction = DEFAULT_TRUST_LEVEL,
                 max_clock_drift_ns: int = DEFAULT_MAX_CLOCK_DRIFT_NS,
                 logger: Optional[Logger] = None):
        validate_trust_level(trust_level)
        self.chain_id = chain_id
        self.trust_options = trust_options
        self.primary = primary
        self.witnesses = list(witnesses)
        self.store = trusted_store
        self.mode = verification_mode
        self.trust_level = trust_level
        self.max_clock_drift_ns = max_clock_drift_ns
        self.logger = logger if logger is not None else \
            new_logger("light")

    # ------------------------------------------------------------------
    async def initialize(self,
                         now: Optional[Timestamp] = None) -> LightBlock:
        """Fetch + pin the trust root (reference: initializeWithTrustOptions)."""
        now = now or Timestamp.now()
        existing = self.store.light_block(self.trust_options.height)
        if existing is not None:
            return existing
        lb = await self.primary.light_block(self.trust_options.height)
        if lb.signed_header.header.hash() != self.trust_options.hash:
            raise LightClientError(
                "trusted header hash does not match the trust options")
        lb.validate_basic(self.chain_id)
        if header_expired(lb.signed_header,
                          self.trust_options.period_ns, now):
            raise LightClientError("trusted header is expired")
        self.store.save_light_block(lb)
        return lb

    # ------------------------------------------------------------------
    async def verify_light_block_at_height(
            self, height: int,
            now: Optional[Timestamp] = None) -> LightBlock:
        """Reference: VerifyLightBlockAtHeight."""
        return await self._verify_at(height, now, cache=None)

    async def _verify_at(self, height: int, now: Optional[Timestamp],
                         cache: Optional[SignatureCache]
                         ) -> LightBlock:
        now = now or Timestamp.now()
        if height <= 0:
            raise LightClientError("height must be positive")
        existing = self.store.light_block(height)
        if existing is not None:
            return existing
        latest = self.store.latest()
        if latest is None:
            raise LightClientError("client not initialized")
        if height < latest.height:
            first = self.store.first()
            if first is not None and height < first.height:
                return await self._backwards(first, height)
            # between stored roots: verify forward from the closest
            # lower stored block
            base = self._closest_below(height)
            return await self._verify_forward(base, height, now,
                                              cache=cache)
        return await self._verify_forward(latest, height, now,
                                          cache=cache)

    async def update(self, now: Optional[Timestamp] = None
                     ) -> Optional[LightBlock]:
        """Verify the primary's latest header (reference: Update)."""
        now = now or Timestamp.now()
        latest = self.store.latest()
        if latest is None:
            raise LightClientError("client not initialized")
        new = await self.primary.light_block(0)
        if new.height <= latest.height:
            return None
        return await self._verify_forward(latest, new.height, now,
                                          prefetched=new)

    async def verify_to_height(self, height: int,
                               now: Optional[Timestamp] = None
                               ) -> LightBlock:
        """Skipping (bisection) sync to ``height`` with ONE signature
        cache spanning every hop — the scalable consumer loop of the
        proof-serving layer (docs/light_proofs.md).

        Every hop's commit check rides the crypto.batch seam
        (Traced/Guarded verifiers: TPU kernel behind the breaker, CPU
        RLC fallback).  The cache spans the whole sync, so each hop's
        1/3-trust and 2/3 checks — which walk the same commit with
        overlapping old/new validator sets — and any bisection
        re-examination of an already-proved commit skip verified
        signatures instead of re-batching them (adjacent fallback
        hops previously ran uncached entirely)."""
        return await self._verify_at(height, now,
                                     cache=SignatureCache())

    def trusted_light_block(self, height: int) -> Optional[LightBlock]:
        return self.store.light_block(height)

    # ------------------------------------------------------------------
    def _closest_below(self, height: int) -> LightBlock:
        best = None
        for h in self.store.heights():
            if h <= height:
                best = h
        if best is None:
            raise LightClientError("no trusted block below target")
        return self.store.light_block(best)

    async def _verify_forward(self, trusted: LightBlock, height: int,
                              now: Timestamp,
                              prefetched: Optional[LightBlock] = None,
                              cache: Optional[SignatureCache] = None
                              ) -> LightBlock:
        trace: list[LightBlock] = [trusted]
        if self.mode == SEQUENTIAL:
            lb = await self._verify_sequential(trusted, height, now,
                                               trace, cache)
        else:
            lb = await self._verify_skipping(trusted, height, now,
                                             prefetched, trace, cache)
        await self._detect_divergence(lb, now, trace)
        return lb

    async def _verify_sequential(self, trusted: LightBlock,
                                 height: int, now: Timestamp,
                                 trace: Optional[list] = None,
                                 cache: Optional[SignatureCache] = None
                                 ) -> LightBlock:
        """Verify every header between trusted and height (reference:
        verifySequential)."""
        current = trusted
        for h in range(trusted.height + 1, height + 1):
            nxt = await self.primary.light_block(h)
            verify(current.signed_header, current.validator_set,
                   nxt.signed_header, nxt.validator_set,
                   self.trust_options.period_ns, now,
                   self.max_clock_drift_ns, self.trust_level,
                   cache=cache)
            self.store.save_light_block(nxt)
            if trace is not None:
                trace.append(nxt)
            current = nxt
        return current

    async def _verify_skipping(self, trusted: LightBlock, height: int,
                               now: Timestamp,
                               prefetched: Optional[LightBlock] = None,
                               trace: Optional[list] = None,
                               cache: Optional[SignatureCache] = None
                               ) -> LightBlock:
        """Bisection (reference: verifySkipping): try to jump straight
        to the target; on insufficient trust, bisect."""
        target = prefetched if prefetched is not None and \
            prefetched.height == height else \
            await self.primary.light_block(height)
        verified = trusted
        pivots = [target]
        while pivots:
            candidate = pivots[-1]
            try:
                verify(verified.signed_header, verified.validator_set,
                       candidate.signed_header, candidate.validator_set,
                       self.trust_options.period_ns, now,
                       self.max_clock_drift_ns, self.trust_level,
                       cache=cache)
                self.store.save_light_block(candidate)
                if trace is not None:
                    trace.append(candidate)
                verified = candidate
                pivots.pop()
            except LightClientError as e:
                from .verifier import NewValSetCantBeTrustedError
                if not isinstance(e, NewValSetCantBeTrustedError):
                    raise
                # can't jump that far: bisect
                pivot_height = (verified.height + candidate.height) // 2
                if pivot_height in (verified.height, candidate.height):
                    raise LightClientError(
                        "bisection failed: no trust path to target"
                    ) from e
                pivots.append(
                    await self.primary.light_block(pivot_height))
        return verified

    async def _backwards(self, first: LightBlock,
                         height: int) -> LightBlock:
        """Verify below the oldest trusted header via hash links
        (reference: backwards)."""
        current = first
        for h in range(first.height - 1, height - 1, -1):
            older = await self.primary.light_block(h)
            verify_backwards(older.signed_header.header,
                             current.signed_header.header)
            self.store.save_light_block(older)
            current = older
        return current

    # ------------------------------------------------------------------
    async def _detect_divergence(self, verified: LightBlock,
                                 now: Timestamp,
                                 trace: Optional[list] = None) -> None:
        """Cross-check the verified header against witnesses; on
        divergence, bisect OUR trace against the witness to find the
        common block, attribute the equivocators, and report evidence to
        both sides (reference: detector.go detectDivergence +
        examineConflictingHeaderAgainstTrace :236 +
        newLightClientAttackEvidence :420)."""
        if not self.witnesses:
            return
        h = verified.height
        target_hash = verified.signed_header.header.hash()
        trace = trace or [verified]
        bad: list[Provider] = []
        for w in self.witnesses:
            try:
                wlb = await w.light_block(h)
            except (ProviderError, LightBlockNotFoundError):
                continue
            if wlb.signed_header.header.hash() == target_hash:
                continue
            ev = await self._build_attack_evidence(w, wlb, trace)
            try:
                await self.primary.report_evidence(ev)
                await w.report_evidence(ev)
            except ProviderError:
                pass
            bad.append(w)
        if bad:
            for w in bad:
                self.witnesses.remove(w)
            raise DivergenceError(bad[0], evidence=None)

    async def _build_attack_evidence(self, witness: Provider,
                                     conflicting: LightBlock,
                                     trace: list
                                     ) -> LightClientAttackEvidence:
        """Walk the trace to the LAST block the witness agrees with —
        that is the common block; the trusted block is our verified end
        of trace (reference: examineConflictingHeaderAgainstTrace)."""
        common = trace[0]
        for tb in trace:
            try:
                wb = await witness.light_block(tb.height)
            except (ProviderError, LightBlockNotFoundError):
                break
            if wb.signed_header.header.hash() != \
                    tb.signed_header.header.hash():
                break
            common = tb
        trusted = trace[-1]
        if conflicting.height != common.height:
            common_height = common.height
            timestamp = common.signed_header.header.time
            total_power = common.validator_set.total_voting_power()
        else:
            common_height = trusted.height
            timestamp = trusted.signed_header.header.time
            total_power = trusted.validator_set.total_voting_power()
        ev = LightClientAttackEvidence(
            conflicting_block=conflicting,
            common_height=common_height,
            byzantine_validators=[],
            total_voting_power=total_power,
            timestamp=timestamp)
        ev.byzantine_validators = ev.get_byzantine_validators(
            common.validator_set, trusted.signed_header)
        return ev
