"""Trusted light-block store.

Reference: light/store/db/db.go — db-backed store of verified light
blocks, first/last heights, pruning to a size cap.
"""
from __future__ import annotations

import struct
from typing import Optional

from ..db import DB
from ..types.block import LightBlock
from ..wire import pb, encode, decode

_LB = b"lb/"
_SIZE_CAP_DEFAULT = 1000


def _key(height: int) -> bytes:
    return _LB + struct.pack(">q", height)


class TrustedStore:
    def __init__(self, db: DB):
        self._db = db

    def save_light_block(self, lb: LightBlock) -> None:
        self._db.set(_key(lb.height),
                     encode(pb.LIGHT_BLOCK, lb.to_proto()))

    def light_block(self, height: int) -> Optional[LightBlock]:
        raw = self._db.get(_key(height))
        if raw is None:
            return None
        return LightBlock.from_proto(decode(pb.LIGHT_BLOCK, raw))

    def latest(self) -> Optional[LightBlock]:
        for _, raw in self._db.reverse_iterator(_LB, _LB + b"\xff" * 9):
            return LightBlock.from_proto(decode(pb.LIGHT_BLOCK, raw))
        return None

    def first(self) -> Optional[LightBlock]:
        for _, raw in self._db.iterator(_LB, _LB + b"\xff" * 9):
            return LightBlock.from_proto(decode(pb.LIGHT_BLOCK, raw))
        return None

    def heights(self) -> list[int]:
        return [struct.unpack(">q", k[len(_LB):])[0]
                for k, _ in self._db.iterator(_LB, _LB + b"\xff" * 9)]

    def prune(self, size: int = _SIZE_CAP_DEFAULT) -> int:
        hs = self.heights()
        pruned = 0
        while len(hs) - pruned > size:
            self._db.delete(_key(hs[pruned]))
            pruned += 1
        return pruned

    def delete(self, height: int) -> None:
        self._db.delete(_key(height))
