"""Light-client providers: sources of light blocks.

Reference: light/provider/provider.go (interface), provider/http (RPC
client impl).  The RPC provider arrives with the light proxy; the node
provider serves straight from local stores (used in-process and by
tests, mirroring provider/mock + local RPC).
"""
from __future__ import annotations

import abc
import asyncio
from typing import Optional

from ..types.block import LightBlock, SignedHeader


class ProviderError(Exception):
    pass


class LightBlockNotFoundError(ProviderError):
    pass


class Provider(abc.ABC):
    @abc.abstractmethod
    async def light_block(self, height: int) -> LightBlock:
        """Light block at height (0 = latest).  Raises
        LightBlockNotFoundError."""

    @abc.abstractmethod
    async def report_evidence(self, ev) -> None: ...

    def id(self) -> str:
        return self.__class__.__name__


class NodeProvider(Provider):
    """Serves light blocks from a node's stores."""

    def __init__(self, block_store, state_store, chain_id: str):
        self.block_store = block_store
        self.state_store = state_store
        self.chain_id = chain_id
        self.evidence: list = []

    async def light_block(self, height: int) -> LightBlock:
        if height == 0:
            height = self.block_store.height
        meta = self.block_store.load_block_meta(height)
        commit = self.block_store.load_block_commit(height)
        if commit is None:
            commit = self.block_store.load_seen_commit(height)
        if meta is None or commit is None:
            raise LightBlockNotFoundError(
                f"no light block at height {height}")
        vals = self.state_store.load_validators(height)
        return LightBlock(
            signed_header=SignedHeader(header=meta.header,
                                       commit=commit),
            validator_set=vals)

    async def report_evidence(self, ev) -> None:
        self.evidence.append(ev)

    def id(self) -> str:
        return f"node-provider:{self.chain_id}"


class HttpProvider(Provider):
    """Light blocks over a node's RPC (reference:
    light/provider/http/http.go — /commit + paged /validators)."""

    def __init__(self, address: str, chain_id: str = ""):
        from ..rpc.client import HTTPClient
        self.client = HTTPClient(address)
        self.chain_id = chain_id
        self.address = address
        self._has_light_block = True   # downgraded on first -32601

    async def light_block(self, height: int) -> LightBlock:
        from ..rpc.client import RPCClientError
        try:
            if self._has_light_block:
                # one round trip via the lightserve route; servers
                # predating it answer method-not-found and we fall
                # back to /commit + paged /validators for good
                try:
                    lb = await self.client.light_block(height)
                except RPCClientError as e:
                    if "-32601" not in str(e):   # method not found
                        raise
                    self._has_light_block = False
                    lb = None
            else:
                lb = None
            if lb is None:
                signed_header, _ = await self.client.commit(height)
                h = signed_header.header.height
                vals = await self.client.validators(h)
                lb = LightBlock(signed_header=signed_header,
                                validator_set=vals)
        except RPCClientError as e:
            raise LightBlockNotFoundError(str(e)) from None
        except (OSError, asyncio.TimeoutError) as e:
            raise ProviderError(
                f"provider {self.address} unreachable: {e}") from None
        if self.chain_id:
            lb.validate_basic(self.chain_id)
        return lb

    async def report_evidence(self, ev) -> None:
        """POST wire-encoded evidence to the node's broadcast_evidence
        RPC (reference: http provider ReportEvidence ->
        rpc/core/evidence.go)."""
        import base64
        from ..wire import pb as _pb, encode as _encode
        raw = _encode(_pb.EVIDENCE, ev.to_proto_wrapped())
        await self.client.call(
            "broadcast_evidence", evidence=base64.b64encode(raw).decode())

    def id(self) -> str:
        return f"http{{{self.address}}}"
