"""Light-client providers: sources of light blocks.

Reference: light/provider/provider.go (interface), provider/http (RPC
client impl).  The RPC provider arrives with the light proxy; the node
provider serves straight from local stores (used in-process and by
tests, mirroring provider/mock + local RPC).
"""
from __future__ import annotations

import abc
from typing import Optional

from ..types.block import LightBlock, SignedHeader


class ProviderError(Exception):
    pass


class LightBlockNotFoundError(ProviderError):
    pass


class Provider(abc.ABC):
    @abc.abstractmethod
    async def light_block(self, height: int) -> LightBlock:
        """Light block at height (0 = latest).  Raises
        LightBlockNotFoundError."""

    @abc.abstractmethod
    async def report_evidence(self, ev) -> None: ...

    def id(self) -> str:
        return self.__class__.__name__


class NodeProvider(Provider):
    """Serves light blocks from a node's stores."""

    def __init__(self, block_store, state_store, chain_id: str):
        self.block_store = block_store
        self.state_store = state_store
        self.chain_id = chain_id
        self.evidence: list = []

    async def light_block(self, height: int) -> LightBlock:
        if height == 0:
            height = self.block_store.height
        meta = self.block_store.load_block_meta(height)
        commit = self.block_store.load_block_commit(height)
        if commit is None:
            commit = self.block_store.load_seen_commit(height)
        if meta is None or commit is None:
            raise LightBlockNotFoundError(
                f"no light block at height {height}")
        vals = self.state_store.load_validators(height)
        return LightBlock(
            signed_header=SignedHeader(header=meta.header,
                                       commit=commit),
            validator_set=vals)

    async def report_evidence(self, ev) -> None:
        self.evidence.append(ev)

    def id(self) -> str:
        return f"node-provider:{self.chain_id}"
