#!/usr/bin/env python
"""Generate the metrics catalog for docs/observability.md.

Assembles every metric family a node registers (all per-subsystem
Metrics classes on one registry, plus the lazily-registered families
on the process-global DEFAULT: crypto batch-verify / kernel-dispatch
histograms, breaker state, signature-cache counters) and prints a
markdown table of name, type, labels and help — the docs section is
pasted from this output, and the exposition contract test keeps the
registry honest (non-empty help, bounded labels).

Usage: python tools/metrics_catalog.py [--markdown|--json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def collect_catalog() -> list[dict]:
    from cometbft_tpu.abci.metrics import Metrics as ProxyMetrics
    from cometbft_tpu.blocksync.metrics import (
        Metrics as BlocksyncMetrics,
    )
    from cometbft_tpu.consensus.metrics import (
        Metrics as ConsensusMetrics,
    )
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.libs import metrics as libmetrics
    from cometbft_tpu.libs.health import Metrics as HealthMetrics
    from cometbft_tpu.libs.supervisor import (
        Metrics as SupervisorMetrics,
    )
    from cometbft_tpu.lightserve.cache import (
        Metrics as LightserveMetrics,
    )
    from cometbft_tpu.mempool.metrics import Metrics as MempoolMetrics
    from cometbft_tpu.ops import ed25519_jax
    from cometbft_tpu.p2p.metrics import Metrics as P2PMetrics
    from cometbft_tpu.state.metrics import Metrics as StateMetrics
    from cometbft_tpu.statesync.metrics import (
        Metrics as StatesyncMetrics,
    )
    from cometbft_tpu.types import signature_cache

    reg = libmetrics.Registry()
    for cls in (ConsensusMetrics, MempoolMetrics, P2PMetrics,
                BlocksyncMetrics, StatesyncMetrics, StateMetrics,
                ProxyMetrics, SupervisorMetrics, LightserveMetrics,
                HealthMetrics):
        cls(reg)
    # force the lazy process-global families into existence
    from cometbft_tpu.crypto import bls12381
    from cometbft_tpu.crypto import pipeline as crypto_pipeline
    from cometbft_tpu.types import validation as types_validation
    crypto_batch.verify_seconds_histogram()
    crypto_batch.tpu_breaker()
    ed25519_jax._dispatch_histogram()
    ed25519_jax._refine_counter()
    signature_cache._metrics()
    bls12381._agg_pk_metrics()
    types_validation.commit_verify_histogram()
    # verification pipeline: overlap ratio + tile rejects, and the
    # staging/kernel workers' queue-wait/depth families (register a
    # worker on a throwaway registry-backed pair via the lazy
    # singletons' metric declarations)
    crypto_pipeline.overlap_histogram()
    crypto_pipeline._tile_reject_counter()
    from cometbft_tpu.libs.workers import SupervisedWorker
    _w = SupervisedWorker("catalog_probe")
    _w.stop()

    seen = set()
    out = []
    for fam in reg.collect() + libmetrics.DEFAULT.collect():
        if fam["name"] in seen:
            continue
        seen.add(fam["name"])
        out.append(fam)
    return sorted(out, key=lambda f: f["name"])


def to_markdown(catalog: list[dict]) -> str:
    lines = ["| Name | Type | Labels | Help |",
             "|------|------|--------|------|"]
    for fam in catalog:
        labels = ", ".join(f"`{l}`" for l in fam["labels"]) or "—"
        help_ = fam["help"].replace("\n", " ").replace("|", "\\|")
        lines.append(
            f"| `{fam['name']}` | {fam['kind']} | {labels} "
            f"| {help_} |")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", action="store_true",
                    help="JSON instead of markdown")
    args = ap.parse_args(argv)
    catalog = collect_catalog()
    if args.json:
        print(json.dumps(catalog, indent=2))
    else:
        print(to_markdown(catalog))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
