#!/usr/bin/env python3
"""Render a per-height latency breakdown from a flight-record dump.

Input: a JSON dump written by the flight recorder
(cometbft_tpu/libs/tracing.py) — the /trace RPC body, a
``/debug/pprof/trace?dump=1`` file, a supervisor give-up dump, or a
nemesis safety-violation dump.  Output: one row per height attributing
the height's wall-clock to gossip / verify / execute / commit, plus
the batch-verify dispatches observed.

    python tools/trace_report.py flight-<pid>-001-*.json [--height H]

Attribution rules
-----------------
Height *windows* come from consensus events (they carry a height);
events recorded without a height (crypto kernel dispatches, abci
calls, p2p frames) are attributed to the window their monotonic
timestamp falls into.  Buckets:

  * gossip   — window start → ``proposal_complete`` (the time spent
               collecting the proposal over p2p), falling back to the
               ``step:Propose`` span;
  * verify   — crypto ``batch_verify``/``kernel_execute``/``host_prep``
               spans plus consensus ``validate_block``;
  * execute  — abci call spans (the app's share);
  * commit   — ``save_block`` plus the ``step:Commit`` span (fsync +
               finalize path);
  * pipeline — ``apply_block`` + ``barrier_wait``: the pipelined
               execute/commit overlapping the NEXT height, and the
               barrier stalls when it didn't finish in time.  Reported
               separately because pipelined work off the critical path
               must not be read as height wall-clock.

Marker instants (compact-block relay, aggregate-commit catchup, vote
and part arrivals) are counted per height in the ``markers`` column —
they carry no duration, but their counts tell the protocol story
(e.g. ``compact_block_miss`` > 0 means the reconstruct fast path fell
back to full parts).
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

_MS = 1e6  # ns per ms

# crypto span names that count as "verify" work
_VERIFY_NAMES = {"batch_verify", "kernel_execute", "host_prep",
                 "kernel_compile"}

# consensus span -> bucket (tests/test_observability_drift.py pins
# this table against the names the instrumented modules actually
# emit; "step:*" spans are matched by prefix)
CONSENSUS_SPAN_BUCKETS = {
    "validate_block": "verify",
    "save_block": "commit",
    "step:Commit": "commit",
    "apply_block": "pipeline",
    "barrier_wait": "pipeline",
}

# consensus instants counted per height (zero-duration markers)
CONSENSUS_MARKERS = frozenset({
    "proposal_recv", "proposal_received", "proposal_complete",
    "proposal_broadcast", "block_part_recv", "vote_recv",
    "compact_block_recv", "compact_block_rebuilt",
    "compact_block_miss", "compact_block_nack",
    "agg_commit_recv", "agg_commit_shed", "pipeline_advance",
    "commit",
})


def _to_int(v) -> int:
    try:
        return int(v)
    except (TypeError, ValueError):
        return 0


def _events(record: dict) -> list[dict]:
    evs = record.get("events", record if isinstance(record, list)
                     else [])
    out = []
    for e in evs:
        out.append({
            "ts_ns": _to_int(e.get("ts_ns")),
            "dur_ns": _to_int(e.get("dur_ns")),
            "category": e.get("category", ""),
            "name": e.get("name", ""),
            "height": _to_int(e.get("height")),
            "attrs": e.get("attrs") or {},
        })
    out.sort(key=lambda e: e["ts_ns"])
    return out


def _height_windows(events: list[dict]) -> dict[int, tuple[int, int]]:
    """height -> (first_ts, last_ts+dur) from height-stamped events."""
    win: dict[int, tuple[int, int]] = {}
    for e in events:
        h = e["height"]
        if h <= 0:
            continue
        end = e["ts_ns"] + e["dur_ns"]
        lo, hi = win.get(h, (e["ts_ns"], end))
        win[h] = (min(lo, e["ts_ns"]), max(hi, end))
    return win


def _attribute(events: list[dict],
               windows: dict[int, tuple[int, int]]) -> None:
    """Stamp height-less events with the height whose window contains
    their timestamp (in place)."""
    ordered = sorted(windows.items())
    for e in events:
        if e["height"] > 0:
            continue
        ts = e["ts_ns"]
        for h, (lo, hi) in ordered:
            if lo <= ts <= hi:
                e["height"] = h
                break


def analyze(record: dict,
            height: Optional[int] = None) -> dict[int, dict]:
    """Per-height breakdown (values in ms) keyed by height."""
    events = _events(record)
    windows = _height_windows(events)
    _attribute(events, windows)
    out: dict[int, dict] = {}
    for h, (lo, hi) in sorted(windows.items()):
        if height is not None and h != height:
            continue
        row = {"wall_ms": (hi - lo) / _MS, "gossip_ms": 0.0,
               "verify_ms": 0.0, "execute_ms": 0.0, "commit_ms": 0.0,
               "pipeline_ms": 0.0,
               "p2p_events": 0, "p2p_bytes": 0, "stalls": 0,
               "markers": {}, "batches": []}
        propose_span = 0.0
        proposal_complete_ts = None
        for e in events:
            if e["height"] != h:
                continue
            cat, name, dur = e["category"], e["name"], e["dur_ns"]
            if cat == "crypto" and name in _VERIFY_NAMES:
                row["verify_ms"] += dur / _MS
                if name in ("batch_verify", "kernel_execute"):
                    a = e["attrs"]
                    row["batches"].append({
                        "name": name,
                        "batch": a.get("batch"),
                        "backend": a.get("backend",
                                         a.get("kernel", "?")),
                        "bucket": a.get("bucket"),
                        "ms": dur / _MS})
            elif cat == "abci":
                row["execute_ms"] += dur / _MS
            elif cat == "p2p":
                row["p2p_events"] += 1
                row["p2p_bytes"] += _to_int(
                    e["attrs"].get("bytes", 0))
                if name.endswith(("_full", "_stall")):
                    row["stalls"] += 1
            elif cat == "consensus":
                bucket = CONSENSUS_SPAN_BUCKETS.get(name)
                if bucket is not None:
                    row[bucket + "_ms"] += dur / _MS
                elif name in CONSENSUS_MARKERS:
                    row["markers"][name] = \
                        row["markers"].get(name, 0) + 1
                if name == "step:Propose":
                    propose_span = dur / _MS
                elif name == "proposal_complete":
                    proposal_complete_ts = e["ts_ns"]
        row["gossip_ms"] = ((proposal_complete_ts - lo) / _MS
                            if proposal_complete_ts is not None
                            else propose_span)
        out[h] = row
    return out


def render_report(record: dict,
                  height: Optional[int] = None) -> str:
    rows = analyze(record, height=height)
    lines = []
    reason = record.get("reason")
    if reason:
        lines.append(f"flight record: {reason} "
                     f"({record.get('wall_time', '?')})")
    extra = record.get("extra") or {}
    if extra.get("conflicting_heights"):
        lines.append("conflicting-commit heights: "
                     f"{extra['conflicting_heights']}")
    if not rows:
        lines.append("no height-stamped events in this record")
        return "\n".join(lines) + "\n"
    hdr = (f"{'height':>7} {'wall_ms':>9} {'gossip_ms':>10} "
           f"{'verify_ms':>10} {'execute_ms':>11} {'commit_ms':>10} "
           f"{'pipe_ms':>8} {'p2p ev':>7} {'stalls':>7}")
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for h, r in rows.items():
        lines.append(
            f"{h:>7} {r['wall_ms']:>9.2f} {r['gossip_ms']:>10.2f} "
            f"{r['verify_ms']:>10.2f} {r['execute_ms']:>11.2f} "
            f"{r['commit_ms']:>10.2f} {r['pipeline_ms']:>8.2f} "
            f"{r['p2p_events']:>7} {r['stalls']:>7}")
    for h, r in rows.items():
        if r["markers"]:
            mk = " ".join(f"{k}={v}" for k, v in
                          sorted(r["markers"].items()))
            lines.append(f"        h{h} markers: {mk}")
        for b in r["batches"]:
            lines.append(
                f"        h{h} {b['name']}: batch={b['batch']} "
                f"backend={b['backend']} bucket={b['bucket']} "
                f"{b['ms']:.2f}ms")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Per-height latency breakdown from a flight-"
                    "record dump")
    p.add_argument("dump", help="flight-record JSON file")
    p.add_argument("--height", type=int, default=None,
                   help="restrict to one height")
    p.add_argument("--json", action="store_true",
                   help="JSON instead of text")
    args = p.parse_args(argv)
    with open(args.dump) as f:
        record = json.load(f)
    if args.json:
        json.dump(analyze(record, height=args.height), sys.stdout,
                  indent=2)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render_report(record, height=args.height))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
