"""cwd-write: node code must not write relative paths.

The PR 4 flight-dump bug class: the recorder dropped
``flight-*.json`` into whatever directory the process happened to be
started from, polluting the repo root in tests and silently
scattering crash dumps in production.  The fix threaded an explicit
dump dir (data dir / config / env / tempdir); this rule keeps every
*other* write honest.

Flags write-mode ``open()`` and ``Path("...").write_text/write_bytes``
whose path is a *visibly relative* literal (a plain or formatted
string not anchored at ``/``, ``~`` or a variable prefix).  Paths
held in variables are not judged — the rule bounds false positives by
only flagging what it can prove.  CLI tools under cometbft_tpu/tools/
are exempt: writing reports into the invoker's CWD is their contract.
"""
from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import Checker, FileContext, Finding

# "+" catches update modes ("r+", "rb+") that write without w/a/x
_WRITE_MODES = ("w", "a", "x", "+")
_PATH_WRITE_TAILS = {"write_text", "write_bytes"}


def _relative_literal(arg: ast.expr) -> Optional[str]:
    """Return a display string when ``arg`` is a provably-relative
    path literal, else None."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        v = arg.value
        if v and not v.startswith(("/", "~")):
            return v
    if isinstance(arg, ast.JoinedStr) and arg.values:
        head = arg.values[0]
        # f"{var}/..." anchors at a variable — not judged; a leading
        # relative literal (f"flight-{h}.json") is provably relative
        if isinstance(head, ast.Constant) and \
                isinstance(head.value, str) and head.value and \
                not head.value.startswith(("/", "~")):
            return ast.unparse(arg) if hasattr(ast, "unparse") \
                else head.value + "..."
    return None


def _open_write_mode(call: ast.Call) -> bool:
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    return (isinstance(mode, ast.Constant)
            and isinstance(mode.value, str)
            and any(c in mode.value for c in _WRITE_MODES))


class CwdWriteChecker(Checker):
    rule = "cwd-write"
    description = ("write to a relative path lands in the process "
                   "CWD (the PR 4 flight-dump bug class)")
    scope = ("cometbft_tpu/*",)

    def in_scope(self, logical_path: str) -> bool:
        if logical_path.startswith("cometbft_tpu/tools/"):
            return False
        return super().in_scope(logical_path)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.nodes(ast.Call):
            rel = None
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id == "open" and \
                    node.args and _open_write_mode(node):
                rel = _relative_literal(node.args[0])
            elif isinstance(fn, ast.Attribute) and \
                    fn.attr in _PATH_WRITE_TAILS and \
                    isinstance(fn.value, ast.Call) and \
                    isinstance(fn.value.func, ast.Name) and \
                    fn.value.func.id == "Path" and fn.value.args:
                rel = _relative_literal(fn.value.args[0])
            if rel is None:
                continue
            yield ctx.finding(
                self.rule, node,
                f"write to relative path `{rel}` lands in whatever "
                f"CWD the process started from — anchor it at the "
                f"node data dir, an explicit config dir, or a "
                f"tempdir (see Recorder.resolved_dump_dir)")
