"""monotonic-clock: no wall-clock arithmetic in consensus/p2p/mempool.

PR 2's clock-discipline satellite converted reactor
``seconds_since_start_time`` and pex ``last_seen`` to
``time.monotonic()`` after wall-clock steps (NTP slew, VM suspend)
were shown to corrupt interval arithmetic — freshness ordering,
timeout scheduling, rate windows.  Wall time is only meaningful at
persistence boundaries (the pex addrbook save/load converts via the
current offset) and in exposition metadata (exemplar timestamps).

The checker flags ``time.time()`` / ``datetime.now()`` /
``datetime.utcnow()`` in consensus, p2p, mempool and libs code.
Known persistence boundaries are allowlisted below; anything else
needs an inline ``# bftlint: disable=monotonic-clock`` with a reason,
or a fix.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..core import Checker, FileContext, Finding, call_name

_WALL_CALLS = {
    "time.time",
    "datetime.now", "datetime.utcnow", "datetime.today",
    "datetime.datetime.now", "datetime.datetime.utcnow",
}

# (logical path, enclosing function) pairs where wall clock is the
# point: serializing to/from disk, where monotonic stamps would be
# meaningless across reboots.  PR 2 established the pex addrbook
# save/load as the canonical wall<->monotonic conversion boundary.
PERSISTENCE_ALLOWLIST: set[tuple[str, str]] = {
    ("cometbft_tpu/p2p/pex.py", "AddrBook.save"),
    ("cometbft_tpu/p2p/pex.py", "AddrBook._load"),
}


class MonotonicClockChecker(Checker):
    rule = "monotonic-clock"
    description = ("wall-clock call in interval-arithmetic scope; "
                   "use time.monotonic() (wall time only at "
                   "persistence boundaries)")
    scope = (
        "cometbft_tpu/consensus/*",
        "cometbft_tpu/p2p/*",
        "cometbft_tpu/mempool/*",
        "cometbft_tpu/libs/*",
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.nodes(ast.Call):
            name = call_name(node)
            if name not in _WALL_CALLS:
                continue
            key = (ctx.logical_path, ctx.scope_of(node))
            if key in PERSISTENCE_ALLOWLIST:
                continue
            yield ctx.finding(
                self.rule, node,
                f"{name}() is wall clock — steps under NTP slew/VM "
                f"suspend corrupt interval arithmetic; use "
                f"time.monotonic(), converting to wall time only at "
                f"persistence boundaries (PR 2 clock discipline)")
