"""blocking-in-async: no synchronous sleeps/sockets/file I/O inside
``async def`` on hot paths.

One blocking call inside a coroutine stalls the *entire* node — every
reactor, every peer connection, the consensus state machine — because
there is exactly one event loop.  ``time.sleep(0.1)`` in a receive
handler is a 100ms global freeze; a synchronous ``open()`` on a slow
disk is unbounded.

Flags, inside any ``async def`` in consensus/p2p/mempool/abci/node
code: ``time.sleep``, synchronous socket construction/connection,
``subprocess`` calls, ``os.system``, ``urllib`` fetches, builtin
``open`` and ``Path.read_*/write_*``.  Intentional synchronous
durability points (the consensus WAL's write-through fsync is a
correctness requirement, not an accident) get inline suppressions or
baseline entries with the reason.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..core import Checker, FileContext, Finding, call_name

_BLOCKING_CALLS = {
    "time.sleep",
    "socket.socket", "socket.create_connection",
    "socket.getaddrinfo", "socket.gethostbyname",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "os.system", "os.popen",
    "urllib.request.urlopen", "requests.get", "requests.post",
    "open",
}
_BLOCKING_TAILS = {"read_text", "read_bytes", "write_text",
                   "write_bytes"}


class BlockingInAsyncChecker(Checker):
    rule = "blocking-in-async"
    description = ("synchronous sleep/socket/file I/O inside an "
                   "async def stalls the whole event loop")
    scope = (
        "cometbft_tpu/consensus/*",
        "cometbft_tpu/p2p/*",
        "cometbft_tpu/mempool/*",
        "cometbft_tpu/abci/*",
        "cometbft_tpu/node/*",
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.nodes(ast.Call):
            if not ctx.in_async_def(node):
                continue
            name = call_name(node)
            tail = name.rsplit(".", 1)[-1]
            # attribute calls only: a bare local `read_text()` is not
            # Path I/O, but any receiver counts — including a chained
            # `Path("wal.json").read_text()`, where call_name
            # truncates at the inner Call and drops the dot
            if name in _BLOCKING_CALLS or \
                    (tail in _BLOCKING_TAILS
                     and isinstance(node.func, ast.Attribute)):
                yield ctx.finding(
                    self.rule, node,
                    f"{name}() blocks the event loop inside an async "
                    f"def — every reactor and peer stalls with it; "
                    f"use the asyncio equivalent (asyncio.sleep, "
                    f"loop.run_in_executor, to_thread) or justify "
                    f"the synchronous durability point")


__all__ = ["BlockingInAsyncChecker"]
