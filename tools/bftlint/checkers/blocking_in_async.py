"""blocking-in-async: no synchronous sleeps/sockets/file I/O inside
``async def`` on hot paths.

One blocking call inside a coroutine stalls the *entire* node — every
reactor, every peer connection, the consensus state machine — because
there is exactly one event loop.  ``time.sleep(0.1)`` in a receive
handler is a 100ms global freeze; a synchronous ``open()`` on a slow
disk is unbounded.

Flags, inside any ``async def`` in consensus/p2p/mempool/abci/node
code: ``time.sleep``, synchronous socket construction/connection,
``subprocess`` calls, ``os.system``, ``urllib`` fetches, builtin
``open`` and ``Path.read_*/write_*``.  Intentional synchronous
durability points (the consensus WAL's write-through fsync is a
correctness requirement, not an accident) get inline suppressions or
baseline entries with the reason.

ISSUE 14 extension — synchronous signature verification is the same
bug with a bigger constant: a 10k-signature ``BatchVerifier.verify()``
freezes the loop for ~190 ms (QA_r08 profiled verify stalls stacking
behind p2p recv), and ``block_until_ready()`` pins the loop on a
device future.  Inside consensus/reactor async scopes the rule flags
``<*verifier*>.verify()`` / ``bv.verify()``, bare or attribute
``preverify_signatures(...)``, and any ``block_until_ready()`` —
the off-loop seam (``verify_async()`` /
``preverify_signatures_async()`` + the verification staging worker,
crypto/pipeline.py) is the replacement.

ISSUE 20 extension — interprocedural: a ``time.sleep()`` moved one
helper-call deep used to be invisible.  With the whole-package effect
summaries (callgraph.py), a call in a scoped ``async def`` to a
resolved helper whose ``may_block`` summary is true is flagged at the
call site, with the full witness chain in the message (``helper →
sub_helper → open() [path:line]``).  Sound default: unresolved calls
carry ``may_block=False`` — the rule only claims blocking it can
prove, so stdlib/dynamic dispatch cannot flood async code with
unfixable findings.  The sync-verify receiver heuristic above stays
intra-procedural on purpose: it keys on receiver *names*, which do
not survive the hop into a helper's parameter list.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..callgraph import BLOCKING_CALLS as _BLOCKING_CALLS
from ..callgraph import BLOCKING_TAILS as _BLOCKING_TAILS
from ..core import Checker, FileContext, Finding, call_name

# synchronous verification inside an async scope: the receiver names
# that identify a batch verifier (narrow on purpose — `proof.verify()`
# shapes outside the crypto seam must not trip)
_VERIFIER_RECEIVERS = ("bv", "verifier", "batch_verifier")
_VERIFY_BLOCK_TAILS = {"block_until_ready", "preverify_signatures"}


def _receiver_name(node: ast.Call) -> str:
    """Final identifier of the call receiver: ``self._bv.verify()``
    -> ``_bv``; bare ``verify()`` -> ''."""
    if not isinstance(node.func, ast.Attribute):
        return ""
    recv = node.func.value
    if isinstance(recv, ast.Attribute):
        return recv.attr
    if isinstance(recv, ast.Name):
        return recv.id
    return ""


def _is_sync_verify(node: ast.Call, name: str, tail: str) -> bool:
    if tail in _VERIFY_BLOCK_TAILS:
        return True
    if tail != "verify" or not isinstance(node.func, ast.Attribute):
        return False
    recv = _receiver_name(node).lower()
    return recv in _VERIFIER_RECEIVERS or recv.endswith("verifier")


class BlockingInAsyncChecker(Checker):
    rule = "blocking-in-async"
    description = ("synchronous sleep/socket/file I/O inside an "
                   "async def stalls the whole event loop")
    scope = (
        "cometbft_tpu/consensus/*",
        "cometbft_tpu/p2p/*",
        "cometbft_tpu/mempool/*",
        "cometbft_tpu/abci/*",
        "cometbft_tpu/node/*",
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.nodes(ast.Call):
            if not ctx.in_async_def(node):
                continue
            name = call_name(node)
            tail = name.rsplit(".", 1)[-1]
            # attribute calls only: a bare local `read_text()` is not
            # Path I/O, but any receiver counts — including a chained
            # `Path("wal.json").read_text()`, where call_name
            # truncates at the inner Call and drops the dot
            if name in _BLOCKING_CALLS or \
                    (tail in _BLOCKING_TAILS
                     and isinstance(node.func, ast.Attribute)):
                yield ctx.finding(
                    self.rule, node,
                    f"{name}() blocks the event loop inside an async "
                    f"def — every reactor and peer stalls with it; "
                    f"use the asyncio equivalent (asyncio.sleep, "
                    f"loop.run_in_executor, to_thread) or justify "
                    f"the synchronous durability point")
            elif _is_sync_verify(node, name, tail):
                yield ctx.finding(
                    self.rule, node,
                    f"{name}() runs signature verification (or a "
                    f"device-future wait) synchronously inside an "
                    f"async def — a 10k-sig batch freezes every "
                    f"reactor for ~200 ms; submit it through the "
                    f"off-loop seam instead (verify_async() / "
                    f"preverify_signatures_async(), "
                    f"crypto/pipeline.py)")
            elif ctx.program is not None:
                callee = ctx.program.resolve_call(ctx, node)
                if callee is None:
                    continue
                if ctx.program.summary(callee).may_block:
                    chain = " -> ".join(
                        ctx.program.blocking_chain(callee))
                    yield ctx.finding(
                        self.rule, node,
                        f"{name}() transitively blocks the event "
                        f"loop inside an async def via "
                        f"{callee.qualname} -> {chain}; move the "
                        f"blocking call off-loop (asyncio.sleep, "
                        f"run_in_executor, to_thread, the "
                        f"verification staging worker) or justify "
                        f"the synchronous durability point at the "
                        f"blocking site")


__all__ = ["BlockingInAsyncChecker"]
