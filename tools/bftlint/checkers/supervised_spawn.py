"""supervised-spawn: reactor/node background loops are supervisor-owned.

PR 1 (failure-domain supervision) moved every reactor/switch/consensus
background loop under libs/supervisor.py so an uncaught exception
restarts the loop (bounded, metered) instead of silently killing it.
A bare ``asyncio.create_task`` / ``loop.create_task`` /
``ensure_future`` in reactor or node code is a regression — spawn
through ``self.supervisor.spawn(...)`` instead.

This checker absorbs tests/test_supervised_tasks_ast.py, carrying its
scope and (empty) allowlist over exactly.  Library plumbing that
manages its own task lifecycle with in-loop error handling
(p2p/conn.py MConnection, abci/client.py SocketClient, libs/service)
is deliberately out of scope — those are transports, not reactor/node
loops.

ISSUE 20 extension — one level of wrappers: ``def _start(self):
asyncio.create_task(...)`` called from reactor code used to hide the
bare spawn if the wrapper lived outside the scoped files.  Calls in
scoped files that resolve (callgraph.py) to a function whose
``spawns_directly`` summary is true are now flagged at the call site,
naming the wrapper.  One level only, by design: the summary records
*direct* spawns, not transitive ones — deep spawn plumbing should be
the supervisor, not a wrapper chain.  ``self.supervisor.spawn(...)``
stays clean (an attribute-of-attribute receiver never resolves), and
spawns inline-suppressed at their own site do not propagate into the
wrapper's summary.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..core import Checker, FileContext, Finding

_SPAWN_ATTRS = {"create_task", "ensure_future"}

# (logical path, line) pairs exempted from the invariant.  Keep this
# EMPTY unless a spawn is provably supervisor-mediated and cannot be
# expressed through Supervisor.spawn — and document why here.
# (Carried over, still empty, from test_supervised_tasks_ast.py.)
ALLOWLIST: set[tuple[str, int]] = set()


class SupervisedSpawnChecker(Checker):
    rule = "supervised-spawn"
    description = ("bare create_task/ensure_future in reactor/node "
                   "scope; use self.supervisor.spawn(...)")
    scope = (
        "cometbft_tpu/*/reactor.py",
        "cometbft_tpu/node/node.py",
        "cometbft_tpu/consensus/state.py",
        "cometbft_tpu/p2p/switch.py",
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.nodes(ast.Call):
            fn = node.func
            name = ""
            if isinstance(fn, ast.Attribute) and \
                    fn.attr in _SPAWN_ATTRS:
                name = fn.attr
            elif isinstance(fn, ast.Name) and fn.id in _SPAWN_ATTRS:
                name = fn.id
            if not name:
                if ctx.program is not None:
                    callee = ctx.program.resolve_call(ctx, node)
                    if callee is not None and \
                            ctx.program.summary(callee) \
                               .spawns_directly and \
                            (ctx.logical_path, node.lineno) \
                            not in ALLOWLIST:
                        yield ctx.finding(
                            self.rule, node,
                            f"call spawns an unsupervised task one "
                            f"level down via {callee.qualname} "
                            f"({callee.location()}) — route the "
                            f"spawn through "
                            f"self.supervisor.spawn(...) so crashes "
                            f"restart (bounded) instead of dying "
                            f"silently")
                continue
            if (ctx.logical_path, node.lineno) in ALLOWLIST:
                continue
            yield ctx.finding(
                self.rule, node,
                f"unsupervised task spawn ({name}) in reactor/node "
                f"code — use self.supervisor.spawn(...) so crashes "
                f"restart (bounded) instead of dying silently")
