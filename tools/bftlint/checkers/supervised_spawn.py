"""supervised-spawn: reactor/node background loops are supervisor-owned.

PR 1 (failure-domain supervision) moved every reactor/switch/consensus
background loop under libs/supervisor.py so an uncaught exception
restarts the loop (bounded, metered) instead of silently killing it.
A bare ``asyncio.create_task`` / ``loop.create_task`` /
``ensure_future`` in reactor or node code is a regression — spawn
through ``self.supervisor.spawn(...)`` instead.

This checker absorbs tests/test_supervised_tasks_ast.py, carrying its
scope and (empty) allowlist over exactly.  Library plumbing that
manages its own task lifecycle with in-loop error handling
(p2p/conn.py MConnection, abci/client.py SocketClient, libs/service)
is deliberately out of scope — those are transports, not reactor/node
loops.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..core import Checker, FileContext, Finding

_SPAWN_ATTRS = {"create_task", "ensure_future"}

# (logical path, line) pairs exempted from the invariant.  Keep this
# EMPTY unless a spawn is provably supervisor-mediated and cannot be
# expressed through Supervisor.spawn — and document why here.
# (Carried over, still empty, from test_supervised_tasks_ast.py.)
ALLOWLIST: set[tuple[str, int]] = set()


class SupervisedSpawnChecker(Checker):
    rule = "supervised-spawn"
    description = ("bare create_task/ensure_future in reactor/node "
                   "scope; use self.supervisor.spawn(...)")
    scope = (
        "cometbft_tpu/*/reactor.py",
        "cometbft_tpu/node/node.py",
        "cometbft_tpu/consensus/state.py",
        "cometbft_tpu/p2p/switch.py",
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.nodes(ast.Call):
            fn = node.func
            name = ""
            if isinstance(fn, ast.Attribute) and \
                    fn.attr in _SPAWN_ATTRS:
                name = fn.attr
            elif isinstance(fn, ast.Name) and fn.id in _SPAWN_ATTRS:
                name = fn.id
            if not name:
                continue
            if (ctx.logical_path, node.lineno) in ALLOWLIST:
                continue
            yield ctx.finding(
                self.rule, node,
                f"unsupervised task spawn ({name}) in reactor/node "
                f"code — use self.supervisor.spawn(...) so crashes "
                f"restart (bounded) instead of dying silently")
