"""The bftlint checker registry — one rule per module, each docstring
naming the PR/bug class that motivated it (docs/static_analysis.md
renders the catalog)."""
from .await_atomicity import AwaitAtomicityChecker
from .blocking_in_async import BlockingInAsyncChecker
from .cwd_write import CwdWriteChecker
from .monotonic_clock import MonotonicClockChecker
from .supervised_spawn import SupervisedSpawnChecker
from .swallowed_exception import SwallowedExceptionChecker
from .unbounded_label import UnboundedLabelChecker
from .wire_tag import WireTagChecker
from .yield_in_loop import YieldInLoopChecker

ALL_CHECKERS = (
    SupervisedSpawnChecker(),
    MonotonicClockChecker(),
    SwallowedExceptionChecker(),
    YieldInLoopChecker(),
    AwaitAtomicityChecker(),
    BlockingInAsyncChecker(),
    UnboundedLabelChecker(),
    CwdWriteChecker(),
    WireTagChecker(),
)

__all__ = ["ALL_CHECKERS"]
