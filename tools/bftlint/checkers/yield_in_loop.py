"""yield-in-loop: every ``continue`` path in an async hot loop must
await.

The PR 1 livelock: ``_gossip_data_routine``'s proposal branch
``continue``d without yielding when the peer send-queue was full, so
the event loop spun forever on one coroutine and the whole node wedged
— no crash, no log, just a 100% CPU core and no progress.  The
nemesis runner caught it once; this rule keeps it caught.

For each ``while True:`` (or other constant-true) loop inside an
``async def``, the checker takes every ``continue`` owned by that loop
and asks: can anything on the way to this ``continue`` suspend?  It
collects the subtrees of all statements that lexically precede the
``continue`` at each nesting level inside the loop (plus enclosing
``if``/``while`` tests, which may await) and looks for ``await`` /
``async for`` / ``async with``.  If no suspension point can possibly
execute before the ``continue``, one starved branch becomes a busy
loop — flagged.

ISSUE 20 extension — interprocedural await credit: ``await expr``
only suspends if the awaited coroutine itself reaches a suspension
point, so ``await self._helper()`` where ``_helper`` *never* awaits
is a busy-spin in disguise (a false-negative class this rule used to
miss).  With the package effect summaries (callgraph.py) an ``await``
over a resolved call is credited iff the callee's ``may_await``
summary is true — awaits inside always/may-awaiting helpers keep
their credit (the false-positive class a naive "only literal awaits
count" upgrade would have introduced), never-awaiting ones lose it.
Sound default: unresolved operands (``asyncio.sleep``, futures,
``gather``) stay credited, exactly the pre-interprocedural behavior.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..core import Checker, FileContext, Finding, walk_scope


def _is_const_true(test: ast.expr) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value) is True


def _await_credited(ctx: FileContext, aw: ast.Await) -> bool:
    """An await is a suspension unless its operand resolves to a
    package helper that provably never awaits."""
    if ctx.program is None or not isinstance(aw.value, ast.Call):
        return True
    return ctx.program.summary_for_call(ctx, aw.value).may_await


def _has_suspension(ctx: FileContext, nodes) -> bool:
    # an await inside a nested def/lambda defined before the continue
    # never ran on this path — it is not a suspension
    for root in nodes:
        if isinstance(root, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        for node in walk_scope(root):
            if isinstance(node, (ast.AsyncFor, ast.AsyncWith)):
                return True
            if isinstance(node, ast.Await) and \
                    _await_credited(ctx, node):
                return True
    return False


def _owning_loop(ctx: FileContext, cont: ast.Continue):
    for anc in ctx.ancestors(cont):
        if isinstance(anc, (ast.While, ast.For, ast.AsyncFor)):
            return anc
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
    return None


class YieldInLoopChecker(Checker):
    rule = "yield-in-loop"
    description = ("continue path in an async while-True loop with no "
                   "possible await: event-loop livelock")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for loop in ctx.nodes(ast.While):
            if not _is_const_true(loop.test):
                continue
            fn = ctx.enclosing_function(loop)
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for cont in ast.walk(loop):
                if not isinstance(cont, ast.Continue) or \
                        _owning_loop(ctx, cont) is not loop:
                    continue
                # everything that could run before this continue:
                # preceding siblings at each block level up to the
                # loop, plus the tests of enclosing if/while nodes
                before: list[ast.AST] = []
                node: ast.AST = cont
                while node is not loop:
                    parent = ctx.parent(node)
                    if parent is None:      # pragma: no cover
                        break
                    for fname in ("body", "orelse", "finalbody"):
                        block = getattr(parent, fname, None)
                        if isinstance(block, list) and node in block:
                            before.extend(
                                block[:block.index(node)])
                    if isinstance(parent, ast.Try):
                        # sibling except handlers are alternatives,
                        # never predecessors — an await there cannot
                        # have run on this path.  The try body *may*
                        # have suspended before raising into a
                        # handler (and fully ran before orelse /
                        # partially before finalbody), so it counts.
                        if node in parent.handlers or \
                                (parent.orelse and
                                 node in parent.orelse) or \
                                (parent.finalbody and
                                 node in parent.finalbody):
                            before.extend(parent.body)
                    if isinstance(parent, (ast.If, ast.While)) and \
                            parent is not loop:
                        before.append(parent.test)
                    node = parent
                if not _has_suspension(ctx, before):
                    yield ctx.finding(
                        self.rule, cont,
                        "this continue can be reached without any "
                        "await since the loop iteration began — a "
                        "persistently-true branch busy-spins the "
                        "event loop (the PR 1 gossip livelock); "
                        "await before continuing, or asyncio.sleep(0)")
