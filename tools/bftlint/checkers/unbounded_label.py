"""unbounded-label: metric label values must be bounded at the call
site.

PR 1 capped the ``with_labels`` memo and PR 4 added the runtime
``overflow`` series (> _CHILDREN_MAX label sets collapse) after
unbounded label cardinality was shown to grow scrape size and memory
without limit.  The runtime guard is the backstop; this rule is the
front door — every ``with_labels(...)`` argument must be visibly
bounded at the call site:

  * a literal (str/int/bool constant), or
  * a name in the reviewed-bounded allowlist below (small closed
    enumerations: config lanes, backend names, breaker states...), or
  * ``str(x)``/f-string of such a name.

Anything else — peer ids, channel ids formatted from the wire,
heights, error strings — is potential cardinality and must be
suppressed or baselined with a reason (usually "bounded by runtime
overflow collapse" or "bounded by max peer count").
"""
from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import Checker, FileContext, Finding

# Variable names reviewed as small closed enumerations.  Add a name
# here only when every assignment to it in the repo is provably
# bounded (config enum, hard-coded choice set) — when in doubt,
# baseline the call site instead so the review trail stays visible.
ALLOWED_NAMES = {
    "lane",          # mempool lanes: closed set from genesis config
    "backend",       # batch-verify backend: {tpu, cpu, native, pure}
    "kind",          # supervisor task kind: hard-coded per spawn site
    "state",         # breaker state name: {closed, open, half_open}
    "conn_name",     # ABCI app connection: 4 named conns
    "choice",        # kernel dispatch choice: closed set in ops
    "vt_label",      # vote type: {prevote, precommit}
    "timely",        # PBTS timeliness: {true, false}
    "ch_id",         # p2p channel id string: claimed channels only
                     # (touch_channel materializes series at reactor
                     # registration; ids are a closed per-node set)
    "worker_name",   # SupervisedWorker names: hard-coded at the few
                     # construction sites (crypto/pipeline.py)
    "pad_bucket",    # kernel pad-bucket label: str of the closed
                     # bucket ladder / the one configured pipeline
                     # tile size per process
}


def _bounded(arg: ast.expr) -> bool:
    if isinstance(arg, ast.Constant):
        return True
    if isinstance(arg, ast.Name):
        return arg.id in ALLOWED_NAMES
    # "accepted" if ok else "rejected": both arms bounded -> bounded
    if isinstance(arg, ast.IfExp):
        return _bounded(arg.body) and _bounded(arg.orelse)
    # str(name) / f"{name}" of an allowlisted name stays bounded
    if isinstance(arg, ast.Call) and \
            isinstance(arg.func, ast.Name) and arg.func.id == "str" \
            and len(arg.args) == 1:
        return _bounded(arg.args[0])
    if isinstance(arg, ast.JoinedStr):
        return all(_bounded(v.value) for v in arg.values
                   if isinstance(v, ast.FormattedValue))
    return False


def _offender(call: ast.Call) -> Optional[ast.expr]:
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        if not _bounded(arg):
            return arg
    return None


class UnboundedLabelChecker(Checker):
    rule = "unbounded-label"
    description = ("with_labels() argument is not a literal or "
                   "reviewed-bounded name: metric cardinality risk")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.nodes(ast.Call):
            fn = node.func
            if not (isinstance(fn, ast.Attribute)
                    and fn.attr == "with_labels"):
                continue
            bad = _offender(node)
            if bad is None:
                continue
            desc = ast.unparse(bad) if hasattr(ast, "unparse") \
                else type(bad).__name__
            yield ctx.finding(
                self.rule, node,
                f"label value `{desc}` is not a literal or "
                f"reviewed-bounded name — unbounded label values "
                f"grow scrape size/memory until the runtime overflow "
                f"collapse kicks in; bound it at the call site or "
                f"baseline with the boundedness argument")
