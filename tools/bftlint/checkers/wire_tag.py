"""wire-tag: the proto field-tag tables are a consensus-critical
contract — pin them.

Every ``Msg("pkg.Name", F(num, "field", "kind"), ...)`` descriptor
(wire/pb.py and the reactor message arms) defines wire bytes other
nodes parse; a silently-changed field number or kind is a network
fork, and a duplicate tag within one message makes decode
order-dependent.  The aggregate-commit 0xff marker (wire/pb.py) is
one hand-rolled byte away from exactly that class of collision — the
runtime ``Msg.__init__`` duplicate check only fires when the
descriptor is *constructed*, which for rarely-imported arms may be
never in CI.

Statically extracted, per message, from the AST (no imports, no
construction): field number -> (name, kind, repeated).  Findings:

  * duplicate-tag — two ``F``s in one ``Msg`` share a field number
    (flagged everywhere, fixtures included);
  * manifest drift — for files under ``cometbft_tpu/``, the extracted
    tables must match ``tools/bftlint/wire_manifest.json`` exactly:
    changed/added/removed fields, new messages, and messages deleted
    from a manifest-tracked file are all findings.  Intentional wire
    changes are committed via the regeneration subcommand::

        python -m tools.bftlint wire-manifest

    mirroring ``baseline`` — the diff of wire_manifest.json *is* the
    wire-compat review artifact.

Extraction is best-effort on purpose: only ``F(<int const>,
<str const>, <str const>, ...)`` positional shapes are read (the only
shape the tree uses); a computed field number extracts as unknown and
is reported, since an unreadable tag table cannot be pinned.
"""
from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Iterator, Optional

from ..core import Checker, FileContext, Finding

_DEFAULT_MANIFEST = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "wire_manifest.json")

MANIFEST_VERSION = 1


@dataclass
class MsgDecl:
    """One statically-extracted ``Msg(...)`` descriptor."""
    name: str
    node: ast.Call
    # field number -> "name kind" or "name kind repeated"
    fields: dict[int, str] = field(default_factory=dict)
    duplicates: list[tuple[int, ast.Call]] = field(default_factory=list)
    unreadable: list[ast.Call] = field(default_factory=list)


def _callee_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def _field_sig(f_call: ast.Call) -> Optional[tuple[int, str]]:
    """``F(1, "seconds", "int64", repeated=True)`` ->
    ``(1, "seconds int64 repeated")``; None when the shape is not the
    constant-positional idiom."""
    args = f_call.args
    if len(args) < 3:
        return None
    num, name, kind = args[0], args[1], args[2]
    if not (isinstance(num, ast.Constant) and
            isinstance(num.value, int) and
            not isinstance(num.value, bool)):
        return None
    if not (isinstance(name, ast.Constant) and
            isinstance(name.value, str)):
        return None
    if not (isinstance(kind, ast.Constant) and
            isinstance(kind.value, str)):
        return None
    sig = f"{name.value} {kind.value}"
    for kw in f_call.keywords:
        if kw.arg == "repeated" and \
                isinstance(kw.value, ast.Constant) and \
                kw.value.value is True:
            sig += " repeated"
    return num.value, sig


def extract_messages(ctx: FileContext) -> list[MsgDecl]:
    """All ``Msg(...)`` descriptor declarations in the file, in source
    order.  Shared by the checker and the ``wire-manifest``
    regeneration subcommand so they can never disagree."""
    decls: list[MsgDecl] = []
    for node in ctx.nodes(ast.Call):
        if _callee_name(node) != "Msg" or not node.args:
            continue
        head = node.args[0]
        if not (isinstance(head, ast.Constant) and
                isinstance(head.value, str)):
            continue
        decl = MsgDecl(name=head.value, node=node)
        for arg in node.args[1:]:
            if isinstance(arg, ast.Starred):
                # *fields splat: contents invisible statically
                decl.unreadable.append(node)
                continue
            if not (isinstance(arg, ast.Call) and
                    _callee_name(arg) == "F"):
                continue
            sig = _field_sig(arg)
            if sig is None:
                decl.unreadable.append(arg)
                continue
            num, fsig = sig
            if num in decl.fields:
                decl.duplicates.append((num, arg))
            else:
                decl.fields[num] = fsig
        decls.append(decl)
    return decls


def load_manifest(path: str = _DEFAULT_MANIFEST) -> dict:
    """The committed manifest: {} when absent (drift checking is then
    skipped — the rule degrades to duplicate-tag only)."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or \
            data.get("version") != MANIFEST_VERSION or \
            not isinstance(data.get("messages"), dict):
        raise ValueError(
            f"{path}: not a v{MANIFEST_VERSION} wire manifest")
    return data["messages"]


def manifest_payload(per_path: dict[str, list[MsgDecl]]) -> dict:
    """Serializable manifest from extracted declarations, keyed by
    message name; deterministic ordering so the committed file diffs
    cleanly."""
    messages: dict[str, dict] = {}
    for path in sorted(per_path):
        for decl in per_path[path]:
            messages[decl.name] = {
                "path": path,
                "fields": {str(n): decl.fields[n]
                           for n in sorted(decl.fields)},
            }
    return {"version": MANIFEST_VERSION,
            "messages": dict(sorted(messages.items()))}


class WireTagChecker(Checker):
    rule = "wire-tag"
    description = ("proto field-tag table drift or duplicate field "
                   "number in a Msg descriptor (wire-compat contract; "
                   "regenerate with the wire-manifest subcommand)")
    # no scope: descriptors anywhere are checked for duplicates;
    # manifest drift is enforced only under cometbft_tpu/ (fixtures
    # and scratch files must not demand manifest entries)
    _DRIFT_PREFIX = "cometbft_tpu/"

    def __init__(self, manifest_path: str = _DEFAULT_MANIFEST):
        self._manifest_path = manifest_path
        self._manifest: Optional[dict] = None

    def _load(self) -> dict:
        if self._manifest is None:
            self._manifest = load_manifest(self._manifest_path)
        return self._manifest

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        decls = extract_messages(ctx)
        if not decls:
            return
        for decl in decls:
            for num, f_call in decl.duplicates:
                yield ctx.finding(
                    self.rule, f_call,
                    f"duplicate field number {num} in "
                    f"{decl.name} — two fields share one wire tag, "
                    f"so decode order silently picks a winner; "
                    f"renumber (Msg.__init__ would also raise, but "
                    f"only if this descriptor is ever constructed)")
            for bad in decl.unreadable:
                yield ctx.finding(
                    self.rule, bad,
                    f"field of {decl.name} is not the "
                    f"F(<int>, <name>, <kind>) constant shape — the "
                    f"wire tag table cannot be statically pinned; "
                    f"use literal field numbers/kinds")
        if not ctx.logical_path.startswith(self._DRIFT_PREFIX):
            return
        manifest = self._load()
        if not manifest:
            return
        seen_here: set[str] = set()
        for decl in decls:
            seen_here.add(decl.name)
            entry = manifest.get(decl.name)
            if entry is None:
                yield ctx.finding(
                    self.rule, decl.node,
                    f"{decl.name} is not in wire_manifest.json — a "
                    f"new wire message is a wire-compat change; "
                    f"review it, then run `python -m tools.bftlint "
                    f"wire-manifest` to commit the table")
                continue
            want = {int(k): v for k, v in entry["fields"].items()}
            if want == decl.fields:
                continue
            details = []
            for num in sorted(set(want) | set(decl.fields)):
                a, b = want.get(num), decl.fields.get(num)
                if a == b:
                    continue
                details.append(
                    f"field {num}: manifest={a or 'absent'} "
                    f"code={b or 'absent'}")
            yield ctx.finding(
                self.rule, decl.node,
                f"{decl.name} drifted from wire_manifest.json "
                f"({'; '.join(details)}) — changed tags/kinds break "
                f"wire compat with every peer; revert, or review and "
                f"regenerate via `python -m tools.bftlint "
                f"wire-manifest`")
        # messages the manifest pins to THIS file but which no longer
        # exist here: a deleted/renamed wire message is drift too
        for name, entry in manifest.items():
            if entry.get("path") == ctx.logical_path and \
                    name not in seen_here:
                # ast.Module has no position: anchor on the first
                # statement (a file with decls always has one)
                yield ctx.finding(
                    self.rule, ctx.tree.body[0],
                    f"{name} is pinned to this file by "
                    f"wire_manifest.json but is no longer declared — "
                    f"deleting/renaming a wire message breaks peers "
                    f"still sending it; review and regenerate via "
                    f"`python -m tools.bftlint wire-manifest`")


__all__ = ["WireTagChecker", "extract_messages", "load_manifest",
           "manifest_payload", "MsgDecl", "MANIFEST_VERSION"]
