"""await-atomicity: consensus state written after an ``await`` needs
re-validation at the store — the asyncio analogue of a data race.

asyncio removes preemption but not interleaving: every ``await`` is a
point where another task (the timeout ticker, a supervisor restart, a
stop-peer one-shot) can run — and since the commit pipeline
(docs/pipeline.md) put two heights in flight, a point where a
background execute/commit is running concurrently with the receive
routine.  A method that computes a decision, suspends, and then writes
round state without re-checking can apply that decision to a
height/round the machine has already left — exactly the class of bug
TLA+ audits of HotStuff/Tendermint keep finding in the
"vote-after-timeout" corner (PAPERS.md).

Heuristic (strengthened with the pipelined-commit refactor; the
original rule only fired when the same attribute was also *loaded*
before the await): inside an ``async def`` of a consensus-critical
class, flag a *store* to a tracked attribute (``self.rs.*``,
``self.rs``, ``self.sm_state``, ``height``/``round``/``step``
mirrors) when

  * any ``await`` precedes the store in the function, and
  * no load of that attribute appears in an ``if``/``while``/
    ``assert`` test between the last such ``await`` and the store
    (re-validation).

The sanctioned mutation path is the RoundState transition seam
(consensus/round_state.py): ``rs.advance()``, ``rs.begin_round()``,
``rs.lock()``, ``rs.relock()``, ``rs.set_valid()``,
``rs.reset_proposal_parts()``, ``rs.drop_proposal_block()``,
``rs.adopt_block()``, ``rs.enter_commit()``, ``rs.begin_height()``,
``rs.set_last_commit()``, ``rs.apply_proposal()``,
``rs.complete_proposal_block()``, ``rs.mark_timeout_precommit()``,
``rs.rebuild_votes()``.
Each transition re-validates its own precondition (monotonicity of
(round, step), a live lock, ...) at the moment of the write, so a
seam call after an await is exactly the guarded store this rule asks
for — calls to ``_TRANSITION_METHODS`` are never findings.

The dominant idiom in consensus/state.py is a local alias
(``rs = self.rs``), so the checker tracks simple whole-object
aliases: after ``rs = self.rs``, loads/stores of ``rs.height`` count
as ``rs.height`` state accesses.  Deeper aliasing (``votes =
self.rs.votes``) is not chased — it bounds false positives, not
false negatives.  Findings are triaged like any other rule:
restructure onto the seam, re-validate, or baseline with a
justification explaining why the interleaving is benign.

ISSUE 20 extension — interprocedural await points: what counts as a
suspension is now judged through the package effect summaries
(callgraph.py).  ``await self._helper()`` where ``_helper`` provably
never awaits is *not* a suspension — no other task can run there, so
a store after it needs no re-validation (a false-positive class the
textual rule had).  An await through a may-awaiting helper remains a
straddle point exactly as before, so extracting the suspension into a
helper cannot hide a seam-bypassing store.  Sound default: unresolved
operands keep their await-point status (``may_await=True``).
"""
from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import Checker, FileContext, Finding, walk_scope

# attribute roots considered consensus-critical state.  "prs" is the
# reactor-side peer round state (consensus/reactor.py PeerRoundState):
# the gossip-rewrite PR gave PeerState the same single-writer seam
# RoundState got, and this rule tracks its stores the same way —
# ``prs = ps.prs`` / ``prs = self.prs`` aliases included.
_TRACKED_BASES = {"rs", "sm_state", "prs"}
_TRACKED_DIRECT = {"rs", "sm_state", "prs", "height", "round", "step",
                   "locked_round", "valid_round"}

# the RoundState transition seam: internally re-validating mutation
# methods — the sanctioned way to write round state after an await.
# Each entry maps to the attributes the method re-validates before
# writing; a seam call therefore counts as a guard for exactly those
# keys (tests/test_bftlint.py pins this table against the live
# RoundState API so it cannot silently drift).
_TRANSITION_GUARDS: dict[str, tuple[str, ...]] = {
    "advance": ("round", "step"),
    "begin_round": ("round", "step"),
    "begin_height": ("height", "round", "step"),
    "enter_commit": ("step", "commit_round"),
    "lock": ("locked_round",),
    "relock": ("locked_round",),
    "set_valid": ("valid_round",),
    "reset_proposal_parts": (),
    "drop_proposal_block": (),
    "adopt_block": (),
    # sync-mutation-site extension (ROADMAP carry-over): the seam now
    # covers every RoundState write in consensus/state.py, sync or
    # async — these re-validate their own preconditions at the write
    "set_last_commit": ("last_commit",),
    "apply_proposal": ("proposal", "proposal_receive_time",
                       "proposal_block_parts"),
    "complete_proposal_block": ("proposal_block",),
    "mark_timeout_precommit": ("triggered_timeout_precommit",),
    "rebuild_votes": ("validators", "votes"),
}
_TRANSITION_METHODS = frozenset(_TRANSITION_GUARDS)

# the PeerState seam (consensus/reactor.py): mutation methods that
# re-validate the peer's (height, round) at the write.  Unlike the
# RoundState seam, these are called on the PeerState object (``ps``),
# not on the tracked ``prs`` base, so any call to one of these names
# counts as a guard for the listed ``prs.*`` keys — the receiver is
# not resolved (a deliberately weaker heuristic; the method names are
# specific enough that false guards are unlikely, and the fixtures
# pin both directions).
_PEERSTATE_GUARDS: dict[str, tuple[str, ...]] = {
    "apply_new_round_step": ("height", "round", "step"),
    "apply_new_valid_block": ("proposal_block_parts",
                              "proposal_block_parts_header"),
    "apply_proposal": ("proposal",),
    "apply_proposal_pol": ("proposal_pol",),
    "set_has_proposal_block_part": ("proposal_block_parts",),
    "init_catchup_parts": ("proposal_block_parts",
                           "proposal_block_parts_header"),
    "mark_compact_sent": (),
    "mark_peer_has_full_block": (),
    "ensure_catchup_commit_round": ("catchup_commit_round",
                                    "catchup_commit"),
}


def _attr_key(node: ast.AST,
              aliases: dict[str, str] | None = None) -> Optional[str]:
    """``self.rs.height`` -> ``rs.height``; ``self.rs`` -> ``rs``;
    with ``aliases={'rs': 'rs'}`` (from ``rs = self.rs``),
    ``rs.height`` -> ``rs.height``; anything else -> None."""
    if not isinstance(node, ast.Attribute):
        return None
    if isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr if node.attr in _TRACKED_DIRECT else None
    if isinstance(node.value, ast.Name) and aliases and \
            node.value.id in aliases:
        return f"{aliases[node.value.id]}.{node.attr}"
    if isinstance(node.value, ast.Attribute) and \
            isinstance(node.value.value, ast.Name) and \
            node.value.value.id == "self" and \
            node.value.attr in _TRACKED_BASES:
        return f"{node.value.attr}.{node.attr}"
    return None


def _collect_aliases(fn: ast.AsyncFunctionDef) -> dict[str, str]:
    """``rs = self.rs`` / ``state = self.sm_state`` / ``prs = ps.prs``
    local aliases: local name -> tracked base.  The base object of a
    ``prs`` alias is deliberately not restricted to ``self`` — the
    reactor idiom reads peer round state off a PeerState argument."""
    aliases: dict[str, str] = {}
    for node in walk_scope(fn):
        if not isinstance(node, ast.Assign) or \
                len(node.targets) != 1 or \
                not isinstance(node.targets[0], ast.Name):
            continue
        v = node.value
        if not isinstance(v, ast.Attribute) or \
                v.attr not in _TRACKED_BASES:
            continue
        if (isinstance(v.value, ast.Name) and
                v.value.id == "self") or v.attr == "prs":
            aliases[node.targets[0].id] = v.attr
    return aliases


def _pos(node: ast.AST) -> tuple[int, int]:
    return (node.lineno, node.col_offset)


class AwaitAtomicityChecker(Checker):
    rule = "await-atomicity"
    description = ("consensus state written after an await without "
                   "re-validation (use the RoundState transition "
                   "seam or re-check before the store)")
    scope = ("cometbft_tpu/consensus/*",)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn in ctx.nodes(ast.AsyncFunctionDef):
            yield from self._check_fn(ctx, fn)

    @staticmethod
    def _is_await_point(ctx: FileContext, aw: ast.Await) -> bool:
        """Summary-aware suspension test: an await over a helper that
        provably never awaits cannot interleave another task."""
        if ctx.program is None or not isinstance(aw.value, ast.Call):
            return True
        return ctx.program.summary_for_call(ctx, aw.value).may_await

    def _check_fn(self, ctx: FileContext,
                  fn: ast.AsyncFunctionDef) -> Iterator[Finding]:
        aliases = _collect_aliases(fn)
        stores: list[tuple[tuple[int, int], str, ast.AST]] = []
        awaits: list[tuple[int, int]] = []
        guards: list[tuple[tuple[int, int], str]] = []
        # walk_scope: a nested def's awaits/loads/stores run on its
        # own call's flow, not this function's — counting them here
        # invents straddles that cannot happen (nested async defs are
        # analyzed separately via ctx.nodes)
        for node in walk_scope(fn):
            if isinstance(node, ast.Await):
                if self._is_await_point(ctx, node):
                    awaits.append(_pos(node))
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _TRANSITION_GUARDS:
                # a transition-seam call re-validates the listed keys
                # at the write — it counts as a guard for them
                base_key = _attr_key(node.func.value, aliases) \
                    if isinstance(node.func.value, ast.Attribute) \
                    else (aliases.get(node.func.value.id)
                          if isinstance(node.func.value, ast.Name) and
                          aliases else None)
                if isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "self":
                    base_key = None       # self.advance() is not rs
                if base_key in _TRACKED_BASES:
                    for attr in _TRANSITION_GUARDS[node.func.attr]:
                        guards.append((_pos(node),
                                       f"{base_key}.{attr}"))
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _PEERSTATE_GUARDS:
                # PeerState seam call (any receiver; see the table's
                # docstring): guards the listed prs.* keys
                for attr in _PEERSTATE_GUARDS[node.func.attr]:
                    guards.append((_pos(node), f"prs.{attr}"))
            elif isinstance(node, (ast.If, ast.While, ast.Assert)):
                test = node.test
                for sub in ast.walk(test):
                    key = _attr_key(sub, aliases)
                    if key and isinstance(
                            getattr(sub, "ctx", None), ast.Load):
                        guards.append((_pos(test), key))
            elif isinstance(node, ast.Attribute):
                key = _attr_key(node, aliases)
                if key is None:
                    continue
                if isinstance(node.ctx, ast.Store):
                    stores.append((_pos(node), key, node))
        if not awaits or not stores:
            return
        awaits.sort()
        flagged: set[str] = set()
        for spos, key, node in sorted(stores, key=lambda t: t[0]):
            if key in flagged:
                continue
            # the LAST await before this store: the store must be
            # re-validated after the final suspension, not before it
            straddle = None
            for apos in awaits:
                if apos < spos:
                    straddle = apos
                else:
                    break
            if straddle is None:
                continue
            # a guard re-reading `key` between that await and the
            # store counts as re-validation
            if any(straddle <= gpos <= spos for gpos, k in guards
                   if k == key):
                continue
            flagged.add(key)
            yield ctx.finding(
                self.rule, node,
                f"self.{key} is written after an await (line "
                f"{straddle[0]}) without re-validation — another "
                f"task (timeout ticker, pipelined apply completion, "
                f"stop-peer one-shot) may have advanced the round "
                f"state across that suspension; route the mutation "
                f"through the RoundState transition seam "
                f"(round_state.py) or re-check height/round/step "
                f"between the await and the store")
