"""await-atomicity: consensus state read-then-written across an
``await`` needs re-validation — the asyncio analogue of a data race.

asyncio removes preemption but not interleaving: every ``await`` is a
point where another task (the timeout ticker, a supervisor restart, a
stop-peer one-shot) can run and mutate shared state.  A method that
reads ``self.rs.height`` before an ``await`` and writes round state
after it, without re-checking, can apply a decision computed for a
height/round the machine has already left — exactly the class of bug
TLA+ audits of HotStuff/Tendermint keep finding in the
"vote-after-timeout" corner (PAPERS.md).

Heuristic: inside an ``async def`` of a consensus-critical class,
flag a *store* to a tracked attribute (``self.rs.*``, ``self.rs``,
``self.sm_state``, ``self.height``/``round``/``step`` mirrors) when

  * the same attribute was *loaded* before an earlier ``await`` in
    the same function, and
  * no load of that attribute appears in an ``if``/``while``/
    ``assert`` test between that ``await`` and the store
    (re-validation).

The dominant idiom in consensus/state.py is a local alias
(``rs = self.rs``), so the checker tracks simple whole-object
aliases: after ``rs = self.rs``, loads/stores of ``rs.height`` count
as ``rs.height`` state accesses.  Deeper aliasing (``votes =
self.rs.votes``) is not chased — it bounds false positives, not
false negatives.  Findings are triaged like any other rule:
restructure, re-validate, or baseline with a justification
explaining why the interleaving is benign.
"""
from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import Checker, FileContext, Finding, walk_scope

# attribute roots considered consensus-critical state
_TRACKED_BASES = {"rs", "sm_state"}
_TRACKED_DIRECT = {"rs", "sm_state", "height", "round", "step",
                   "locked_round", "valid_round"}


def _attr_key(node: ast.AST,
              aliases: dict[str, str] | None = None) -> Optional[str]:
    """``self.rs.height`` -> ``rs.height``; ``self.rs`` -> ``rs``;
    with ``aliases={'rs': 'rs'}`` (from ``rs = self.rs``),
    ``rs.height`` -> ``rs.height``; anything else -> None."""
    if not isinstance(node, ast.Attribute):
        return None
    if isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr if node.attr in _TRACKED_DIRECT else None
    if isinstance(node.value, ast.Name) and aliases and \
            node.value.id in aliases:
        return f"{aliases[node.value.id]}.{node.attr}"
    if isinstance(node.value, ast.Attribute) and \
            isinstance(node.value.value, ast.Name) and \
            node.value.value.id == "self" and \
            node.value.attr in _TRACKED_BASES:
        return f"{node.value.attr}.{node.attr}"
    return None


def _collect_aliases(fn: ast.AsyncFunctionDef) -> dict[str, str]:
    """``rs = self.rs`` / ``state = self.sm_state`` local aliases:
    local name -> tracked base."""
    aliases: dict[str, str] = {}
    for node in walk_scope(fn):
        if not isinstance(node, ast.Assign) or \
                len(node.targets) != 1 or \
                not isinstance(node.targets[0], ast.Name):
            continue
        v = node.value
        if isinstance(v, ast.Attribute) and \
                isinstance(v.value, ast.Name) and \
                v.value.id == "self" and v.attr in _TRACKED_BASES:
            aliases[node.targets[0].id] = v.attr
    return aliases


def _pos(node: ast.AST) -> tuple[int, int]:
    return (node.lineno, node.col_offset)


class AwaitAtomicityChecker(Checker):
    rule = "await-atomicity"
    description = ("consensus state read before an await and written "
                   "after it without re-validation")
    scope = ("cometbft_tpu/consensus/*",)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn in ctx.nodes(ast.AsyncFunctionDef):
            yield from self._check_fn(ctx, fn)

    def _check_fn(self, ctx: FileContext,
                  fn: ast.AsyncFunctionDef) -> Iterator[Finding]:
        aliases = _collect_aliases(fn)
        loads: list[tuple[tuple[int, int], str]] = []
        stores: list[tuple[tuple[int, int], str, ast.AST]] = []
        awaits: list[tuple[int, int]] = []
        guards: list[tuple[tuple[int, int], str]] = []
        # walk_scope: a nested def's awaits/loads/stores run on its
        # own call's flow, not this function's — counting them here
        # invents straddles that cannot happen (nested async defs are
        # analyzed separately via ctx.nodes)
        for node in walk_scope(fn):
            if isinstance(node, ast.Await):
                awaits.append(_pos(node))
            elif isinstance(node, (ast.If, ast.While, ast.Assert)):
                test = node.test
                for sub in ast.walk(test):
                    key = _attr_key(sub, aliases)
                    if key and isinstance(
                            getattr(sub, "ctx", None), ast.Load):
                        guards.append((_pos(test), key))
            elif isinstance(node, ast.Attribute):
                key = _attr_key(node, aliases)
                if key is None:
                    continue
                if isinstance(node.ctx, ast.Load):
                    loads.append((_pos(node), key))
                elif isinstance(node.ctx, ast.Store):
                    stores.append((_pos(node), key, node))
        if not awaits or not stores:
            return
        awaits.sort()
        flagged: set[str] = set()
        for spos, key, node in sorted(stores, key=lambda t: t[0]):
            if key in flagged:
                continue
            # earliest await that both follows a load of `key` and
            # precedes this store
            straddle = None
            for apos in awaits:
                if apos < spos and any(
                        lpos < apos for lpos, k in loads
                        if k == key):
                    straddle = apos
                    break
            if straddle is None:
                continue
            # a guard re-reading `key` between the await and the
            # store counts as re-validation
            if any(straddle <= gpos <= spos for gpos, k in guards
                   if k == key):
                continue
            flagged.add(key)
            yield ctx.finding(
                self.rule, node,
                f"self.{key} was read before an await (line "
                f"{straddle[0]}) and is written here without "
                f"re-validating — another task (timeout ticker, "
                f"stop-peer one-shot) may have advanced the round "
                f"state across that suspension; re-check "
                f"height/round/step after the await or restructure "
                f"to avoid the straddle")
