"""swallowed-exception: broad except handlers must do *something*.

Every debugging session that ends in "the task died an hour ago and
nothing was logged" starts with an ``except Exception: pass``.  The
node's supervision story (PR 1) only works when failures surface — a
handler that catches everything and drops it silently defeats both
the supervisor's restart accounting and the flight recorder's crash
timelines.

A broad handler (``except:``, ``except Exception``,
``except BaseException``, or a tuple containing one of those) passes
when its body — in the handler's own control flow, not inside a
nested def/lambda that may never run — does any of:

  * re-raise (any ``raise``);
  * log — a call whose target name contains the word debug/info/
    warn/warning/error/exception/critical/log (word-boundary match
    on the final attribute), or ``print`` (the CLI-tool idiom);
  * record a metric — a call ending in inc/observe, or in set/add
    when the receiver is recognizably a metric (the dotted chain
    names a metric/counter/gauge/histogram, or hangs off
    ``with_labels(...)``) — a bare ``event.set()`` / ``seen.add()``
    is not handling;
  * reference the bound exception variable (``except Exception as e:
    self._fail(e)`` delegates the error instead of dropping it).

Anything else is a swallow: fix it, or baseline it with a
justification.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..core import Checker, FileContext, Finding, call_name, \
    walk_scope

_BROAD = {"Exception", "BaseException"}
# matched against the call target's final attribute, split on "_":
# `logger.error`, `log_error`, `print` count; `rebuild_catalog` or
# `backlog_drain` must NOT (word-boundary match, not endswith)
_LOG_WORDS = {"debug", "info", "warn", "warning", "error",
              "exception", "critical", "log", "print"}
_METRIC_TAILS = ("inc", "observe")
# set/add only count when the receiver is recognizably a metric:
# `asyncio.Event.set()` / builtin-`set.add()` handlers are swallows
_AMBIGUOUS_METRIC_TAILS = ("set", "add")
_METRIC_HINTS = ("metric", "counter", "gauge", "histogram")


def _is_log_call(tail: str) -> bool:
    return tail in _LOG_WORDS or \
        any(part in _LOG_WORDS for part in tail.split("_"))


def _is_metric_call(node: ast.Call, tail: str) -> bool:
    if tail in _METRIC_TAILS:
        return True
    if tail not in _AMBIGUOUS_METRIC_TAILS:
        return False
    chain = call_name(node).lower().split(".")[:-1]
    if any(h in part for part in chain for h in _METRIC_HINTS):
        return True
    # family.with_labels(...).add(1): call_name truncates the chain
    # at the inner call, so look one hop through it
    fn = node.func
    return (isinstance(fn, ast.Attribute)
            and isinstance(fn.value, ast.Call)
            and call_name(fn.value).rsplit(".", 1)[-1] == "with_labels")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Attribute):
        return t.attr in _BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD
                   or isinstance(e, ast.Attribute) and e.attr in _BROAD
                   for e in t.elts)
    return False


def _handles(handler: ast.ExceptHandler) -> bool:
    exc_name = handler.name
    # walk_scope: a raise/log/metric inside a nested def or lambda
    # only runs if that function is later invoked — at the except
    # site the failure is still dropped silently
    for node in walk_scope(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            tail = call_name(node).rsplit(".", 1)[-1]
            if _is_log_call(tail) or _is_metric_call(node, tail):
                return True
        if exc_name and isinstance(node, ast.Name) and \
                node.id == exc_name and \
                isinstance(node.ctx, ast.Load):
            return True
    if exc_name:
        # the bound exception escaping into a closure still delegates
        # it (`except Exception as e: defer(lambda: handle(e))`)
        for node in ast.walk(handler):
            if isinstance(node, ast.Name) and node.id == exc_name \
                    and isinstance(node.ctx, ast.Load):
                return True
    return False


class SwallowedExceptionChecker(Checker):
    rule = "swallowed-exception"
    description = ("broad except whose body neither logs, re-raises, "
                   "records a metric, nor uses the exception")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.nodes(ast.ExceptHandler):
            if not _is_broad(node) or _handles(node):
                continue
            yield ctx.finding(
                self.rule, node,
                "broad except swallows the failure — log it (with "
                "context), record a metric, re-raise, or narrow the "
                "exception type; silent drops defeat supervision "
                "and the flight recorder")
