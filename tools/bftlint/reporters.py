"""bftlint reporters: text for humans, JSON for tooling."""
from __future__ import annotations

import json
from typing import Iterable

from .baseline import BaselineDiff
from .core import Finding, LintResult


def text_report(result: LintResult, diff: BaselineDiff,
                verbose: bool = False) -> str:
    lines: list[str] = []
    for f in diff.new:
        lines.append(f"{f.location()}: [{f.rule}] {f.message}")
        lines.append(f"    {f.snippet}")
    if verbose:
        for f in diff.baselined:
            lines.append(f"{f.location()}: [{f.rule}] baselined: "
                         f"{f.message}")
    for fp in diff.stale:
        lines.append(f"stale baseline entry (site fixed or moved — "
                     f"rerun `baseline` to shrink the file): {fp}")
    for err in result.parse_errors:
        lines.append(f"parse error: {err}")
    lines.append(
        f"bftlint: {result.files_scanned} files, "
        f"{len(diff.new)} new finding(s), "
        f"{len(diff.baselined)} baselined, "
        f"{len(diff.stale)} stale baseline entr(ies)")
    return "\n".join(lines)


def _finding_obj(f: Finding, baselined: bool) -> dict:
    return {"rule": f.rule, "path": f.path, "line": f.line,
            "col": f.col, "scope": f.scope, "message": f.message,
            "snippet": f.snippet, "fingerprint": f.fingerprint,
            "baselined": baselined}


def json_report(result: LintResult, diff: BaselineDiff,
                rules: Iterable[str]) -> str:
    return json.dumps({
        "schema": 1,
        "files_scanned": result.files_scanned,
        "rules": sorted(rules),
        "findings": ([_finding_obj(f, False) for f in diff.new]
                     + [_finding_obj(f, True)
                        for f in diff.baselined]),
        "stale_baseline": diff.stale,
        "parse_errors": result.parse_errors,
        "counts": {"new": len(diff.new),
                   "baselined": len(diff.baselined),
                   "stale": len(diff.stale)},
    }, indent=2, sort_keys=True) + "\n"
