"""bftlint baseline: grandfathered findings, with justifications.

The committed ``bftlint_baseline.json`` lets ``check`` gate CI at
*zero new findings* without first fixing every historical site: each
entry names a finding fingerprint (rule + path + scope + source line,
deliberately line-number-free), how many identical occurrences it
covers, and a one-line justification a reviewer can audit.  The flow
mirrors tools/perf_lab.py's committed perf_baseline.json:

  * ``bftlint baseline``          write/refresh the file (keeps
                                  existing justifications)
  * fix a site                    the entry goes stale; ``check``
                                  reports it so the baseline shrinks
                                  monotonically instead of rotting
"""
from __future__ import annotations

import json
import os
from collections import Counter
from dataclasses import dataclass, field
from typing import Container, Iterable

from .core import Finding

SCHEMA = 1
DEFAULT_JUSTIFICATION = "grandfathered; triage before copying this pattern"


@dataclass
class BaselineDiff:
    new: list[Finding] = field(default_factory=list)
    baselined: list[Finding] = field(default_factory=list)
    stale: list[str] = field(default_factory=list)   # fingerprints


def load(path: str) -> dict[str, dict]:
    """fingerprint -> {count, justification}."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        raw = json.load(f)
    if raw.get("schema") != SCHEMA:
        raise ValueError(
            f"baseline schema {raw.get('schema')} != {SCHEMA}; "
            f"rerun `python -m tools.bftlint baseline`")
    return {e["fingerprint"]: {"count": int(e.get("count", 1)),
                               "justification": e.get(
                                   "justification",
                                   DEFAULT_JUSTIFICATION)}
            for e in raw.get("entries", [])}


def diff(findings: Iterable[Finding],
         baseline: dict[str, dict]) -> BaselineDiff:
    """Split findings into baselined (covered by an entry, up to its
    count) and new; entries with unconsumed slack are stale — an
    entry whose count exceeds its matches would otherwise silently
    absorb a *reintroduced* finding with the same fingerprint, so
    partial fixes must shrink the baseline too."""
    out = BaselineDiff()
    used: Counter[str] = Counter()
    for f in findings:
        fp = f.fingerprint
        entry = baseline.get(fp)
        if entry is not None and used[fp] < entry["count"]:
            used[fp] += 1
            out.baselined.append(f)
        else:
            out.new.append(f)
    out.stale = sorted(fp for fp, e in baseline.items()
                       if used[fp] < e["count"])
    return out


def write(path: str, findings: Iterable[Finding],
          previous: dict[str, dict] | None = None,
          active_rules: set[str] | None = None,
          scanned_paths: Container[str] | None = None) -> int:
    """Write a baseline covering ``findings``; justifications from
    ``previous`` (usually the existing file) are preserved per
    fingerprint.

    A partial run must never wipe what it did not look at: when the
    run was filtered to a rule subset (``active_rules``) or a path
    subset (``scanned_paths``), previous entries outside that subset
    are carried over untouched.  ``None`` means unfiltered — the
    baseline then shrinks to exactly the current findings (that is
    how fixed sites leave the file).  Returns the number of entries
    written."""
    previous = previous or {}
    counts: Counter[str] = Counter()
    meta: dict[str, Finding] = {}
    for f in findings:
        counts[f.fingerprint] += 1
        meta.setdefault(f.fingerprint, f)
    entries = []
    for fp in sorted(counts):
        f = meta[fp]
        prev = previous.get(fp, {})
        entries.append({
            "fingerprint": fp,
            "rule": f.rule,
            "path": f.path,
            "count": counts[fp],
            "justification": prev.get("justification",
                                      DEFAULT_JUSTIFICATION),
        })
    for fp in sorted(set(previous) - set(counts)):
        parts = fp.split("::", 3)
        if len(parts) < 2:
            # a mangled fingerprint can never match a finding again —
            # drop it from the rewrite rather than carry garbage
            continue
        rule, fpath = parts[:2]
        outside = (active_rules is not None
                   and rule not in active_rules) or \
                  (scanned_paths is not None
                   and fpath not in scanned_paths)
        if outside:
            prev = previous[fp]
            entries.append({
                "fingerprint": fp,
                "rule": rule,
                "path": fpath,
                "count": prev["count"],
                "justification": prev["justification"],
            })
    entries.sort(key=lambda e: e["fingerprint"])
    with open(path, "w", encoding="utf-8") as f_out:
        json.dump({"schema": SCHEMA,
                   "generated_by": "python -m tools.bftlint baseline",
                   "entries": entries},
                  f_out, indent=2, sort_keys=True)
        f_out.write("\n")
    return len(entries)
