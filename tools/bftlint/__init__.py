"""bftlint: AST-based invariant linter for the cometbft_tpu node.

PRs 1-4 each found a latent bug class the hard way — unsupervised
background tasks dying silently, wall-clock arithmetic in consensus
intervals, an event-loop livelock from a ``continue`` that never
yielded, unbounded metric label cardinality — and each left at most a
single ad-hoc guard.  bftlint codifies those invariants (plus the
asyncio analogue of a data race: consensus state read-then-written
across an ``await``) as mechanized checks that run in tier-1, so a new
PR cannot silently reintroduce a bug class the nemesis runner already
caught once.

See docs/static_analysis.md for the rule catalog, suppression syntax
and baseline workflow.  CLI: ``python -m tools.bftlint run|check|baseline``.
"""
from .core import Checker, FileContext, Finding, lint_paths  # noqa: F401

__all__ = ["Checker", "FileContext", "Finding", "lint_paths"]
