"""bftlint CLI — ``run | check | baseline``, mirroring perf_lab's
gate pattern (run for humans, check for CI, baseline to commit the
current floor).

  run        lint, print every finding (baselined ones marked), exit 0
  check      lint, print NEW findings only; exit 1 on any new
             finding or stale baseline entry (the tier-1 gate —
             tests/test_bftlint.py runs this)
  baseline   rewrite bftlint_baseline.json from the current findings,
             preserving existing justifications
  wire-manifest
             regenerate tools/bftlint/wire_manifest.json from the
             statically-extracted Msg descriptors (the wire-tag
             rule's pinned contract; commit the diff as the
             wire-compat review)

``check --diff <git-ref>`` judges only files changed since the ref
(fast pre-commit); the call graph is still built over the whole
package so interprocedural summaries stay sound.  Untracked files
are not part of a git diff — lint them by path, or after ``git add``.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys

from . import baseline as baseline_mod
from .checkers import ALL_CHECKERS
from .checkers import wire_tag as wire_tag_mod
from .core import FileContext, iter_python_files, lint_paths
from .reporters import json_report, text_report

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(_REPO_ROOT, "bftlint_baseline.json")
DEFAULT_PATHS = (os.path.join(_REPO_ROOT, "cometbft_tpu"),)


def _logical(p: str) -> str:
    return os.path.relpath(os.path.abspath(p),
                           _REPO_ROOT).replace(os.sep, "/")


class _ExaminedPaths:
    """Logical paths a path-filtered run re-examined: every scanned
    file, plus everything under a directory argument — a *deleted*
    file's baseline entry under that directory was re-examined too,
    so it must surface stale (and leave the baseline) instead of
    being masked by exact scanned-file membership and carried
    forever."""

    def __init__(self, arg_paths, scanned: set[str]):
        self._scanned = scanned
        prefixes = []
        for p in arg_paths:
            if not os.path.isdir(p):
                continue
            lp = _logical(p)
            # the repo root itself relativizes to "." — every
            # logical path is under it, not under "./"
            prefixes.append("" if lp == "." else lp + "/")
        self._dir_prefixes = tuple(prefixes)

    def __contains__(self, fpath: str) -> bool:
        if fpath in self._scanned:
            return True
        return bool(self._dir_prefixes) and \
            fpath.startswith(self._dir_prefixes)


def _write_wire_manifest(paths, manifest_path: str) -> int:
    """Regenerate the wire-tag manifest from the statically-extracted
    descriptors.  Refuses on duplicate tags, unreadable field shapes,
    or a message name declared twice — a manifest written past any of
    those would pin a broken or ambiguous contract."""
    per_path: dict[str, list] = {}
    owners: dict[str, str] = {}
    errors: list[str] = []
    for path in iter_python_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                ctx = FileContext(path, f.read())
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append(f"{path}: {e}")
            continue
        decls = wire_tag_mod.extract_messages(ctx)
        if not decls:
            continue
        per_path[ctx.logical_path] = decls
        for decl in decls:
            loc = f"{ctx.logical_path}:{decl.node.lineno}"
            for num, _ in decl.duplicates:
                errors.append(f"{loc}: duplicate field number {num} "
                              f"in {decl.name}")
            if decl.unreadable:
                errors.append(f"{loc}: {decl.name} has fields not in "
                              f"the F(<int>, <name>, <kind>) constant "
                              f"shape")
            if decl.name in owners:
                errors.append(f"{loc}: {decl.name} already declared "
                              f"in {owners[decl.name]}")
            owners[decl.name] = ctx.logical_path
    if errors:
        for err in errors:
            print(f"wire-manifest: {err}", file=sys.stderr)
        print("refusing to write the manifest — fix the descriptors "
              "and rerun", file=sys.stderr)
        return 2
    payload = wire_tag_mod.manifest_payload(per_path)
    import json
    with open(manifest_path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")
    print(f"wire manifest written: {manifest_path} "
          f"({len(payload['messages'])} messages from "
          f"{len(per_path)} files)")
    return 0


def _changed_since(ref: str, git_root: str) -> "list[str] | None":
    """Repo-relative .py paths changed since ``ref`` (worktree
    included); None when git fails."""
    try:
        out = subprocess.run(
            ["git", "-C", git_root, "diff", "--name-only", "-z",
             ref, "--"],
            check=True, capture_output=True, text=True)
    except (OSError, subprocess.CalledProcessError) as e:
        msg = getattr(e, "stderr", "") or str(e)
        print(f"git diff {ref} failed: {msg.strip()}",
              file=sys.stderr)
        return None
    return [p for p in out.stdout.split("\0") if p.endswith(".py")]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.bftlint",
        description=__doc__.splitlines()[0])
    ap.add_argument("mode",
                    choices=("run", "check", "baseline",
                             "wire-manifest"),
                    nargs="?", default="run")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: cometbft_tpu/)")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule subset")
    ap.add_argument("--diff", default=None, metavar="GIT_REF",
                    help="judge only .py files changed since GIT_REF "
                         "(under the lint roots); the summary corpus "
                         "stays whole-package")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--wire-manifest-path",
                    default=wire_tag_mod._DEFAULT_MANIFEST,
                    help=argparse.SUPPRESS)
    # --diff's git repo (tests point it at a scratch repo)
    ap.add_argument("--git-root", default=_REPO_ROOT,
                    help=argparse.SUPPRESS)
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline file (fixture tests)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--verbose", action="store_true",
                    help="also print baselined findings (text mode)")
    # intermixed: `check path/to/file.py --no-baseline` must parse
    args = ap.parse_intermixed_args(argv)

    rules = {r.strip() for r in args.rules.split(",") if r.strip()} \
        or None
    if rules:
        known = {c.rule for c in ALL_CHECKERS}
        unknown = rules - known
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}; "
                  f"known: {', '.join(sorted(known))}",
                  file=sys.stderr)
            return 2

    # a typo'd path must not read as a clean pass from the CI gate
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2
    non_py = [p for p in args.paths
              if os.path.isfile(p) and not p.endswith(".py")]
    if non_py:
        # iter_python_files would silently skip it and the gate
        # would pass without ever examining the named file
        print(f"not Python file(s): {', '.join(non_py)}",
              file=sys.stderr)
        return 2

    paths = args.paths or list(DEFAULT_PATHS)

    if args.mode == "wire-manifest":
        return _write_wire_manifest(paths, args.wire_manifest_path)

    program_paths = None
    if args.diff is not None:
        if args.mode == "baseline":
            print("--diff cannot rewrite the baseline — a diff "
                  "subset would drop every out-of-diff entry; use "
                  "explicit paths", file=sys.stderr)
            return 2
        changed = _changed_since(args.diff, args.git_root)
        if changed is None:
            return 2
        # keep only changed files under the lint roots: everything
        # else (tools/, tests/) is never baseline-covered and would
        # fail check spuriously
        root_files: set[str] = set()
        root_prefixes: list[str] = []
        for p in paths:
            lp = _logical(p)
            if os.path.isdir(p):
                root_prefixes.append("" if lp == "." else lp + "/")
            else:
                root_files.add(lp)
        judged = []
        for c in changed:
            full = os.path.join(args.git_root, c)
            if not os.path.exists(full):
                continue        # deleted since the ref
            lc = _logical(full)
            if lc in root_files or \
                    any(lc.startswith(pre) for pre in root_prefixes):
                judged.append(full)
        if not judged:
            print(f"no changed Python files under the lint roots "
                  f"since {args.diff}")
            return 0
        program_paths = paths
        paths = judged

    result = lint_paths(paths, ALL_CHECKERS, rules=rules,
                        program_paths=program_paths)
    if args.paths and not result.files_scanned:
        print(f"no Python files found under: {', '.join(args.paths)}",
              file=sys.stderr)
        return 2

    if args.mode == "baseline":
        if result.parse_errors:
            # an unparseable file yields no findings, so an
            # unfiltered rewrite would silently drop all of that
            # file's entries and their audited justifications
            for err in result.parse_errors:
                print(f"parse error: {err}", file=sys.stderr)
            print("refusing to rewrite the baseline with files "
                  "unparsed — fix them and rerun", file=sys.stderr)
            return 2
        try:
            prev = baseline_mod.load(args.baseline)
        except ValueError as e:
            # a corrupt/mismatched file must not be silently rewritten
            # — that would replace every audited justification with
            # the placeholder; make the operator fix or delete it
            print(f"refusing to rewrite {args.baseline}: {e}\n"
                  f"fix the file (or delete it to start fresh), then "
                  f"rerun", file=sys.stderr)
            return 2
        # a rule- or path-filtered run must not wipe entries it
        # didn't re-examine; an unfiltered run shrinks the file to
        # exactly the current findings
        n = baseline_mod.write(
            args.baseline, result.findings, previous=prev,
            active_rules=rules,
            scanned_paths=(_ExaminedPaths(args.paths,
                                          result.scanned_paths)
                           if args.paths else None))
        print(f"baseline written: {args.baseline} ({n} entries "
              f"covering {len(result.findings)} findings)")
        return 0

    base = {} if args.no_baseline \
        else baseline_mod.load(args.baseline)
    path_filtered = bool(args.paths) or args.diff is not None
    if base and (rules is not None or path_filtered):
        # a rule-/path-/diff-filtered run only re-examined a subset
        # of the baseline; diffing against the full file would
        # falsely report every out-of-filter entry as stale.  In
        # --diff mode the examined set is exactly the judged files
        # (all file args, so no directory prefixes).
        examined_paths = _ExaminedPaths(
            paths if args.diff is not None else args.paths,
            result.scanned_paths)

        def _examined(fp: str) -> bool:
            parts = fp.split("::", 3)
            if len(parts) < 2:
                # a mangled fingerprint matches no finding: keep the
                # entry in the diff so it surfaces stale instead of
                # crashing the filtered run
                return True
            rule, fpath = parts[:2]
            if rules is not None and rule not in rules:
                return False
            if path_filtered and fpath not in examined_paths:
                return False
            return True
        base = {fp: e for fp, e in base.items() if _examined(fp)}
    diff = baseline_mod.diff(result.findings, base)
    active = sorted(c.rule for c in ALL_CHECKERS
                    if rules is None or c.rule in rules)
    if args.format == "json":
        sys.stdout.write(json_report(result, diff, active))
    else:
        print(text_report(result, diff,
                          verbose=args.verbose
                          or args.mode == "run"))

    if result.parse_errors:
        return 2
    # stale entries fail check too: tests/test_bftlint.py gates on
    # them, so the local command must not give a false green
    if args.mode == "check" and (diff.new or diff.stale):
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
