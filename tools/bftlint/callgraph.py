"""Whole-package call graph + fixed-point effect summaries.

Every rule bftlint enforces is an *effect discipline* — verification
stays off the one event loop (docs/pipeline.md), RoundState/PeerState
mutations go through re-validating seams, background tasks are
supervisor-owned — but until this module the checkers were strictly
intra-procedural: a ``time.sleep()``, a sync batch ``verify()`` or a
bare ``create_task`` moved one helper-call deep became invisible,
which is exactly the refactoring pressure every perf PR applies (the
ISSUE 14 off-loop seam, the ISSUE 12 gossip rewrite).  This module
closes the helper blind spot with one pass over the shared
``FileContext``s:

  * a **call graph** resolving module-level functions, ``self.m()`` /
    ``cls.m()`` within a class and its same-package bases, and
    imported names (``from x import f``, ``import x.y as z``);
    anything else — attribute chains through unknown objects,
    stdlib/third-party calls, dynamic dispatch — resolves to the
    explicit :data:`UNKNOWN` summary so each rule can choose its own
    sound default instead of silently guessing;

  * a **fixed-point effect engine** computing, per function:

      - ``may_block``       transitively reaches a blocking call
                            (``time.sleep``, sync sockets, ``open``,
                            ...) — with the witness chain kept for
                            the finding message;
      - ``may_await``       executing the function may suspend: it
                            has a real await point (an ``await`` whose
                            operand is not a resolved never-awaiting
                            call, an ``async for``/``async with``) or
                            awaits a helper that may;
      - ``always_awaits``   every path through the body provably
                            reaches such an await (pessimistic /
                            least fixed point: mutually-recursive
                            helpers that only await each other never
                            actually suspend, and converge to False);
      - ``spawns_directly`` a bare ``create_task``/``ensure_future``
                            in the body (supervised-spawn follows
                            exactly one wrapper level, so this is
                            deliberately not transitive);
      - ``swallows_exception``  the body (or a resolved callee)
                            contains a swallowed-exception site —
                            informational for rule authors today.

Soundness defaults for :data:`UNKNOWN` (unresolved calls): it *may*
await (``await asyncio.sleep(...)`` keeps crediting yield-in-loop and
keeps counting as an await-atomicity suspension — exactly the
pre-interprocedural behavior), it does *not* definitely await, does
*not* block (may_block=False: the linter only claims what it can
prove, so unresolvable calls cannot flood consensus code with
unfixable findings) and does not spawn or swallow.  Each consuming
rule documents which direction it leans; see
docs/static_analysis.md#interprocedural-analysis.

Fixed-point convergence: all effect components are monotone booleans
seeded at False, so iteration terminates on any call-graph cycle
(tests/test_bftlint_callgraph.py pins two- and three-node cycles).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterable, Optional

from .core import FileContext, call_name, walk_scope

# ---------------------------------------------------------------------
# blocking / spawning call tables.  These live here (not in the
# checkers) because the summary engine and the blocking-in-async
# checker must agree byte-for-byte on what "a blocking call" is —
# two drifting copies would make the transitive findings lie.

BLOCKING_CALLS = {
    "time.sleep",
    "socket.socket", "socket.create_connection",
    "socket.getaddrinfo", "socket.gethostbyname",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "os.system", "os.popen",
    "urllib.request.urlopen", "requests.get", "requests.post",
    "open",
}
BLOCKING_TAILS = {"read_text", "read_bytes", "write_text",
                  "write_bytes"}

SPAWN_ATTRS = {"create_task", "ensure_future"}

_MAX_BASE_DEPTH = 8


def _is_blocking_call(node: ast.Call, name: str) -> bool:
    tail = name.rsplit(".", 1)[-1]
    if name in BLOCKING_CALLS:
        return True
    # attribute calls only: a bare local `read_text()` is not Path
    # I/O, but any receiver counts (incl. chained Path(...) calls)
    return tail in BLOCKING_TAILS and isinstance(node.func,
                                                 ast.Attribute)


def _is_spawn_call(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr in SPAWN_ATTRS
    return isinstance(fn, ast.Name) and fn.id in SPAWN_ATTRS


# ---------------------------------------------------------------------
# program model

@dataclass
class FunctionInfo:
    """One module-level function or method in the package."""
    module: str                     # dotted module name
    qualname: str                   # Class.method or function name
    node: ast.AST                   # FunctionDef | AsyncFunctionDef
    ctx: FileContext
    cls: Optional["ClassInfo"] = None

    @property
    def is_async(self) -> bool:
        return isinstance(self.node, ast.AsyncFunctionDef)

    def location(self) -> str:
        return f"{self.ctx.logical_path}:{self.node.lineno}"

    def __repr__(self) -> str:    # pragma: no cover - debugging aid
        return f"<fn {self.module}:{self.qualname}>"


@dataclass
class ClassInfo:
    name: str
    module: str
    methods: dict[str, FunctionInfo] = field(default_factory=dict)
    base_names: list[str] = field(default_factory=list)  # as written


@dataclass
class ModuleInfo:
    name: str
    ctx: FileContext
    functions: dict[str, FunctionInfo] = field(default_factory=dict)
    classes: dict[str, ClassInfo] = field(default_factory=dict)
    # local name -> ("mod", dotted) | ("obj", dotted_module, attr)
    imports: dict[str, tuple] = field(default_factory=dict)


@dataclass(frozen=True)
class EffectSummary:
    may_block: bool = False
    may_await: bool = False
    always_awaits: bool = False
    spawns_directly: bool = False
    swallows_exception: bool = False
    unknown: bool = False           # the unresolved-call sentinel


#: Summary for calls the graph cannot resolve.  may_await=True is the
#: load-bearing default: ``await asyncio.sleep(...)`` (and every other
#: stdlib await) must keep counting as a possible suspension point.
UNKNOWN = EffectSummary(may_await=True, unknown=True)


def module_name_for(logical_path: str) -> str:
    """``cometbft_tpu/consensus/state.py`` ->
    ``cometbft_tpu.consensus.state``; ``pkg/__init__.py`` -> ``pkg``."""
    p = logical_path
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


class _Effects:
    """Mutable per-function effect state during the fixed point."""

    __slots__ = ("may_block", "may_await", "always_awaits",
                 "spawns_directly", "swallows_exception",
                 "block_witness")

    def __init__(self):
        self.may_block = False
        self.may_await = False
        self.always_awaits = False
        self.spawns_directly = False
        self.swallows_exception = False
        # ("direct", call_name, lineno) or ("via", callee_fi, lineno)
        self.block_witness: Optional[tuple] = None


class Program:
    """The whole-package call graph + effect summaries, built once per
    lint run by ``core.lint_paths`` and shared by every checker via
    ``ctx.program``."""

    def __init__(self, contexts: Iterable[FileContext]):
        self.modules: dict[str, ModuleInfo] = {}
        self._fn_of_node: dict[ast.AST, FunctionInfo] = {}
        self._class_of_node: dict[ast.AST, ClassInfo] = {}
        self._effects: dict[int, _Effects] = {}
        self._summaries: dict[int, EffectSummary] = {}
        for ctx in contexts:
            self._index_module(ctx)
        self._functions: list[FunctionInfo] = [
            f for m in self.modules.values()
            for f in list(m.functions.values())
            + [mm for c in m.classes.values()
               for mm in c.methods.values()]]
        # resolved call edges per function: (callee, call node,
        # awaited-at-call-site)
        self._calls: dict[int, list[tuple[FunctionInfo, ast.Call,
                                          bool]]] = {}
        self._direct_pass()
        self._fixed_point()

    # -- indexing -----------------------------------------------------
    def _index_module(self, ctx: FileContext) -> None:
        mod = ModuleInfo(name=module_name_for(ctx.logical_path),
                         ctx=ctx)
        self.modules[mod.name] = mod
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                fi = FunctionInfo(mod.name, node.name, node, ctx)
                mod.functions[node.name] = fi
                self._fn_of_node[node] = fi
            elif isinstance(node, ast.ClassDef):
                ci = ClassInfo(name=node.name, module=mod.name)
                for b in node.bases:
                    bn = _dotted(b)
                    if bn:
                        ci.base_names.append(bn)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        fi = FunctionInfo(
                            mod.name, f"{ci.name}.{item.name}",
                            item, ctx, cls=ci)
                        ci.methods[item.name] = fi
                        self._fn_of_node[item] = fi
                mod.classes[ci.name] = ci
                self._class_of_node[node] = ci
        self._index_imports(ctx, mod)

    def _index_imports(self, ctx: FileContext,
                       mod: ModuleInfo) -> None:
        pkg_parts = mod.name.split(".")[:-1]
        for node in ctx.nodes(ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".")[0]
                target = alias.name if alias.asname \
                    else alias.name.split(".")[0]
                mod.imports[local] = ("mod", target)
        for node in ctx.nodes(ast.ImportFrom):
            if node.level:
                base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                if node.level - 1 > len(pkg_parts):
                    continue
                src = ".".join(base + ([node.module]
                                       if node.module else []))
            else:
                src = node.module or ""
            if not src:
                continue
            for alias in node.names:
                local = alias.asname or alias.name
                if alias.name == "*":
                    continue
                mod.imports[local] = ("obj", src, alias.name)

    # -- resolution ---------------------------------------------------
    def resolve_call(self, ctx: FileContext,
                     call: ast.Call) -> Optional[FunctionInfo]:
        """Resolve a call node to a package function, or None.

        Handles: bare names (module functions, ``from x import f``),
        ``self.m()`` / ``cls.m()`` (the enclosing class, then its
        same-package bases), and ``mod.f()`` through ``import``
        aliases.  Everything else is *deliberately* unresolved —
        rules get :data:`UNKNOWN` and apply their sound default."""
        mod = self.modules.get(module_name_for(ctx.logical_path))
        if mod is None:
            return None
        fn = call.func
        if isinstance(fn, ast.Name):
            fi = mod.functions.get(fn.id)
            if fi is not None:
                return fi
            imp = mod.imports.get(fn.id)
            if imp and imp[0] == "obj":
                target = self.modules.get(imp[1])
                if target:
                    return target.functions.get(imp[2])
            return None
        if not isinstance(fn, ast.Attribute):
            return None
        recv = fn.value
        if isinstance(recv, ast.Name) and recv.id in ("self", "cls"):
            ci = self._enclosing_class(ctx, call)
            if ci is None:
                return None
            return self._resolve_method(ci, fn.attr)
        if isinstance(recv, ast.Name):
            imp = mod.imports.get(recv.id)
            if imp and imp[0] == "mod":
                target = self.modules.get(imp[1])
                if target:
                    return target.functions.get(fn.attr)
        return None

    def _enclosing_class(self, ctx: FileContext,
                         node: ast.AST) -> Optional[ClassInfo]:
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.ClassDef):
                return self._class_of_node.get(anc)
        return None

    def _resolve_method(self, ci: ClassInfo, name: str,
                        depth: int = 0,
                        seen: Optional[set] = None
                        ) -> Optional[FunctionInfo]:
        if depth > _MAX_BASE_DEPTH:
            return None
        seen = seen if seen is not None else set()
        key = (ci.module, ci.name)
        if key in seen:
            return None
        seen.add(key)
        fi = ci.methods.get(name)
        if fi is not None:
            return fi
        mod = self.modules.get(ci.module)
        if mod is None:
            return None
        for base_name in ci.base_names:
            base = self._resolve_class(mod, base_name)
            if base is None:
                continue
            fi = self._resolve_method(base, name, depth + 1, seen)
            if fi is not None:
                return fi
        return None

    def _resolve_class(self, mod: ModuleInfo,
                       dotted: str) -> Optional[ClassInfo]:
        head, _, tail = dotted.partition(".")
        if not tail:
            ci = mod.classes.get(head)
            if ci is not None:
                return ci
            imp = mod.imports.get(head)
            if imp and imp[0] == "obj":
                target = self.modules.get(imp[1])
                if target:
                    return target.classes.get(imp[2])
            return None
        imp = mod.imports.get(head)
        if imp and imp[0] == "mod" and "." not in tail:
            target = self.modules.get(imp[1])
            if target:
                return target.classes.get(tail)
        return None

    # -- summaries ----------------------------------------------------
    def summary(self, fi: FunctionInfo) -> EffectSummary:
        s = self._summaries.get(id(fi))
        if s is None:
            e = self._effects.get(id(fi))
            if e is None:
                return UNKNOWN
            s = EffectSummary(
                may_block=e.may_block, may_await=e.may_await,
                always_awaits=e.always_awaits,
                spawns_directly=e.spawns_directly,
                swallows_exception=e.swallows_exception)
            self._summaries[id(fi)] = s
        return s

    def summary_for_call(self, ctx: FileContext,
                         call: ast.Call) -> EffectSummary:
        fi = self.resolve_call(ctx, call)
        if fi is None:
            return UNKNOWN
        return self.summary(fi)

    def blocking_chain(self, fi: FunctionInfo) -> list[str]:
        """Human-readable witness chain from ``fi`` to the blocking
        call it transitively reaches, for the finding message:
        ``['_flush_wal (consensus/wal.py:88)', 'open()']``."""
        chain: list[str] = []
        seen: set[int] = set()
        cur: Optional[FunctionInfo] = fi
        while cur is not None and id(cur) not in seen:
            seen.add(id(cur))
            e = self._effects.get(id(cur))
            if e is None or e.block_witness is None:
                break
            kind, payload, lineno = e.block_witness
            if kind == "direct":
                chain.append(f"{payload}() "
                             f"[{cur.ctx.logical_path}:{lineno}]")
                return chain
            nxt: FunctionInfo = payload
            chain.append(f"{nxt.qualname} ({nxt.location()})")
            cur = nxt
        chain.append("<cycle>")      # pragma: no cover - defensive
        return chain

    # -- effect computation -------------------------------------------
    def _direct_pass(self) -> None:
        swallow_fns = self._swallow_functions()
        for fi in self._functions:
            e = _Effects()
            self._effects[id(fi)] = e
            calls: list[tuple[FunctionInfo, ast.Call, bool]] = []
            ctx = fi.ctx
            for node in walk_scope(fi.node):
                if isinstance(node, ast.Call):
                    name = call_name(node)
                    if _is_blocking_call(node, name) and \
                            not ctx.suppressed(node.lineno,
                                               "blocking-in-async"):
                        e.may_block = True
                        if e.block_witness is None:
                            e.block_witness = ("direct", name,
                                               node.lineno)
                    if _is_spawn_call(node) and \
                            not ctx.suppressed(node.lineno,
                                               "supervised-spawn"):
                        e.spawns_directly = True
                    callee = self.resolve_call(ctx, node)
                    if callee is not None:
                        parent = ctx.parent(node)
                        awaited = isinstance(parent, ast.Await) and \
                            parent.value is node
                        calls.append((callee, node, awaited))
                elif isinstance(node, (ast.AsyncFor, ast.AsyncWith)):
                    e.may_await = True
                elif isinstance(node, ast.Await):
                    # refined below: an await over a resolved
                    # never-awaiting call is NOT a suspension; only
                    # unresolvable operands count as direct awaits
                    if not (isinstance(node.value, ast.Call) and
                            self.resolve_call(ctx, node.value)
                            is not None):
                        e.may_await = True
            if id(fi) in swallow_fns:
                e.swallows_exception = True
            self._calls[id(fi)] = calls

    def _swallow_functions(self) -> set[int]:
        """ids of FunctionInfos containing a swallowed-exception
        finding (the checker is reused so the two never drift)."""
        # lazy import: checkers import callgraph's tables, so a
        # module-level import here would be circular
        from .checkers.swallowed_exception import (
            SwallowedExceptionChecker,
        )
        checker = SwallowedExceptionChecker()
        out: set[int] = set()
        by_ctx: dict[int, list[FunctionInfo]] = {}
        for fi in self._functions:
            by_ctx.setdefault(id(fi.ctx), []).append(fi)
        done_ctx: set[int] = set()
        for fi in self._functions:
            if id(fi.ctx) in done_ctx:
                continue
            done_ctx.add(id(fi.ctx))
            ctx = fi.ctx
            try:
                findings = list(checker.check(ctx))
            except Exception:       # pragma: no cover - defensive
                continue
            for f in findings:
                if ctx.suppressed(f.line, f.rule):
                    continue
                for cand in by_ctx.get(id(ctx), ()):
                    end = getattr(cand.node, "end_lineno",
                                  cand.node.lineno)
                    if cand.node.lineno <= f.line <= end:
                        out.add(id(cand))
        return out

    def _fixed_point(self) -> None:
        # all components are monotone booleans seeded False, so naive
        # iteration converges (and cycles cannot oscillate)
        changed = True
        while changed:
            changed = False
            for fi in self._functions:
                e = self._effects[id(fi)]
                for callee, node, awaited in self._calls[id(fi)]:
                    ce = self._effects.get(id(callee))
                    if ce is None:
                        continue
                    # any call into a blocking helper blocks the
                    # caller — awaited or not (awaiting an async
                    # helper runs its body on this very loop)
                    if ce.may_block and not e.may_block:
                        e.may_block = True
                        e.block_witness = ("via", callee,
                                           node.lineno)
                        changed = True
                    if awaited and ce.may_await and not e.may_await:
                        e.may_await = True
                        changed = True
                    if ce.swallows_exception and \
                            not e.swallows_exception:
                        e.swallows_exception = True
                        changed = True
            # always_awaits consumes may_await fixpoint results and is
            # itself monotone, so it gets its own inner iteration
            aw_changed = True
            while aw_changed:
                aw_changed = False
                for fi in self._functions:
                    e = self._effects[id(fi)]
                    if e.always_awaits or not fi.is_async:
                        continue
                    body = getattr(fi.node, "body", [])
                    if self._stmts_definitely_await(fi.ctx, body):
                        e.always_awaits = True
                        e.may_await = True
                        aw_changed = True
                        changed = True
        self._summaries.clear()

    # -- always-awaits walker -----------------------------------------
    def _await_is_definite(self, ctx: FileContext,
                           aw: ast.Await) -> bool:
        v = aw.value
        if isinstance(v, ast.Call):
            fi = self.resolve_call(ctx, v)
            if fi is not None:
                return self._effects[id(fi)].always_awaits
        # unresolved operand (asyncio.sleep, a future, gather...):
        # treated as a definite suspension — the pragmatic default
        # that keeps `await asyncio.sleep(0)` a credited yield
        return True

    def _expr_definitely_awaits(self, ctx: FileContext,
                                expr: Optional[ast.AST]) -> bool:
        if expr is None:
            return False
        for node in walk_scope(expr):
            if isinstance(node, ast.Await) and \
                    self._await_is_definite(ctx, node):
                return True
        return False

    def _stmts_definitely_await(self, ctx: FileContext,
                                stmts: list) -> bool:
        """True when every path through ``stmts`` reaches a definite
        await.  Conservative: any possible early exit (return/raise/
        break/continue) before a proven await yields False."""
        for stmt in stmts:
            if isinstance(stmt, (ast.Return, ast.Raise)):
                v = stmt.value if isinstance(stmt, ast.Return) \
                    else stmt.exc
                return self._expr_definitely_awaits(ctx, v)
            if isinstance(stmt, (ast.Break, ast.Continue)):
                return False
            if self._stmt_definitely_awaits(ctx, stmt):
                return True
            if self._has_possible_exit(stmt):
                return False
        return False

    def _stmt_definitely_awaits(self, ctx: FileContext,
                                stmt: ast.AST) -> bool:
        if isinstance(stmt, (ast.AsyncFor, ast.AsyncWith)):
            return True
        if isinstance(stmt, ast.If):
            if self._expr_definitely_awaits(ctx, stmt.test):
                return True
            return bool(stmt.orelse) and \
                self._stmts_definitely_await(ctx, stmt.body) and \
                self._stmts_definitely_await(ctx, stmt.orelse)
        if isinstance(stmt, ast.While):
            # the test evaluates at least once
            return self._expr_definitely_awaits(ctx, stmt.test)
        if isinstance(stmt, ast.With):
            return self._stmts_definitely_await(ctx, stmt.body)
        if isinstance(stmt, ast.Try):
            return self._stmts_definitely_await(ctx, stmt.body)
        if isinstance(stmt, (ast.Expr, ast.Assign, ast.AugAssign,
                             ast.AnnAssign)):
            return self._expr_definitely_awaits(
                ctx, getattr(stmt, "value", None))
        return False

    @staticmethod
    def _has_possible_exit(stmt: ast.AST) -> bool:
        for node in walk_scope(stmt):
            if isinstance(node, (ast.Return, ast.Raise, ast.Break,
                                 ast.Continue)):
                return True
        return False


def _dotted(node: ast.AST) -> Optional[str]:
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def build_program(contexts: Iterable[FileContext]) -> Program:
    return Program(contexts)


__all__ = ["Program", "FunctionInfo", "EffectSummary", "UNKNOWN",
           "build_program", "module_name_for",
           "BLOCKING_CALLS", "BLOCKING_TAILS", "SPAWN_ATTRS"]
