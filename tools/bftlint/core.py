"""bftlint core: file contexts, the checker protocol, and the driver.

Design mirrors what the repo's ad-hoc AST guards already did well
(tests/test_supervised_tasks_ast.py — parse once, walk, explain the
invariant in the message) and adds what they lacked: one parse per
file shared by every checker, parent/scope tracking, inline
suppressions, and a committed baseline for grandfathered findings so
``check`` can gate CI at zero new findings from day one.
"""
from __future__ import annotations

import ast
import fnmatch
import os
import re
from dataclasses import dataclass, field
from typing import Iterable, Iterator, Optional

# ``# bftlint: disable=rule-a,rule-b`` on a flagged line suppresses
# those rules on that line; on a comment-only line it applies to the
# next code line.  ``# bftlint: disable-file=rule`` anywhere in the
# first _FILE_PRAGMA_LINES lines suppresses the rule file-wide.
# ``# bftlint: path=<logical path>`` (fixture files) overrides the
# path used for scope matching, so tests can exercise path-scoped
# checkers from tests/bftlint_fixtures/.
_SUPPRESS_RE = re.compile(r"#\s*bftlint:\s*disable=([\w,\-]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*bftlint:\s*disable-file=([\w,\-]+)")
_PATH_RE = re.compile(r"#\s*bftlint:\s*path=(\S+)")
_FILE_PRAGMA_LINES = 15

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str           # repo-relative posix path (logical, see above)
    line: int
    col: int
    message: str
    scope: str          # dotted class/def chain enclosing the node
    snippet: str        # stripped source of the flagged line

    @property
    def fingerprint(self) -> str:
        """Line-number-free identity used by the baseline: findings
        keep matching their grandfather entry across unrelated edits
        that only shift line numbers."""
        return "::".join((self.rule, self.path, self.scope,
                          self.snippet))

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"


class FileContext:
    """One parsed file, shared by every checker.

    Provides the parent map, dotted scope lookup, async-enclosure
    tests and the suppression index so checkers stay small.
    """

    def __init__(self, path: str, source: str,
                 repo_root: str = _REPO_ROOT):
        self.abs_path = os.path.abspath(path)
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        rel = os.path.relpath(self.abs_path, repo_root)
        self.rel_path = rel.replace(os.sep, "/")
        self.logical_path = self.rel_path
        # one walk serves everyone: the parent map and a by-type node
        # index (checkers iterate ctx.nodes(ast.Call) instead of
        # re-walking the whole tree 8 times — see the
        # bftlint_selfcheck benchmark in tools/perf_lab.py)
        self._parents: dict[ast.AST, ast.AST] = {}
        self._by_type: dict[type, list[ast.AST]] = {}
        for node in ast.walk(self.tree):
            self._by_type.setdefault(type(node), []).append(node)
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
        self._line_suppress: dict[int, set[str]] = {}
        self._file_suppress: set[str] = set()
        self._index_pragmas()
        # whole-package call graph + effect summaries, attached by
        # lint_paths before checkers run (tools/bftlint/callgraph.py);
        # None for a bare FileContext — checkers must fall back to
        # their intra-procedural behavior then
        self.program = None

    # -- pragmas ------------------------------------------------------
    def _index_pragmas(self) -> None:
        pending: set[str] = set()
        for i, raw in enumerate(self.lines, start=1):
            if i <= _FILE_PRAGMA_LINES:
                m = _PATH_RE.search(raw)
                if m:
                    self.logical_path = m.group(1)
                m = _SUPPRESS_FILE_RE.search(raw)
                if m:
                    self._file_suppress.update(
                        r.strip() for r in m.group(1).split(","))
            m = _SUPPRESS_RE.search(raw)
            code = raw.split("#", 1)[0].strip()
            if m:
                rules = {r.strip() for r in m.group(1).split(",")}
                if code:        # trailing comment: this line only —
                    # plus any pending comment-only pragma, which
                    # targets this code line too (it must not leak
                    # past it to a later line)
                    self._line_suppress.setdefault(i, set()) \
                        .update(rules | pending)
                    pending = set()
                else:           # comment-only line: the next code line
                    pending |= rules
            elif code and pending:
                self._line_suppress.setdefault(i, set()) \
                    .update(pending)
                pending = set()

    def suppressed(self, line: int, rule: str) -> bool:
        if rule in self._file_suppress:
            return True
        return rule in self._line_suppress.get(line, set())

    # -- tree helpers -------------------------------------------------
    def nodes(self, *types: type) -> Iterator[ast.AST]:
        """All nodes of the given AST types, in walk order — the
        shared index that keeps every checker O(relevant nodes)
        instead of O(whole tree)."""
        for t in types:
            yield from self._by_type.get(t, ())

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def scope_of(self, node: ast.AST) -> str:
        names = []
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.append(anc.name)
        return ".".join(reversed(names)) or "<module>"

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef,
                                ast.AsyncFunctionDef)):
                return anc
        return None

    def in_async_def(self, node: ast.AST) -> bool:
        return isinstance(self.enclosing_function(node),
                          ast.AsyncFunctionDef)

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST,
                message: str) -> Finding:
        return Finding(rule=rule, path=self.logical_path,
                       line=node.lineno, col=node.col_offset,
                       message=message,
                       scope=self.scope_of(node),
                       snippet=self.snippet(node.lineno))


def walk_scope(root: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` that does not descend into nested function
    definitions or lambdas: lexically-nested code does not execute in
    the enclosing function's control flow, so flow-sensitive checkers
    (yield-in-loop, await-atomicity) must not credit or blame its
    awaits/loads/stores to the outer function.  ``root`` itself is
    yielded even when it is a function def."""
    stack: list[ast.AST] = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.append(child)


def call_name(node: ast.Call) -> str:
    """Dotted best-effort name of a call target: ``time.time``,
    ``asyncio.create_task``, ``self.metrics.x.with_labels`` ->
    ``with_labels`` keeps only the tail attribute chain of Names and
    Attributes (subscripts/calls in the chain truncate it)."""
    parts: list[str] = []
    cur = node.func
    while True:
        if isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        elif isinstance(cur, ast.Name):
            parts.append(cur.id)
            break
        else:
            break
    return ".".join(reversed(parts))


class Checker:
    """A single rule.  Subclasses set ``rule``/``description``, may
    narrow ``scope`` (fnmatch patterns over the logical repo-relative
    path; empty = every file), and implement ``check``."""

    rule: str = ""
    description: str = ""
    scope: tuple[str, ...] = ()

    def in_scope(self, logical_path: str) -> bool:
        if not self.scope:
            return True
        return any(fnmatch.fnmatch(logical_path, pat)
                   for pat in self.scope)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError


@dataclass
class LintResult:
    findings: list[Finding] = field(default_factory=list)
    files_scanned: int = 0
    scanned_paths: set[str] = field(default_factory=set)  # logical
    parse_errors: list[str] = field(default_factory=list)


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    # overlapping arguments (`check pkg pkg/file.py`) must not lint a
    # file twice — duplicate findings would overflow count-capped
    # baseline entries and read as new
    seen: set[str] = set()
    for p in paths:
        if os.path.isfile(p):
            files = [p] if p.endswith(".py") else []
        else:
            files = []
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                files.extend(os.path.join(root, f)
                             for f in sorted(names)
                             if f.endswith(".py"))
        for f in files:
            real = os.path.realpath(f)
            if real not in seen:
                seen.add(real)
                yield f


def lint_paths(paths: Iterable[str], checkers: Iterable[Checker],
               rules: Optional[set[str]] = None,
               repo_root: str = _REPO_ROOT,
               program_paths: Optional[Iterable[str]] = None
               ) -> LintResult:
    """Parse each file once, build the whole-corpus call graph
    (callgraph.py) once, run every in-scope checker over each judged
    file, and drop inline-suppressed findings.  Baseline filtering is
    the caller's concern (tools/bftlint/baseline.py).

    ``program_paths`` widens the *summary corpus* beyond the judged
    ``paths``: ``check --diff`` judges only changed files but still
    feeds the entire package to the call graph so interprocedural
    summaries stay sound.  Corpus-only files that fail to parse
    contribute nothing (their calls resolve to the explicit unknown
    summary) but do not fail the run — they will when judged."""
    # lazy import: callgraph imports core's FileContext helpers
    from .callgraph import build_program
    checkers = list(checkers)
    if rules:
        checkers = [c for c in checkers if c.rule in rules]
    result = LintResult()
    judged: list[FileContext] = []
    corpus: dict[str, FileContext] = {}     # realpath -> ctx
    for path in iter_python_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            ctx = FileContext(path, source, repo_root=repo_root)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            result.parse_errors.append(f"{path}: {e}")
            continue
        judged.append(ctx)
        corpus[os.path.realpath(path)] = ctx
    if program_paths is not None:
        for path in iter_python_files(program_paths):
            real = os.path.realpath(path)
            if real in corpus:
                continue
            try:
                with open(path, encoding="utf-8") as f:
                    source = f.read()
                corpus[real] = FileContext(path, source,
                                           repo_root=repo_root)
            except (SyntaxError, UnicodeDecodeError, OSError):
                continue
    program = build_program(corpus.values())
    for ctx in judged:
        ctx.program = program
        result.files_scanned += 1
        result.scanned_paths.add(ctx.logical_path)
        for checker in checkers:
            if not checker.in_scope(ctx.logical_path):
                continue
            for finding in checker.check(ctx):
                if not ctx.suppressed(finding.line, finding.rule):
                    result.findings.append(finding)
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return result
